"""Device-time observatory for the serving tier (the PR 15 tentpole).

Three joined capabilities, all pure host bookkeeping threaded through the
points the transactional tick already visits (no new device programs, no
new syncs — JP106's one-dispatch tick is untouched):

- **Per-program device-time attribution**: every committed working tick's
  wall clock is classified into four buckets that PARTITION it exactly —

  * ``dispatch``   — host time inside the jitted call(s): trace/compile
    lookup + argument upload + async enqueue;
  * ``device``     — the window between the last dispatch return and the
    tick's completion barrier *starting*: the device is executing while
    the host runs overlapped bookkeeping (host work here is off the
    critical path, which is why it attributes to the device);
  * ``sync``       — host BLOCKED on the per-tick device->host
    materialization (the device is still executing: ``device + sync`` is
    the host's best view of device-busy time without a profiler);
  * ``bookkeep``   — everything else (admission, page allocation, drain
    walks, emission staging) = ``wall - dispatch - device - sync``.

  Buckets accumulate into rollback-covered :class:`observe.Histogram`
  objects keyed ``perf_<family>_<bucket>_s`` per program family
  (``tick.steady`` / ``tick.admission`` / ``tick.spec`` for the
  ``_ragged_tick_fn`` forms, plus ``swap_in`` and ``handoff`` epoch
  windows and the sequential/pp oracles), ride the engine's committed
  /metrics exposition (the router fleet-sums them), and stamp per-tick
  fields into the flight-recorder record.

- **Runtime recompile sentinel** — JP104's runtime twin: a
  ``jax.monitoring.register_event_duration_secs_listener`` hook counts
  backend-compile events and seconds, attributes them to the program
  family whose dispatch window they fired inside (compiles happen
  synchronously inside the jitted call on the dispatching thread), and
  classifies each against the manifest-locked grid in
  ``analysis/programs.lock.json``:

  * first compile of a grid point = **cold** (the budgeted warm-up
    compile the static audit priced);
  * a compile for a point ALREADY compiled in this engine =
    ``compiles_warm`` (the jit cache should have hit — a shape/semantic
    retrace is eating seconds mid-serving; the BENCH gate pins this to 0
    after warm-up);
  * a compile whose point is NOT in the locked grid =
    ``compiles_out_of_grid``, flagged loudly (warn log + /health ``perf``
    block + monotonic /metrics counter + flight-ring field): the engine
    is paying for a program the static recompile-surface audit (JP104)
    never saw.

- **MFU / roofline accounting**: measured per-tick device time (the
  backend-honest ``dispatch - compile + device + sync`` view — see
  ``_device_view``) joins the manifest's ``cost_analysis`` flops /
  bytes-accessed for the dispatched grid point.  The manifest records the
  AUDIT model's cost, so the join scales by the analytic per-token flops
  ratio between the serving model and the audit model (decode cost is
  weight-matmul dominated, so one ratio serves flops and bytes; XLA's
  cost analysis counts a while-loop body ONCE, so the decode-horizon
  estimate multiplies by the tick's executed iteration count — which the
  engine already syncs as ``n_exec``).  Reported per tick class:
  achieved flops/s, achieved bytes/s, and MFU = achieved / peak, where
  peak comes from ``IPEX_LLM_TPU_PEAK_FLOPS`` /
  ``IPEX_LLM_TPU_PEAK_BYTES_PER_S`` (falling back to documented nominal
  per-platform defaults an operator should pin for real hardware).

Engines whose grid point the manifest does not cover (bigger row counts,
wider buckets than the audit sampled) still get full attribution and
sentinel compile counting — only the MFU join reports None, and
out-of-grid compiles flag, which is the message: extend the audit grid
(``scripts/jaxprcheck --update``) to cover the config you serve.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager

from ipex_llm_tpu.serving.observe import FAST_LATENCY_BUCKETS_S, Histogram

__all__ = [
    "PerfWatch",
    "BUCKETS",
    "PLAN_ERROR_BUCKETS",
    "model_flops_per_token",
    "parse_point_key",
    "locked_points",
    "point_in_grid",
    "resolve_peaks",
]

log = logging.getLogger("ipex_llm_tpu.perfwatch")

BUCKETS = ("dispatch", "device", "sync", "bookkeep")

# planner prediction error, |actual - predicted| / predicted: RATIO
# buckets, not seconds — a 10ms tick mispredicted by 5ms and a 1s tick
# mispredicted by 500ms are the same 0.5 model miss.  Fleet-summable
# like every other histogram here.
PLAN_ERROR_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0)

# jax.monitoring event names (jax 0.4.37): one backend_compile per
# compiled program — THE unit the sentinel counts — while the trace/
# lowering events fire per (possibly nested) jaxpr and would overcount.
_COMPILE_COUNT_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILE_TIME_PREFIX = "/jax/core/compile/"

# magnitude axes of the ragged-tick grid: the audit samples power-of-two
# representatives (rows 4/8, width 8/128, horizon 1/8), and the engine's
# budget clamping only ever generates power-of-two values on them — so
# membership admits any pow2 value up to the locked maximum of the
# structurally-matching group.  Every other axis (kv, wq, tp, cq, wd,
# spec) is structural: it must match a locked point exactly (spec: any
# value up to the locked max, since per-request clamps keep it bounded).
_MAG_AXES = ("rows", "width", "horizon")

# retrace-driving shape axes the engine keys its warm/cold compile dedup
# on but the audit grid does NOT lock (its builders fix them: batch pad
# p=2, table-width bucket, eos pad width 2) — they ride the sentinel's
# point identity so a fresh pow2 batch pad is a COLD compile, not a
# false warm flag, and the membership check ignores them.
_UNLOCKED_AXES = ("pb", "maxp", "ew")

# nominal roofline peaks per platform — deliberately round numbers an
# operator overrides via env for their real part (a v5p, a Sapphire
# Rapids socket...).  MFU is a ratio; the honest denominator is yours.
_DEFAULT_PEAKS = {
    "tpu": (275e12, 1.2e12),   # bf16 flops/s, HBM bytes/s (v4-class)
    "cpu": (5e10, 2e10),       # one-core XLA CPU ballpark
}


def resolve_peaks(platform: str | None = None) -> tuple[float, float]:
    """(peak_flops_per_s, peak_bytes_per_s) — env override first, then
    the nominal per-platform default."""
    if platform is None:
        try:
            from ipex_llm_tpu.ops.dispatch import backend_platform
            platform = backend_platform()
        except Exception:
            platform = "cpu"
    flops, byps = _DEFAULT_PEAKS.get(platform, _DEFAULT_PEAKS["cpu"])
    try:
        flops = float(os.environ.get("IPEX_LLM_TPU_PEAK_FLOPS", "") or flops)
        byps = float(os.environ.get("IPEX_LLM_TPU_PEAK_BYTES_PER_S", "")
                     or byps)
    except ValueError:
        pass
    return flops, byps


def model_flops_per_token(cfg) -> float:
    """Analytic dense-matmul flops for ONE decode token through the model
    (2 flops per MAC: qkv/o projections, gate+up+down MLP, lm head) — the
    MFU scale basis.  Attention score/value math and norms are omitted on
    both sides of the ratio (they are the same small fraction at decode
    shapes), so the audit-model / serving-model ratio stays honest."""
    h = cfg.hidden_size
    q = cfg.num_heads * cfg.head_dim
    kv = cfg.num_kv_heads * cfg.head_dim
    per_layer = h * (q + 2 * kv) + q * h + 3 * h * cfg.intermediate_size
    return 2.0 * (cfg.num_layers * per_layer + h * cfg.vocab_size)


# ---------------------------------------------------------------------------
# manifest grid membership


def parse_point_key(key: str) -> dict:
    """``"horizon=8,kv=fp8,rows=4"`` -> typed axis dict (ints where the
    value parses, ``False`` for the ``wd=False`` axis)."""
    out: dict = {}
    for part in key.split(","):
        if not part:
            continue
        k, _, v = part.partition("=")
        if v == "False":
            out[k] = False
        elif v == "True":
            out[k] = True
        else:
            try:
                out[k] = int(v)
            except ValueError:
                out[k] = v
    return out


def locked_points(manifest: dict | None,
                  program: str = "serving.ragged_tick") -> list[dict] | None:
    """The locked grid for one program as typed point dicts; None when
    the manifest (or the program's entries) is unavailable — membership
    checks are then disabled rather than false-flagging everything."""
    if not manifest:
        return None
    entries = (manifest.get("programs", {}).get(program, {})
               .get("entries"))
    if not entries:
        return None
    return [parse_point_key(k) for k in entries]


def _structure(point: dict) -> tuple:
    """The structural identity of a grid point: every non-magnitude axis
    verbatim, plus whether the width axis is the steady (0) or the
    admission (>0) form — magnitude values are range-checked per group
    instead of matched exactly (the audit samples pow2 representatives,
    the engine generates the whole pow2 family)."""
    keys = sorted(k for k in point if k not in _MAG_AXES
                  and k != "spec" and k not in _UNLOCKED_AXES)
    return (tuple((k, point[k]) for k in keys),
            int(point.get("width", 0) or 0) > 0,
            "spec" in point and bool(point.get("spec")))


def _pow2(v: int) -> bool:
    return v > 0 and (v & (v - 1)) == 0


def _mag_group(point: dict) -> tuple:
    """The magnitude-bounds grouping: non-magnitude axes MINUS the
    program-form splits (wd, wq, width=0 vs >0).  The audit samples each
    form at representative widths/rows, but the pow2 family the engine's
    budget clamping generates is shared across the forms — a wd=False
    pure-chunk tick at width 16, or an int4 admission wave at width 32,
    is bounded by the widest width the STRUCTURALLY adjacent forms
    sampled (the bf16 admission rows' 128), not by the single width
    that form happened to lower at (the wq form keeps width=8 only
    because wider chunks shape-collide with the widened int4 audit
    model's weight stacks — see the registry grid comment).  Structural
    existence is still exact: a (wq, kv) form with no locked row at all
    flags."""
    keys = sorted(k for k in point if k not in _MAG_AXES
                  and k not in ("spec", "wd", "wq")
                  and k not in _UNLOCKED_AXES)
    return (tuple((k, point[k]) for k in keys),
            "spec" in point and bool(point.get("spec")))


def point_in_grid(point: dict, locked: list[dict] | None) -> bool:
    """Whether a dispatched grid point falls inside the manifest-locked
    recompile surface: its exact structural form (kv/wq/tp/cq/wd/
    steady-vs-admission/spec) must be locked, and each magnitude axis
    (rows/width/horizon) must be a power of two no larger than the
    maximum the audit sampled for the structural family.  ``locked=None``
    (no manifest) admits everything — the sentinel still counts, it just
    cannot classify."""
    if locked is None:
        return True
    if not any(_structure(p) == _structure(point) for p in locked):
        return False
    group = [p for p in locked if _mag_group(p) == _mag_group(point)]
    for ax in _MAG_AXES:
        v = int(point.get(ax, 0) or 0)
        if ax == "width" and v == 0:
            continue            # steady form: width matched structurally
        if not (_pow2(v) and v <= max(int(p.get(ax, 0) or 0)
                                      for p in group)):
            return False
    sp = int(point.get("spec", 0) or 0)
    if sp and sp > max(int(p.get("spec", 0) or 0) for p in group):
        return False
    return True


# ---------------------------------------------------------------------------
# the jax.monitoring listener (module-global, installed once)

_tls = threading.local()           # .watch — the PerfWatch whose dispatch
#                                    window is open on this thread
_install_lock = threading.Lock()
_installed = False


def _on_event(event, duration=0.0, **_kw):
    w = getattr(_tls, "watch", None)
    if w is not None and isinstance(event, str) \
            and event.startswith(_COMPILE_TIME_PREFIX):
        w._compile_event(event, float(duration))


def _install_listener():
    """Register the module's single jax.monitoring listener (jax 0.4.37
    has no per-listener unregister, so one global hook fans out to the
    thread-local active watch — engines on different threads, in-process
    fleets included, attribute their own compiles)."""
    global _installed
    with _install_lock:
        if _installed:
            return
        try:
            import jax.monitoring as _mon

            _mon.register_event_duration_secs_listener(_on_event)
            _installed = True
        except Exception:       # stripped install: sentinel degrades to 0s
            log.warning("jax.monitoring unavailable: the recompile "
                        "sentinel will not observe compile events")


# ---------------------------------------------------------------------------
# PerfWatch


def _fam_key(family: str) -> str:
    return family.replace(".", "_").replace("-", "_")


class PerfWatch:
    """The engine-facing observatory facade.

    Lifecycle (engine thread): ``tick_begin()`` opens the tick scratch;
    ``dispatch(family, point)`` wraps every jitted call (timing window +
    compile attribution + the tick-dispatch count the JP106 cross-check
    compares against the engine's hand-maintained counter);
    ``note_sync(seconds)`` marks the blocking materializations;
    ``tick_finish(...)`` (called ONLY for committed working ticks, from
    the flight recorder) classifies the buckets, joins MFU, and returns
    the per-tick flight fields; ``tick_abort()`` discards the scratch of
    a rolled-back tick — attribution residue cannot survive a rollback
    because nothing is accumulated before ``tick_finish``.

    Sentinel counters (``compiles_*``) are monotonic and deliberately
    NOT rollback-covered: a compile really happened even if the tick it
    fired in rolled back (same rule as the ``rejected`` counter).

    ``hists`` is the dict the histograms register into — the engine
    passes its own ``self.hists`` so checkpoint/rollback/commit and the
    /metrics exposition cover them with zero extra plumbing.
    """

    def __init__(self, hists: dict | None = None, manifest: dict = None,
                 flops_scales: dict | None = None,
                 peak_flops: float | None = None,
                 peak_bytes_s: float | None = None,
                 program: str = "serving.ragged_tick"):
        self.hists = hists if hists is not None else {}
        self.grid = locked_points(manifest, program)
        self._cost: dict[str, tuple[int, int]] = {}
        self._cost_points: list[tuple[dict, int, int]] = []
        if manifest:
            entries = (manifest.get("programs", {}).get(program, {})
                       .get("entries", {}))
            for k, e in entries.items():
                rec = (int(e.get("flops", 0) or 0),
                       int(e.get("bytes_accessed", 0) or 0))
                self._cost[k] = rec
                self._cost_points.append((parse_point_key(k), *rec))
        # per-variant serving-model/audit-model flops ratio, keyed like
        # the audit model choice: "bf16" (the default audit model),
        # "sym_int4" (the widened int4 audit model), "tp" (the tp audit
        # model).  Missing key -> 1.0 (the caller IS the audit model).
        self.flops_scales = dict(flops_scales or {})
        pf, pb = resolve_peaks()
        self.peak_flops = float(peak_flops) if peak_flops else pf
        self.peak_bytes_s = float(peak_bytes_s) if peak_bytes_s else pb
        self._lock = threading.Lock()
        # sentinel state: points already compiled (the warm/cold line),
        # monotonic counters, the last out-of-grid evidence for /health
        self._compiled_points: set[str] = set()
        self.compiles = {"compiles_total": 0, "compiles_warm": 0,
                         "compiles_out_of_grid": 0,
                         "compile_s_total": 0.0}
        self._per_family_compiles: dict[str, dict] = {}
        self.out_of_grid_points: list[str] = []
        # per-family committed aggregates (the MFU join's denominators)
        self._fam: dict[str, dict] = {}
        self.ticks_attributed = 0
        self.dispatch_mismatches = 0
        self._tick = None               # open tick scratch
        self._windows: list[dict] = []  # open window stack (tick + epoch)
        _install_listener()

    # -- window / tick lifecycle (engine thread) ----------------------------

    def tick_begin(self):
        self._tick = {"t0": time.perf_counter(), "dispatch": [],
                      "sync": [], "families": [], "points": [],
                      "tick_dispatches": 0, "compiles": 0,
                      "compiles_warm": 0, "out_of_grid": 0,
                      "compile_s": 0.0, "executed": 1}
        self._windows = [self._tick]

    def tick_abort(self):
        """Discard the rolled-back tick's scratch: nothing it measured
        was committed, so nothing it measured is kept (sentinel compile
        counters already landed — compiles are real either way)."""
        self._tick = None
        self._windows = []

    @contextmanager
    def dispatch(self, family: str, point: dict | None = None,
                 tick: bool = True):
        """Timing window around ONE jitted call.  Runs the sentinel on
        any compile events that fire inside (they fire synchronously on
        this thread), stamps the family/point on the open tick scratch,
        and counts toward the tick-dispatch cross-check when ``tick``."""
        prev = getattr(_tls, "watch", None)
        _tls.watch = self
        n0 = self.compiles["compiles_total"]
        s0 = self.compiles["compile_s_total"]
        self._window_point = point
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            _tls.watch = prev
            self._window_point = None
            for w in self._windows:
                w["dispatch"].append((t0, t1))
                w["families"].append(family)
                if point is not None:
                    w["points"].append(point)
                if tick and w is self._tick:
                    w["tick_dispatches"] += 1
                w["compiles"] += self.compiles["compiles_total"] - n0
                w["compile_s"] += self.compiles["compile_s_total"] - s0
            with self._lock:
                fc = self._per_family_compiles.setdefault(
                    family, {"compiles": 0, "compile_s": 0.0,
                             "dispatches": 0})
                fc["dispatches"] += 1
                dn = self.compiles["compiles_total"] - n0
                if dn:
                    fc["compiles"] += dn
                    fc["compile_s"] += round(
                        self.compiles["compile_s_total"] - s0, 6)

    def note_sync(self, seconds: float):
        """One blocking device->host materialization ending NOW (the
        caller just measured it) — recorded as a [start, end] window on
        every open scratch."""
        t1 = time.perf_counter()
        for w in self._windows:
            w["sync"].append((t1 - seconds, t1))

    def note_executed(self, n: int):
        """The tick's executed horizon-iteration count (``n_exec``): the
        multiplier for the manifest's once-counted loop-body flops."""
        if self._tick is not None:
            self._tick["executed"] = max(int(n), 1)

    @contextmanager
    def epoch_window(self, family: str):
        """Attribution window for epoch-boundary work (swap-in, handoff
        export/import): its own wall span classified with the same bucket
        math, nested inside a tick or free-standing between ticks.  The
        aggregate updates at close — epoch work either happens entirely
        before a fault point (handoff host ops run between ticks) or is
        re-done wholesale by the retried tick (swap-in), so per-window
        accounting stays honest without checkpoint plumbing."""
        w = {"t0": time.perf_counter(), "dispatch": [], "sync": [],
             "families": [], "points": [], "tick_dispatches": 0,
             "compiles": 0, "compiles_warm": 0, "out_of_grid": 0,
             "compile_s": 0.0, "executed": 1}
        self._windows.append(w)
        try:
            yield
        except BaseException:
            # an aborted window (injected fault mid-swap-in, transport
            # error) measures nothing: the retried tick re-runs it whole
            self._windows.remove(w)
            raise
        else:
            self._windows.remove(w)
            buckets, wall = self._classify(w, time.perf_counter())
            # histogram observations are rollback-covered by the engine
            # checkpoint (they live in engine.hists); the un-checkpointed
            # family aggregates defer to tick commit when a tick is open,
            # so a rolled-back tick's swap-in leaves no residue there
            for b, v in buckets.items():
                self._hist(family, b).observe(v)
            dev_s = self._device_view(buckets, w["compile_s"])
            if self._tick is not None:
                self._tick.setdefault("epoch", []).append(
                    (family, buckets, wall, dev_s))
            else:
                self._fam_update(family, buckets, wall, device_s=dev_s)

    # -- the sentinel (listener thread side = dispatching thread) -----------

    def _compile_event(self, event: str, seconds: float):
        with self._lock:
            self.compiles["compile_s_total"] = round(
                self.compiles["compile_s_total"] + seconds, 6)
            if event != _COMPILE_COUNT_EVENT:
                return
            self.compiles["compiles_total"] += 1
            point = getattr(self, "_window_point", None)
            if point is None:
                return
            key = ",".join(f"{k}={point[k]}" for k in sorted(point))
            warm = key in self._compiled_points
            self._compiled_points.add(key)
            in_grid = point_in_grid(point, self.grid)
            if warm:
                self.compiles["compiles_warm"] += 1
                if self._tick is not None:
                    self._tick["compiles_warm"] += 1
                log.warning(
                    "warm-path recompile of grid point %s (%d warm "
                    "compiles total): the jit cache should have hit — "
                    "a retrace is eating compile seconds mid-serving",
                    key, self.compiles["compiles_warm"])
            if not in_grid:
                self.compiles["compiles_out_of_grid"] += 1
                if self._tick is not None:
                    self._tick["out_of_grid"] += 1
                if key not in self.out_of_grid_points:
                    self.out_of_grid_points.append(key)
                    del self.out_of_grid_points[:-16]
                log.warning(
                    "compile for grid point %s OUTSIDE the manifest-"
                    "locked recompile surface (analysis/programs.lock."
                    "json): the JP104 static audit never priced this "
                    "program — extend the registry grid and rerun "
                    "`scripts/jaxprcheck --update`, or this engine pays "
                    "unbudgeted compiles", key)

    # -- bucket math ---------------------------------------------------------

    @staticmethod
    def _classify(scratch: dict, t1: float) -> tuple[dict, float]:
        """Partition ``[scratch.t0, t1]`` into the four buckets.  By
        construction ``sum(buckets) == wall`` exactly: ``device`` is the
        host-idle/overlapped measure between the first dispatch start
        and the last device-activity end, minus the dispatch/sync
        windows themselves; ``bookkeep`` is the remainder."""
        t0 = scratch["t0"]
        wall = max(t1 - t0, 0.0)
        disp = sorted(scratch["dispatch"])
        sync = sorted(scratch["sync"])
        d_s = sum(b - a for a, b in disp)
        s_s = sum(b - a for a, b in sync)
        dev = 0.0
        if disp:
            span0 = disp[0][0]
            span1 = max([b for _, b in disp] + [b for _, b in sync])
            busy = sorted(disp + sync)
            merged: list[list[float]] = []
            for a, b in busy:
                if merged and a <= merged[-1][1]:
                    merged[-1][1] = max(merged[-1][1], b)
                else:
                    merged.append([a, b])
            covered = sum(min(b, span1) - max(a, span0)
                          for a, b in merged
                          if b > span0 and a < span1)
            dev = max((span1 - span0) - covered, 0.0)
        book = max(wall - d_s - s_s - dev, 0.0)
        return ({"dispatch": d_s, "device": dev, "sync": s_s,
                 "bookkeep": book}, wall)

    @staticmethod
    def _device_view(buckets: dict, compile_s: float = 0.0) -> float:
        """The host's best view of device-busy seconds, backend-honest:
        ``device + sync`` (the dispatch-to-barrier window) PLUS the
        dispatch window minus any compile seconds that fired inside it —
        on an async backend dispatch is an enqueue (microseconds, no
        skew), while XLA:CPU executes much of the program synchronously
        inside the call, which would otherwise vanish from the MFU
        denominator entirely."""
        return (max(buckets["dispatch"] - compile_s, 0.0)
                + buckets["device"] + buckets["sync"])

    def _hist(self, family: str, bucket: str) -> Histogram:
        name = f"perf_{_fam_key(family)}_{bucket}_s"
        h = self.hists.get(name)
        if h is None:
            h = self.hists[name] = Histogram(FAST_LATENCY_BUCKETS_S)
        return h

    def note_plan_error(self, predicted_s: float, actual_s: float) -> float:
        """Planner plan-vs-actual: observe the relative prediction error
        into the ``perf_plan_error`` histogram (lazily registered into
        the engine's checkpointed hists dict, so rollback covers it like
        every attribution histogram) and return the rounded error for
        the flight record."""
        err = abs(actual_s - predicted_s) / max(predicted_s, 1e-9)
        h = self.hists.get("perf_plan_error")
        if h is None:
            h = self.hists["perf_plan_error"] = Histogram(PLAN_ERROR_BUCKETS)
        h.observe(err)
        return round(err, 4)

    def _fam_update(self, family: str, buckets: dict, wall: float,
                    flops: float = 0.0, byts: float = 0.0,
                    device_s: float | None = None):
        if device_s is None:
            device_s = self._device_view(buckets)
        with self._lock:
            f = self._fam.setdefault(
                family, {"ticks": 0, "wall_s": 0.0, "device_s": 0.0,
                         "flops": 0.0, "bytes": 0.0, "joined": 0})
            f["ticks"] += 1
            f["wall_s"] = round(f["wall_s"] + wall, 6)
            f["device_s"] = round(f["device_s"] + device_s, 6)
            if flops:
                f["flops"] += flops
                f["bytes"] += byts
                f["joined"] += 1

    # -- cost join -----------------------------------------------------------

    def _scale_for(self, point: dict) -> float:
        if "tp" in point:
            return float(self.flops_scales.get("tp", 1.0))
        if point.get("wq"):
            return float(self.flops_scales.get(str(point["wq"]), 1.0))
        return float(self.flops_scales.get("bf16", 1.0))

    def cost_for(self, point: dict, executed: int = 1
                 ) -> tuple[float, float] | None:
        """(flops, bytes) estimate for ONE tick dispatching ``point`` —
        the manifest's audit-model cost_analysis scaled to the serving
        model, times the executed loop iterations (XLA counts the
        while-loop body once).

        Exact grid points use their entry verbatim.  A point the audit
        sampled AROUND (a bigger pow2 row count, a chunk width between
        the sampled 8 and 128) falls back to the nearest structurally-
        matching entry scaled LINEARLY in rows and width — the manifest
        itself shows both axes linear (rows=8 costs 2.006x rows=4) —
        with the provenance still the locked cost_analysis.  None when
        no structurally-matching entry exists at all (MFU reports None;
        attribution and the sentinel keep working)."""
        clean = {k: v for k, v in point.items() if k not in _UNLOCKED_AXES}
        key = ",".join(f"{k}={clean[k]}" for k in sorted(clean))
        rec = self._cost.get(key)
        scale = self._scale_for(point)
        ex = max(int(executed), 1)
        if rec is not None:
            return rec[0] * scale * ex, rec[1] * scale * ex
        want = _structure(clean)
        rows = int(clean.get("rows", 0) or 0)
        width = int(clean.get("width", 0) or 0)
        hz = int(clean.get("horizon", 1) or 1)
        best = None
        for p, fl, by in self._cost_points:
            if _structure(p) != want:
                continue
            pr = int(p.get("rows", 0) or 0)
            pw = int(p.get("width", 0) or 0)
            dist = (abs(rows - pr) + abs(width - pw)
                    + (0 if int(p.get("horizon", 1) or 1) == hz else 1))
            if best is None or dist < best[0]:
                best = (dist, p, fl, by)
        if best is None:
            return None
        _, p, fl, by = best
        r = 1.0
        if rows and p.get("rows"):
            r *= rows / int(p["rows"])
        if width and p.get("width"):
            r *= width / int(p["width"])
        return fl * scale * r * ex, by * scale * r * ex

    # -- tick close ----------------------------------------------------------

    def tick_finish(self, manual_dispatches: int, working: bool) -> dict:
        """Close the committed tick: classify buckets, cross-check the
        dispatch count, join MFU, fold into the per-family aggregates,
        and return the flight-record fields.  ``working=False`` (idle
        tick) discards the scratch and returns {}.  Raises
        AssertionError (debug builds) on a dispatch-count divergence —
        the runtime enforcement of JP106's hand-maintained bookkeeping.
        """
        scratch, self._tick = self._tick, None
        self._windows = []
        if scratch is None or not working:
            return {}
        t1 = time.perf_counter()
        buckets, wall = self._classify(scratch, t1)
        fams = scratch["families"]
        if "tick.spec" in fams:
            family = "tick.spec"
        elif "tick.admission" in fams:
            family = "tick.admission"
        elif fams:
            family = fams[-1]
        else:
            family = "tick.host"
        observed = scratch["tick_dispatches"]
        mismatch = observed != manual_dispatches
        out = {
            "perf_family": family,
            "attrib": {b: round(buckets[b], 6) for b in BUCKETS},
            "wall_s": round(wall, 6),
        }
        # MFU join over the tick's dispatched points (one per tick on
        # the fused engine; the sequential oracle sums its chunk+sample)
        flops = byts = 0.0
        joined = False
        for point in scratch["points"]:
            cost = self.cost_for(point, scratch["executed"])
            if cost is not None:
                flops += cost[0]
                byts += cost[1]
                joined = True
        dev_s = self._device_view(buckets, scratch["compile_s"])
        if joined and dev_s > 0:
            out["mfu"] = round(flops / dev_s / self.peak_flops, 6)
            out["bytes_per_s"] = round(byts / dev_s, 1)
        if scratch["compiles"]:
            out["compiles"] = scratch["compiles"]
            out["compile_s"] = round(scratch["compile_s"], 6)
        if scratch["compiles_warm"]:
            out["compiles_warm"] = scratch["compiles_warm"]
        if scratch["out_of_grid"]:
            out["compiles_out_of_grid"] = scratch["out_of_grid"]
        if scratch["points"]:
            p = scratch["points"][-1]
            out["grid_point"] = ",".join(
                f"{k}={p[k]}" for k in sorted(p))
        if mismatch:
            self.dispatch_mismatches += 1
            out["dispatch_mismatch"] = {"observed": observed,
                                        "manual": manual_dispatches}
            log.warning(
                "tick dispatch-count divergence: perfwatch observed %d "
                "tick-program dispatch windows but the engine's "
                "hand-maintained _tick_dispatches says %d — one of the "
                "`+= 1` call sites in serving/engine.py drifted from "
                "its dispatch", observed, manual_dispatches)
        for b, v in buckets.items():
            self._hist(family, b).observe(v)
        self._fam_update(family, buckets, wall, flops=flops, byts=byts,
                         device_s=dev_s)
        for e_fam, e_buckets, e_wall, e_dev in scratch.get("epoch", ()):
            # swap-ins committed with this tick (their histograms landed
            # live — the engine checkpoint covers those)
            self._fam_update(e_fam, e_buckets, e_wall, device_s=e_dev)
        with self._lock:
            self.ticks_attributed += 1
        # the debug ASSERT lives in the engine, AFTER the flight ring has
        # recorded this dict — the mismatch evidence must survive the
        # raise (and survive `-O` builds, where only the field remains)
        return out

    # -- views ---------------------------------------------------------------

    def sentinel_view(self) -> dict:
        with self._lock:
            out = dict(self.compiles)
            out["grid_locked"] = (len(self.grid)
                                  if self.grid is not None else None)
            out["grid_points_compiled"] = len(self._compiled_points)
            if self.out_of_grid_points:
                out["out_of_grid_points"] = list(self.out_of_grid_points)
            out["per_family"] = {k: dict(v) for k, v
                                 in self._per_family_compiles.items()}
        return out

    def view(self) -> dict:
        """The /health ``perf`` block: per-family attribution + MFU, the
        sentinel counters, the roofline denominators."""
        fams = {}
        with self._lock:
            fam_snapshot = {k: dict(v) for k, v in self._fam.items()}
        for name, f in fam_snapshot.items():
            row = {"ticks": f["ticks"],
                   "wall_s": round(f["wall_s"], 4),
                   "device_s": round(f["device_s"], 4)}
            if f["joined"] and f["device_s"] > 0:
                row["flops_per_s"] = round(f["flops"] / f["device_s"], 1)
                row["bytes_per_s"] = round(f["bytes"] / f["device_s"], 1)
                row["mfu"] = round(
                    f["flops"] / f["device_s"] / self.peak_flops, 6)
            fams[name] = row
        return {
            "families": fams,
            "ticks_attributed": self.ticks_attributed,
            "dispatch_mismatches": self.dispatch_mismatches,
            "sentinel": self.sentinel_view(),
            "roofline": {"peak_flops": self.peak_flops,
                         "peak_bytes_per_s": self.peak_bytes_s,
                         "flops_scales": dict(self.flops_scales)},
        }

    def mfu(self, family: str | None = None) -> float | None:
        """Aggregate MFU over committed ticks — ``family=None`` joins
        every family with a cost entry; None when nothing joined."""
        with self._lock:
            fams = ([self._fam.get(family)] if family
                    else list(self._fam.values()))
        flops = sum(f["flops"] for f in fams if f)
        dev = sum(f["device_s"] for f in fams if f and f["joined"])
        if not flops or dev <= 0:
            return None
        return round(flops / dev / self.peak_flops, 6)

    def metrics_numeric(self) -> dict:
        """Flat counters for the /metrics exposition (``perf_`` prefix
        added by the caller); every value is fleet-summable or a
        per-replica gauge the router leaves unsummed."""
        with self._lock:
            out = {k: v for k, v in self.compiles.items()}
            out["ticks_attributed"] = self.ticks_attributed
            out["dispatch_mismatches"] = self.dispatch_mismatches
            for name, f in self._fam.items():
                out[f"{_fam_key(name)}_ticks"] = f["ticks"]
                out[f"{_fam_key(name)}_device_s"] = round(f["device_s"], 6)
        m = self.mfu()
        if m is not None:
            out["mfu"] = m
        return out

    def dump_fields(self) -> dict:
        """Compact sentinel evidence for a flight-recorder dump
        (_fail_all / quarantine / chaos-gate failure rows)."""
        c = self.compiles
        out = {"perf_compiles_total": c["compiles_total"],
               "perf_compiles_warm": c["compiles_warm"],
               "perf_compiles_out_of_grid": c["compiles_out_of_grid"]}
        if self.out_of_grid_points:
            out["perf_out_of_grid_points"] = list(self.out_of_grid_points)
        return out
