"""FastChat model worker speaking the controller protocol.

Reference counterpart: serving/fastchat/ipex_llm_worker.py — a worker that
registers with a FastChat controller, heartbeats its queue length, and
streams NUL-delimited JSON chunks ({"text": cumulative, "error_code": 0,
"usage": {...}, "finish_reason": ...}) from /worker_generate_stream
(reference ipex_llm_worker.py:266-414 protocol).  Here generation runs on
the paged continuous-batching TPU engine instead of a HF generate thread,
so one worker process serves concurrent requests.

Run:  python -m ipex_llm_tpu.serving.fastchat_worker --model-path <ckpt> \
          --controller-address http://localhost:21001
(--no-register for standalone use, e.g. tests.)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import uuid

from aiohttp import web

from ipex_llm_tpu.serving.engine import (EngineConfig, Request,
                                         ServingEngine, next_stream_item)
from ipex_llm_tpu.serving.faults import EngineOverloaded
from ipex_llm_tpu.serving.observe import Tracer, parse_traceparent

HEARTBEAT_INTERVAL_S = 45.0

# FastChat protocol error codes (fastchat.constants.ErrorCode peers): the
# controller retries another worker on 50301/50302; 50001 is internal.
ERROR_CODE_INTERNAL = 50001
ERROR_CODE_OVERLOADED = 50302
ERROR_CODE_TIMEOUT = 50300


class FastChatWorker:
    def __init__(self, model, tokenizer, model_names: list[str],
                 controller_addr: str | None = None,
                 worker_addr: str = "http://localhost:21002",
                 limit_worker_concurrency: int = 8,
                 engine_config: EngineConfig | None = None,
                 drain_timeout_s: float = 30.0):
        self.tok = tokenizer
        self.drain_timeout_s = drain_timeout_s
        self.model_names = model_names
        self.controller_addr = controller_addr
        self.worker_addr = worker_addr
        self.worker_id = uuid.uuid4().hex[:8]
        self.limit = limit_worker_concurrency
        self.call_ct = 0
        self.in_flight = 0
        eos = model.generation_config.eos_token_id
        self._eos = tuple(eos) if isinstance(eos, (list, tuple)) else (
            (eos,) if eos is not None else ())
        self.engine = ServingEngine(
            model.config, model.params,
            engine_config or EngineConfig(
                max_rows=limit_worker_concurrency),
            default_eos=self._eos,
            mesh=getattr(model, "mesh", None),
        ).start()
        self.app = web.Application()
        self.app.add_routes([
            web.post("/worker_generate_stream", self.api_generate_stream),
            web.post("/worker_generate", self.api_generate),
            web.post("/worker_get_status", self.api_get_status),
            web.post("/count_token", self.api_count_token),
            web.post("/model_details", self.api_model_details),
            web.post("/worker_get_conv_template", self.api_conv_template),
            # observability surface (serving/observe.py): the same
            # /trace + /debug/flight views api_server exposes, so a
            # FastChat fleet is traceable/postmortem-able too
            web.get("/trace/{trace_id}", self.api_trace),
            web.get("/debug/flight", self.api_flight),
            # device-time observatory (serving/perfwatch.py): the perf
            # block api_server serves under /health, plus the dispatch-
            # ladder provenance — a FastChat worker's recompile sentinel
            # and MFU join are inspectable without the OpenAI surface
            web.get("/debug/perf", self.api_perf),
        ])
        # graceful drain on SIGTERM (reference workers restart-on-error;
        # here the replica finishes in-flight requests before exiting)
        self.app.on_shutdown.append(self._on_shutdown)

    async def _on_shutdown(self, app):
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.engine.drain,
                                   self.drain_timeout_s)
        self.engine.stop()

    # -- controller protocol ------------------------------------------------

    def status(self) -> dict:
        # queue_length feeds the controller's least-loaded routing.
        # in_flight counts each stream for its WHOLE lifetime — engine
        # queue wait included — so adding engine.queue_depth would count
        # queued requests twice and make this worker look busier than it
        # is.
        return {"model_names": self.model_names, "speed": 1,
                "queue_length": self.in_flight}

    async def register(self, session) -> None:
        await session.post(
            self.controller_addr + "/register_worker",
            json={"worker_name": self.worker_addr, "check_heart_beat": True,
                  "worker_status": self.status()},
        )

    async def heartbeat_loop(self) -> None:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            try:
                await self.register(session)
            except Exception:
                pass
            while True:
                await asyncio.sleep(HEARTBEAT_INTERVAL_S)
                try:
                    r = await session.post(
                        self.controller_addr + "/receive_heart_beat",
                        json={"worker_name": self.worker_addr,
                              "queue_length": self.in_flight},
                    )
                    if not (await r.json()).get("exist", True):
                        await self.register(session)
                except Exception:
                    pass  # controller down: keep serving, retry next beat

    # -- generation ---------------------------------------------------------

    def _make_request(self, params: dict) -> tuple[Request, int]:
        prompt = params["prompt"]
        ids = self.tok(prompt)["input_ids"]
        stop = params.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        stop_ids = tuple(params.get("stop_token_ids") or ())
        temperature = float(params.get("temperature", 1.0))
        if not bool(params.get("do_sample", temperature > 0)):
            temperature = 0.0
        tk = int(params.get("top_k", -1))
        # W3C trace context rides the worker protocol's JSON params (the
        # protocol is body-shaped; HTTP callers may also put the header
        # value here) — the engine's spans then key to the caller's trace
        tp = parse_traceparent(params.get("traceparent"))
        req = Request(
            prompt_ids=list(map(int, ids)),
            max_new_tokens=int(params.get("max_new_tokens", 256)),
            temperature=temperature,
            top_p=float(params.get("top_p", 1.0)),
            top_k=0 if tk <= 0 else tk,
            eos_token_id=tuple(self._eos) + stop_ids,
            stop_strings=list(stop),
            trace_id=tp[0] if tp else None,
        )
        return req, len(ids)

    async def _next_tok(self, req: Request) -> int | None:
        """Bounded-wait token fetch via the engine's shared dead-engine-
        detecting protocol: fails the request with an error chunk instead
        of hanging the client."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, next_stream_item,
                                          self.engine, req)

    async def _stream_chunks(self, params: dict):
        """Yield the protocol's cumulative-text JSON chunks."""
        self.call_ct += 1
        self.in_flight += 1
        req = None
        try:
            req, n_in = self._make_request(params)
            echo = bool(params.get("echo", True))
            base = params["prompt"] if echo else ""
            try:
                self.engine.submit(req)
            except EngineOverloaded as e:
                # load-shed in the protocol's own shape: a non-zero
                # error_code chunk makes the controller retry elsewhere
                req = None
                yield {"text": f"worker overloaded: {e}",
                       "error_code": ERROR_CODE_OVERLOADED,
                       "finish_reason": "abort"}
                return
            toks: list[int] = []
            while True:
                tok = await self._next_tok(req)
                if tok is None:
                    break
                toks.append(tok)
                yield {
                    "text": base + self.tok.decode(
                        toks, skip_special_tokens=True),
                    "error_code": 0,
                    "usage": {"prompt_tokens": n_in,
                              "completion_tokens": len(toks),
                              "total_tokens": n_in + len(toks)},
                    "finish_reason": None,
                }
            shed = req.finish_reason == "abort" and not req.cancelled
            if req.finish_reason in ("error", "timeout") or shed:
                # drain-deadline shed surfaces as overloaded (non-zero
                # error_code -> the controller retries another worker),
                # never as a 200 with truncated text
                text, code = {
                    "timeout": ("request deadline exceeded",
                                ERROR_CODE_TIMEOUT),
                    "abort": ("worker draining: request aborted",
                              ERROR_CODE_OVERLOADED),
                }.get(req.finish_reason,
                      ("request failed in the engine", ERROR_CODE_INTERNAL))
                yield {"text": text, "error_code": code,
                       "finish_reason": req.finish_reason}
                return
            yield {
                "text": base + self.tok.decode(toks, skip_special_tokens=True),
                "error_code": 0,
                "usage": {"prompt_tokens": n_in,
                          "completion_tokens": len(toks),
                          "total_tokens": n_in + len(toks)},
                "finish_reason": req.finish_reason or "stop",
            }
        finally:
            self.in_flight -= 1
            # consumer vanished mid-stream (client disconnect raised out of
            # the generator): free the engine row instead of decoding the
            # rest of max_new_tokens into an orphaned queue
            if req is not None and req.finish_reason is None:
                self.engine.abort(req)

    # -- HTTP endpoints -----------------------------------------------------

    async def api_generate_stream(self, request: web.Request):
        params = await request.json()
        resp = web.StreamResponse()
        await resp.prepare(request)
        async for chunk in self._stream_chunks(params):
            await resp.write(json.dumps(chunk).encode() + b"\0")
        await resp.write_eof()
        return resp

    async def api_generate(self, request: web.Request):
        params = await request.json()
        last = None
        async for chunk in self._stream_chunks(params):
            last = chunk
        return web.json_response(last)

    async def api_get_status(self, request: web.Request):
        return web.json_response(self.status())

    async def api_count_token(self, request: web.Request):
        params = await request.json()
        n = len(self.tok(params["prompt"])["input_ids"])
        return web.json_response({"count": n, "error_code": 0})

    async def api_model_details(self, request: web.Request):
        ctx = getattr(self.engine.cfg, "max_position_embeddings", 4096)
        return web.json_response({"context_length": ctx})

    async def api_conv_template(self, request: web.Request):
        # templating lives client-side for this worker (one_shot default)
        return web.json_response({"conv": None})

    async def api_trace(self, request: web.Request):
        tid = request.match_info["trace_id"]
        tr = self.engine.trace_view(tid)
        if tr is None:
            return web.json_response(
                {"error": f"unknown trace {tid!r} (tracing disabled, or "
                          "aged out)", "error_code": ERROR_CODE_INTERNAL},
                status=404)
        if request.query.get("format") == "chrome":
            return web.json_response(Tracer.chrome_events([tr]))
        return web.json_response(tr)

    async def api_flight(self, request: web.Request):
        return web.json_response(self.engine.flight.view())

    async def api_perf(self, request: web.Request):
        from ipex_llm_tpu.ops.dispatch import ladder_provenance

        return web.json_response({"perf": self.engine.perf_view(),
                                  "dispatch": ladder_provenance(),
                                  "planner": self.engine.planner_view()})


def build_worker(model_path: str, low_bit: str = "sym_int4",
                 controller_addr: str | None = None,
                 worker_addr: str = "http://localhost:21002",
                 model_names: list[str] | None = None,
                 limit_worker_concurrency: int = 8,
                 drain_timeout_s: float = 30.0,
                 engine_config: EngineConfig | None = None) -> FastChatWorker:
    from transformers import AutoTokenizer

    from ipex_llm_tpu.transformers import AutoModelForCausalLM

    # the serving stack owns the weight-width axis end to end: both
    # halves of the rule live in serving/engine.py — a pinned
    # EngineConfig.weight_qtype outranks low_bit for the LOAD
    # (resolve_load_low_bit), and the loaded width threads back into the
    # config for truthful /health (default_weight_qtype).  The max_rows
    # fallback must be applied BEFORE the defaulting rule so an absent
    # engine_config still sizes the engine to the worker's concurrency
    # limit (FastChatWorker's own `or` fallback never fires once a
    # config object exists).
    from ipex_llm_tpu.serving.engine import (default_weight_qtype,
                                             resolve_load_low_bit)

    load_q = resolve_load_low_bit(engine_config, low_bit)
    model = AutoModelForCausalLM.from_pretrained(model_path,
                                                 load_in_low_bit=load_q)
    tok = AutoTokenizer.from_pretrained(model_path, trust_remote_code=True)
    names = model_names or [model_path.rstrip("/").split("/")[-1]]
    ec = default_weight_qtype(
        engine_config or EngineConfig(max_rows=limit_worker_concurrency),
        load_q)
    return FastChatWorker(model, tok, names, controller_addr, worker_addr,
                          limit_worker_concurrency,
                          engine_config=ec,
                          drain_timeout_s=drain_timeout_s)


def main(argv=None):
    ap = argparse.ArgumentParser("ipex-llm-tpu FastChat model worker")
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=21002)
    ap.add_argument("--controller-address", default="http://localhost:21001")
    ap.add_argument("--worker-address", default=None)
    ap.add_argument("--model-names", default=None)
    ap.add_argument("--limit-worker-concurrency", type=int, default=8)
    ap.add_argument("--weight-qtype", default=None, metavar="QTYPE",
                    help="serving weight width (default: --low-bit), "
                         "authoritative end to end: the checkpoint loads "
                         "at this width, full-width weights re-pack at "
                         "engine build, and the fused tick reads packed "
                         "codes with dequant fused into the matmul")
    ap.add_argument("--kv-storage", default="bf16",
                    choices=("bf16", "fp8"), metavar="FMT",
                    help="paged KV pool storage: bf16 (default) or fp8 "
                         "e5m2 (half the KV bytes, twice the pages per "
                         "byte budget; slightly lossy)")
    ap.add_argument("--kv-pool-bytes", type=int, default=0, metavar="BYTES",
                    help="KV pool byte budget (page count derived from "
                         "bytes / page size at --kv-storage width; 0 = "
                         "auto page sizing)")
    ap.add_argument("--spec-k", type=int, default=0, metavar="K",
                    help="on-device prompt-lookup speculative serving "
                         "(reference ipex_llm_worker `speculative` load "
                         "flag): draft/verify/accept up to K candidates "
                         "per row per decode step inside the fused tick; "
                         "composes with --decode-horizon")
    ap.add_argument("--spec-ngram", type=int, default=3, metavar="N",
                    help="longest n-gram the speculative lookup proposer "
                         "matches against the row's token history")
    ap.add_argument("--decode-horizon", type=int, default=1, metavar="H",
                    help="fused multi-step decode: H decode steps per "
                         "device program, one host sync per H tokens")
    ap.add_argument("--planner", default="mpc", choices=("mpc", "static"),
                    help="tick planner (serving/planner.py): mpc (default) "
                         "re-picks the tick shape — chunk budget, decode "
                         "horizon, spec widths, admission — per tick for "
                         "deadline goodput, within the locked grid; "
                         "static = the fixed-knob escape hatch "
                         "(bit-identical to the pre-planner engine)")
    ap.add_argument("--trace", action="store_true",
                    help="request-lifecycle tracing (per-request spans "
                         "staged in the transactional tick; /trace/{id} "
                         "and the caller's traceparent honored)")
    ap.add_argument("--no-register", action="store_true")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    metavar="SECONDS",
                    help="graceful-drain window on SIGTERM: stop admission, "
                         "finish in-flight requests, abort stragglers")
    args = ap.parse_args(argv)
    worker_addr = args.worker_address or f"http://localhost:{args.port}"
    names = args.model_names.split(",") if args.model_names else None
    w = build_worker(args.model_path, args.low_bit,
                     None if args.no_register else args.controller_address,
                     worker_addr, names, args.limit_worker_concurrency,
                     drain_timeout_s=args.drain_timeout,
                     engine_config=EngineConfig(
                         max_rows=args.limit_worker_concurrency,
                         weight_qtype=args.weight_qtype,
                         kv_storage=args.kv_storage,
                         kv_pool_bytes=args.kv_pool_bytes,
                         spec_k=args.spec_k, spec_ngram=args.spec_ngram,
                         decode_horizon=args.decode_horizon,
                         trace_requests=args.trace,
                         planner=args.planner))
    if w.controller_addr:
        async def on_start(app):
            app["hb"] = asyncio.create_task(w.heartbeat_loop())

        w.app.on_startup.append(on_start)
    web.run_app(w.app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
