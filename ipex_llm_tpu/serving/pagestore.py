"""Host-RAM KV page store: the spill tier between the device page pool
and the replica fleet.

The device pool (kv.PagedKVCache + engine.PageAllocator) treats a prefix
page evicted under pressure as LOST — the next request that would have
hit it recomputes the prefill (``prefix_evictions`` is exactly that
future-miss trace).  The reference's whole identity is making models fit
hardware they shouldn't (low-bit weights, FlashMoE host-RAM experts);
this module applies the same move to paged KV: an evicted prefix page
(and, at finish, a completed row's decode pages — the multi-turn
follow-up's prefix) DEMOTES to a byte-budgeted host-RAM LRU instead of
vanishing, and swaps back through the audited ``hostutil.h2d/d2h``
boundary on the next prefix hit — a PCIe copy instead of a recompute.

Contracts:

- **byte identity**: entries hold the pool's own storage bytes (e5m2
  codes for fp8 pools, bf16 halves for bf16 pools) captured with ``d2h``
  and restored with ``h2d`` + ``PagedKVCache.scatter_pages`` — a
  swapped-in page is byte-identical to one that never left the pool.
  (``spill_storage="fp8"`` opts a bf16 pool into e5m2-recoded spill
  entries — half the host bytes, lossy like the fp8 pool itself.)
- **budget**: ``hostutil.HostLRU`` — the same evict-to-fit accounting
  ``offload.ExpertStore`` budgets HBM experts with — bounds resident
  host bytes; spilling never grows without limit.
- **transactionality**: the engine's checkpoint/rollback covers the
  store (``snapshot``/``restore`` are O(entries) bookkeeping copies —
  blobs are immutable), so a rolled-back tick leaves no spill residue
  and a rolled-back swap-in puts the consumed entry back.

The store itself is pure host bookkeeping — no device calls, no jitted
programs (the gathers/scatters live at the engine's epoch boundaries;
JP106's one-dispatch tick is untouched).
"""

from __future__ import annotations

from collections import deque

import numpy as np

from ipex_llm_tpu.hostutil import HostLRU

__all__ = ["PageStore"]


class PageStore:
    """Byte-budgeted host LRU of spilled KV pages, keyed by the engine's
    chained prefix hash (a key commits to every token before it, so equal
    keys imply equal page contents — the property that makes a host
    entry substitutable for the device page it came from)."""

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError("PageStore needs a positive byte budget")
        self.lru = HostLRU(budget_bytes)
        # lifetime counters (swap_ins / lookups are the affinity tier's
        # freshness signal; see router._affinity_fresh)
        self.spills = 0          # pages demoted into the store
        self.swap_ins = 0        # pages promoted back into the pool
        self.swap_in_lookups = 0  # prefix-chain probes against the store
        # rolling swap-in latency window for /health (seconds per page)
        self.swap_in_s: "deque[float]" = deque(maxlen=128)
        # snapshot memoization: the engine checkpoints the store EVERY
        # tick, but most ticks never touch it — re-copying the whole LRU
        # bookkeeping per tick would make tick latency scale with tier
        # occupancy.  A mutation counter keys a cached snapshot, so an
        # untouched-store checkpoint is O(1) and only mutating ticks pay
        # the O(entries) copy.
        self._mut = 0
        self._snap: "tuple[int, dict] | None" = None

    # -- spill / swap-in ----------------------------------------------------

    @staticmethod
    def _nbytes(k_page: np.ndarray, v_page: np.ndarray) -> int:
        return k_page.nbytes + v_page.nbytes

    def spill(self, key: bytes, k_page: np.ndarray, v_page: np.ndarray):
        """Demote one page's host copy ([L, Hkv, page, D] k and v, pool
        storage dtype) into the LRU under the byte budget."""
        self._mut += 1
        self.spills += 1
        self.lru.put(key, (k_page, v_page), self._nbytes(k_page, v_page))

    def take(self, key: bytes):
        """Consume the entry for ``key`` (None = miss), counting the
        lookup; the caller scatters it back into the pool and records the
        latency via ``record_swap_in`` — or hands it back via ``untake``
        if the promotion could not complete (dry pool, rollback)."""
        self._mut += 1
        self.swap_in_lookups += 1
        if key not in self.lru:
            return None
        return self.lru.pop(key)

    def untake(self, key: bytes, entry):
        """Return a consumed entry unchanged (failed promotion)."""
        self._mut += 1
        k_page, v_page = entry
        self.lru.put(key, entry, self._nbytes(k_page, v_page))

    def record_swap_in(self, seconds: float, pages: int = 1):
        """Count ``pages`` promoted in one timed promotion (the engine
        batches a whole prefix chain into one scatter + one completion
        barrier, so one latency figure can cover several pages —
        ``swap_ins`` stays per-page so ``swap_in_hit_rate`` against the
        per-page ``swap_in_lookups`` stays honest)."""
        self._mut += 1
        self.swap_ins += pages
        self.swap_in_s.append(seconds)

    def peek(self, key: bytes):
        """Non-consuming, non-counting read (the export path serves
        spilled pages without disturbing swap-in economics).  Truly
        side-effect-free: no hit/miss accounting and — crucially — no
        ``_mut`` bump, so an export does NOT invalidate the snapshot
        memo and the next (untouched-store) checkpoint stays O(1).
        (It used to route through ``lru.get`` and advance ``_mut``,
        which re-copied the whole store bookkeeping on the tick after
        every export — the ROADMAP item 1 follow-up.)"""
        return self.lru.peek(key)

    # -- observability ------------------------------------------------------

    def stats(self) -> dict:
        """The /health kv-block spill counters (flat numeric keys so the
        replica's /metrics exposition and the router's fleet aggregation
        pick them up unchanged).  Called from the HTTP thread while the
        engine thread mutates the store, so the latency window is copied
        with ``list()`` — one GIL-atomic C-level copy, the same
        mutation-during-iteration guard api_server uses for the metrics
        dict — before any Python-level iteration."""
        vals = list(self.swap_in_s)
        win = np.asarray(vals, np.float64) if vals else None
        return {
            "spill_enabled": True,
            "spill_budget_bytes": self.lru.budget,
            "spill_bytes": self.lru.used,
            "spill_pages": len(self.lru),
            "spills": self.spills,
            "spill_lru_evictions": self.lru.evictions,
            "swap_ins": self.swap_ins,
            "swap_in_lookups": self.swap_in_lookups,
            "swap_in_hit_rate": round(
                self.swap_ins / self.swap_in_lookups, 4)
            if self.swap_in_lookups else 0.0,
            "swap_in_p95_s": round(float(np.percentile(win, 95)), 5)
            if win is not None else 0.0,
        }

    # -- transactionality ---------------------------------------------------

    def snapshot(self) -> dict:
        """Memoized on the mutation counter: checkpointing an untouched
        store returns the cached copy (O(1)); only a tick that actually
        spilled/swapped pays the O(entries) bookkeeping copy."""
        if self._snap is None or self._snap[0] != self._mut:
            self._snap = (self._mut, {
                "lru": self.lru.snapshot(),
                "spills": self.spills,
                "swap_ins": self.swap_ins,
                "swap_in_lookups": self.swap_in_lookups,
                "swap_in_s": list(self.swap_in_s),
            })
        return self._snap[1]

    def restore(self, snap: dict):
        self.lru.restore(snap["lru"])
        self.spills = snap["spills"]
        self.swap_ins = snap["swap_ins"]
        self.swap_in_lookups = snap["swap_in_lookups"]
        self.swap_in_s = deque(snap["swap_in_s"],
                               maxlen=self.swap_in_s.maxlen)
        # the restored state IS this snapshot: re-key the memo so the
        # next (unmutated) checkpoint reuses it instead of re-copying
        self._snap = (self._mut, snap)
