"""OpenAI-compatible HTTP server (aiohttp) over the ServingEngine.

Reference counterpart: serving/fastapi/api_server.py:90 (+openai_protocol.py)
— same endpoints (`/v1/chat/completions`, `/v1/completions`, `/v1/models`),
same SSE streaming shape (``data: {chunk}\\n\\n`` … ``data: [DONE]``).
FastAPI isn't available in this image; aiohttp.web provides the async server.

Run: ``python -m ipex_llm_tpu.serving.api_server --model <dir> --port 8000``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid
from typing import Any

try:
    from aiohttp import web
except ImportError as _e:  # pragma: no cover
    web = None
    _AIOHTTP_ERR = _e

from ipex_llm_tpu.serving.engine import (EngineConfig, Request,
                                         ServingEngine, next_stream_item)
from ipex_llm_tpu.serving.faults import EngineOverloaded
from ipex_llm_tpu.serving.kv_transport import TransportError
from ipex_llm_tpu.serving.observe import Tracer, parse_traceparent


def _now() -> int:
    return int(time.time())


def _req_failed(req: Request) -> bool:
    """True when the request's terminal state is a server-side failure the
    client must see as an error object: an engine fault, an expired
    deadline, or a server-initiated abort (drain-deadline shed).  A
    client-initiated abort (``req.cancelled`` — disconnect or stop-string)
    is not a failure: the client asked for it."""
    return (req.finish_reason in ("error", "timeout")
            or (req.finish_reason == "abort" and not req.cancelled))


class OpenAIServer:
    def __init__(self, engine: ServingEngine, tokenizer, model_name: str,
                 asr=None, drain_timeout_s: float = 30.0,
                 kv_import_token: str | None = None,
                 profile_dir: str | None = None):
        if web is None:  # pragma: no cover
            raise ImportError(f"aiohttp is required for serving: {_AIOHTTP_ERR}")
        self.engine = engine
        self.tok = tokenizer
        self.model_name = model_name
        self.drain_timeout_s = drain_timeout_s
        # shared-token authn for /kv/import (X-KV-Import-Token): the wire
        # format's checksum proves INTEGRITY, not identity — without a
        # token any caller that can reach the port can scatter
        # checksum-consistent garbage into the shared prefix cache and
        # poison every future prefix hit.  None = open (single-tenant /
        # loopback deployments).
        self.kv_import_token = kv_import_token
        # /debug/profile capture target (a fresh temp dir per capture
        # when None)
        self.profile_dir = profile_dir
        # replica identity for the router tier: a stable uuid for this
        # server's lifetime (a restart mints a new one — that is the
        # point: the router can tell "same process recovered" from
        # "process was replaced"), plus the uptime epoch the /health
        # replica block reports against
        self.replica_id = uuid.uuid4().hex
        self.started_monotonic = time.monotonic()
        # asr = (whisper model, feature extractor, tokenizer) enabling the
        # OpenAI audio surface (the reference serves whisper through its
        # workers; SURVEY L6 lists the audio endpoint)
        self.asr = asr
        # client_max_size: aiohttp's 1 MiB default would 413 every
        # realistically-sized /kv/import page-set blob (one 7B-shaped
        # bf16 page is ~64 MiB) and silently break the disaggregated
        # handoff in production; 1 GiB bounds a whole long-prompt page
        # set while still refusing pathological bodies
        self.app = web.Application(client_max_size=1 << 30)
        self.app.router.add_post("/v1/chat/completions", self.chat)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_get("/v1/models", self.models)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/metrics", self.metrics)
        # TGI-protocol surface (reference serving/fastchat/tgi_api_server.py)
        self.app.router.add_post("/generate", self.tgi_generate)
        self.app.router.add_post("/generate_stream", self.tgi_generate_stream)
        # transportable-KV surface (disaggregated prefill/decode): the
        # router's handoff orchestration drives these two legs
        self.app.router.add_post("/kv/prefill", self.kv_prefill)
        self.app.router.add_post("/kv/import", self.kv_import)
        # observability surface (serving/observe.py): per-request traces
        # (assembled fleet-wide by the router), the tick flight recorder,
        # and an operational jax.profiler capture window
        self.app.router.add_get("/trace/{trace_id}", self.trace_get)
        self.app.router.add_get("/debug/traces", self.traces_export)
        self.app.router.add_get("/debug/flight", self.debug_flight)
        self.app.router.add_get("/debug/profile", self.debug_profile)
        if asr is not None:
            self.app.router.add_post("/v1/audio/transcriptions",
                                     self.transcriptions)
        # graceful drain on SIGTERM/SIGINT: aiohttp's run_app shutdown
        # sequence awaits on_shutdown before tearing connections down, so
        # in-flight requests finish inside the drain window while /health
        # reports "draining"
        self.app.on_shutdown.append(self._on_shutdown)

    async def _on_shutdown(self, app):
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.engine.drain,
                                   self.drain_timeout_s)
        self.engine.stop()

    # -- helpers ------------------------------------------------------------

    def _encode_chat(self, messages: list[dict]) -> list[int]:
        if hasattr(self.tok, "apply_chat_template") and getattr(
            self.tok, "chat_template", None
        ):
            return list(self.tok.apply_chat_template(
                messages, add_generation_prompt=True
            ))
        text = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
        text += "\nassistant:"
        return list(self.tok(text)["input_ids"])

    def _mk_request(self, body: dict, prompt_ids: list[int],
                    headers=None) -> Request:
        def num(key, default, cast):
            v = body.get(key)
            return cast(default if v is None else v)

        # W3C trace context: the real HTTP header wins (curl/OTel
        # clients), the body field is the router's transport-agnostic
        # carrier (HTTPBackend promotes it to the header; scripted
        # backends deliver it in-body) — either way the engine's spans
        # key to the caller's trace id and /trace assembles end to end
        tp = parse_traceparent((headers or {}).get("traceparent")
                               or body.get("traceparent"))

        eos: tuple[int, ...] = ()
        if self.tok.eos_token_id is not None:
            eos = (int(self.tok.eos_token_id),)
        req = Request(
            prompt_ids=prompt_ids,
            max_new_tokens=num("max_tokens", 128, int),
            # OpenAI API defaults: temperature=1.0, top_p=1.0 (clients
            # relying on the documented default expect sampled output)
            temperature=num("temperature", 1.0, float),
            top_p=num("top_p", 1.0, float),
            top_k=int(body.get("top_k") or 0),
            seed=(int(body["seed"]) if body.get("seed") is not None
                  else None),
            eos_token_id=eos,
            request_id=str(uuid.uuid4()),
            # per-request wall-clock budget (queue wait + generation); the
            # router stamps each failover attempt's REMAINING budget here,
            # so a deadline spans attempts instead of resetting per replica
            deadline_s=(float(body["deadline_s"])
                        if body.get("deadline_s") else None),
            trace_id=tp[0] if tp else None,
        )
        stop = body.get("stop")
        req.stop_strings = ([stop] if isinstance(stop, str) else stop) or []
        return req

    @staticmethod
    def _find_stop(text: str, stops: list[str]) -> int:
        """Earliest stop-sequence offset in ``text``, or -1."""
        hits = [text.find(s) for s in stops if s and text.find(s) >= 0]
        return min(hits) if hits else -1

    # Internal finish reasons: engine "stop" (EOS) / "length" / "abort" /
    # "error" (quarantined or engine failure) / "timeout" (deadline), plus
    # server-side "stop_string" for stop-sequence truncation.  The OpenAI
    # surface maps stop_string -> "stop" and surfaces error/timeout — and
    # a server-initiated abort (drain-deadline shed, _req_failed) — as
    # JSON error objects (HTTP 500/408/503, or a terminal SSE error
    # event); the TGI surface maps stop -> "eos_token", stop_string ->
    # "stop_sequence" and failures to its {"error", "error_type"} shape.
    @staticmethod
    def _openai_reason(fr: str | None) -> str | None:
        return "stop" if fr == "stop_string" else fr

    def _retry_after_s(self, e: EngineOverloaded) -> int:
        """Honest Retry-After for a shed submission.  Draining: the rest
        of the drain window (by then the replica has restarted or shed
        everything).  Queue full: the backlog in units of engine waves —
        a queue of depth D in front of an R-row engine clears in about
        D/R admission waves; clamped to [1, 30] so a burst never tells
        clients to go away for minutes."""
        if e.draining:
            return max(1, int(self.engine.drain_seconds_left) + 1)
        rows = max(1, self.engine.ec.max_rows)
        return max(1, min(30, -(-e.queue_depth // rows)))

    def _submit(self, req: Request) -> Request:
        """Engine submit with load-shedding mapped onto HTTP: a full
        bounded queue is 429 (retryable overload), a draining engine is
        503 (this replica is going away) — both as OpenAI-style error
        objects with Retry-After derived from queue depth / the drain
        deadline (the router's backpressure signal)."""
        try:
            return self.engine.submit(req)
        except EngineOverloaded as e:
            body = json.dumps({"error": {
                "message": str(e),
                "type": "overloaded_error",
                "code": "engine_draining" if e.draining else "queue_full",
                "queue_depth": e.queue_depth,
            }})
            cls = (web.HTTPServiceUnavailable if e.draining
                   else web.HTTPTooManyRequests)
            raise cls(text=body, content_type="application/json",
                      headers={"Retry-After": str(self._retry_after_s(e))})

    @staticmethod
    def _error_payload(req: Request) -> dict:
        if req.finish_reason == "timeout":
            return {"error": {"message": "request deadline exceeded "
                                         "(queue wait + generation)",
                              "type": "timeout_error", "code": "timeout"}}
        if req.finish_reason == "abort":
            return {"error": {"message": "request aborted: server "
                                         "draining (retry elsewhere)",
                              "type": "unavailable_error",
                              "code": "server_draining"}}
        return {"error": {"message": "request failed in the engine "
                                     "(isolated fault)",
                          "type": "server_error", "code": "error"}}

    def _error_response(self, req: Request):
        status = {"timeout": 408, "abort": 503}.get(req.finish_reason, 500)
        return web.json_response(self._error_payload(req), status=status)

    async def _next_tok(self, req: Request) -> int | None:
        """One token from the stream queue via the engine's shared
        dead-engine-detecting fetch (replaces the queue.get-with-no-
        timeout hang)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, next_stream_item,
                                          self.engine, req)

    async def _collect(self, req: Request) -> str:
        toks: list[int] = []
        drop = set(req.eos_token_id)
        stops = getattr(req, "stop_strings", [])
        while True:
            tok = await self._next_tok(req)
            if tok is None:
                break
            if tok in drop:
                continue
            toks.append(tok)
            if stops:
                text = self.tok.decode(toks)
                cut = self._find_stop(text, stops)
                if cut >= 0:
                    self.engine.abort(req)
                    req.finish_reason = "stop_string"
                    return text[:cut]
        return self.tok.decode(toks)

    async def _stream_sse(self, request, req: Request, chunk_fn,
                          final_fn=None, send_done: bool = True,
                          error_fn=None):
        """Shared SSE streaming loop (OpenAI and TGI surfaces).

        ``chunk_fn(piece, finish, tok)`` renders one incremental event;
        ``final_fn(sent_text, finish_reason)`` (optional) renders the
        terminal event instead of ``chunk_fn("", finish, None)``;
        ``error_fn(req)`` (optional) renders the terminal error event for
        an "error"/"timeout" finish in the surface's own error shape."""
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        drop = set(req.eos_token_id)
        stops = getattr(req, "stop_strings", [])
        sent = ""
        toks: list[int] = []
        try:
            while True:
                tok = await self._next_tok(req)
                if tok is None:
                    break
                if tok in drop:
                    continue
                toks.append(tok)
                text = self.tok.decode(toks)
                cut = self._find_stop(text, stops) if stops else -1
                if cut >= 0:
                    piece, done = text[:cut][len(sent):], True
                else:
                    piece, done = text[len(sent):], False
                if piece:
                    sent += piece
                    await resp.write(
                        f"data: {json.dumps(chunk_fn(piece, None, tok))}\n\n"
                        .encode()
                    )
                if done:
                    self.engine.abort(req)
                    req.finish_reason = "stop_string"
                    break
            if _req_failed(req):
                # terminal error event (the stream already carries a 200
                # status line; an error object in the stream is the OpenAI
                # streaming convention)
                err = (error_fn or self._error_payload)(req)
                await resp.write(f"data: {json.dumps(err)}\n\n".encode())
            else:
                final = (final_fn(sent, req.finish_reason) if final_fn
                         else chunk_fn("", req.finish_reason, None))
                await resp.write(f"data: {json.dumps(final)}\n\n".encode())
            if send_done:
                await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: free the engine row instead of decoding on
            self.engine.abort(req)
            raise
        return resp

    # -- endpoints ----------------------------------------------------------

    async def chat(self, request):
        body = await request.json()
        ids = self._encode_chat(body.get("messages", []))
        rf = body.get("response_format") or {}
        if rf.get("type") in ("json_object", "json_schema"):
            # constrained decoding runs the offline validator-filtered path
            # (structured.py), bypassing the batch engine
            return await self._chat_json(body, ids)
        req = self._submit(self._mk_request(body, ids,
                                            request.headers))
        rid = f"chatcmpl-{req.request_id[:12]}"

        if body.get("stream"):
            def chunk(piece: str, finish, tok=None):
                delta = {"content": piece} if piece else {}
                return {
                    "id": rid, "object": "chat.completion.chunk",
                    "created": _now(), "model": self.model_name,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason":
                                     self._openai_reason(finish)}],
                }
            return await self._stream_sse(request, req, chunk)

        text = await self._collect(req)
        if _req_failed(req):
            return self._error_response(req)
        return web.json_response({
            "id": rid, "object": "chat.completion", "created": _now(),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": self._openai_reason(req.finish_reason),
            }],
            "usage": {
                "prompt_tokens": len(req.prompt_ids),
                "completion_tokens": len(req.output_ids),
                "total_tokens": len(req.prompt_ids) + len(req.output_ids),
            },
        })

    async def _chat_json(self, body: dict, ids: list[int]):
        rf = body.get("response_format") or {}
        import asyncio as _asyncio

        from ipex_llm_tpu.structured import generate_json

        loop = _asyncio.get_running_loop()
        text = await loop.run_in_executor(
            None,
            lambda: generate_json(
                self.engine.cfg, self.engine.params, self.tok, ids,
                max_new_tokens=int(body.get("max_tokens") or 256),
                schema=(rf.get("json_schema") or {}).get("schema"),
            ),
        )
        return web.json_response({
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion", "created": _now(),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": "stop",
            }],
            "usage": {"prompt_tokens": len(ids)},
        })

    async def completions(self, request):
        body = await request.json()
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0]
        ids = list(self.tok(prompt)["input_ids"])
        req = self._submit(self._mk_request(body, ids,
                                            request.headers))
        rid = f"cmpl-{req.request_id[:12]}"

        if body.get("stream"):
            def chunk(piece: str, finish, tok=None):
                return {
                    "id": rid, "object": "text_completion", "created": _now(),
                    "model": self.model_name,
                    "choices": [{"index": 0, "text": piece,
                                 "finish_reason":
                                     self._openai_reason(finish)}],
                }
            return await self._stream_sse(request, req, chunk)

        text = await self._collect(req)
        if _req_failed(req):
            return self._error_response(req)
        choice = {"index": 0, "text": text,
                  "finish_reason": self._openai_reason(req.finish_reason)}
        if body.get("logprobs"):
            # chosen-token logprobs (top-alternatives not tracked)
            choice["logprobs"] = {
                "tokens": [self.tok.decode([t]) for t in req.output_ids],
                "token_logprobs": [round(lp, 6) for lp in req.logprobs],
                "top_logprobs": None,
                "text_offset": [],
            }
        return web.json_response({
            "id": rid, "object": "text_completion", "created": _now(),
            "model": self.model_name,
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(req.prompt_ids),
                "completion_tokens": len(req.output_ids),
                "total_tokens": len(req.prompt_ids) + len(req.output_ids),
            },
        })

    async def models(self, request):
        return web.json_response({
            "object": "list",
            "data": [{"id": self.model_name, "object": "model",
                      "owned_by": "ipex_llm_tpu"}],
        })

    async def health(self, request):
        """Liveness that actually reflects the engine (failure-detection
        surface, SURVEY §5): dead engine thread -> 503; recent step errors
        surface as degraded; a draining engine (SIGTERM received, letting
        in-flight requests finish) reports "draining" so load balancers
        stop routing to this replica."""
        thread = self.engine._thread
        if thread is None or not thread.is_alive():
            return web.json_response(
                {"status": "dead", "error": "engine thread not running"},
                status=503)
        body = {"status": "ok"}
        last = self.engine.metrics.get("last_error")
        if last:
            body = {"status": "degraded", "last_error": str(last)}
        if self.engine.draining:
            body["status"] = "draining"
        # replica identity + liveness for the router tier: a stable uuid
        # (new per server start — distinguishes "recovered" from
        # "replaced"), uptime, and the committed-tick counter — `ticks`
        # frozen while `uptime_s` advances is the wedged-replica signal
        body["replica"] = {
            "replica_id": self.replica_id,
            "uptime_s": round(time.monotonic() - self.started_monotonic, 3),
            "ticks": self.engine.metrics.get("ticks", 0),
        }
        # host-sync economics of the fused decode horizon: tokens emitted
        # per blocking device->host sync, total seconds blocked, and the
        # horizon the last fused step actually ran (page pressure can
        # shorten it below EngineConfig.decode_horizon)
        m = self.engine.metrics
        body["decode"] = {
            "tokens_per_sync": m.get("tokens_per_sync", 0.0),
            "host_sync_s": m.get("host_sync_s", 0.0),
            "decode_horizon_effective": m.get("decode_horizon_effective", 0),
            # admission-wave economics (the mixed prefill+decode step):
            # mixed ticks run, prompt tokens batched per tick, and the
            # rolling TTFT p95 the step is sized against
            "mixed_steps": m.get("mixed_steps", 0),
            "prefill_tokens_per_step": m.get("prefill_tokens_per_step", 0.0),
            "ttft_p95_s": m.get("ttft_p95_s", 0.0),
        }
        # KV-pool economics: storage format and byte footprint, occupancy,
        # and the pressure trace (prefix-cache LRU evictions, allocation-
        # failure clamps) — what the fp8-vs-bf16 fixed-budget story is
        # operated on (capacity planning reads pool_bytes/pages_total,
        # incident triage reads the clamp/eviction counters)
        body["kv"] = self.engine.kv_stats()
        # weight-pool economics, side by side with the kv block: the two
        # byte lines (weights.weight_bytes + kv.pool_bytes) are the one
        # HBM budget an operator provisions — int4 weights hand their
        # saved bytes to the KV pool (more pages, more concurrent rows at
        # the same cap; see bench_weight_qtype)
        body["weights"] = self.engine.weight_stats()
        # multi-chip routing: which tick serves this mesh (the fully-
        # manual shard_map tick vs the per-op GSPMD fallback, with the
        # recorded reason), the collective wire family, and the per-shard
        # KV split — the first thing to read when a tp engine is slower
        # than expected (a silent GSPMD fallback looks like a perf bug)
        if self.engine.mesh is not None:
            eng = self.engine
            # per-shard bytes off the REAL placement (shard_paged_cache
            # head-shards the pool on the GSPMD path too, when heads
            # divide — dividing by tp only under the manual tick would
            # overreport fallback engines by tp)
            shard_bytes = (eng.cache.k.addressable_shards[0].data.nbytes
                           + eng.cache.v.addressable_shards[0].data.nbytes)
            body["parallel"] = {
                "mesh": dict(eng.mesh.shape),
                "tp_manual": eng._tp_manual,
                "tp_fallback_reason": eng._tp_fallback_reason,
                "collective_qtype": eng._collective_qtype,
                "kv_pool_bytes_per_shard": int(shard_bytes),
            }
        # device-time observatory (serving/perfwatch.py): per-family
        # attribution buckets + MFU/roofline join, and the recompile
        # sentinel — compiles_warm or compiles_out_of_grid advancing
        # mid-serving is the first thing to read when tick latency
        # develops multi-second spikes (a shape-driven recompile is
        # invisible in every other series)
        perf = self.engine.perf_view()
        if perf is not None:
            body["perf"] = perf
        # dispatch-ladder provenance: which measured microbench round
        # each Pallas-vs-XLA decision rests on — a stale ladder (builtin
        # rows date to BENCH_r05/r12) is visible instead of silently
        # trusted
        from ipex_llm_tpu.ops.dispatch import ladder_provenance
        body["dispatch"] = ladder_provenance()
        # fault-domain observability: admission backlog vs the bound (what
        # a 429 means), per-request failures isolated by bisection,
        # transient step retries, load-shed and deadline-expired counts
        body["fault_domain"] = {
            "queue_depth": self.engine.queue_depth,
            "max_queue": self.engine.ec.max_queue,
            "errors_isolated": m.get("errors_isolated", 0),
            "retries": m.get("retries", 0),
            "rejected": m.get("rejected", 0),
            "timeouts": m.get("timeouts", 0),
        }
        # speculative-decoding economics (spec_k > 0 engines): draft
        # counts, the rolling accept rate the operator tunes spec_k /
        # spec_ngram against, and tokens emitted per spec-tick dispatch —
        # the on-device draft+verify+accept loop's amortization story
        if self.engine.ec.spec_k > 0:
            body["spec"] = self.engine.spec_stats()
        # tick planner (serving/planner.py): the decide half of the
        # observe->decide loop — last plan, per-reason decision counts,
        # measured per-family step rates, and the deadline-miss rate the
        # planner is optimizing against.  mode "static" = the escape
        # hatch (PR 15 behavior, bit-identical)
        body["planner"] = self.engine.planner_view()
        return web.json_response(body)

    def _metrics_numeric(self) -> dict:
        """Flat numeric counter/gauge map: engine metrics + kv_ pool stats
        + replica liveness — the per-replica series the router aggregates."""
        # dict() first: a single GIL-atomic copy — the engine thread
        # inserts new counter keys at runtime, and iterating the live
        # dict races a "changed size during iteration" 500
        out = {k: v for k, v in dict(self.engine.metrics).items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
        for k, v in self.engine.kv_stats().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"kv_{k}"] = v
        for k, v in self.engine.weight_stats().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"weights_{k}"] = v
        # perfwatch counters (perf_ prefix): the recompile-sentinel
        # series (compiles_total/warm/out_of_grid are fleet-summable
        # true counters) + per-family attributed ticks/device seconds
        for k, v in self.engine.perf_numeric().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = v
        out["uptime_s"] = round(
            time.monotonic() - self.started_monotonic, 3)
        return out

    async def metrics(self, request):
        """Prometheus-style text exposition, every series labelled with
        this replica's stable id so a fleet scrape stays per-replica
        attributable, now including REAL histogram series
        (``_bucket``/``_sum``/``_count`` — TTFT, per-token latency, tick
        sync, swap-in); ``?format=json`` keeps the machine-readable shape
        the router's aggregation fetches and fleet-sums."""
        vals = self._metrics_numeric()
        hists = self.engine.histograms()
        if request.query.get("format") == "json":
            return web.json_response(
                {"replica_id": self.replica_id, "metrics": vals,
                 "histograms": {k: h.to_dict() for k, h in hists.items()}})
        lines = []
        for name in sorted(vals):
            lines.append(f'ipex_llm_tpu_{name}'
                         f'{{replica_id="{self.replica_id}"}} {vals[name]}')
        for name in sorted(hists):
            lines.extend(hists[name].prometheus_lines(
                f"ipex_llm_tpu_{name}",
                labels=f'replica_id="{self.replica_id}"'))
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    # -- transportable KV (disaggregated prefill/decode) --------------------

    def _body_prompt_ids(self, body: dict) -> list[int]:
        """Token ids for any surface's prompt shape (chat messages /
        completions prompt / TGI inputs) — the handoff legs must map a
        body to the SAME ids the eventual completion request will, or
        the exported pages' chain hashes won't match at admission."""
        if body.get("messages"):
            return self._encode_chat(body["messages"])
        p = body.get("prompt", body.get("inputs", ""))
        if isinstance(p, list):
            p = p[0] if p else ""
        return list(self.tok(str(p))["input_ids"])

    async def kv_prefill(self, request):
        """Handoff leg 1 (prefill replica): run the prompt through this
        engine — a one-token greedy generation, i.e. the prefill plus a
        throwaway first sample — then export the cached prefix pages as
        a transportable page set (serving/kv_transport.py), returned as
        application/octet-stream.  The decode replica that imports it
        re-derives the first token itself from the uncovered tail.  422
        when nothing is exportable (prompt under one page, or the pages
        already evicted with no spill tier to serve them)."""
        body = await request.json()
        wire = str(body.get("wire", "auto"))
        if wire not in ("auto", "fp8", "bf16"):
            return web.json_response(
                {"error": {"message": f"unknown wire format {wire!r}: "
                                      "one of auto, fp8, bf16",
                           "type": "invalid_request_error",
                           "code": "bad_wire_format"}}, status=400)
        ids = self._body_prompt_ids(body)
        if not ids:
            return web.json_response(
                {"error": {"message": "empty prompt",
                           "type": "invalid_request_error",
                           "code": "empty_prompt"}}, status=400)
        req = self._mk_request(
            dict(body, max_tokens=1, temperature=0.0, stream=False), ids)
        self._submit(req)
        while await self._next_tok(req) is not None:
            pass
        if _req_failed(req):
            return self._error_response(req)
        loop = asyncio.get_running_loop()
        blob = await loop.run_in_executor(
            None, self.engine.export_prefix, ids, wire)
        if blob is None:
            return web.json_response(
                {"error": {"message": "no full prefix page cached for "
                                      "this prompt",
                           "type": "invalid_request_error",
                           "code": "nothing_to_export"}}, status=422)
        return web.Response(body=blob,
                            content_type="application/octet-stream",
                            headers={"X-KV-Tokens": str(len(ids))})

    async def kv_import(self, request):
        """Handoff leg 2 (decode replica): verify + import a page set
        into this engine's pool and prefix cache, so the completion
        routed here next prefills only the uncovered tail.  Malformed
        blobs are 400 (``TransportError`` — unverified bytes are never
        scattered).  With ``--kv-import-token`` set, callers must
        present the shared token (X-KV-Import-Token): the blob checksum
        proves integrity, NOT identity — without authn any reachable
        caller could scatter checksum-consistent pages into the shared
        prefix cache and poison every future prefix hit."""
        if self.kv_import_token is not None:
            import hmac
            presented = request.headers.get("X-KV-Import-Token")
            # constant-time: a short-circuiting != leaks correct token
            # prefixes through 401 latency — exactly the caller this
            # check exists to keep out
            if not hmac.compare_digest(presented or "",
                                       self.kv_import_token):
                return web.json_response(
                    {"error": {"message": "missing or invalid "
                                          "X-KV-Import-Token",
                               "type": "authentication_error",
                               "code": "kv_import_unauthorized"}},
                    status=401)
        blob = await request.read()
        loop = asyncio.get_running_loop()
        try:
            res = await loop.run_in_executor(
                None, self.engine.import_pages, blob)
        except TransportError as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error",
                           "code": "bad_page_set"}}, status=400)
        return web.json_response(res)

    # -- observability (serving/observe.py) ---------------------------------

    async def trace_get(self, request):
        """One request's lifecycle trace (``?format=chrome`` renders the
        Chrome trace-event shape).  404 when tracing is off or the trace
        aged out of the bounded LRU; the router's /trace/{id} merges
        this replica's spans with its own and the other replicas'."""
        tid = request.match_info["trace_id"]
        tr = self.engine.trace_view(tid)
        if tr is None:
            return web.json_response(
                {"error": {"message": f"unknown trace {tid!r} (tracing "
                                      "disabled, or aged out)",
                           "type": "invalid_request_error",
                           "code": "unknown_trace"}}, status=404)
        if request.query.get("format") == "chrome":
            return web.json_response(Tracer.chrome_events([tr]))
        return web.json_response(tr)

    async def traces_export(self, request):
        """Whole-window trace export: every trace still in the LRU, as
        ids (default) or one Perfetto-loadable Chrome trace-event JSON
        (``?format=chrome``) — the grab-everything artifact for a latency
        investigation."""
        tracer = self.engine.tracer
        if tracer is None:
            return web.json_response(
                {"error": {"message": "tracing disabled (--trace / "
                                      "EngineConfig.trace_requests)",
                           "type": "invalid_request_error",
                           "code": "tracing_disabled"}}, status=404)
        if request.query.get("format") == "chrome":
            return web.json_response(tracer.export_chrome())
        return web.json_response({"trace_ids": tracer.trace_ids()})

    async def debug_flight(self, request):
        """The tick flight recorder: the last N working-tick records and
        any frozen postmortem dumps (_fail_all / quarantine capture one
        automatically) — what the SIGKILL and chaos gates previously had
        no artifact for."""
        return web.json_response(self.engine.flight.view())

    async def debug_profile(self, request):
        """Operational jax.profiler capture: trace this replica for
        ``?seconds=N`` (clamped; default 3) into ``?dir=`` (restricted
        to a subdirectory of --profile-dir — an unauthenticated caller
        must not get an arbitrary-filesystem-write primitive out of
        profiler artifacts) / ``--profile-dir`` / a fresh temp dir, via
        profiling.trace — xprof/tensorboard/Perfetto-loadable.  409 when
        a capture is already running (jax allows one at a time)."""
        import os
        import tempfile

        from ipex_llm_tpu import profiling

        try:
            seconds = float(request.query.get("seconds", 3.0))
        except ValueError:
            return web.json_response(
                {"error": {"message": "seconds must be a number",
                           "type": "invalid_request_error",
                           "code": "bad_seconds"}}, status=400)
        base = self.profile_dir
        log_dir = base or tempfile.mkdtemp(prefix="ipex-llm-tpu-profile-")
        sub = request.query.get("dir")
        if sub:
            if base is None:
                return web.json_response(
                    {"error": {"message": "?dir= requires --profile-dir "
                                          "(captures are confined to it)",
                               "type": "invalid_request_error",
                               "code": "no_profile_dir"}}, status=400)
            cand = os.path.realpath(os.path.join(base, sub))
            if cand != os.path.realpath(base) and not cand.startswith(
                    os.path.realpath(base) + os.sep):
                return web.json_response(
                    {"error": {"message": "?dir= must stay inside "
                                          "--profile-dir",
                               "type": "invalid_request_error",
                               "code": "bad_profile_dir"}}, status=400)
            log_dir = cand
        loop = asyncio.get_running_loop()
        try:
            res = await loop.run_in_executor(
                None, profiling.capture, log_dir, seconds)
        except RuntimeError as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "conflict_error",
                           "code": "capture_in_progress"}}, status=409)
        return web.json_response(res)

    # -- TGI protocol -------------------------------------------------------

    def _tgi_request(self, body: dict, headers=None) -> Request:
        """TGI shape: {"inputs": str, "parameters": {...}} (reference
        tgi_api_protocol.py ChatCompletionParam)."""
        p = body.get("parameters") or {}
        mapped = {
            "max_tokens": p.get("max_new_tokens", 64),
            "temperature": (p.get("temperature", 1.0)
                            if p.get("do_sample", False) else 0.0),
            "top_p": p.get("top_p", 1.0),
            "top_k": p.get("top_k", 0),
            "stop": p.get("stop"),
            "seed": p.get("seed"),
            "deadline_s": body.get("deadline_s"),
        }
        if body.get("traceparent"):
            mapped["traceparent"] = body["traceparent"]
        ids = list(self.tok(body.get("inputs", ""))["input_ids"])
        return self._mk_request(mapped, ids, headers)

    @staticmethod
    def _tgi_reason(fr: str | None) -> str:
        return {"stop": "eos_token", "stop_string": "stop_sequence"}.get(
            fr, fr or "length")

    @staticmethod
    def _tgi_error_payload(req: Request) -> dict:
        """TGI error shape: flat {"error", "error_type"}."""
        if req.finish_reason == "timeout":
            return {"error": "request deadline exceeded",
                    "error_type": "timeout"}
        if req.finish_reason == "abort":
            return {"error": "request aborted: server draining",
                    "error_type": "unavailable"}
        return {"error": "request failed in the engine (isolated fault)",
                "error_type": "generation"}

    async def tgi_generate(self, request):
        body = await request.json()
        req = self._submit(self._tgi_request(body, request.headers))
        text = await self._collect(req)
        if _req_failed(req):
            status = {"timeout": 408,
                      "abort": 503}.get(req.finish_reason, 500)
            return web.json_response(self._tgi_error_payload(req),
                                     status=status)
        return web.json_response({
            "generated_text": text,
            "details": {
                "finish_reason": self._tgi_reason(req.finish_reason),
                "generated_tokens": len(req.output_ids),
                "prefill": [],
            },
        })

    async def tgi_generate_stream(self, request):
        body = await request.json()
        req = self._submit(self._tgi_request(body, request.headers))

        def chunk(piece, finish, tok):
            n = len(req.output_ids)
            lp = req.logprobs[n - 1] if 0 < n <= len(req.logprobs) else 0.0
            return {"token": {"id": int(tok), "text": piece,
                              "logprob": round(float(lp), 6),
                              "special": False},
                    "generated_text": None}

        def final(sent, finish):
            return {"token": None, "generated_text": sent,
                    "details": {"finish_reason": self._tgi_reason(finish),
                                "generated_tokens": len(req.output_ids)}}

        return await self._stream_sse(request, req, chunk, final_fn=final,
                                      send_done=False,
                                      error_fn=self._tgi_error_payload)

    # -- audio (whisper) ----------------------------------------------------

    async def transcriptions(self, request):
        """OpenAI /v1/audio/transcriptions: multipart WAV in, text out."""
        import asyncio

        form = await request.post()
        part = form.get("file")
        if part is None:
            return web.json_response(
                {"error": {"message": "missing 'file' form field"}},
                status=400)
        data = part.file.read()
        asr_model, fe, asr_tok = self.asr

        def pipeline():
            """WAV decode + resample + mel features + generate — all off
            the event loop so concurrent SSE streams never stall."""
            import numpy as np

            samples, sr = _read_wav(data)
            want_sr = getattr(fe, "sampling_rate", 16000)
            if sr != want_sr:  # linear resample (no audio stack in image)
                n = int(len(samples) * want_sr / sr)
                samples = np.interp(
                    np.linspace(0, len(samples) - 1, n),
                    np.arange(len(samples)), samples).astype("float32")
            feats = fe(samples, sampling_rate=want_sr,
                       return_tensors="np")["input_features"]
            # the extractor pads to 30 s; clip to the encoder window
            feats = feats[:, :, :2 * asr_model.config.max_source_positions]
            return asr_model.generate(feats, max_new_tokens=224)

        loop = asyncio.get_running_loop()
        try:
            ids = await loop.run_in_executor(None, pipeline)
        except Exception as e:
            return web.json_response(
                {"error": {"message": f"only PCM WAV input is supported "
                                      f"in this build ({e})"}}, status=400)
        text = asr_tok.decode(list(map(int, ids[0])),
                              skip_special_tokens=True)
        return web.json_response({"text": text})


def _read_wav(data: bytes):
    """stdlib PCM WAV decode -> (float32 mono samples, sample_rate)."""
    import io
    import wave

    import numpy as np

    with wave.open(io.BytesIO(data), "rb") as w:
        sw = w.getsampwidth()
        nch = w.getnchannels()
        sr = w.getframerate()
        raw = w.readframes(w.getnframes())
    if sw == 2:
        x = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif sw == 4:
        x = np.frombuffer(raw, np.int32).astype(np.float32) / 2147483648.0
    elif sw == 1:
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported sample width {sw}")
    if nch > 1:
        x = x.reshape(-1, nch).mean(axis=1)
    return x, sr


def build_server(model_path: str, low_bit: str = "sym_int4",
                 engine_config: EngineConfig | None = None,
                 model=None, tokenizer=None,
                 asr_model_path: str | None = None,
                 tensor_parallel_size: int = 1,
                 drain_timeout_s: float = 30.0,
                 kv_import_token: str | None = None,
                 profile_dir: str | None = None) -> OpenAIServer:
    """``tensor_parallel_size`` > 1 serves under a tp mesh (SPMD AutoTP, the
    reference's vLLM-TP serving mode); a model already ``.shard(mesh)``-ed
    passes its mesh through implicitly.

    When build_server loads the checkpoint itself, ``low_bit`` threads
    into ``EngineConfig.weight_qtype`` (unless the caller's engine_config
    already pins one), so the SERVING stack owns the weight-width axis
    end to end and /health's ``weights`` block reports it.  A model
    handed in via ``model=`` keeps whatever width it carries — silently
    requantizing a caller's full-width tree would be a lossy surprise;
    such callers opt in via ``EngineConfig(weight_qtype=...)``."""
    from ipex_llm_tpu.transformers import AutoModelForCausalLM

    mesh = None
    if tensor_parallel_size > 1:
        from ipex_llm_tpu.parallel import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(tp=tensor_parallel_size))
    ec = engine_config or EngineConfig()
    if model is None:
        import os

        from ipex_llm_tpu.serving.engine import default_weight_qtype

        if os.path.exists(f"{model_path}/bigdl_config.json"):
            # a save_low_bit checkpoint carries ITS OWN width — thread
            # that, not the CLI --low-bit default, so a bf16 or nf4 save
            # is never silently requantized (and /health never reports a
            # width the tree does not hold)
            model = AutoModelForCausalLM.load_low_bit(model_path, mesh=mesh)
            ec = default_weight_qtype(ec, getattr(model, "qtype", None))
        else:
            # both halves of the width rule live in serving/engine.py: a
            # pinned EngineConfig.weight_qtype outranks --low-bit for the
            # LOAD (resolve_load_low_bit — the flag is authoritative end
            # to end), and the loaded width threads back into the config
            from ipex_llm_tpu.serving.engine import resolve_load_low_bit

            load_q = resolve_load_low_bit(ec, low_bit)
            model = AutoModelForCausalLM.from_pretrained(
                model_path, load_in_low_bit=load_q, mesh=mesh
            )
            ec = default_weight_qtype(ec, load_q)
    if tokenizer is None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(model_path,
                                                  trust_remote_code=True)
    engine = ServingEngine(
        model.config, model.params, ec,
        default_eos=model.generation_config.eos_token_id,
        mesh=mesh if mesh is not None else getattr(model, "mesh", None),
    ).start()
    asr = None
    if asr_model_path is not None:
        from transformers import AutoFeatureExtractor, AutoTokenizer

        from ipex_llm_tpu.models.whisper import (
            TPUWhisperForConditionalGeneration,
        )

        asr = (
            TPUWhisperForConditionalGeneration.from_pretrained(
                asr_model_path, load_in_low_bit=low_bit),
            AutoFeatureExtractor.from_pretrained(asr_model_path),
            AutoTokenizer.from_pretrained(asr_model_path),
        )
    return OpenAIServer(engine, tokenizer, model_name=model_path, asr=asr,
                        drain_timeout_s=drain_timeout_s,
                        kv_import_token=kv_import_token,
                        profile_dir=profile_dir)


def main(argv=None):
    ap = argparse.ArgumentParser("ipex-llm-tpu OpenAI-compatible server")
    ap.add_argument("--model", required=True)
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-rows", type=int, default=16)
    ap.add_argument("--max-seq-len", type=int, default=4096)
    ap.add_argument("--asr-model", default=None,
                    help="whisper checkpoint enabling /v1/audio/transcriptions")
    ap.add_argument("--collective-qtype", default=None,
                    choices=("bf16", "e5m2", "int8"), metavar="FAMILY",
                    help="AllReduce wire family for the manual-mesh tp "
                         "tick (ops/collectives.py): bf16 = exact (f32 "
                         "accumulate, tp2 bit-identical to single-chip); "
                         "e5m2/int8 = EQuARX-style quantized payloads, "
                         "bounded error for less ICI traffic.  Default: "
                         "the IPEX_LLM_TPU_COLLECTIVE_QTYPE env, else "
                         "bf16")
    ap.add_argument("--tensor-parallel-size", type=int, default=1,
                    help="serve under a tp mesh of this many chips")
    ap.add_argument("--spec-k", "--speculative", type=int, default=0,
                    metavar="K", dest="spec_k",
                    help="prompt-lookup speculative serving: draft, "
                         "verify, and accept up to K candidates per row "
                         "per decode step, ON DEVICE inside the fused "
                         "tick (reference ipex_llm_worker `speculative` "
                         "flag); composes with --decode-horizon; "
                         "accept rate in /health's spec block")
    ap.add_argument("--spec-ngram", type=int, default=3, metavar="N",
                    help="longest n-gram the speculative lookup proposer "
                         "matches against the row's token history")
    ap.add_argument("--decode-horizon", type=int, default=1, metavar="H",
                    help="fused multi-step decode: run H decode steps per "
                         "device program (one host sync per H tokens; "
                         "streaming granularity becomes up to H tokens, "
                         "times K+1 with --spec-k)")
    ap.add_argument("--planner", default="mpc", choices=("mpc", "static"),
                    help="tick planner (serving/planner.py): mpc (default) "
                         "re-picks chunk budget, decode horizon, per-row "
                         "spec widths, and admission count once per tick "
                         "to maximize predicted goodput (completed-under-"
                         "deadline tok/s), choosing only among manifest-"
                         "locked grid points; static = the pre-planner "
                         "fixed-knob behavior, bit-identical escape hatch. "
                         "/health's planner block shows the last plan and "
                         "decision counts")
    ap.add_argument("--step-token-budget", type=int, default=None,
                    metavar="B",
                    help="mixed prefill+decode step: per-tick token budget "
                         "during admission waves — prefill chunks for ALL "
                         "joining requests batch with the decode step in "
                         "one device program.  Default: the prefill "
                         "bucket; 0 reverts to sequential one-row-one-"
                         "chunk admission")
    ap.add_argument("--weight-qtype", default=None, metavar="QTYPE",
                    help="serving weight width (default: --low-bit), "
                         "authoritative end to end: the checkpoint loads "
                         "at this width (sym_int4/nf4/sym_int8/...; a "
                         "save_low_bit checkpoint keeps its own recorded "
                         "width), any full-width linear weights re-pack "
                         "at engine build, and the fused tick reads "
                         "packed codes with dequant fused into the "
                         "matmul — ~4.5 bits/weight of HBM traffic "
                         "instead of 16.  /health reports packed bytes + "
                         "bytes saved in its weights block")
    ap.add_argument("--kv-storage", default="bf16",
                    choices=("bf16", "fp8"), metavar="FMT",
                    help="paged KV pool storage format: bf16 (full width, "
                         "default) or fp8 (e5m2 codes — half the KV bytes "
                         "per token, twice the pages per byte budget; "
                         "slightly lossy vs bf16)")
    ap.add_argument("--kv-pool-bytes", type=int, default=0, metavar="BYTES",
                    help="KV pool byte budget: pool page count is derived "
                         "as BYTES / page_bytes(model, --kv-storage), so "
                         "fp8 automatically holds 2x the pages.  0 = size "
                         "in pages (the auto heuristic)")
    ap.add_argument("--kv-spill-bytes", type=int, default=0,
                    metavar="BYTES",
                    help="host-RAM KV spill tier budget: prefix pages "
                         "evicted under pool pressure (and finished "
                         "rows' decode pages) demote to a host LRU and "
                         "swap back on the next prefix hit instead of "
                         "being recomputed.  0 = off")
    ap.add_argument("--max-queue", type=int, default=256,
                    help="bounded admission queue: submissions beyond this "
                         "many waiting requests are load-shed with HTTP "
                         "429 (0 = unbounded)")
    ap.add_argument("--request-deadline", type=float, default=0.0,
                    metavar="SECONDS",
                    help="default per-request wall-clock deadline covering "
                         "queue wait + generation; an expired request "
                         "finishes with HTTP 408 (0 = no deadline)")
    ap.add_argument("--max-step-retries", type=int, default=3,
                    help="bounded retries (exponential backoff) for "
                         "transient device faults before the engine "
                         "bisects and quarantines the culprit request")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    metavar="SECONDS",
                    help="graceful-drain window on SIGTERM: stop admission "
                         "(503), let in-flight requests finish, then "
                         "abort stragglers")
    ap.add_argument("--trace", action="store_true",
                    help="request-lifecycle tracing: per-request spans "
                         "(queue wait, prefill chunks, swap-ins, first "
                         "token, decode horizons, spec rounds, retries, "
                         "finish) staged inside the transactional tick, "
                         "served at /trace/{id} and /debug/traces "
                         "(Chrome trace-event JSON via ?format=chrome); "
                         "honors/propagates W3C traceparent")
    ap.add_argument("--kv-import-token", default=None, metavar="TOKEN",
                    help="require this shared token (X-KV-Import-Token "
                         "header) on /kv/import: blob checksums prove "
                         "integrity, not identity — without a token the "
                         "shared prefix cache is poisonable by any "
                         "reachable caller.  The router forwards its "
                         "--kv-import-token on handoff legs")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="jax.profiler capture target for /debug/profile"
                         "?seconds=N (default: a fresh temp dir per "
                         "capture)")
    args = ap.parse_args(argv)
    srv = build_server(
        args.model, args.low_bit,
        EngineConfig(max_rows=args.max_rows, max_seq_len=args.max_seq_len,
                     spec_k=args.spec_k, spec_ngram=args.spec_ngram,
                     decode_horizon=args.decode_horizon,
                     step_token_budget=args.step_token_budget,
                     kv_storage=args.kv_storage,
                     kv_pool_bytes=args.kv_pool_bytes,
                     kv_spill_bytes=args.kv_spill_bytes,
                     weight_qtype=args.weight_qtype,
                     max_queue=args.max_queue,
                     request_deadline_s=args.request_deadline,
                     max_step_retries=args.max_step_retries,
                     trace_requests=args.trace,
                     collective_qtype=args.collective_qtype,
                     planner=args.planner),
        asr_model_path=args.asr_model,
        tensor_parallel_size=args.tensor_parallel_size,
        drain_timeout_s=args.drain_timeout,
        kv_import_token=args.kv_import_token,
        profile_dir=args.profile_dir,
    )
    web.run_app(srv.app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
