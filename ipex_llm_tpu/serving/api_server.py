"""OpenAI-compatible HTTP server (aiohttp) over the ServingEngine.

Reference counterpart: serving/fastapi/api_server.py:90 (+openai_protocol.py)
— same endpoints (`/v1/chat/completions`, `/v1/completions`, `/v1/models`),
same SSE streaming shape (``data: {chunk}\\n\\n`` … ``data: [DONE]``).
FastAPI isn't available in this image; aiohttp.web provides the async server.

Run: ``python -m ipex_llm_tpu.serving.api_server --model <dir> --port 8000``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
import uuid
from typing import Any

try:
    from aiohttp import web
except ImportError as _e:  # pragma: no cover
    web = None
    _AIOHTTP_ERR = _e

from ipex_llm_tpu.serving.engine import EngineConfig, Request, ServingEngine


def _now() -> int:
    return int(time.time())


class OpenAIServer:
    def __init__(self, engine: ServingEngine, tokenizer, model_name: str):
        if web is None:  # pragma: no cover
            raise ImportError(f"aiohttp is required for serving: {_AIOHTTP_ERR}")
        self.engine = engine
        self.tok = tokenizer
        self.model_name = model_name
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self.chat)
        self.app.router.add_post("/v1/completions", self.completions)
        self.app.router.add_get("/v1/models", self.models)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/metrics", self.metrics)

    # -- helpers ------------------------------------------------------------

    def _encode_chat(self, messages: list[dict]) -> list[int]:
        if hasattr(self.tok, "apply_chat_template") and getattr(
            self.tok, "chat_template", None
        ):
            return list(self.tok.apply_chat_template(
                messages, add_generation_prompt=True
            ))
        text = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
        text += "\nassistant:"
        return list(self.tok(text)["input_ids"])

    def _mk_request(self, body: dict, prompt_ids: list[int]) -> Request:
        def num(key, default, cast):
            v = body.get(key)
            return cast(default if v is None else v)

        eos: tuple[int, ...] = ()
        if self.tok.eos_token_id is not None:
            eos = (int(self.tok.eos_token_id),)
        req = Request(
            prompt_ids=prompt_ids,
            max_new_tokens=num("max_tokens", 128, int),
            # OpenAI API defaults: temperature=1.0, top_p=1.0 (clients
            # relying on the documented default expect sampled output)
            temperature=num("temperature", 1.0, float),
            top_p=num("top_p", 1.0, float),
            eos_token_id=eos,
            request_id=str(uuid.uuid4()),
        )
        stop = body.get("stop")
        req.stop_strings = ([stop] if isinstance(stop, str) else stop) or []
        return req

    @staticmethod
    def _find_stop(text: str, stops: list[str]) -> int:
        """Earliest stop-sequence offset in ``text``, or -1."""
        hits = [text.find(s) for s in stops if s and text.find(s) >= 0]
        return min(hits) if hits else -1

    async def _collect(self, req: Request) -> str:
        loop = asyncio.get_running_loop()
        toks: list[int] = []
        drop = set(req.eos_token_id)
        stops = getattr(req, "stop_strings", [])
        while True:
            tok = await loop.run_in_executor(None, req.stream_queue.get)
            if tok is None:
                break
            if tok in drop:
                continue
            toks.append(tok)
            if stops:
                text = self.tok.decode(toks)
                cut = self._find_stop(text, stops)
                if cut >= 0:
                    self.engine.abort(req)
                    req.finish_reason = "stop"
                    return text[:cut]
        return self.tok.decode(toks)

    async def _stream_sse(self, request, req: Request, chunk_fn):
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        drop = set(req.eos_token_id)
        stops = getattr(req, "stop_strings", [])
        sent = ""
        toks: list[int] = []
        try:
            while True:
                tok = await loop.run_in_executor(None, req.stream_queue.get)
                if tok is None:
                    break
                if tok in drop:
                    continue
                toks.append(tok)
                text = self.tok.decode(toks)
                cut = self._find_stop(text, stops) if stops else -1
                if cut >= 0:
                    piece, done = text[:cut][len(sent):], True
                else:
                    piece, done = text[len(sent):], False
                if piece:
                    sent += piece
                    await resp.write(
                        f"data: {json.dumps(chunk_fn(piece, None))}\n\n".encode()
                    )
                if done:
                    self.engine.abort(req)
                    req.finish_reason = "stop"
                    break
            await resp.write(
                f"data: {json.dumps(chunk_fn('', req.finish_reason))}\n\n".encode()
            )
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: free the engine row instead of decoding on
            self.engine.abort(req)
            raise
        return resp

    # -- endpoints ----------------------------------------------------------

    async def chat(self, request):
        body = await request.json()
        ids = self._encode_chat(body.get("messages", []))
        rf = body.get("response_format") or {}
        if rf.get("type") in ("json_object", "json_schema"):
            # constrained decoding runs the offline validator-filtered path
            # (structured.py), bypassing the batch engine
            return await self._chat_json(body, ids)
        req = self.engine.submit(self._mk_request(body, ids))
        rid = f"chatcmpl-{req.request_id[:12]}"

        if body.get("stream"):
            def chunk(piece: str, finish):
                delta = {"content": piece} if piece else {}
                return {
                    "id": rid, "object": "chat.completion.chunk",
                    "created": _now(), "model": self.model_name,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": finish}],
                }
            return await self._stream_sse(request, req, chunk)

        text = await self._collect(req)
        return web.json_response({
            "id": rid, "object": "chat.completion", "created": _now(),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": req.finish_reason,
            }],
            "usage": {
                "prompt_tokens": len(req.prompt_ids),
                "completion_tokens": len(req.output_ids),
                "total_tokens": len(req.prompt_ids) + len(req.output_ids),
            },
        })

    async def _chat_json(self, body: dict, ids: list[int]):
        rf = body.get("response_format") or {}
        import asyncio as _asyncio

        from ipex_llm_tpu.structured import generate_json

        loop = _asyncio.get_running_loop()
        text = await loop.run_in_executor(
            None,
            lambda: generate_json(
                self.engine.cfg, self.engine.params, self.tok, ids,
                max_new_tokens=int(body.get("max_tokens") or 256),
                schema=(rf.get("json_schema") or {}).get("schema"),
            ),
        )
        return web.json_response({
            "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
            "object": "chat.completion", "created": _now(),
            "model": self.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": "stop",
            }],
            "usage": {"prompt_tokens": len(ids)},
        })

    async def completions(self, request):
        body = await request.json()
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0]
        ids = list(self.tok(prompt)["input_ids"])
        req = self.engine.submit(self._mk_request(body, ids))
        rid = f"cmpl-{req.request_id[:12]}"

        if body.get("stream"):
            def chunk(piece: str, finish):
                return {
                    "id": rid, "object": "text_completion", "created": _now(),
                    "model": self.model_name,
                    "choices": [{"index": 0, "text": piece,
                                 "finish_reason": finish}],
                }
            return await self._stream_sse(request, req, chunk)

        text = await self._collect(req)
        return web.json_response({
            "id": rid, "object": "text_completion", "created": _now(),
            "model": self.model_name,
            "choices": [{"index": 0, "text": text,
                         "finish_reason": req.finish_reason}],
            "usage": {
                "prompt_tokens": len(req.prompt_ids),
                "completion_tokens": len(req.output_ids),
                "total_tokens": len(req.prompt_ids) + len(req.output_ids),
            },
        })

    async def models(self, request):
        return web.json_response({
            "object": "list",
            "data": [{"id": self.model_name, "object": "model",
                      "owned_by": "ipex_llm_tpu"}],
        })

    async def health(self, request):
        return web.json_response({"status": "ok"})

    async def metrics(self, request):
        return web.json_response(dict(self.engine.metrics))


def build_server(model_path: str, low_bit: str = "sym_int4",
                 engine_config: EngineConfig | None = None,
                 model=None, tokenizer=None) -> OpenAIServer:
    from ipex_llm_tpu.transformers import AutoModelForCausalLM

    if model is None:
        import os

        if os.path.exists(f"{model_path}/bigdl_config.json"):
            model = AutoModelForCausalLM.load_low_bit(model_path)
        else:
            model = AutoModelForCausalLM.from_pretrained(
                model_path, load_in_low_bit=low_bit
            )
    if tokenizer is None:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(model_path,
                                                  trust_remote_code=True)
    engine = ServingEngine(
        model.config, model.params, engine_config,
        default_eos=model.generation_config.eos_token_id,
    ).start()
    return OpenAIServer(engine, tokenizer, model_name=model_path)


def main(argv=None):
    ap = argparse.ArgumentParser("ipex-llm-tpu OpenAI-compatible server")
    ap.add_argument("--model", required=True)
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-rows", type=int, default=16)
    ap.add_argument("--max-seq-len", type=int, default=4096)
    args = ap.parse_args(argv)
    srv = build_server(
        args.model, args.low_bit,
        EngineConfig(max_rows=args.max_rows, max_seq_len=args.max_seq_len),
    )
    web.run_app(srv.app, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
