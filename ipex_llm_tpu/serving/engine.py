"""Continuous-batching decode engine (the vLLM-equivalent core).

Design (TPU-first; contrast reference vllm/ + PPModelWorker
pipeline_parallel.py:482-928 which rely on vLLM's paged attention):

- a fixed pool of ``max_rows`` sequence rows sharing one static KV buffer
  ``[L, R, H, S_max, D]`` — static shapes mean the decode step compiles
  exactly once;
- every step decodes ALL rows in one jitted call; inactive rows are masked
  (their sampled token is ignored), so join/leave never recompiles;
- a new request prefills on the bucketed single-row program (reusing
  generation.prefill_step) and its KV slice is copied into a free row
  between steps — prefill never blocks other rows' decode for more than one
  step boundary;
- per-row temperature/top-p live as traced vectors, so heterogeneous
  sampling params ride the same program.

The engine thread owns the device; asyncio handlers talk to it through
queues (reference fastapi server uses the same queue pattern,
api_server.py).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.generation import _round_up, prefill_step
from ipex_llm_tpu.kv import KVCache
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.decoder import decoder_forward

NEG_INF = -1e30


@dataclass(frozen=True)
class EngineConfig:
    max_rows: int = 4           # concurrent sequences
    max_seq_len: int = 2048     # per-row KV capacity
    prefill_bucket: int = 128


@dataclass
class Request:
    prompt_ids: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0    # 0 = greedy
    top_p: float = 1.0
    eos_token_id: tuple[int, ...] = ()
    stream_queue: "queue.Queue[int | None]" = field(default_factory=queue.Queue)
    request_id: str = ""
    # filled by the engine
    output_ids: list[int] = field(default_factory=list)
    finish_reason: str | None = None
    first_token_s: float = 0.0
    submitted_s: float = field(default_factory=time.perf_counter)
    cancelled: bool = False  # set via ServingEngine.abort (client disconnect)
    stop_strings: list[str] = field(default_factory=list)

    def abort(self):
        self.cancelled = True


@partial(jax.jit, static_argnames=("cfg",))
def _decode_step(cfg: ModelConfig, params, cache, toks, row_lens, active,
                 temps, top_ps, key):
    """One batched decode step over the whole row pool.

    toks [R] current token per row; row_lens [R] tokens already in cache.
    Returns (next_tokens [R], cache, key).
    """
    from ipex_llm_tpu.ops.sampling import sample_rows

    logits, cache = decoder_forward(
        cfg, params, toks[:, None], cache, row_lens[:, None],
        last_token_only=True, slot_offsets=row_lens,
    )
    key, sub = jax.random.split(key)
    nxt = sample_rows(logits, temps, top_ps, sub)
    nxt = jnp.where(active, nxt, 0)
    return nxt, cache, key


@partial(jax.jit, donate_argnums=(0,))
def _insert_row(cache: KVCache, prefill_cache: KVCache, n_valid, row):
    """Copy a prefilled single-row cache (left-padded) into pool row ``row``
    at slot 0."""
    # valid slots of the prefill cache are [tpad - n, tpad); shift to 0
    tpad = prefill_cache.k.shape[3]
    start = tpad - n_valid

    def per_layer_copy(pool_buf, pre_buf):
        # pool_buf [L,R,H,S,D]; pre_buf [L,1,H,Tpad,D]
        src = jnp.roll(pre_buf[:, 0], -start, axis=2)       # valid now at 0
        src = src[:, :, : pool_buf.shape[3]]                # clip to S_max
        pad = pool_buf.shape[3] - src.shape[2]
        if pad > 0:
            src = jnp.pad(src, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return pool_buf.at[:, row].set(src.astype(pool_buf.dtype))

    return KVCache(
        k=per_layer_copy(cache.k, prefill_cache.k),
        v=per_layer_copy(cache.v, prefill_cache.v),
        length=cache.length,
        storage=cache.storage,
    )


class ServingEngine:
    """Threaded continuous-batching engine around one model."""

    def __init__(self, cfg: ModelConfig, params: dict,
                 engine_config: EngineConfig | None = None,
                 default_eos: tuple[int, ...] = ()):
        self.cfg = cfg
        self.params = params
        self.ec = engine_config or EngineConfig()
        self.default_eos = default_eos
        r, s = self.ec.max_rows, self.ec.max_seq_len
        self.cache = KVCache.init(cfg.num_layers, r, s, cfg.num_kv_heads,
                                  cfg.head_dim)
        self.rows: list[Request | None] = [None] * r
        self.row_lens = np.zeros((r,), np.int32)
        self.row_budget = np.zeros((r,), np.int32)
        self.toks = np.zeros((r,), np.int32)
        self.temps = np.zeros((r,), np.float32)
        self.top_ps = np.ones((r,), np.float32)
        self.key = jax.random.PRNGKey(0)
        self._inbox: "queue.Queue[Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.metrics = {"requests": 0, "tokens": 0, "steps": 0}

    # -- public API ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)

    def submit(self, req: Request) -> Request:
        if not req.eos_token_id:
            req.eos_token_id = self.default_eos
        self._inbox.put(req)
        return req

    # -- engine loop --------------------------------------------------------

    def _free_row(self) -> int | None:
        for i, r in enumerate(self.rows):
            if r is None:
                return i
        return None

    def abort(self, req: Request):
        """Cancel a request (e.g. client disconnect); its row frees at the
        next step boundary."""
        req.cancelled = True

    def _admit(self, max_joins: int = 1):
        """Join pending requests into free rows (between decode steps).

        At most ``max_joins`` per step boundary while other rows decode, so
        a burst of prefills can't stall in-flight streams for more than one
        prefill forward per emitted token.
        """
        joined = 0
        while joined < max_joins:
            row = self._free_row()
            if row is None:
                return
            try:
                req = self._inbox.get_nowait()
            except queue.Empty:
                return
            if req.cancelled:
                req.finish_reason = "abort"
                req.stream_queue.put(None)
                continue
            joined += 1
            prompt = np.asarray(req.prompt_ids, np.int32)
            n_p = len(prompt)
            if n_p + req.max_new_tokens > self.ec.max_seq_len:
                req.finish_reason = "length"
                req.stream_queue.put(None)
                continue
            tpad = _round_up(max(n_p, 1), self.ec.prefill_bucket)
            toks = np.zeros((1, tpad), np.int32)
            toks[0, tpad - n_p:] = prompt
            pre_cache = KVCache.init(
                self.cfg.num_layers, 1, tpad, self.cfg.num_kv_heads,
                self.cfg.head_dim,
            )
            logits, pre_cache = prefill_step(
                self.cfg, self.params, pre_cache, jnp.asarray(toks),
                jnp.asarray([n_p], np.int32),
            )
            self.cache = _insert_row(
                self.cache, pre_cache, jnp.asarray(n_p, jnp.int32),
                jnp.asarray(row, jnp.int32),
            )
            from ipex_llm_tpu.ops.sampling import sample_rows

            self.key, sub = jax.random.split(self.key)
            first = int(np.asarray(sample_rows(
                logits, jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_p], jnp.float32), sub,
            ))[0])
            req.first_token_s = time.perf_counter() - req.submitted_s
            self.rows[row] = req
            self.row_lens[row] = n_p
            self.row_budget[row] = req.max_new_tokens
            self.toks[row] = first
            self.temps[row] = req.temperature
            self.top_ps[row] = req.top_p
            self.metrics["requests"] += 1
            self._emit(row, first)

    def _emit(self, row: int, token: int):
        req = self.rows[row]
        if req.cancelled:
            self._finish(row, "abort")
            return
        req.output_ids.append(token)
        req.stream_queue.put(token)
        self.metrics["tokens"] += 1
        if token in req.eos_token_id:
            self._finish(row, "stop")
        elif len(req.output_ids) >= self.row_budget[row]:
            self._finish(row, "length")

    def _finish(self, row: int, reason: str):
        req = self.rows[row]
        # first writer wins: the HTTP handler may have already recorded
        # 'stop' (stop-string truncation) before asking for the abort —
        # overwriting it here would misreport the finish reason
        if req.finish_reason is None:
            req.finish_reason = reason
        req.stream_queue.put(None)
        self.rows[row] = None
        self.row_lens[row] = 0
        self.toks[row] = 0

    def _fail_all(self, exc: BaseException):
        """Engine-level failure: finish every in-flight/queued request so no
        client blocks forever, then keep serving."""
        for i, req in enumerate(self.rows):
            if req is not None:
                self._finish(i, "error")
        while True:
            try:
                req = self._inbox.get_nowait()
            except queue.Empty:
                break
            req.finish_reason = "error"
            req.stream_queue.put(None)
        self.metrics["errors"] = self.metrics.get("errors", 0) + 1
        self.metrics["last_error"] = f"{type(exc).__name__}: {exc}"

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._step_once()
            except Exception as exc:  # keep the serving thread alive
                self._fail_all(exc)

    def _step_once(self):
        self._admit()
        for i, req in enumerate(self.rows):  # drop disconnected clients
            if req is not None and req.cancelled:
                self._finish(i, "abort")
        active = np.array([r is not None for r in self.rows])
        if not active.any():
            try:
                req = self._inbox.get(timeout=0.02)
                self._inbox.put(req)
            except queue.Empty:
                pass
            return
        # KV write for the current token happens inside the step; the
        # token at row_lens gets slot row_lens
        nxt, self.cache, self.key = _decode_step(
            self.cfg, self.params, self.cache,
            jnp.asarray(self.toks), jnp.asarray(self.row_lens),
            jnp.asarray(active), jnp.asarray(self.temps),
            jnp.asarray(self.top_ps), self.key,
        )
        nxt = np.asarray(nxt)
        self.metrics["steps"] += 1
        for i in range(len(self.rows)):
            if not active[i] or self.rows[i] is None:
                continue
            self.row_lens[i] += 1
            tok = int(nxt[i])
            self.toks[i] = tok
            self._emit(i, tok)


def stream_tokens(req: Request, timeout: float = 120.0):
    """Yield tokens from a submitted request until completion."""
    while True:
        tok = req.stream_queue.get(timeout=timeout)
        if tok is None:
            return
        yield tok
