"""Continuous-batching decode engine over a paged KV pool (vLLM-core peer).

Design (TPU-first; contrast reference vllm/ + PPModelWorker
pipeline_parallel.py:482-928 which rely on vLLM's paged attention):

- **paged KV**: one static pool ``[L, P, H, page, D]`` shared by every row,
  per-row block tables (kv.PagedKVCache) — HBM scales with TOKENS IN USE,
  not rows x S_max, so concurrency is bounded by real load, and the decode
  step still compiles exactly once (all shapes static);
- **prefix caching**: full pages of a prompt are content-hashed (a chained
  hash, so a page's identity covers everything before it); a new request
  reuses matching pages from earlier requests with refcounts and prefills
  only the remainder — the vLLM prefix-cache equivalent;
- **chunked prefill**: admission runs the prompt through fixed-size chunks,
  at most ONE chunk between decode steps, so a 2k-token prefill never stalls
  in-flight streams by more than one chunk forward (reference gap: r2's
  engine ran whole prefills synchronously on the engine thread);
- every step decodes ALL rows in one jitted call; inactive rows are masked,
  so join/leave never recompiles; per-row temperature/top-p ride as traced
  vectors.

The engine thread owns the device; asyncio handlers talk to it through
queues (reference fastapi server uses the same queue pattern,
api_server.py).
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import nullcontext
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.hostutil import d2h, h2d
from ipex_llm_tpu.kv import PagedKVCache, paged_page_bytes
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.decoder import decoder_forward
from ipex_llm_tpu.serving.faults import (EngineOverloaded, FaultInjector,
                                         is_transient)
from ipex_llm_tpu.serving.observe import (FAST_LATENCY_BUCKETS_S,
                                          LATENCY_BUCKETS_S, FlightRecorder,
                                          Histogram, Tracer, span)
from ipex_llm_tpu.serving.planner import make_planner

NEG_INF = -1e30

# _bisect_culprit outcome: the fault did not reproduce on re-run (distinct
# from "engine-level fault", which is None)
_FAULT_VANISHED = object()

# The copying upload helper was born here (PR 2's stream-corruption fix:
# jnp.asarray zero-copy-aliases mutable numpy buffers while async dispatch
# is still reading them) and is now shared by every async-dispatch module
# from ipex_llm_tpu.hostutil; jaxlint rule JL001 enforces its use.  The
# old private name stays importable for compatibility.
_h2d = h2d


@dataclass(frozen=True)
class EngineConfig:
    max_rows: int = 16          # concurrent sequences
    max_seq_len: int = 4096     # per-row KV capacity (block-table width)
    page_size: int = 128        # KV page length (slots)
    pool_pages: int = 0         # 0 = auto: max_rows * max_seq_len / page / 2
    # KV pool storage format (kv.KV_STORAGE_DTYPES): "bf16" full width, or
    # "fp8" e5m2 codes — the reference DynamicFp8Cache / xe_addons.sdp_fp8
    # format on the PAGED pool.  Decode is KV-bandwidth-bound, so fp8
    # halves the per-token HBM read; at a fixed kv_pool_bytes budget it
    # also doubles the page count — fewer pool-contention horizon clamps,
    # fewer prefix-cache LRU evictions, higher sustainable concurrency.
    # e5m2 storage is lossy vs bf16 (engine output stays bit-identical
    # ACROSS engine paths — mixed/sequential, H8/H1 — for a given
    # storage); see docs/quickstart/serving.md "KV storage & memory
    # budget" for the quality expectations.
    kv_storage: str = "bf16"
    # KV pool byte budget: when > 0, the pool page count is DERIVED as
    # kv_pool_bytes // page_bytes(model, page_size, kv_storage), so
    # capacity follows the storage width automatically (fp8 => 2x pages)
    # and operators size the pool in the unit they actually provision
    # (HBM bytes).  Overrides pool_pages.  0 = pool_pages/auto sizing.
    kv_pool_bytes: int = 0
    # host-RAM KV spill tier (serving/pagestore.py): when > 0, a prefix
    # page evicted under pool pressure — and a finished row's decode
    # pages (the multi-turn follow-up's prefix) — DEMOTES into a
    # byte-budgeted host LRU instead of being lost, and swaps back into
    # the pool on the next prefix hit: a PCIe copy through the audited
    # hostutil.h2d/d2h boundary instead of a recompute.  Swapped-in
    # pages are byte-identical to ones that never left the pool.  All
    # spill/swap work happens at epoch boundaries (allocation, admission,
    # finish), never inside the fused tick — JP106's one-dispatch tick
    # is untouched.  0 = evictions stay losses (the pre-spill engine).
    kv_spill_bytes: int = 0
    # weight-quantization axis (the reference's identity feature,
    # load_in_low_bit="sym_int4", applied to the SERVING hot path): at
    # engine build, every native-width (bf16/fp16) linear weight in the
    # stacked layer params — qkv/o/gate_up/down stacks, the lm head —
    # re-packs into block-quantized QTensor planes
    # (models/build.requantize_params, the same quantize/core.py codecs a
    # low-bit checkpoint load uses), and the single compiled layer body
    # routes those matmuls through ops/linear.qmatmul with dequant fused
    # next to the MXU (Pallas on TPU, XLA-fused block dequant on CPU —
    # the data-driven dispatch ladder decides).  Decode is HBM-bandwidth
    # bound, so ~4.5 bits/weight instead of 16 is a direct tok/s and —
    # at a fixed HBM byte budget, weights + KV pool together — a
    # concurrency win (bytes the weights stop using become KV pages; see
    # bench_weight_qtype).  Zero new device programs: the QTensor planes
    # ride the existing param pytree through the one-dispatch tick, so
    # JP106 stays ==1, and the JP107 trace rule fails the audit if a
    # hot-path program ever materializes a full-width copy of a stacked
    # packed weight.  None = serve the params at the width they were
    # handed over (a tree loaded with load_in_low_bit is ALREADY packed
    # and passes through untouched — requantizing packed codes would
    # stack error, so weight_qtype on such a tree is a no-op).
    weight_qtype: str | None = None
    prefill_bucket: int = 128   # chunked-prefill chunk length
    # speculative serving (reference ipex_llm_worker.py:57 `speculative`
    # load flag): >0 enables prompt-lookup speculative decode steps — each
    # step verifies spec_k n-gram candidates per row in ONE batched
    # T=spec_k+1 forward.  Every position samples with the row's own
    # params, so greedy AND sampled rows emit the accepted prefix with
    # the plain engine's distribution (seeded rows bit-identically; see
    # _verify_step).  Decode is bandwidth-bound, so the wider step costs
    # ~one weight pass but can emit up to spec_k+1 tokens.
    #
    # On the fused engine (the default: step_token_budget > 0, no pp
    # mesh) the WHOLE loop is on-device and composes with the decode
    # horizon: a jitted prompt-lookup proposer scans each row's
    # device-resident token history (ops/speculate.py), the [R, spec_k+1]
    # verify forward and the acceptance walk ride INSIDE
    # ``_decode_horizon_loop``'s while_loop, and a horizon step emits
    # 1..spec_k+1 tokens per iteration with no extra dispatch (JP106
    # still gates the tick at ==1) and no per-step sync.  The sequential
    # engine (step_token_budget=0) keeps the host-walk ``_spec_step`` —
    # the seeded bit-identity oracle — and a pp mesh keeps the
    # stage-sequential ``_pp_verify_step`` (GPipe pipelines only T=1
    # steps at H=1; the fused tick is a single-program engine path).
    spec_k: int = 0
    spec_ngram: int = 3         # n-gram length for the lookup proposer
    # fused decode horizon: >1 runs up to H decode+sample steps in ONE
    # jitted on-device loop (``_decode_multi_step``: ``lax.while_loop``
    # that exits early once every row is dead) with device-resident engine
    # state, so the host pays ONE blocking sync per H tokens instead
    # of per token — the fix for the dispatch-bound regime BENCH_r05
    # measured (per-stream tok/s collapsing under concurrency from host
    # orchestration, not FLOPs; the vLLM multi-step / MaxText on-device
    # generate-loop peers).  Per-row EOS/length early-stop is masked on
    # device, so fused output is bit-identical to H=1 under the seeded-
    # stream contract.  Streaming granularity becomes up to H tokens
    # (times spec_k+1 when speculative decode rides the same loop).
    decode_horizon: int = 1
    # mixed prefill+decode step: per-tick prefill token budget for the
    # admission wave.  While ANY row is prefilling, the engine runs
    # ``_mixed_step`` — ONE fused program (``_ragged_tick_fn``) advances
    # EVERY prefilling row by a ragged chunk, samples-and-merges first
    # tokens on device for prompts that complete, and runs the decode
    # step for every active row, all in a single dispatch (the TPU
    # ragged-paged-attention superkernel tick; JP106 locks it).  The budget
    # fair-shares across joining rows in power-of-two per-row chunk
    # widths (bounded retraces); decode rows keep their ordinary [R, 1]
    # step cost.  None = auto (prefill_bucket); 0 disables the mixed step
    # (the sequential one-row-one-chunk admission path, kept for pp/spec
    # and as the equivalence baseline).
    step_token_budget: int | None = None
    # fault domain (PR 3): the unit of failure is a request, not the
    # engine.  Transient step faults (device preemption, pool pressure,
    # tunnel hiccups — faults.is_transient) retry up to max_step_retries
    # times with exponential backoff after rolling host bookkeeping back
    # to the last committed tick; deterministic faults bisect the tick's
    # row set and quarantine exactly one culprit row with
    # finish_reason="error", keeping survivors bit-identical to an
    # unfaulted run.  _fail_all remains only for faults bisection cannot
    # localize (engine-level).
    max_step_retries: int = 3
    retry_backoff_s: float = 0.02   # base of the exponential backoff
    # admission control: submit() raises EngineOverloaded once this many
    # requests are queued (inbox + pending, not counting in-flight rows);
    # the HTTP surfaces map it to 429.  0 = unbounded (the pre-PR3
    # behaviour).
    max_queue: int = 256
    # default per-request deadline covering queue wait + generation
    # (Request.deadline_s overrides); enforced at admission (an expired
    # request finishes "timeout" without ever occupying a row) and at
    # every emission epoch.  0 = no deadline.
    request_deadline_s: float = 0.0
    # request-lifecycle tracing (serving/observe.py): when True the
    # engine records per-request spans — queue wait, swap-ins, prefill
    # chunks, first token, every decode horizon, spec rounds with accept
    # counts, retries, quarantine, finish — staged inside the
    # transactional tick and flushed only on commit (a rolled-back tick
    # never leaks a span), retrievable per request via /trace/{id} and
    # exportable as Chrome trace-event JSON.  All timestamps are host
    # clock reads at points the tick already visits: no new device
    # syncs, JP106's one-dispatch tick untouched.  False = the tracer is
    # None and every trace site is one `is None` check (bench_observe
    # prices both).  The tick flight recorder and the latency histograms
    # are always on — they are a dict append per working tick and a few
    # float ops per token.
    trace_requests: bool = False
    trace_buffer: int = 256     # traces retained (LRU); spans/trace capped
    flight_ring: int = 256      # tick records the flight recorder retains
    # device-time observatory (serving/perfwatch.py): per-tick wall-clock
    # attribution (dispatch / device-execute / host-sync / host-
    # bookkeeping buckets per program family, rollback-covered
    # histograms on /metrics + per-tick flight-recorder fields), the
    # runtime recompile sentinel (JP104's twin: jax.monitoring compile
    # events classified against the manifest-locked grid in
    # analysis/programs.lock.json — warm-path and out-of-grid compiles
    # flagged in /health's perf block), and MFU/roofline accounting
    # joining measured device time against the manifest's cost_analysis
    # for the dispatched grid point.  All host clock reads at points the
    # tick already visits — no new device programs or syncs, JP106 stays
    # ==1.  False = no PerfWatch at all (bench_observe prices the pair).
    perfwatch: bool = True
    # multi-chip collective wire family (ops/collectives.py, the EQuARX
    # axis): what the manual-mesh tick's row-parallel AllReduces carry.
    # "bf16" = the exact family (f32 accumulation; tp2 output is
    # bit-stable against tp1 — the bit-identity gate's family); "e5m2" /
    # "int8" = quantized payloads, bounded error for less ICI traffic —
    # decode's real multi-chip bottleneck.  None = resolve through
    # collectives.resolve_qtype (the IPEX_LLM_TPU_COLLECTIVE_QTYPE env,
    # else the exact default) — an explicit value here always wins.
    # Ignored off-mesh and on the GSPMD fallback path (XLA owns those
    # collectives).
    collective_qtype: str | None = None
    # tick planner (serving/planner.py): ONE host-side decision function
    # runs at the top of every tick (pure bookkeeping — zero new device
    # programs, JP106's one-dispatch tick untouched) and owns the tick's
    # whole shape: prefill chunk budget, decode horizon, per-row
    # speculative draft caps, and admission count.  "mpc" (the default)
    # is the model-predictive goodput planner — it joins the manifest's
    # cost_analysis with perfwatch's measured per-family tick history and
    # the rolling spec accept window, and deviates from the static
    # decisions only on evidence (deadline slack, draft economics); with
    # no deadlines and no adverse spec signal it makes the static
    # choices, selecting ONLY among grid points the config already
    # bounds, so the recompile sentinel stays structurally quiet.
    # "static" is the escape hatch: the pre-planner engine's decisions
    # verbatim (fixed step_token_budget, the admission-wave H-clamp,
    # static spec_k, unbounded admission) — bit-identical by
    # construction.  The plan is computed BEFORE the tick checkpoint, so
    # a rollback/retry (and every bisection probe) replays the same plan.
    planner: str = "mpc"

    @property
    def n_pages(self) -> int:
        if self.pool_pages:
            return self.pool_pages
        # 2x oversubscription: the paged pool holds half the worst case,
        # which real mixed-length traffic rarely approaches (the point of
        # paging); raise pool_pages for pathological all-max-len loads.
        # +2: page 0 is the reserved scratch page
        return max(self.max_rows * self.max_seq_len // self.page_size // 2,
                   self.max_rows + 2)

    @property
    def max_pages(self) -> int:
        return self.max_seq_len // self.page_size


def resolve_load_low_bit(engine_config: EngineConfig | None,
                         low_bit: str | None) -> str | None:
    """The load-width half of the serving width rule (one definition for
    both server entry points): a pinned ``EngineConfig.weight_qtype``
    outranks the ``low_bit`` load argument — loading packed at one width
    and asking the engine for another would leave the request a
    warn-and-ignore (requantizing packed codes stacks error), so the
    pinned width drives the checkpoint load itself."""
    if engine_config is not None and engine_config.weight_qtype:
        return engine_config.weight_qtype
    return low_bit


def default_weight_qtype(engine_config: EngineConfig | None,
                         low_bit: str | None) -> EngineConfig:
    """The config half of the serving width rule, beside
    :func:`resolve_load_low_bit`: thread the width the checkpoint was
    loaded at into ``EngineConfig.weight_qtype`` unless the caller
    already pinned one.  Only meaningful when the server also LOADED the
    checkpoint at that width (the repack is then a pass-through and the
    config records the width truthfully) — callers handing in their own
    full-width model must opt into repacking explicitly via
    ``weight_qtype``, never get it silently."""
    ec = engine_config or EngineConfig()
    if ec.weight_qtype is None and low_bit:
        ec = replace(ec, weight_qtype=low_bit)
    return ec


@dataclass
class Request:
    prompt_ids: list[int]
    max_new_tokens: int = 128
    temperature: float = 0.0    # 0 = greedy
    top_p: float = 1.0
    top_k: int = 0              # 0 = off
    seed: int | None = None     # deterministic per-request sampling stream
    eos_token_id: tuple[int, ...] = ()
    stream_queue: "queue.Queue[int | None]" = field(default_factory=queue.Queue)
    request_id: str = ""
    # filled by the engine
    output_ids: list[int] = field(default_factory=list)
    logprobs: list[float] = field(default_factory=list)  # per output token
    finish_reason: str | None = None
    first_token_s: float = 0.0
    submitted_s: float = field(default_factory=time.perf_counter)
    cancelled: bool = False  # set via ServingEngine.abort (client disconnect)
    stop_strings: list[str] = field(default_factory=list)
    # None = engine default (on when EngineConfig.spec_k > 0); False opts a
    # request out of speculative acceptance (it still rides the wide step)
    speculative: bool | None = None
    # per-request draft width, clamped to EngineConfig.spec_k (the trace
    # width); None = engine default
    spec_k: int | None = None
    # wall-clock budget covering queue wait + generation, from submission;
    # None = EngineConfig.request_deadline_s (0 there = no deadline).  An
    # expired request finishes with finish_reason="timeout" — at admission
    # without ever occupying a row, or mid-generation at the next tick.
    deadline_s: float | None = None
    # lifecycle-trace identity: the W3C traceparent trace id the HTTP
    # surfaces parse from the router/client (None = the engine keys the
    # trace on request_id), so one trace assembles across processes
    trace_id: str | None = None
    # last emission wall time (token-latency histogram bookkeeping;
    # checkpointed with the tick so a rolled-back emission never skews
    # the inter-token distribution)
    _last_tok_s: float = 0.0

    def abort(self):
        self.cancelled = True


class PageAllocator:
    """Host-side page pool bookkeeping: free list, refcounts, and the
    chained-hash prefix cache (LRU-evicted when the pool runs dry).

    ``spill``: optional callback ``spill([(key, pid), ...])`` invoked
    BEFORE a batch of cached prefix pages is dropped — the engine's hook
    into the host-RAM page store, turning an eviction from a loss into a
    demotion.  Batched so an allocation burst (``reserve``) pays ONE
    device gather + sync for all its evictions instead of one each."""

    def __init__(self, n_pages: int, spill=None):
        # page 0 is the device scratch page (kv.PagedKVCache.update_layer
        # routes out-of-range/pad writes there) — never handed out
        self.n_pages = n_pages
        self.spill = spill
        self.free: list[int] = list(range(n_pages - 1, 0, -1))
        self.ref = np.zeros((n_pages,), np.int32)
        # prefix cache: chain-hash -> page id; insertion order ~ LRU
        self.prefix: "OrderedDict[bytes, int]" = OrderedDict()
        self._page_key: dict[int, bytes] = {}
        # pool-pressure trace: cached prefix pages dropped to satisfy new
        # allocations (each one is a future prefix miss a bigger pool —
        # or a narrower storage — would have kept; with a spill tier the
        # page demotes to host RAM instead of being lost, so the counter
        # becomes "demotions", not "losses" — the router's affinity
        # freshness check reads it together with the spill block)
        self.prefix_evictions = 0

    def alloc(self) -> int | None:
        if not self.free and not self._evict(1):
            return None
        pid = self.free.pop()
        self.ref[pid] = 1
        return pid

    def reserve(self, n: int):
        """Pre-evict so the next ``n`` allocations are covered: exactly
        the pages lazy per-alloc eviction would drop (same LRU order,
        same count), but spilled in ONE batch — an allocation burst
        under pressure pays one device gather, not one per page."""
        short = n - len(self.free)
        if short > 0:
            self._evict(short)

    def addref(self, pid: int):
        self.ref[pid] += 1

    def decref(self, pid: int):
        self.ref[pid] -= 1
        if self.ref[pid] == 0:
            self.free.append(pid)

    def _evict(self, n: int) -> int:
        """Drop up to ``n`` least-recently-used prefix pages held only
        by the cache, spilling them to the host tier first (one batched
        callback) when one is wired.  Returns how many were dropped."""
        picks = []
        for key, pid in self.prefix.items():
            if self.ref[pid] == 1:  # only the cache references it
                picks.append((key, pid))
                if len(picks) == n:
                    break
        if not picks:
            return 0
        if self.spill is not None:
            # before the bookkeeping drop, while the pages are still
            # owned: a raise here (injected fault) leaves every cache
            # entry intact for the retry
            self.spill(picks)
        for key, pid in picks:
            del self.prefix[key]
            del self._page_key[pid]
            self.decref(pid)
            self.prefix_evictions += 1
        return len(picks)

    def register_prefix(self, key: bytes, pid: int):
        if key in self.prefix or pid in self._page_key:
            return
        self.prefix[key] = pid
        self._page_key[pid] = key
        self.addref(pid)  # the cache's own reference

    def lookup_prefix(self, key: bytes) -> int | None:
        pid = self.prefix.get(key)
        if pid is not None:
            self.prefix.move_to_end(key)  # LRU touch
        return pid

    @property
    def pages_in_use(self) -> int:
        return self.n_pages - 1 - len(self.free)


def _chain_hashes(prompt: np.ndarray, page_size: int) -> list[bytes]:
    """Chained content hash per full page: page i's key commits to every
    token before it, so equal keys imply equal K/V contents."""
    keys, h = [], b""
    for i in range(len(prompt) // page_size):
        h = hashlib.sha256(
            h + prompt[i * page_size : (i + 1) * page_size].tobytes()
        ).digest()
        keys.append(h)
    return keys


def _decode_horizon_loop(cfg: ModelConfig, params, cache, toks, row_lens,
                         active, temps, top_ps, key, seeds, steps, top_ks,
                         eos, remain, horizon: int, hist=None, spec_ks=None,
                         spec_k: int = 0, spec_ngram: int = 3):
    """The fused decode horizon BODY: up to ``horizon`` decode+sample
    steps over the whole row pool (a ``lax.while_loop`` — not
    ``lax.scan``, because the loop must exit early the moment every row
    is dead).  ONE definition, traced into BOTH jitted entries —
    ``_decode_multi_step`` (the historical fused-decode program, kept as
    the equivalence oracle) and ``_ragged_tick_fn`` (the single-dispatch
    tick) — so the two programs cannot drift and the superkernel tick
    stays bit-identical to the chained path by construction.

    toks [R] current token per row; row_lens [R] slots already in cache;
    eos [R, E] per-row stop ids (-1 pad); remain [R] output-token budget
    left.  A row that hits EOS or exhausts its budget INSIDE the horizon
    goes dead on device: its later positions emit masked padding (0), its
    toks/row_lens freeze, and the one KV slot it keeps rewriting is dead
    state the host reclaims at finish — the speculative-rollback
    convention (rejected slots are free to leave dirty).  Every live
    position computes exactly what the H=1 step computes (same forward,
    same split-per-step key chain, same fold_in(seed, output_index)
    stream), so fused output is bit-identical to H=1.

    ``spec_k > 0`` (static) selects the SPECULATIVE loop body: each
    iteration proposes up to ``spec_k`` prompt-lookup drafts per row from
    the device-resident token history ``hist`` [R, S] (``hist[r,
    row_lens[r]]`` is the row's current token — ops/speculate.py, the
    jitted twin of the host ``_propose_ngram``), runs ONE [R, spec_k+1]
    verify forward, samples every position with the row's params keyed by
    OUTPUT INDEX (``_sample_verify_positions`` — the same definition the
    host-walk ``_verify_step`` traces), and walks the acceptance chain ON
    DEVICE: emit s_0; while the draft fed at position j equals the token
    just emitted, s_j is a draw from the true conditional — so every
    emitted token has the plain engine's distribution (seeded rows
    bit-identically, greedy rows token-identically), and an iteration
    emits 1..spec_k+1 tokens.  EOS/budget truncation happens inside the
    accepted window; rejected drafts' KV slots are dead until overwritten
    (the paged pool's free rollback — the write cursor just doesn't
    advance past them).  ``spec_ks`` [R] caps the proposed run per row
    (0 = a plain step for that row: per-request opt-outs and the pool-
    contention fallback ride as traced masks, not separate programs).
    Returns the plain tuple extended with (take_block [R, H], hist,
    proposed, accepted); tok/lp blocks become [R, H, spec_k+1], positions
    past a row's per-iteration take masked to padding (0).
    """
    from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs

    if spec_k > 0:
        return _spec_horizon_loop(
            cfg, params, cache, toks, row_lens, active, temps, top_ps,
            key, seeds, steps, top_ks, eos, remain, horizon, hist,
            spec_ks, spec_k, spec_ngram)

    def step(n, cache, toks, row_lens, alive, key, steps, remain):
        # dead/masked rows route their (masked) K/V write to the scratch
        # page instead of rewriting the slot at their frozen row_lens: a
        # masked row may be mid-prefill with its DEVICE length stale (the
        # mixed step advances the host copy between epochs), and a
        # garbage write at a stale slot would corrupt KV a later chunk
        # already filled.  Live rows' offsets are untouched, so fused
        # output stays bit-identical.
        write_at = jnp.where(
            alive, row_lens,
            h2d(cache.tables.shape[1] * cache.page_size,
                        jnp.int32))
        logits, cache = decoder_forward(
            cfg, params, toks[:, None], cache, row_lens[:, None],
            last_token_only=True, slot_offsets=write_at,
        )
        key, sub = jax.random.split(key)
        nxt, lp = sample_rows_with_logprobs(logits, temps, top_ps, sub,
                                            seeds=seeds, steps=steps,
                                            top_ks=top_ks, active=alive)
        # on-device early-stop: EOS emission or budget exhaustion kills the
        # row for the rest of the horizon (it keeps riding the batch fully
        # masked); the host's _emit walks the same boundary when draining
        hit_eos = (nxt[:, None] == eos).any(axis=1) & alive
        adv = alive.astype(jnp.int32)
        row_lens = row_lens + adv
        steps = steps + adv
        remain = remain - adv
        alive = alive & ~hit_eos & (remain > 0)
        toks = jnp.where(alive, nxt, toks)
        return (n + 1, cache, toks, row_lens, alive, key, steps, remain,
                nxt, lp)

    if horizon == 1:
        # the H=1 program is the loop body inlined — structurally the
        # same XLA program as the historical single-step decode
        (n, cache, toks, row_lens, active, key, steps, remain, nxt,
         lp) = step(jnp.asarray(0, jnp.int32), cache, toks, row_lens,
                    active, key, steps, remain)
        tok_block, lp_block = nxt[:, None], lp[:, None]
    else:
        r = toks.shape[0]

        def body(carry):
            n, cache, toks, row_lens, alive, key, steps, remain, tb, \
                lb = carry
            (n1, cache, toks, row_lens, alive, key, steps, remain,
             nxt, lp) = step(n, cache, toks, row_lens, alive, key,
                             steps, remain)
            tb = jax.lax.dynamic_update_index_in_dim(tb, nxt, n, 0)
            lb = jax.lax.dynamic_update_index_in_dim(lb, lp, n, 0)
            return (n1, cache, toks, row_lens, alive, key, steps,
                    remain, tb, lb)

        init = (jnp.asarray(0, jnp.int32), cache, toks, row_lens,
                active, key, steps, remain,
                jnp.zeros((horizon, r), jnp.int32),
                jnp.zeros((horizon, r), jnp.float32))
        (n, cache, toks, row_lens, active, key, steps, remain, tb,
         lb) = jax.lax.while_loop(
            lambda c: (c[0] < horizon) & c[4].any(), body, init)
        tok_block, lp_block = tb.T, lb.T               # [H, R] -> [R, H]
    return (tok_block, lp_block, n, cache, toks, row_lens, active, steps,
            remain, key)


def _spec_horizon_loop(cfg: ModelConfig, params, cache, toks, row_lens,
                       active, temps, top_ps, key, seeds, steps, top_ks,
                       eos, remain, horizon: int, hist, spec_ks,
                       spec_k: int, spec_ngram: int):
    """The speculative form of ``_decode_horizon_loop`` (see its
    docstring for the contract) — split out only to keep the plain body
    byte-identical to the pre-spec program."""
    from ipex_llm_tpu.ops.speculate import propose_ngram_rows

    r = toks.shape[0]
    k1 = spec_k + 1
    scratch = h2d(cache.tables.shape[1] * cache.page_size, jnp.int32)
    s_hist = hist.shape[1]

    def spec_step(n, cache, toks, row_lens, alive, key, steps, remain,
                  hist, prop, acc):
        # draft: most-recent-n-gram continuation from the device history
        # (hist[:, :row_lens+1] is prompt + emitted tokens, current token
        # last); dead/opted-out/contention rows propose nothing and take
        # a plain step through the same wide program
        drafts, n_prop = propose_ngram_rows(hist, row_lens + 1, spec_k,
                                            spec_ngram)
        n_prop = jnp.where(alive, jnp.minimum(n_prop, spec_ks), 0)
        drafts = jnp.where(jnp.arange(spec_k)[None, :] < n_prop[:, None],
                           drafts, 0)
        # verify: ONE [R, k+1] ragged forward over [cur_tok; drafts].
        # Dead rows scratch-route their whole window (stale device lens
        # must never corrupt live pages — the plain body's rule); live
        # rows write slots row_lens..row_lens+k, of which only the
        # accepted prefix survives (unbacked tail slots land on the
        # scratch page via update_layer's valid mask)
        write_at = jnp.where(alive, row_lens, scratch)
        tokens = jnp.concatenate([toks[:, None], drafts], axis=1)
        pos = write_at[:, None] + jnp.arange(k1)[None, :]
        logits, cache = decoder_forward(
            cfg, params, tokens, cache, pos, slot_offsets=write_at,
        )
        t_all, lp_all, key = _sample_verify_positions(
            logits, alive, temps, top_ps, key, seeds, steps, top_ks,
            spec_k)
        # acceptance chain (the host walk's exact rule): position j+1's
        # sample is a draw from the true conditional only while the draft
        # fed there equals the token just emitted
        okm = (drafts == t_all[:, :spec_k]) & (
            jnp.arange(spec_k)[None, :] < n_prop[:, None])
        n_acc = jnp.argmin(jnp.concatenate(
            [okm, jnp.zeros((r, 1), bool)], axis=1).astype(jnp.int32),
            axis=1).astype(jnp.int32)
        # EOS/budget truncation INSIDE the accepted window — the same
        # boundary the host's _emit walk stops at
        eos_hit = (t_all[:, :, None] == eos[:, None, :]).any(-1)
        ehit = eos_hit & (jnp.arange(k1)[None, :] <= n_acc[:, None])
        any_eos = ehit.any(axis=1)
        first_eos = jnp.argmax(ehit, axis=1).astype(jnp.int32)
        n_stop = jnp.where(any_eos, first_eos + 1, n_acc + 1)
        n_take = jnp.where(alive, jnp.minimum(n_stop, remain), 0)
        keep = jnp.arange(k1)[None, :] < n_take[:, None]
        tok_step = jnp.where(keep, t_all, 0)
        lp_step = jnp.where(keep, lp_all, 0.0)
        # append the emitted run to the device history (next iteration's
        # proposer input); masked positions scatter-drop past the buffer
        hpos = jnp.where(keep, row_lens[:, None] + 1
                         + jnp.arange(k1)[None, :], s_hist)
        hist = hist.at[jnp.arange(r)[:, None], hpos].set(t_all,
                                                         mode="drop")
        died_eos = any_eos & (first_eos < n_take)
        row_lens = row_lens + n_take
        steps = steps + n_take
        remain = remain - n_take
        alive = alive & ~died_eos & (remain > 0)
        new_tok = jnp.take_along_axis(
            t_all, jnp.maximum(n_take - 1, 0)[:, None], axis=1)[:, 0]
        toks = jnp.where(alive, new_tok, toks)
        prop = prop + n_prop.sum()
        acc = acc + jnp.maximum(n_take - 1, 0).sum()
        return (n + 1, cache, toks, row_lens, alive, key, steps, remain,
                hist, prop, acc, tok_step, lp_step, n_take)

    zero = jnp.asarray(0, jnp.int32)
    if horizon == 1:
        (n, cache, toks, row_lens, active, key, steps, remain, hist,
         prop, acc, tok_step, lp_step, n_take) = spec_step(
            zero, cache, toks, row_lens, active, key, steps, remain,
            hist, zero, zero)
        tok_block = tok_step[:, None, :]
        lp_block = lp_step[:, None, :]
        take_block = n_take[:, None]
    else:
        def body(carry):
            (n, cache, toks, row_lens, alive, key, steps, remain, hist,
             prop, acc, tb, lb, kb) = carry
            (n1, cache, toks, row_lens, alive, key, steps, remain, hist,
             prop, acc, ts, ls, nt) = spec_step(
                n, cache, toks, row_lens, alive, key, steps, remain,
                hist, prop, acc)
            tb = jax.lax.dynamic_update_index_in_dim(tb, ts, n, 0)
            lb = jax.lax.dynamic_update_index_in_dim(lb, ls, n, 0)
            kb = jax.lax.dynamic_update_index_in_dim(kb, nt, n, 0)
            return (n1, cache, toks, row_lens, alive, key, steps, remain,
                    hist, prop, acc, tb, lb, kb)

        init = (zero, cache, toks, row_lens, active, key, steps, remain,
                hist, zero, zero,
                jnp.zeros((horizon, r, k1), jnp.int32),
                jnp.zeros((horizon, r, k1), jnp.float32),
                jnp.zeros((horizon, r), jnp.int32))
        (n, cache, toks, row_lens, active, key, steps, remain, hist,
         prop, acc, tb, lb, kb) = jax.lax.while_loop(
            lambda c: (c[0] < horizon) & c[4].any(), body, init)
        tok_block = tb.transpose(1, 0, 2)          # [H, R, k1] -> [R, H, k1]
        lp_block = lb.transpose(1, 0, 2)
        take_block = kb.T
    return (tok_block, lp_block, n, cache, toks, row_lens, active, steps,
            remain, key, take_block, hist, prop, acc)


# donation covers the cache AND every dead-after-call piece of the
# device-resident row state (toks/row_lens/active/steps/remain): the host
# rebinds its _dev handles to the returned arrays each call, so the
# inputs alias their advanced outputs instead of being copied per tick.
# temps/top_ps/seeds/top_ks/eos are HELD — the host re-passes the same
# buffers until the next epoch upload — and must never be donated.  The
# PRNG key is held too, less obviously: _checkpoint snapshots self.key BY
# REFERENCE for the bit-identical transient-retry contract, so donating
# it would hand _rollback a deleted buffer whenever a fault lands after
# the dispatch (the d2h sync is exactly where async XLA faults surface).
# The trace audit (JP101 in analysis/trace/) locks both directions.
@partial(jax.jit, static_argnames=("cfg", "horizon", "mesh"),
         donate_argnums=(2, 3, 4, 5, 10, 13))
def _decode_multi_step(cfg: ModelConfig, params, cache, toks, row_lens,
                       active, temps, top_ps, key, seeds, steps, top_ks,
                       eos, remain, horizon: int = 1, mesh=None):
    """The historical fused-decode program: ``_decode_horizon_loop`` as
    its own jitted entry.  The live tick path now routes through
    ``_ragged_tick_fn`` (which traces the SAME loop body, so outputs are
    bit-identical); this entry remains for the pre-superkernel callers
    and as the chained-path oracle the equivalence tests drive.

    ``mesh`` (static) marks TP serving: op dispatch then emits
    shard_map-wrapped kernels, and its presence in the jit key keeps
    single-device and sharded engines in one process from sharing a trace.
    Returns ([R, H] tokens, [R, H] logprobs, the number of steps actually
    executed (the horizon early-exits once EVERY row is dead — tail
    quantization never pays for h-1 dead forwards), cache, and the
    advanced device state: toks, row_lens, active, steps, remain, key).
    """
    from ipex_llm_tpu.ops import dispatch

    with dispatch.spmd(mesh):
        return _decode_horizon_loop(
            cfg, params, cache, toks, row_lens, active, temps, top_ps,
            key, seeds, steps, top_ks, eos, remain, horizon)


@partial(jax.jit, static_argnames=("cfg", "mesh", "n_micro"),
         donate_argnums=(2,))
def _pp_decode_sample(cfg: ModelConfig, params, cache, toks, row_lens,
                      active, temps, top_ps, key, seeds, steps, top_ks,
                      mesh=None, n_micro=2):
    """Pipelined decode step + sampling (PPModelWorker peer): request
    groups flow through the pp stages in the GPipe schedule
    (parallel/pipeline.py::pp_decode_step) instead of the stage-sequential
    GSPMD execution _decode_step would produce on a pp mesh."""
    from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs
    from ipex_llm_tpu.parallel.pipeline import pp_decode_step

    logits, cache = pp_decode_step(cfg, params, cache, toks, row_lens,
                                   mesh, n_micro)
    key, sub = jax.random.split(key)
    nxt, lp = sample_rows_with_logprobs(logits, temps, top_ps, sub,
                                        seeds=seeds, steps=steps,
                                        top_ks=top_ks, active=active)
    return nxt, lp, cache, key


def _sample_verify_positions(logits, active, temps, top_ps, key, seeds,
                             steps, top_ks, k: int):
    """Per-position sampling shared by BOTH verify steps: position j draws
    from p(.|ctx, d_1..d_j) with the row's params, seeded rows keyed by
    fold_in(seed, output_index).  ONE definition — the pp and single-mesh
    paths must stay bit-identical for the seeded-stream contract."""
    from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs

    key, sub = jax.random.split(key)
    subkeys = jax.random.split(sub, k + 1)            # per-position keys
    steps_mat = steps[:, None] + jnp.arange(k + 1)[None, :]  # [R, k+1]
    t_all, lp_all = jax.vmap(
        lambda lg_j, key_j, st_j: sample_rows_with_logprobs(
            lg_j, temps, top_ps, key_j, seeds=seeds, steps=st_j,
            top_ks=top_ks, active=active),
        in_axes=(1, 0, 1), out_axes=1,
    )(logits, subkeys, steps_mat)                     # [R, k+1] each
    return t_all, lp_all, key


@partial(jax.jit, static_argnames=("cfg", "k", "mesh", "n_micro"),
         donate_argnums=(2,))
def _pp_verify_step(cfg: ModelConfig, params, cache, toks, drafts, row_lens,
                    active, temps, top_ps, key, seeds, steps, top_ks,
                    k: int, mesh=None, n_micro=2):
    """Speculative verify step through the GPipe pipeline: the [R, k+1]
    window rides the request-group microbatches (pp_decode_step's wide
    form), then every position samples exactly like _verify_step."""
    from ipex_llm_tpu.parallel.pipeline import pp_decode_step

    tokens = jnp.concatenate([toks[:, None], drafts], axis=1)   # [R, k+1]
    logits, cache = pp_decode_step(cfg, params, cache, tokens, row_lens,
                                   mesh, n_micro)
    t_all, lp_all, key = _sample_verify_positions(
        logits, active, temps, top_ps, key, seeds, steps, top_ks, k)
    return t_all, lp_all, cache, key


@partial(jax.jit, static_argnames=("cfg", "k", "mesh"), donate_argnums=(2,))
def _verify_step(cfg: ModelConfig, params, cache, toks, drafts, row_lens,
                 active, temps, top_ps, key, seeds, steps, top_ks, k: int,
                 mesh=None):
    """Speculative decode step: ONE [R, k+1] forward over [cur_tok; drafts].

    EVERY position samples with the row's full sampling params (position j
    from p(.|ctx, d_1..d_j), with the row's seeded stream keyed by OUTPUT
    INDEX).  The host walks the acceptance chain: emit s_0; while the draft
    fed at position j equals the token just emitted, the j-th continuation
    s_j is a valid sample from the true conditional — emit it and continue.
    Each emitted token is therefore distributed exactly as plain decoding
    (the reference's speculative.py:805 distribution-preservation contract,
    generalized to temperature>0 — at T=0 this reduces to the greedy
    token-identical chain, lookup.py:274).  Seeded rows reproduce the plain
    engine's stream bit-for-bit because fold_in(seed, output_index) is the
    same key either way.  KV for accepted tokens was already written by this
    forward; rejected slots are dead until overwritten (paged rollback is
    free, the r3 speculative.py design note).
    """
    from ipex_llm_tpu.ops import dispatch

    with dispatch.spmd(mesh):
        tokens = jnp.concatenate([toks[:, None], drafts], axis=1)  # [R,k+1]
        pos = row_lens[:, None] + jnp.arange(k + 1)[None, :]
        logits, cache = decoder_forward(
            cfg, params, tokens, cache, pos, slot_offsets=row_lens,
        )
        t_all, lp_all, key = _sample_verify_positions(
            logits, active, temps, top_ps, key, seeds, steps, top_ks, k)
    return t_all, lp_all, cache, key


def _propose_ngram(history: np.ndarray, k: int, ngram: int) -> np.ndarray:
    """Prompt-lookup candidates (reference lookup.py:145-273): find the most
    recent earlier occurrence of the trailing n-gram (longest n first) and
    propose the k tokens that followed it.  Returns [k] int32, -1-padded."""
    out = np.full((k,), -1, np.int32)
    ln = len(history)
    for n in range(min(ngram, ln - 1), 0, -1):
        tail = history[ln - n:]
        wins = np.lib.stride_tricks.sliding_window_view(history, n)
        hits = np.nonzero((wins[: ln - n] == tail).all(axis=1))[0]
        if len(hits):
            s = int(hits[-1])  # most recent earlier occurrence
            nxt = history[s + n: s + n + k]
            out[: len(nxt)] = nxt
            return out
    return out


@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def _prefill_chunk(cfg: ModelConfig, params, cache, tokens, table_row,
                   base_len, n_valid, mesh=None):
    """Run one right-padded prompt chunk for a single row.

    tokens [1, C]; table_row [1, maxP] (that row's block table); base_len
    scalar: slots already filled.  Pad positions write garbage K/V into the
    row's own future slots — subsequent chunks/decode steps overwrite them
    in order, and causal masking keeps valid queries from seeing them.
    Returns (last-valid-position logits [1, V], updated cache).
    """
    from ipex_llm_tpu.ops import dispatch

    with dispatch.spmd(mesh):
        row_cache = replace(cache, tables=table_row)
        pos = base_len + jnp.arange(tokens.shape[1])[None, :]
        logits, row_cache = decoder_forward(
            cfg, params, tokens, row_cache, pos,
            slot_offsets=jnp.reshape(base_len, (1,)),
        )
        last = jnp.take_along_axis(
            logits, (n_valid - 1).reshape(1, 1, 1).astype(jnp.int32), axis=1
        )[:, 0]
    return last, replace(row_cache, tables=cache.tables)


@partial(jax.jit, static_argnames=("cfg", "mesh"), donate_argnums=(2,))
def _mixed_prefill_fn(cfg: ModelConfig, params, cache, tokens, base_lens,
                      n_valid, emit, temps, top_ps, key, seeds, top_ks,
                      mesh=None):
    """Batched ragged prefill over the PREFILLING rows: one device program
    advances every joining row by a chunk, replacing the per-row
    ``_prefill_chunk`` dispatch loop (O(rows x chunks) tiny programs).

    The caller passes a cache whose ``tables`` view is row-sliced to the
    prefilling rows (power-of-two padded), so batch position i is
    prefilling row ``rows[i]``: tokens [P, W] right-padded chunks,
    base_lens [P] slots already filled (pad rows carry base past the
    table width so every write routes to the scratch page), n_valid [P]
    real tokens this tick.  Chunk K/V scatters exactly like the
    single-row chunk — right-pad garbage lands on the row's own future
    slots or the scratch page, hidden from valid queries by causal
    masking — so chunk values are bitwise those of the sequential path.

    ``emit`` marks rows whose prompt completes this tick: their FIRST
    token is sampled here, on device, from the last valid position
    (fold_in(seed, 0) for seeded rows — the sequential engine's exact
    first-token stream), eliminating the per-chunk host sampling round
    trip.  Returns ([P] tokens, [P] logprobs, cache, key).

    HISTORICAL NOTE: the live tick no longer dispatches this program —
    ``_ragged_tick_fn`` fuses the same prefill stage with the decode
    horizon into ONE dispatch (stage 1 there is this function's body
    with per-row ``chunk_lens`` threaded into attention).  It remains
    module-level-jitted as half of the chained two-program oracle the
    equivalence suite (tests/test_serving_ragged.py) drives against the
    fused tick.
    """
    from ipex_llm_tpu.ops import dispatch
    from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs

    with dispatch.spmd(mesh):
        pos = base_lens[:, None] + jnp.arange(tokens.shape[1])[None, :]
        logits, cache = decoder_forward(
            cfg, params, tokens, cache, pos, slot_offsets=base_lens,
            gather_positions=jnp.maximum(n_valid - 1, 0),
        )
        key, sub = jax.random.split(key)
        nxt, lp = sample_rows_with_logprobs(
            logits, temps, top_ps, sub, seeds=seeds,
            steps=jnp.zeros_like(n_valid), top_ks=top_ks, active=emit)
    return nxt, lp, cache, key


# Donation contract identical to _decode_multi_step (same positions):
# cache/toks/row_lens/active/steps/remain are dead after the call — the
# host rebinds its _dev handles to the returned arrays — while temps/
# top_ps/seeds/top_ks/eos are held across epochs and the PRNG key is
# checkpoint-held BY REFERENCE for bit-identical transient retry (PR 6's
# rule), so neither may be donated.  ``hist`` (the speculative token
# history, spec_k > 0 only) is device-resident dead-after-call state like
# toks — the host rebinds _dev["hist"] to the returned buffer — so it
# donates by name.  The prefill block's arrays and ``spec_ks`` are fresh
# per-tick uploads, too small to matter.  JP101 locks both directions.
def _tick_body(cfg: ModelConfig, params, cache, toks, row_lens,
               active, temps, top_ps, key, seeds, steps, top_ks,
               eos, remain, prefill=None, horizon: int = 1,
               with_decode: bool = True, hist=None, spec_ks=None,
               spec_k: int = 0, spec_ngram: int = 3):
    """The fused tick BODY (see ``_ragged_tick_fn`` for the contract):
    traced either directly under GSPMD dispatch, or — the manual-mesh
    serving form — once per shard inside ``parallel/manual.tp_tick``'s
    fully-manual shard_map region with a shard-local cfg/params/pool."""
    from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs

    r = toks.shape[0]
    first_t = first_lp = None
    if prefill is not None:
        (p_tokens, p_tables, p_base, p_nvalid, p_emit, p_canjoin,
         p_rowmap) = prefill
        w = p_tokens.shape[1]
        row_cache = replace(cache, tables=p_tables)
        pos = p_base[:, None] + jnp.arange(w)[None, :]
        logits, row_cache = decoder_forward(
            cfg, params, p_tokens, row_cache, pos,
            slot_offsets=p_base,
            gather_positions=jnp.maximum(p_nvalid - 1, 0),
            chunk_lens=p_nvalid,
        )
        cache = replace(cache, k=row_cache.k, v=row_cache.v)
        key, sub = jax.random.split(key)
        first_t, first_lp = sample_rows_with_logprobs(
            logits, temps[p_rowmap], top_ps[p_rowmap], sub,
            seeds=seeds[p_rowmap], steps=jnp.zeros_like(p_nvalid),
            top_ks=top_ks[p_rowmap], active=p_emit)
        # merge the wave into the decode state (pad slots drop):
        # lengths advance for EVERY prefill row, completing rows join
        # with their first token pre-published — the on-device form
        # of the epoch upload the chained path paid here
        new_len = p_base + p_nvalid
        row_lens = row_lens.at[p_rowmap].set(new_len, mode="drop")
        hit_eos = (first_t[:, None] == eos[p_rowmap]).any(axis=1)
        rem_after = remain[p_rowmap] - 1
        join = p_emit & p_canjoin & ~hit_eos & (rem_after > 0)
        toks = toks.at[p_rowmap].set(
            jnp.where(p_emit, first_t, toks[p_rowmap]), mode="drop")
        steps = steps.at[p_rowmap].set(
            jnp.where(p_emit, 1, steps[p_rowmap]), mode="drop")
        remain = remain.at[p_rowmap].set(
            jnp.where(p_emit, rem_after, remain[p_rowmap]),
            mode="drop")
        active = active.at[p_rowmap].set(
            jnp.where(p_emit, join, active[p_rowmap]), mode="drop")
        if spec_k > 0:
            # a completing row's history gains its first token ON
            # DEVICE (the prompt itself landed with the admission
            # epoch upload), so the decode stage below can already
            # draft for it; pad slots and non-emitting rows drop
            hpos = jnp.where(p_emit, new_len, hist.shape[1])
            hist = hist.at[p_rowmap, hpos].set(first_t, mode="drop")
    if with_decode and spec_k > 0:
        (tok_block, lp_block, n_exec, cache, toks, row_lens, active,
         steps, remain, key, take_block, hist, prop,
         acc) = _decode_horizon_loop(
            cfg, params, cache, toks, row_lens, active, temps,
            top_ps, key, seeds, steps, top_ks, eos, remain, horizon,
            hist=hist, spec_ks=spec_ks, spec_k=spec_k,
            spec_ngram=spec_ngram)
    elif with_decode:
        (tok_block, lp_block, n_exec, cache, toks, row_lens, active,
         steps, remain, key) = _decode_horizon_loop(
            cfg, params, cache, toks, row_lens, active, temps,
            top_ps, key, seeds, steps, top_ks, eos, remain, horizon)
    else:
        tok_block = jnp.zeros((r, horizon), jnp.int32)
        lp_block = jnp.zeros((r, horizon), jnp.float32)
        n_exec = jnp.asarray(0, jnp.int32)
    if spec_k > 0:
        return (first_t, first_lp, tok_block, lp_block, n_exec, cache,
                toks, row_lens, active, steps, remain, key, take_block,
                hist, prop, acc)
    return (first_t, first_lp, tok_block, lp_block, n_exec, cache, toks,
            row_lens, active, steps, remain, key)


@partial(jax.jit,
         static_argnames=("cfg", "horizon", "with_decode", "spec_k",
                          "spec_ngram", "mesh", "tp_manual",
                          "collective_qtype"),
         donate_argnums=(2, 3, 4, 5, 10, 13), donate_argnames=("hist",))
def _ragged_tick_fn(cfg: ModelConfig, params, cache, toks, row_lens,
                    active, temps, top_ps, key, seeds, steps, top_ks,
                    eos, remain, prefill=None, horizon: int = 1,
                    with_decode: bool = True, hist=None, spec_ks=None,
                    spec_k: int = 0, spec_ngram: int = 3, mesh=None,
                    tp_manual: bool = False,
                    collective_qtype: str = "bf16"):
    """ONE device program per engine tick, whatever the admission mix —
    the ragged-paged-attention superkernel tick (ROADMAP item 1; the
    JP106 gate counts exactly this entry).

    Internally three fused stages, each optional per the tick's shape:

    1. **ragged prefill** (``prefill`` is not None): the batched ragged
       chunk forward over the prefilling rows — ``prefill`` is
       ``(p_tokens [P, W], p_tables [P, maxp_b], p_base [P], p_nvalid
       [P], p_emit [P], p_canjoin [P], p_rowmap [P])``, a row-sliced
       table view plus the map from prefill slot to engine row (pad
       slots carry ``p_rowmap == R`` so their scatters drop).  Attention
       rides the per-row ``chunk_lens`` causal contract of
       ops/pallas/ragged_paged_attention.py, and the per-row last-valid
       hidden gather (``gather_positions``) is fused in.
    2. **first-token sampling + state merge**: rows whose prompt
       completes this tick (``p_emit``) sample their first token here —
       fold_in(seed, 0), the sequential stream — and join the decode
       state ON DEVICE exactly as the host's epoch upload would have
       published them (toks=first, steps=1, remain-=1, active unless the
       first token hit EOS / exhausted the budget / ``p_canjoin`` says
       the host could not back the decode KV slot).  Every prefill row's
       device length advances to its true value, so pure-chunk ticks
       still need no epoch upload.
    3. **the fused decode horizon** (``with_decode``): the SAME
       ``_decode_horizon_loop`` body ``_decode_multi_step`` traces, over
       the merged state — so decode output is bit-identical to the
       chained two-program tick, and a steady-state tick (prefill=None)
       lowers to structurally the historical fused-decode program.

    ``with_decode=False`` (a pure-chunk tick with no decoding rows)
    skips stage 3 entirely: no wasted all-masked forward, and the key
    chain only advances by the prefill split — the chained path's exact
    behaviour.  Returns (first_t [P], first_lp [P] — None without a
    prefill block —, [R, H] tokens, [R, H] logprobs, steps executed,
    cache, toks, row_lens, active, steps, remain, key).

    ``spec_k > 0`` (static) runs stage 3 as the SPECULATIVE horizon loop
    (``_spec_horizon_loop``: on-device draft from ``hist``, [R, spec_k+1]
    verify, on-device acceptance — still ONE dispatch, JP106 unchanged);
    stage 2 additionally publishes a completing row's first token into
    ``hist`` so a prompt that finishes this tick can speculate on its
    very first decode iteration.  The return tuple then extends to
    (..., key, take_block [R, H], hist, draft_proposed, draft_accepted)
    with [R, H, spec_k+1] token/logprob blocks.
    """
    from ipex_llm_tpu.ops import dispatch

    if tp_manual:
        from ipex_llm_tpu.parallel.manual import tp_tick

        return tp_tick(
            _tick_body, cfg, mesh, collective_qtype, params, cache,
            (toks, row_lens, active, temps, top_ps, key, seeds, steps,
             top_ks, eos, remain),
            prefill=prefill, horizon=horizon, with_decode=with_decode,
            hist=hist, spec_ks=spec_ks, spec_k=spec_k,
            spec_ngram=spec_ngram)
    with dispatch.spmd(mesh):
        return _tick_body(
            cfg, params, cache, toks, row_lens, active, temps, top_ps,
            key, seeds, steps, top_ks, eos, remain, prefill=prefill,
            horizon=horizon, with_decode=with_decode, hist=hist,
            spec_ks=spec_ks, spec_k=spec_k, spec_ngram=spec_ngram)


class ServingEngine:
    """Threaded continuous-batching engine around one model."""

    def __init__(self, cfg: ModelConfig, params: dict,
                 engine_config: EngineConfig | None = None,
                 default_eos: tuple[int, ...] = (),
                 mesh=None, fault_injector: FaultInjector | None = None,
                 weight_imatrix: dict | None = None):
        """``mesh``: a ``jax.sharding.Mesh`` for TP serving — params are
        placed under the AutoTP rules and the paged pool's kv heads are
        sharded, the reference's vLLM-TP-worker serving mode
        (vllm/xpu/engine/engine.py:40) expressed as SPMD instead of Ray
        workers.  None = single-chip (the r3 behaviour).

        ``fault_injector``: a ``faults.FaultInjector`` whose scripted
        exceptions fire at the engine's guarded sites — the deterministic
        test/chaos harness for the fault-domain layer.

        ``weight_imatrix``: optional llama.cpp importance-matrix dict
        (quantize/imatrix.load_imatrix) calibrating the
        ``EngineConfig.weight_qtype`` repack — the reference's
        ``ggml_quantize_tensor_with_weights`` path, applied at engine
        build."""
        if cfg.rope_2d:
            # chatglm v1 block positions need each row's prompt boundary
            # threaded through every step; generate() supports it, the paged
            # engine does not (a 2018-era model is not a serving target)
            raise NotImplementedError(
                "2D-rope (chatglm v1) models are generate()-only")
        if "embed" not in params:
            # disk_embedding models keep the table in host RAM; the jitted
            # engine step cannot host-gather per token (model.py:186)
            raise NotImplementedError(
                "disk_embedding (streamed host table) models are "
                "generate()-only — the paged engine needs the embed table "
                "in HBM")
        self.cfg = cfg
        self.ec = engine_config or EngineConfig()
        if self.ec.spec_k > 0 and self.ec.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1 when spec_k > 0")
        if self.ec.decode_horizon < 1:
            raise ValueError("decode_horizon must be >= 1")
        if (self.ec.step_token_budget is not None
                and self.ec.step_token_budget < 0):
            raise ValueError("step_token_budget must be >= 0 (0 disables "
                             "the mixed prefill+decode step)")
        if self.ec.kv_pool_bytes < 0:
            raise ValueError("kv_pool_bytes must be >= 0 (0 = size the "
                             "pool in pages via pool_pages)")
        if self.ec.kv_spill_bytes < 0:
            raise ValueError("kv_spill_bytes must be >= 0 (0 disables "
                             "the host-RAM KV spill tier)")
        # KV storage axis: bytes ONE page costs at this model shape and
        # storage width — the unit kv_pool_bytes divides by (validates
        # kv_storage, raising with the valid names)
        self.page_bytes = paged_page_bytes(
            cfg.num_layers, cfg.num_kv_heads, self.ec.page_size,
            cfg.head_dim, v_head_dim=cfg.v_dim,
            storage=self.ec.kv_storage)
        if self.ec.kv_pool_bytes:
            # byte-budgeted pool: capacity in pages follows the storage
            # width (fp8 pages are half the bytes => twice the pages)
            pages = self.ec.kv_pool_bytes // self.page_bytes
            floor = self.ec.max_rows + 2   # one page per row + scratch
            if pages < floor:
                # refuse rather than silently overshoot the operator's
                # explicit byte cap: the budget cannot even back one page
                # per row — shrink max_rows, the page size, or the model,
                # or switch to fp8 storage (half the bytes per page)
                raise ValueError(
                    f"kv_pool_bytes={self.ec.kv_pool_bytes} holds only "
                    f"{pages} {self.ec.kv_storage} pages of "
                    f"{self.page_bytes} bytes — max_rows={self.ec.max_rows}"
                    f" needs at least {floor} ({floor * self.page_bytes} "
                    f"bytes)")
            self.ec = replace(self.ec, pool_pages=pages)
        # weight-quantization axis: re-pack native-width linear weights
        # into block-quantized planes BEFORE device placement/sharding
        # (shard_params stamps tp_mode on whatever planes it is handed).
        # Already-low-bit trees pass through untouched; an unknown or
        # non-requantizable qtype raises here, before any pool allocates.
        from ipex_llm_tpu.models.build import param_bytes, requantize_params

        if self.ec.weight_qtype is not None:
            params = requantize_params(params, self.ec.weight_qtype,
                                       imatrix_data=weight_imatrix)
        # weight byte accounting for /health's weights block and the
        # fixed-budget bench: what the tree costs as stored vs at bf16
        # full width, plus the packed formats actually present (an
        # already-quantized tree reports its real width even when
        # weight_qtype is None)
        self._weight_bytes, self._weight_dense_bytes = param_bytes(params)
        from ipex_llm_tpu.quantize.core import QTensor as _QT
        from ipex_llm_tpu.quantize.qtypes import resolve as _qresolve

        self._weight_qtypes = tuple(sorted({
            leaf.qtype for leaf in jax.tree_util.tree_leaves(
                params, is_leaf=lambda x: isinstance(x, _QT))
            if isinstance(leaf, _QT)
            and _qresolve(leaf.qtype).kind != "native"}))
        # the SERVED width (what /health's weights.qtype reports): the
        # configured axis when it matches the planes; the planes' own
        # format when the tree arrived already packed at a different (or
        # no configured) width — the axis is a request, the planes are
        # the truth.  A mismatching explicit request warns loudly: the
        # pass-through is by design (requantizing packed codes stacks
        # error), but the operator asked for a width they are not getting.
        # canonical name from the start: an alias axis ("woq_int4",
        # "fp8") must report the format the planes actually carry
        resolved = (_qresolve(self.ec.weight_qtype).name
                    if self.ec.weight_qtype is not None else None)
        self._served_qtype = resolved
        if self._weight_qtypes:
            if len(self._weight_qtypes) > 1:
                # more than one packed width in the tree (mixed-precision
                # int8 head over an int4 body, heterogeneous GGUF): no
                # single name is the served width — even one matching the
                # request — so report "mixed" and let packed_qtypes carry
                # the list
                self._served_qtype = "mixed"
            elif resolved not in self._weight_qtypes:
                # the request (or its absence) names no plane actually in
                # the tree: report the one format that IS served
                self._served_qtype = self._weight_qtypes[0]
            if resolved is not None and resolved not in self._weight_qtypes:
                import warnings

                warnings.warn(
                    f"weight_qtype={self.ec.weight_qtype!r} requested but "
                    f"the param tree is already packed as "
                    f"{list(self._weight_qtypes)} — requantizing packed "
                    "codes would stack quantization error, so the tree "
                    "serves as-is (/health's weights block reports the "
                    "served width)", stacklevel=2)
        elif resolved is not None \
                and _qresolve(resolved).kind != "native":
            # a packed width was requested but nothing packed: the tree
            # holds plain-array weights (a dequantized/dense twin), which
            # the repack does not cover — it cannot tell a linear weight
            # from an embed table in a bare array.  Report the truth
            # (nothing is served at that width) and say so.
            self._served_qtype = None
            import warnings

            warnings.warn(
                f"weight_qtype={self.ec.weight_qtype!r} requested but the "
                "param tree carries no quantizable QTensor leaves (plain "
                "arrays repack does not cover) — serving full width; "
                "build the tree through models/build (or load_in_low_bit)"
                " for a packable tree", stacklevel=2)
        self.default_eos = default_eos
        self.mesh = mesh if (mesh is not None and mesh.size > 1) else None
        r = self.ec.max_rows
        cache = PagedKVCache.init(
            cfg.num_layers, self.ec.n_pages, r, self.ec.max_pages,
            cfg.num_kv_heads, self.ec.page_size, cfg.head_dim,
            v_head_dim=cfg.v_dim, storage=self.ec.kv_storage,
        )
        # multi-chip serving: on a PURE-tp mesh whose shapes divide, the
        # engine takes the MANUAL tick — the whole fused tick inside one
        # fully-manual shard_map region (parallel/manual.py), per-shard
        # pools and explicit quantized collectives, GSPMD out of the loop.
        # Anything the manual layout does not cover (composed meshes, MoE,
        # MLA, non-dividing heads, the sequential oracle engine) falls
        # back to the per-op GSPMD path, with the reason recorded for
        # /health-side debugging.
        self._tp_manual = False
        self._tp_fallback_reason: str | None = None
        from ipex_llm_tpu.ops import collectives

        # config wins, then the IPEX_LLM_TPU_COLLECTIVE_QTYPE env, then
        # the exact family; raises on an unknown family name
        self._collective_qtype = collectives.resolve_qtype(
            self.ec.collective_qtype)
        if self.mesh is not None:
            from ipex_llm_tpu.parallel import manual
            from ipex_llm_tpu.parallel.shard import (shard_paged_cache,
                                                     shard_params)

            budget = (self.ec.prefill_bucket
                      if self.ec.step_token_budget is None
                      else int(self.ec.step_token_budget))
            reason = manual.ineligible_reason(cfg, params, self.mesh,
                                              budget)
            if reason is None:
                params = manual.shard_params_manual(params, cfg,
                                                    self.mesh)
                self._tp_manual = True
            else:
                # re-placing already-sharded params is an idempotent
                # device_put
                params = shard_params(params, self.mesh)
                self._tp_fallback_reason = reason
            cache = shard_paged_cache(cache, self.mesh)
        self.params = params
        self.cache = cache
        # pipelined decode (PPModelWorker peer): GPipe request groups over
        # the pp axis, on PURE-pp meshes; speculative verify steps ride
        # the pipeline's wide (T=k+1) form.  What it can't serve (MoE
        # dual stack, non-dividing shapes, composed meshes) falls back to
        # GSPMD decode, which is correct but leaves chips idle.
        # COMPOSED-MESH LIMIT (jax 0.4.37): ppermute inside a partial-auto
        # shard_map region on a mesh with a second >1 axis CHECK-CRASHES
        # the XLA SPMD partitioner (spmd_partitioner.cc
        # IsManualSubgroup) — an abort, not an exception — so a tp x pp
        # mesh must not take the GPipe path; it serves through the fused
        # GSPMD tick instead (tp=2 compositions are the characterized-
        # safe grid, tests/test_parallel.py).
        pp = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
        composed = (self.mesh is not None
                    and any(n > 1 for a, n in self.mesh.shape.items()
                            if a != "pp"))
        self._pp_mode = (
            pp > 1
            and not composed
            and cfg.num_layers % pp == 0
            and r % pp == 0
            and "layers_dense" not in params
        )
        # mixed prefill+decode step (admission-wave regime): resolved token
        # budget per tick; 0 = sequential one-row-one-chunk admission.  The
        # pp engine keeps the sequential path (the mixed forward would run
        # GSPMD stage-sequential instead of the GPipe schedule).
        self._step_budget = (self.ec.prefill_bucket
                             if self.ec.step_token_budget is None
                             else int(self.ec.step_token_budget))
        self._mixed_mode = self._step_budget > 0 and not self._pp_mode
        # on-device speculative decode inside the fused tick: the mixed/
        # horizon engine threads spec through _ragged_tick_fn (draft +
        # verify + accept in the device horizon loop, still one dispatch).
        # The sequential engine (step_token_budget=0) and the pp engine
        # keep the host-walk _spec_step — the former is the seeded
        # bit-identity oracle the fused path is tested against, the
        # latter genuinely cannot fuse (GPipe pipelines T=1 steps only).
        self._fused_spec = self.ec.spec_k > 0 and self._mixed_mode
        if (self.ec.spec_k > 0 and self.ec.decode_horizon > 1
                and not self._fused_spec):
            # the host-walk paths run ONE verify round per tick and would
            # silently drop the requested horizon — refuse loudly (the
            # genuinely unsupported combos: a pp mesh, or the sequential
            # budget=0 oracle engine)
            raise ValueError(
                "decode_horizon > 1 with spec_k > 0 needs the fused "
                "engine (step_token_budget > 0 and no pp mesh); the "
                "host-walk verify path cannot fuse horizons")
        # host-RAM KV spill tier: evicted prefix pages (and finished
        # rows' decode pages) demote here instead of being lost, and
        # swap back on a prefix hit (serving/pagestore.py)
        self.pagestore = None
        if self.ec.kv_spill_bytes > 0:
            from ipex_llm_tpu.serving.pagestore import PageStore

            self.pagestore = PageStore(self.ec.kv_spill_bytes)
        self.alloc = PageAllocator(
            self.ec.n_pages,
            spill=self._spill_pages if self.pagestore is not None else None)
        self.tables = np.full((r, self.ec.max_pages), -1, np.int32)
        # block-table dirty-row tracking: every host-side mutation of
        # ``self.tables`` records its row here, and device syncs scatter
        # ONLY those rows into the resident tables (kv.with_table_rows)
        # instead of re-uploading the whole [R, maxP] table per chunk.
        # PagedKVCache.init and self.tables both start all -1, so host and
        # device are in sync from construction.
        self._dirty_tables: set[int] = set()
        self.rows: list[Request | None] = [None] * r
        self.row_lens = np.zeros((r,), np.int32)
        self.row_budget = np.zeros((r,), np.int32)
        self.toks = np.zeros((r,), np.int32)
        self.temps = np.zeros((r,), np.float32)
        self.top_ps = np.ones((r,), np.float32)
        self.seeds = np.full((r,), -1, np.int32)
        self.top_ks = np.zeros((r,), np.int32)
        # chunked prefill: rows still consuming their prompt
        self._prefilling: dict[int, np.ndarray] = {}  # row -> remaining ids
        self._row_keys: dict[int, list[bytes]] = {}   # row -> prefix hashes
        self.key = jax.random.PRNGKey(0)
        self._inbox: "queue.Queue[Request]" = queue.Queue()
        # engine-thread host operations (KV page-set export/import):
        # closures enqueued here run BETWEEN transactional ticks on the
        # engine thread — over committed state, with exclusive access to
        # the pool/allocator/prefix cache — via run_on_engine().  Gathers
        # and scatters they perform are epoch-boundary work, never tick
        # work (JP106's one-dispatch tick is untouched).
        self._host_ops: "queue.Queue[tuple]" = queue.Queue()
        # host-side FIFO the engine thread owns: submissions drain from the
        # (cross-thread) inbox into this deque, admission pops its head,
        # and a pool-dry requeue puts the head BACK AT THE HEAD — the old
        # inbox.put() requeue rotated it behind later arrivals (the same
        # bug class as the _wait_for_work peek fix).  Being engine-owned
        # it also checkpoints/rolls back with the rest of the tick state.
        self._pending: "deque[Request]" = deque()
        self._work = threading.Event()   # set on submit: idle-loop wakeup
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # fault domain: the scripted fault source (tests/chaos bench), the
        # per-tick emission staging buffer (client-visible queue puts are
        # deferred until the tick commits, so a rolled-back tick never
        # leaks a token), requests masked out of the step during bisection
        # probes, arrivals drained mid-transaction (re-appended on
        # rollback so they are never lost), and the transient-retry and
        # drain lifecycle state.
        self.injector = fault_injector
        self._staging: list[tuple["queue.Queue", int | None]] | None = None
        self._masked: set[str] = set()
        self._tick_arrivals: list[Request] = []
        self._retries = 0
        # device dispatches issued by the current tick (flight-recorder
        # bookkeeping; the JP106 audit pins the fused tick's at 1)
        self._tick_dispatches = 0
        self._draining = False
        self._drain_deadline: float | None = None
        self._drain_abort = threading.Event()
        # device-resident hot state (toks / row_lens / active / sampling
        # params / eos / budgets): uploaded ONLY on epochs — admission,
        # prefill progress, finish, page allocation — and otherwise carried
        # forward on device by the fused decode step.  ``_dirty`` marks
        # that host-side state diverged from the device copies.
        self._dev: dict[str, jnp.ndarray] | None = None
        self._dirty = True
        # request-lifecycle tracing (observe.py): None unless the config
        # enables it — every trace site below guards on that None, so the
        # disabled engine pays one attribute check per site.  Spans stage
        # in _span_staging during a transactional tick and flush only on
        # _commit (the _queue_put discipline applied to telemetry).
        self.tracer = (Tracer(self.ec.trace_buffer)
                       if self.ec.trace_requests else None)
        self._span_staging: list[tuple[str, dict]] = []
        # tick flight recorder: always on (one small dict per committed
        # working tick); _fail_all and quarantine freeze it automatically
        self.flight = FlightRecorder(self.ec.flight_ring)
        # recovery-evidence baselines: retries and injector hits land
        # BETWEEN records (the failed tick rolls back and never records,
        # _recover bumps afterwards, and the next checkpoint absorbs the
        # bump into its m0) — so per-record deltas key off the last
        # RECORDED tick, not the per-tick checkpoint, or the ring would
        # show retries=0 and no fault_sites for exactly the faults it
        # exists to explain
        self._flight_retries0 = 0
        self._flight_hits0: dict = {}
        # honest latency histograms (fixed Prometheus buckets, fleet-
        # summable, checkpoint/rollback-safe): TTFT, client-visible
        # inter-token latency (bursty by design under a fused horizon —
        # the distribution SHOWS the H-token delivery granularity),
        # blocking tick-sync time, and swap-in measured through the
        # completion barrier (the vacuous enqueue-only p95 fix)
        self.hists: dict[str, Histogram] = {
            "ttft_s": Histogram(LATENCY_BUCKETS_S),
            "token_latency_s": Histogram(LATENCY_BUCKETS_S),
            "tick_sync_s": Histogram(FAST_LATENCY_BUCKETS_S),
            "swap_in_s": Histogram(FAST_LATENCY_BUCKETS_S),
        }
        # device-time observatory (serving/perfwatch.py): attribution
        # histograms register into self.hists so checkpoint/rollback and
        # the committed /metrics exposition cover them for free; the
        # manifest + audit-model flops give the MFU join its cost basis
        # (a stripped install without the analysis package keeps serving
        # — the sentinel then counts compiles without grid membership
        # and the MFU join reports None).
        self.perf = None
        self._perf_asserted = False
        if self.ec.perfwatch:
            from ipex_llm_tpu.serving.perfwatch import (PerfWatch,
                                                        model_flops_per_token)

            manifest = None
            scales: dict[str, float] = {}
            try:
                from ipex_llm_tpu.analysis.trace import manifest as _mf
                from ipex_llm_tpu.analysis.trace.registry import (
                    audit_cfg, audit_cfg_tp)

                loaded = _mf.load()
                mine = model_flops_per_token(cfg)
                scales = {
                    "bf16": mine / model_flops_per_token(audit_cfg("bf16")),
                    "sym_int4": mine / model_flops_per_token(
                        audit_cfg("sym_int4")),
                    "tp": mine / model_flops_per_token(audit_cfg_tp()),
                }
                # assigned only once the scales computed: a manifest
                # without its model scale would join MFU at scale 1.0 —
                # the audit model's flops reported as this model's,
                # silently wrong by orders of magnitude (None is the
                # honest degraded mode)
                manifest = loaded
            except Exception:
                scales = {}
            self.perf = PerfWatch(hists=self.hists, manifest=manifest,
                                  flops_scales=scales)
        # the COMMITTED view /metrics serves: `self.hists` mutates
        # mid-tick and reverts on rollback, so a scrape reading it live
        # could observe counts a rollback then subtracts — a Prometheus
        # counter going backwards reads as a reset and fabricates rates.
        # _commit republishes this dict (atomic reference swap; the
        # published Histograms are never mutated after publication).
        self._hists_committed: dict[str, Histogram] = {
            k: h.copy() for k, h in self.hists.items()}
        # rolling TTFT window for /health (what the admission-wave mixed
        # step is judged on)
        self._ttfts: "deque[float]" = deque(maxlen=128)
        # rolling speculative-acceptance window for /health: per-tick
        # (drafts proposed, drafts accepted) pairs — checkpoint/rollback-
        # safe like the TTFT window, so a retried tick never double-counts
        self._spec_window: "deque[tuple[int, int]]" = deque(maxlen=128)
        self.metrics = {"requests": 0, "tokens": 0, "steps": 0,
                        # committed transactional ticks — monotonic even
                        # when idle (the loop keeps ticking), so a frozen
                        # value with uptime advancing is the router's
                        # wedged-replica liveness signal (/health replica
                        # block)
                        "ticks": 0,
                        "prefix_hits": 0, "prefix_pages_shared": 0,
                        # host-sync economics (the fused-horizon story):
                        # decode iterations per blocking device->host sync,
                        # seconds spent blocked, uploads of row state
                        "host_syncs": 0, "host_sync_s": 0.0,
                        "tokens_per_sync": 0.0, "epoch_syncs": 0,
                        "decode_horizon_effective": 0,
                        # admission-wave economics (the mixed-step story):
                        # mixed ticks run, prompt tokens prefilled per
                        # tick, dirty-row table syncs, rolling TTFT p95
                        "mixed_steps": 0, "mixed_prefill_tokens": 0,
                        "prefill_tokens_per_step": 0.0,
                        "table_row_syncs": 0, "ttft_p95_s": 0.0,
                        # fault-domain observability: per-request failures
                        # isolated by bisection, transient step retries,
                        # load-shed submissions, expired deadlines, and
                        # the current admission backlog
                        "errors_isolated": 0, "retries": 0, "rejected": 0,
                        "timeouts": 0, "queue_depth": 0,
                        # kv-pool pressure: allocation failures that forced
                        # a clamp/fallback (paired with the allocator's
                        # prefix_evictions in /health's kv block)
                        "alloc_fail_clamps": 0}
        # tick planner (serving/planner.py): the decision function that
        # owns the tick's shape.  Constructed last so the initial plan —
        # what a pre-loop _step_once caller runs under — sees a fully
        # built engine; _tick re-plans every fresh tick (retries and
        # bisection probes replay the checkpointed plan instead).
        self.planner = make_planner(self.ec)
        self._plan = self.planner.plan(self)
        # mid-tick evidence that the page-pool safety clamp cut the
        # planned horizon (the flight ring's plan_clamped field); reset
        # per tick attempt
        self._plan_overrun = False
        # device-resident spec token history goes stale when a decode
        # emits through the plain steady program (the planner masking
        # spec off for a tick): the next spec tick forces an epoch
        # re-upload, which rebuilds hist from the host-side ids
        self._hist_stale = False

    # -- public API ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)

    def submit(self, req: Request) -> Request:
        """Enqueue a request; raises ``EngineOverloaded`` when the engine
        is draining (HTTP surfaces map it to 503) or the bounded queue is
        full (→ 429) — load shedding instead of unbounded backlog."""
        if self._draining:
            self.metrics["rejected"] = self.metrics.get("rejected", 0) + 1
            raise EngineOverloaded("engine is draining",
                                   queue_depth=self.queue_depth,
                                   draining=True)
        depth = self.queue_depth
        if self.ec.max_queue and depth >= self.ec.max_queue:
            self.metrics["rejected"] = self.metrics.get("rejected", 0) + 1
            raise EngineOverloaded(
                f"queue full ({depth} requests waiting)", queue_depth=depth)
        if not req.request_id:
            # quarantine/bisection and injector scoping key on request_id
            req.request_id = uuid.uuid4().hex
        if not req.eos_token_id:
            req.eos_token_id = self.default_eos
        self._inbox.put(req)
        self._work.set()
        return req

    @property
    def queue_depth(self) -> int:
        """Requests waiting for a row (inbox + pending, not in-flight)."""
        return self._inbox.qsize() + len(self._pending)

    def run_on_engine(self, fn, timeout: float = 120.0):
        """Run ``fn()`` on the engine thread BETWEEN transactional ticks
        — over committed state, with exclusive pool/allocator/prefix-
        cache access — and return its result (raising whatever ``fn``
        raised).  The transport surface (export_prefix / import_pages)
        routes through here so its gathers/scatters are epoch-boundary
        work that can never interleave with a half-done tick.  Called
        FROM the engine thread, or with no live engine thread (tests
        driving ``_tick`` directly), it runs inline."""
        t = self._thread
        if (t is None or not t.is_alive()
                or threading.current_thread() is t):
            return fn()
        box: "queue.Queue" = queue.Queue()
        self._host_ops.put((fn, box))
        self._work.set()
        try:
            ok, res = box.get(timeout=timeout)
        except queue.Empty:
            # the engine thread died/stopped with the op still queued
            # (the loop's exit drain failures-out stragglers, but a
            # thread killed hard never reaches it): fail clean instead
            # of leaking a bare queue.Empty to the HTTP handler
            raise RuntimeError(
                "engine did not service the host operation "
                f"within {timeout}s (stopped or wedged)") from None
        if not ok:
            raise res
        return res

    def _drain_host_ops(self):
        """Run queued host operations at the tick boundary: they see
        only committed state, and what they mutate IS committed state
        for the next tick's checkpoint."""
        while True:
            try:
                fn, box = self._host_ops.get_nowait()
            except queue.Empty:
                return
            try:
                box.put((True, fn()))
            except Exception as e:      # delivered to the waiting caller
                box.put((False, e))

    def kv_stats(self) -> dict:
        """KV-pool observability for /health and the bench sweeps: what
        the pool costs (storage format, page/pool bytes), how full it is,
        and the pressure trace (prefix-cache LRU evictions, allocation
        failures that forced a clamp) — the numbers the fp8-vs-bf16
        fixed-byte-budget story is judged on."""
        a = self.alloc
        out = {
            "storage": self.ec.kv_storage,
            "page_size": self.ec.page_size,
            "pages_total": a.n_pages,       # page 0 = reserved scratch
            "pages_free": len(a.free),
            "pages_in_use": a.pages_in_use,
            "page_bytes": self.page_bytes,
            "pool_bytes": a.n_pages * self.page_bytes,
            "prefix_pages_cached": len(a.prefix),
            "prefix_evictions": a.prefix_evictions,
            "alloc_fail_clamps": self.metrics.get("alloc_fail_clamps", 0),
            "horizon_clamped": self.metrics.get("horizon_clamped", 0),
        }
        # spill-tier block (flat numeric keys ride the replica /metrics
        # exposition and the router's fleet aggregation unchanged; the
        # router's affinity freshness check reads spill_enabled /
        # spill_pages / swap_in_hit_rate to tell a demotion from a loss)
        if self.pagestore is not None:
            out.update(self.pagestore.stats())
        else:
            out["spill_enabled"] = False
        return out

    def weight_stats(self) -> dict:
        """Weight-pool observability for /health's ``weights`` block and
        the fixed-byte-budget bench: the serving width axis
        (``EngineConfig.weight_qtype`` plus the packed formats actually
        present in the tree), what the params cost in HBM as stored, what
        the same tree would cost at bf16 full width, and the bytes the
        packing freed — the budget the KV pool is co-planned with
        (``kv_pool_bytes`` + ``weight_bytes`` side by side is the one
        HBM cap an operator provisions).  ``qtype`` is the width actually
        SERVED (derived from the planes when the tree arrived packed at a
        different width than requested — the mismatch warns at build);
        ``requested_qtype`` echoes the config axis."""
        return {
            "qtype": self._served_qtype,
            "requested_qtype": self.ec.weight_qtype,
            "packed_qtypes": list(self._weight_qtypes),
            "weight_bytes": self._weight_bytes,
            "dense_bytes": self._weight_dense_bytes,
            "bytes_saved": self._weight_dense_bytes - self._weight_bytes,
        }

    def spec_stats(self) -> dict:
        """Speculative-decoding observability for /health and the bench
        sweeps: lifetime draft economics, the rolling accept rate (128-
        tick window — what the operator tunes spec_k/spec_ngram against),
        and tokens emitted per spec-tick dispatch (the amortization the
        on-device loop buys; 0 when spec is off or nothing ran)."""
        m = self.metrics
        win = list(self._spec_window)
        w_prop = sum(p for p, _ in win)
        w_acc = sum(a for _, a in win)
        return {
            "spec_k": self.ec.spec_k,
            "spec_ngram": self.ec.spec_ngram,
            "fused": self._fused_spec,
            "draft_proposed": m.get("draft_proposed", 0),
            "draft_accepted": m.get("draft_accepted", 0),
            "accept_rate": round(w_acc / w_prop, 4) if w_prop else 0.0,
            "accept_rate_lifetime": m.get("spec_accept_rate", 0.0),
            "tokens_per_dispatch": m.get("spec_tokens_per_dispatch", 0.0),
        }

    # -- observability (serving/observe.py) ---------------------------------

    def _trace(self, req: Request | None, name: str, t0: float | None = None,
               t1: float | None = None, **attrs):
        """Record one lifecycle span/event on ``req``'s trace.  Inside a
        transactional tick the span STAGES (beside the token emissions)
        and flushes only on ``_commit`` — a rolled-back tick never leaks
        a span; outside a tick (recovery, quarantine) it lands directly.
        One ``is None`` check when tracing is disabled."""
        if self.tracer is None or req is None:
            return
        s = span(name, time.time() if t0 is None else t0, t1,
                 origin="engine", **attrs)
        tid = req.trace_id or req.request_id
        if self._staging is not None:
            self._span_staging.append((tid, s))
        else:
            self.tracer.add(tid, s)

    def trace_view(self, trace_id: str) -> dict | None:
        """Assembled span list for one trace (/trace/{id}); None when
        tracing is disabled or the trace aged out of the LRU."""
        if self.tracer is None:
            return None
        return self.tracer.get(trace_id)

    def histograms(self) -> dict[str, Histogram]:
        """The engine's latency histograms (real Prometheus
        ``_bucket/_sum/_count`` series on /metrics; fleet-summed by the
        router).  Returns the last COMMITTED view, not the live tick
        state: mid-tick observations a rollback would subtract are never
        scrape-visible, so the exposed series stay monotonic."""
        return self._hists_committed

    def _perf_dispatch(self, family: str, point: dict | None = None,
                       tick: bool = True):
        """Perfwatch timing window around ONE device dispatch (no-op
        context when the observatory is off).  ``tick=True`` windows
        count toward the JP106 runtime cross-check against the
        hand-maintained ``_tick_dispatches`` counter."""
        if self.perf is None:
            return nullcontext()
        return self.perf.dispatch(family, point=point, tick=tick)

    def _perf_point(self, horizon: int, width: int = 0,
                    with_decode: bool = True, spec: bool = False,
                    pb: int = 0, maxp: int = 0, ew: int = 0) -> dict:
        """The dispatched tick's grid point — the SAME axes the trace
        audit's registry grid keys ``serving.ragged_tick`` entries on
        (rows/width/horizon/kv plus the structural spec/wq/wd/tp/cq
        axes), which is what lets the sentinel classify a runtime
        compile against the manifest and the MFU join find its
        cost_analysis entry.  ``pb``/``maxp``/``ew`` are the
        retrace-driving pad axes the audit does not lock: they ride the
        sentinel's warm/cold identity only."""
        pt: dict = {"rows": self.ec.max_rows, "width": int(width),
                    "horizon": int(horizon), "kv": self.ec.kv_storage}
        if not with_decode:
            pt["wd"] = False
        if spec:
            pt["spec"] = self.ec.spec_k
        if self._served_qtype is not None:
            pt["wq"] = self._served_qtype
        if self._tp_manual:
            pt["tp"] = int(self.mesh.shape.get("tp", 1))
            if self._collective_qtype != "bf16":
                pt["cq"] = self._collective_qtype
        if pb:
            pt["pb"] = int(pb)
        if maxp:
            pt["maxp"] = int(maxp)
        if ew:
            pt["ew"] = int(ew)
        return pt

    def perf_view(self) -> dict | None:
        """The /health ``perf`` block (None when perfwatch is off)."""
        return self.perf.view() if self.perf is not None else None

    def perf_numeric(self) -> dict:
        """Flat ``perf_``-prefixed counters for the /metrics exposition."""
        if self.perf is None:
            return {}
        return {f"perf_{k}": v
                for k, v in self.perf.metrics_numeric().items()}

    def _flight_pending(self) -> dict:
        """Recovery evidence accumulated since the last RECORDED tick:
        a failed tick rolls back and never records, and _recover bumps
        its counters afterwards — so retries and injector site hits are
        invisible to per-tick checkpoint deltas and must be carried
        against the last-record baseline instead (the next committed
        record absorbs them; dumps taken at the recovery decision carry
        them immediately)."""
        out = {"retries": self.metrics.get("retries", 0)
               - self._flight_retries0}
        if self.injector is not None:
            hits = {k: v - self._flight_hits0.get(k, 0)
                    for k, v in self.injector.site_hits.items()
                    if v != self._flight_hits0.get(k, 0)}
            if hits:
                out["fault_sites"] = hits
        return out

    def _flight_record(self, m0: dict, snap: dict, t_wall: float):
        """Append one committed tick's record to the flight recorder —
        per-tick DELTAS against the pre-tick checkpoint (recovery
        evidence against the last-record baseline; see
        ``_flight_pending``), so the ring reads as what each tick did,
        not cumulative counters.  Pure idle ticks are skipped (the idle
        loop runs ~50 ticks/s; recording them would flush real work out
        of the ring in seconds)."""
        m = self.metrics

        def d(key):
            return m.get(key, 0) - m0.get(key, 0)

        pend = self._flight_pending()
        tokens, admitted = d("tokens"), d("requests")
        working = (tokens or admitted or d("mixed_prefill_tokens")
                   or pend["retries"] or pend.get("fault_sites")
                   or d("errors_isolated") or d("timeouts")
                   or self._tick_dispatches)
        if not working:
            if self.perf is not None:   # discard the idle tick's scratch
                self.perf.tick_finish(self._tick_dispatches, working=False)
            self.flight.skip_idle()
            return
        pages_before = self.ec.n_pages - 1 - len(snap["alloc"][0])
        rec = {
            "t": round(t_wall, 3),
            "tick": m.get("ticks", 0),
            "dispatches": self._tick_dispatches,
            "sync_s": round(m.get("host_sync_s", 0.0)
                            - m0.get("host_sync_s", 0.0), 6),
            "rows_active": int(sum(1 for i, r in enumerate(self.rows)
                                   if r is not None
                                   and i not in self._prefilling)),
            "rows_prefilling": len(self._prefilling),
            "queue_depth": m.get("queue_depth", 0),
            "tokens": tokens,
            "admitted": admitted,
            "pages_in_use": self.alloc.pages_in_use,
            "pages_delta": self.alloc.pages_in_use - pages_before,
            "prefix_evictions": self.alloc.prefix_evictions
            - snap["alloc"][4],
            "alloc_fail_clamps": d("alloc_fail_clamps"),
            "retries": pend["retries"],
        }
        if self.pagestore is not None and snap["pagestore"] is not None:
            rec["pages_spilled"] = (self.pagestore.spills
                                    - snap["pagestore"]["spills"])
            rec["swap_ins"] = (self.pagestore.swap_ins
                               - snap["pagestore"]["swap_ins"])
        if pend.get("fault_sites"):
            rec["fault_sites"] = pend["fault_sites"]
        # device-time observatory: attribution buckets (summing to the
        # tick's wall clock), the MFU join for the dispatched grid
        # point, any compile events the sentinel attributed to this
        # tick, and the JP106 dispatch cross-check — committed ticks
        # only, so a rollback leaves no attribution residue
        pf = {}
        if self.perf is not None:
            pf = self.perf.tick_finish(self._tick_dispatches, working=True)
            rec.update(pf)
        # plan vs. actual: the tick's plan stamp, whether the grid or the
        # page-pool safety clamp cut it, and the prediction error against
        # the measured wall clock (the perf_plan_error histogram the
        # planner is judged on) — then the measured tick feeds the
        # planner's EWMA rates (committed working ticks only, so a
        # rolled-back tick leaves no rate residue)
        plan = self._plan
        if plan is not None:
            rec["plan"] = plan.flight_fields()
            if plan.clamped or self._plan_overrun:
                rec["plan_clamped"] = True
            actual_s = pf.get("wall_s") or (time.time() - t_wall)
            if self.perf is not None and plan.predicted_s > 0:
                rec["plan_err"] = self.perf.note_plan_error(
                    plan.predicted_s, actual_s)
            self.planner.observe(
                family=pf.get("perf_family"), wall_s=actual_s,
                executed=d("steps"),
                prefill_tokens=d("mixed_prefill_tokens"))
        # consumed: the next record's recovery deltas start here
        self._flight_retries0 = m.get("retries", 0)
        if self.injector is not None:
            self._flight_hits0 = dict(self.injector.site_hits)
        self.flight.record(rec)
        # the runtime enforcement of JP106's hand-maintained `+= 1`
        # bookkeeping: the observed dispatch-window count must equal
        # _tick_dispatches.  Debug assert AFTER the ring has the
        # evidence (under -O only the recorded field remains) — and
        # ONCE per engine: a deterministic divergence would otherwise
        # re-raise every tick, escalating an observability discrepancy
        # into a permanent fail-all loop (later ticks keep recording
        # the field and bumping perf.dispatch_mismatches).
        if pf.get("dispatch_mismatch") and not self._perf_asserted:
            self._perf_asserted = True
            assert False, (
                "JP106 runtime cross-check diverged: "
                f"{pf['dispatch_mismatch']} (see the flight ring)")

    def planner_view(self) -> dict:
        """/health ``planner`` block: mode, monotonic decision counters,
        the last plan, the measured EWMA rates, and the deadline-miss
        rate (timeouts over admitted-plus-expired submissions — an
        approximation: queue-expired requests never admit, in-flight
        timeouts count in both terms)."""
        v = self.planner.view()
        t = self.metrics.get("timeouts", 0)
        v["deadline_miss_rate"] = round(
            t / max(self.metrics.get("requests", 0) + t, 1), 4)
        return v

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drain_seconds_left(self) -> float:
        """Seconds until the graceful-drain window closes (0.0 when not
        draining, or when drain was flagged without a recorded deadline)
        — what a 503 Retry-After is derived from: by then this replica
        has either finished restarting or shed everything."""
        if not self._draining or self._drain_deadline is None:
            return 0.0
        return max(0.0, self._drain_deadline - time.monotonic())

    def abort(self, req: Request):
        """Cancel a request (e.g. client disconnect); its row frees at the
        next step boundary."""
        req.cancelled = True

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: reject new submissions (503), let queued and
        in-flight requests finish, then abort stragglers at the deadline.
        Returns True when everything finished inside ``timeout``.  The
        engine thread keeps running (call ``stop()`` afterwards); /health
        reports "draining" for the duration."""
        self._draining = True
        # recorded so the HTTP surfaces can derive an honest Retry-After
        # on the 503 draining path (drain_seconds_left)
        self._drain_deadline = time.monotonic() + timeout

        def busy():
            return (any(r is not None for r in self.rows)
                    or bool(self._pending) or not self._inbox.empty())

        deadline = time.monotonic() + timeout
        while busy() and time.monotonic() < deadline:
            time.sleep(0.01)
        clean = not busy()
        if not clean:
            # deadline passed: have the engine thread shed what remains
            # (rows abort at the next tick boundary, queued requests fail
            # immediately) — cross-thread state stays engine-owned
            self._drain_abort.set()
            self._work.set()
            hard = time.monotonic() + 10.0
            while busy() and time.monotonic() < hard:
                time.sleep(0.01)
        return clean

    # -- fault domain --------------------------------------------------------

    def _fault_point(self, site: str, rows=(), reqs=()):
        """Guarded site: the injector may raise here, BEFORE the device or
        allocator operation the site names, so an injected fault never
        leaves half-committed device state behind (the recovery contract).
        ``rows``/``reqs`` name the participating requests — what scopes a
        poisoned-request spec and what bisection masks."""
        if self.injector is None:
            return
        ids = [r.request_id for r in reqs if r is not None]
        for i in rows:
            r = self.rows[i]
            if r is not None:
                ids.append(r.request_id)
        self.injector.hit(site, ids)

    def _queue_put(self, req: Request, item: int | None):
        """Client-visible emission: staged during a transactional tick and
        flushed only on commit, so a rolled-back (retried/bisected) tick
        never leaks a token or a terminal None to a stream consumer."""
        if self._staging is not None:
            self._staging.append((req.stream_queue, item))
        else:
            req.stream_queue.put(item)

    def _checkpoint(self) -> dict:
        """Snapshot every piece of host state a tick can mutate — row
        bookkeeping, the page allocator (free list, refcounts, prefix
        cache), the pending FIFO, the PRNG key chain, metrics, and the
        mutable fields of every in-flight/queued Request.  Device state is
        deliberately NOT snapshotted: the recovery contract is that KV
        writes beyond the committed ``row_lens`` are scratch (a retried
        tick rewrites the same slots with the same values), and rollback
        forces a full epoch re-upload + whole-table rescatter so the
        device copies converge back to the restored host state."""
        reqs = [r for r in self.rows if r is not None] + list(self._pending)
        return {
            "rows": list(self.rows),
            "row_lens": self.row_lens.copy(),
            "row_budget": self.row_budget.copy(),
            "toks": self.toks.copy(),
            "temps": self.temps.copy(),
            "top_ps": self.top_ps.copy(),
            "seeds": self.seeds.copy(),
            "top_ks": self.top_ks.copy(),
            "tables": self.tables.copy(),
            "prefilling": dict(self._prefilling),
            "row_keys": dict(self._row_keys),
            "pending": list(self._pending),
            "alloc": (list(self.alloc.free), self.alloc.ref.copy(),
                      OrderedDict(self.alloc.prefix),
                      dict(self.alloc._page_key),
                      self.alloc.prefix_evictions),
            "key": self.key,
            "metrics": dict(self.metrics),
            "ttfts": list(self._ttfts),
            "spec_window": list(self._spec_window),
            # the tick's plan (immutable TickPlan): a rolled-back tick's
            # retry — and every bisection probe — replays it verbatim
            "plan": self._plan,
            # the spill tier mutates mid-tick (evictions demote pages,
            # swap-ins consume entries): bookkeeping-only snapshot, so a
            # rolled-back tick leaves the store residue-free
            "pagestore": (self.pagestore.snapshot()
                          if self.pagestore is not None else None),
            # the latency histograms revert with the tick (PR 5's counter
            # rule): a rolled-back tick's TTFT/token-latency observations
            # were never client-visible — O(buckets) per histogram
            "hists": {k: h.snapshot() for k, h in self.hists.items()},
            "reqs": [(r, len(r.output_ids), len(r.logprobs),
                      r.finish_reason, r.first_token_s, r._last_tok_s)
                     for r in reqs],
        }

    def _rollback(self, snap: dict):
        """Restore the checkpoint: the tick never happened.  Staged
        emissions are discarded (clients saw nothing), arrivals drained
        mid-tick re-append to the pending FIFO (they were never admitted),
        and the device copies are marked fully stale."""
        self._staging = None
        self.rows = list(snap["rows"])
        self.row_lens = snap["row_lens"].copy()
        self.row_budget = snap["row_budget"].copy()
        self.toks = snap["toks"].copy()
        self.temps = snap["temps"].copy()
        self.top_ps = snap["top_ps"].copy()
        self.seeds = snap["seeds"].copy()
        self.top_ks = snap["top_ks"].copy()
        self.tables = snap["tables"].copy()
        self._prefilling = dict(snap["prefilling"])
        self._row_keys = dict(snap["row_keys"])
        free, ref, prefix, pkey, evictions = snap["alloc"]
        self.alloc.free = list(free)
        self.alloc.ref = ref.copy()
        self.alloc.prefix = OrderedDict(prefix)
        self.alloc._page_key = dict(pkey)
        self.alloc.prefix_evictions = evictions
        if self.pagestore is not None and snap["pagestore"] is not None:
            # undone spills vanish, consumed swap-in entries come back;
            # data a doomed swap-in scattered into a (now re-freed) pool
            # page is unreferenced garbage, exactly like a rolled-back
            # tick's KV writes past the committed row_lens
            self.pagestore.restore(snap["pagestore"])
        self.key = snap["key"]
        # the rolling TTFT window reverts too: a first token recorded by
        # the doomed tick (or a bisection probe) was never emitted, and the
        # retried tick will record it again
        self._ttfts = deque(snap["ttfts"], maxlen=self._ttfts.maxlen)
        self._spec_window = deque(snap["spec_window"],
                                  maxlen=self._spec_window.maxlen)
        self._plan = snap["plan"]
        # metrics revert wholesale except the cross-thread counter submit()
        # bumps (a rejection during the doomed tick really happened)
        m = dict(snap["metrics"])
        m["rejected"] = max(self.metrics.get("rejected", 0),
                            m.get("rejected", 0))
        self.metrics = m
        for k in list(self.hists):
            if k in snap["hists"]:
                self.hists[k].restore(snap["hists"][k])
            else:
                # a perfwatch family histogram born inside the rolled-
                # back tick (lazy registration): it never existed at the
                # checkpoint, so it does not exist now
                del self.hists[k]
        # staged spans discard with the tick: clients saw no tokens, the
        # trace must show no spans (the retry/quarantine events recovery
        # writes are post-rollback, so they survive by construction)
        self._span_staging = []
        for r, n_out, n_lp, fin, fts, lts in snap["reqs"]:
            del r.output_ids[n_out:]
            del r.logprobs[n_lp:]
            r.finish_reason = fin
            r.first_token_s = fts
            r._last_tok_s = lts
        self._pending = deque(snap["pending"])
        for r in self._tick_arrivals:   # drained mid-tick: fresh again
            r.output_ids.clear()
            r.logprobs.clear()
            r.finish_reason = None
            r.first_token_s = 0.0
            self._pending.append(r)
        self._tick_arrivals = []
        # device copies are now ahead of the restored host state: force a
        # full row-state epoch AND a whole-table rescatter next dispatch
        self._dev = None
        self._dirty = True
        self._dirty_tables = set(range(self.ec.max_rows))

    def _commit(self):
        """Flush the tick's staged emissions to the client queues, in
        emission order — the only point tokens become externally visible."""
        staged, self._staging = self._staging, None
        staged_spans, self._span_staging = self._span_staging, []
        self._tick_arrivals = []
        for q, item in staged:
            q.put(item)
        if self.tracer is not None:
            for tid, s in staged_spans:
                self.tracer.add(tid, s)
        self.metrics["queue_depth"] = self.queue_depth
        # republish the scrape-visible histogram view (O(buckets), same
        # cost class as the per-tick checkpoint snapshots).  With the
        # observatory on, the republish happens at the end of _tick
        # instead (attribution observes in _flight_record, after this
        # point) — doing it here too would copy every histogram twice
        # per tick for nothing.
        if self.perf is None:
            self._hists_committed = {k: h.copy()
                                     for k, h in self.hists.items()}

    def _tick(self):
        """ONE transactional engine tick: checkpoint, run the step,
        commit; on a step fault, roll back (clients saw nothing) and run
        the recovery policy — transient retry, or bisection + per-request
        quarantine.  This is the unit of failure isolation.  Returns True
        when the tick committed cleanly (no recovery ran)."""
        if self._drain_abort.is_set():
            self._shed_remaining()
            self._drain_abort.clear()
        # plan the tick BEFORE the checkpoint: the plan snapshots with
        # the tick state, so a transient-retry re-run and every bisection
        # probe replay the SAME plan — recovery reproduces the failed
        # tick's exact shape instead of re-deciding against post-fault
        # queue state.  (Planner decision counters are sentinel-style
        # monotonic for the same reason compile counters are: a
        # rolled-back tick's planning really happened.)
        if self._retries == 0 or self._plan is None:
            self._plan = self.planner.plan(self)
        self._plan_overrun = False
        snap = self._checkpoint()
        self._staging = []
        self._span_staging = []
        self._tick_arrivals = []
        self._tick_dispatches = 0
        if self.perf is not None:
            self.perf.tick_begin()
        t_wall = time.time()
        try:
            self._step_once()
        except Exception as exc:
            if self.perf is not None:
                self.perf.tick_abort()   # a rolled-back tick measures nothing
            self._rollback(snap)
            self._recover(exc)
            return False
        self._commit()
        self._retries = 0
        # post-commit on purpose: a rolled-back tick never advances the
        # liveness counter, so `ticks` moves iff the engine makes progress
        self.metrics["ticks"] = self.metrics.get("ticks", 0) + 1
        self._flight_record(snap["metrics"], snap, t_wall)
        if self.perf is not None:
            # the attribution observations land in _flight_record (post-
            # commit, committed ticks only) — republish so the scrape
            # view includes THIS tick's buckets, not last tick's
            self._hists_committed = {k: h.copy()
                                     for k, h in self.hists.items()}
        return True

    def _recover(self, exc: BaseException):
        """Post-rollback recovery policy.  Transient → bounded exponential
        backoff, then the loop re-runs the tick from the committed state
        (same key chain, so the retried tick is bit-identical).  Exhausted
        retries or deterministic → bisect the participating request set
        and quarantine the culprit.  Only when bisection cannot localize
        the fault (it fires with every request masked — an engine-level
        failure) does ``_fail_all`` run."""
        if is_transient(exc) and self._retries < self.ec.max_step_retries:
            self._retries += 1
            self.metrics["retries"] = self.metrics.get("retries", 0) + 1
            if self.tracer is not None:
                # post-rollback, so these land directly: the trace shows
                # the retry/rollback the client never saw tokens from
                for req in [r for r in self.rows if r is not None] + \
                        list(self._pending):
                    self._trace(req, "retry", attempt=self._retries,
                                error=f"{type(exc).__name__}: {exc}")
            self._stop.wait(
                self.ec.retry_backoff_s * (2 ** (self._retries - 1)))
            return
        self._retries = 0
        culprit = self._bisect_culprit()
        if culprit is _FAULT_VANISHED:
            # the fault did not reproduce on an immediate re-run: treat it
            # as transient-resolved and carry on from the committed state
            self.metrics["last_error"] = f"{type(exc).__name__}: {exc}"
            return
        if culprit is None:
            self._fail_all(exc)     # engine-level: the blast-radius backstop
            return
        self._quarantine(culprit, exc)

    def _probe(self, masked_ids: set) -> BaseException | None:
        """Bisection probe: re-run the tick with ``masked_ids`` sat out
        (inactive on device, skipped by admission/prefill), emissions
        muted, and EVERYTHING rolled back afterwards — probes only
        observe whether the fault fires, they never commit."""
        snap = self._checkpoint()
        self._staging = []
        self._span_staging = []     # probes mute spans like emissions
        self._tick_arrivals = []
        self._masked = set(masked_ids)
        self._dirty = True   # the active mask changed vs the device copy
        try:
            self._step_once()
            return None
        except Exception as e:
            return e
        finally:
            self._masked = set()
            self._rollback(snap)

    def _bisect_culprit(self):
        """Localize a deterministic fault to ONE request by re-running the
        tick with suspect subsets masked.  Returns the culprit Request,
        ``None`` when the fault is engine-level (fires with every suspect
        masked), or ``_FAULT_VANISHED`` when it does not reproduce."""
        suspects = [r for r in self.rows if r is not None]
        suspects += [r for r in self._pending if r not in suspects]
        if not suspects:
            return None
        if self._probe(set()) is None:
            return _FAULT_VANISHED
        all_ids = {r.request_id for r in suspects}
        if self._probe(all_ids) is not None:
            return None
        cands = suspects
        while len(cands) > 1:
            half = cands[:len(cands) // 2]
            if self._probe({r.request_id for r in half}) is None:
                cands = half            # fault silenced → culprit masked
            else:
                cands = cands[len(cands) // 2:]
        culprit = cands[0]
        # confirm: the culprit alone (everyone else masked) reproduces the
        # fault — guards against a fault that stopped firing mid-bisection
        # quarantining an innocent request
        if self._probe(all_ids - {culprit.request_id}) is None:
            return _FAULT_VANISHED
        return culprit

    def _quarantine(self, req: Request, exc: BaseException):
        """Finish exactly the culprit with ``finish_reason="error"`` —
        whether it holds a row (pages released) or is still queued — and
        keep everything else running.  The next tick re-runs without it
        and commits normally, so survivor streams are bit-identical to an
        unfaulted run (independent per-row sampling streams)."""
        self.metrics["errors_isolated"] = (
            self.metrics.get("errors_isolated", 0) + 1)
        self.metrics["last_error"] = (
            f"isolated to request {req.request_id[:12]}: "
            f"{type(exc).__name__}: {exc}")
        # the postmortem artifact, captured at the blast-radius decision:
        # the flight ring shows what the last N working ticks did leading
        # up to this isolation
        self.flight.dump("quarantine", request_id=req.request_id,
                         error=f"{type(exc).__name__}: {exc}",
                         # the failed ticks leading here rolled back and
                         # never recorded — their retries/injector hits
                         # ride the dump itself
                         **{f"{k}_pending": v for k, v
                            in self._flight_pending().items() if v},
                         **(self.perf.dump_fields()
                            if self.perf is not None else {}))
        self._trace(req, "quarantine",
                    error=f"{type(exc).__name__}: {exc}")
        for i, r in enumerate(self.rows):
            if r is req:
                self._finish(i, "error")
                return
        try:
            self._pending.remove(req)
        except ValueError:
            pass
        if req.finish_reason is None:
            req.finish_reason = "error"
        req.stream_queue.put(None)

    def _shed_remaining(self):
        """Drain-deadline enforcement (engine thread): abort whatever is
        still in flight or queued so ``drain`` can return bounded."""
        for i, r in enumerate(self.rows):
            if r is not None:
                self._finish(i, "abort")
        self._drain_inbox()
        while self._pending:
            req = self._pending.popleft()
            if req.finish_reason is None:
                req.finish_reason = "abort"
            req.stream_queue.put(None)
        self.metrics["queue_depth"] = self.queue_depth

    def _deadline_of(self, req: Request) -> float | None:
        d = (req.deadline_s if req.deadline_s is not None
             else self.ec.request_deadline_s)
        return d if d and d > 0 else None

    def _expire_deadlines(self):
        """Finish requests past their wall-clock budget: in-flight rows at
        this emission epoch, queued requests before they ever occupy a row
        (admission-time enforcement)."""
        now = time.perf_counter()
        for i, r in enumerate(self.rows):
            if r is None:
                continue
            d = self._deadline_of(r)
            if d is not None and now - r.submitted_s > d:
                self.metrics["timeouts"] = (
                    self.metrics.get("timeouts", 0) + 1)
                self._finish(i, "timeout")
        if self._pending:
            keep: "deque[Request]" = deque()
            for r in self._pending:
                d = self._deadline_of(r)
                if d is not None and now - r.submitted_s > d:
                    self.metrics["timeouts"] = (
                        self.metrics.get("timeouts", 0) + 1)
                    if r.finish_reason is None:
                        r.finish_reason = "timeout"
                    self._queue_put(r, None)
                else:
                    keep.append(r)
            self._pending = keep

    # -- page bookkeeping ----------------------------------------------------

    def _ensure_pages(self, row: int, upto_slot: int,
                      req: Request | None = None) -> bool:
        """Allocate pages so slots [0, upto_slot) are backed; False = dry.

        ``upto_slot`` past the table width is tolerated: the overflow is
        only ever right-padded prefill slack, which update_layer routes to
        the scratch page (admission caps real tokens at capacity).
        """
        self._fault_point("page-alloc", rows=(row,), reqs=(req,))
        need = min(-(-upto_slot // self.ec.page_size), self.ec.max_pages)
        missing = sum(1 for j in range(need) if self.tables[row, j] < 0)
        if missing > 1:
            # batch the burst's evictions: one spill gather instead of
            # one per page (drops the same pages lazy eviction would)
            self.alloc.reserve(missing)
        for j in range(need):
            if self.tables[row, j] < 0:
                pid = self.alloc.alloc()
                if pid is None:
                    # every caller clamps on a dry pool (shorter horizon,
                    # requeued admission, spec fallback, 'length' finish);
                    # count the event so pool pressure is visible in
                    # /health's kv block instead of only via its symptoms
                    self.metrics["alloc_fail_clamps"] = (
                        self.metrics.get("alloc_fail_clamps", 0) + 1)
                    return False
                self.tables[row, j] = pid
                # page allocation only touches THIS row's table: a dirty-
                # row scatter sync, not a full row-state epoch
                self._dirty_tables.add(row)
        return True

    def _release_row_pages(self, row: int):
        for j in range(self.ec.max_pages):
            pid = int(self.tables[row, j])
            if pid >= 0:
                self.alloc.decref(pid)
                self.tables[row, j] = -1
                self._dirty_tables.add(row)

    # -- host-RAM spill tier (serving/pagestore.py) -------------------------

    def _spill_pages(self, pairs):
        """PageAllocator eviction hook: demote a batch of cache-owned
        prefix pages' bytes to the host store just before their pool
        slots are recycled — ONE gather + one blocking sync for the
        whole batch (``PageAllocator.reserve`` batches an allocation
        burst's evictions into a single call here).  Epoch-boundary work
        (page allocation is an epoch); a raise fires before any store
        mutation, so rollback + retry see every cache entry intact."""
        self._fault_point("spill-store")
        pids = np.asarray([p for _, p in pairs], np.int32)
        k_pages, v_pages = self.cache.gather_pages(pids)
        t0 = time.perf_counter()
        # jaxlint: disable=JL002 -- designed epoch-boundary sync: the batch's bytes must reach host RAM before the pool slots are recycled (the demotion itself)
        k_np = d2h(k_pages)
        v_np = d2h(v_pages)  # jaxlint: disable=JL002 -- same designed spill sync; already blocked on k_np above
        self._count_sync(time.perf_counter() - t0)
        for i, (key, _) in enumerate(pairs):
            self.pagestore.spill(key,
                                 np.ascontiguousarray(k_np[:, i]),
                                 np.ascontiguousarray(v_np[:, i]))

    def _swap_in_chain(self, entries: list, req: Request | None = None
                       ) -> dict:
        """Promote a chain of spilled pages back into the pool in ONE
        batch: ``reserve()`` pre-evicts for the whole burst, allocation
        stops at the first dry pid (chain order — what fits is the
        unbroken head; the rest hand their entries back via
        ``untake``), one stacked scatter lands every accepted page, and
        ONE completion barrier covers the batch — per-page barriers
        serialized N full device round-trips on exactly the spill-heavy
        admission path the swap-in histogram monitors.  ``entries`` is
        ``[(key, (k_np, v_np)), ...]``; returns {key: pid} for the
        promoted head, each page registered cache-owned at ref 1 —
        byte-identical to one that never left the pool."""
        if not entries:
            return {}
        self._fault_point("swap-in")
        self.alloc.reserve(len(entries))
        pids: list[int] = []
        for _ in entries:
            pid = self.alloc.alloc()
            if pid is None:
                break                       # dry pool: keep what fit
            pids.append(pid)
        taken = entries[:len(pids)]
        for key, entry in entries[len(pids):]:
            self.pagestore.untake(key, entry)   # failed promotion
        if not taken:
            return {}
        t0 = time.perf_counter()
        t0_w = time.time()
        epoch = (self.perf.epoch_window("swap_in")
                 if self.perf is not None else nullcontext())
        with epoch:
            k_stack = np.stack([e[0] for _, e in taken], axis=1)
            v_stack = np.stack([e[1] for _, e in taken], axis=1)
            with self._perf_dispatch("swap_in", tick=False):
                self.cache = self.cache.scatter_pages(
                    np.asarray(pids, np.int32), h2d(k_stack),
                    h2d(v_stack))
            t_bar = time.perf_counter()
            # completion barrier: swap-in latency must cover the scatter
            # REACHING the pool, not just its enqueue — on an async
            # backend the enqueue-only figure was vacuous (microseconds
            # regardless of page size), and the admission that depends on
            # these pages blocks on exactly this work anyway.  Epoch-
            # boundary sync, not tick work (JP106 untouched).
            # jaxlint: disable=JL002 -- designed epoch-boundary completion barrier: the swap-in p95 /health reports must measure transfer completion, not dispatch enqueue (the PR 11 vacuous-timing fix)
            self.cache.k.block_until_ready()
            self.cache.v.block_until_ready()  # jaxlint: disable=JL002 -- rides the same designed swap-in barrier; k already blocked above
            if self.perf is not None:
                self.perf.note_sync(time.perf_counter() - t_bar)
        seconds = time.perf_counter() - t0
        self.pagestore.record_swap_in(seconds, pages=len(taken))
        self.hists["swap_in_s"].observe(seconds)
        self._trace(req, "swap_in", t0=t0_w, t1=time.time(),
                    seconds=round(seconds, 6), pages=len(taken))
        out = {}
        for (key, _), pid in zip(taken, pids):
            # transfer alloc()'s caller reference to the prefix cache
            # (register_prefix addrefs, so drop ours): the page ends
            # cache-owned at ref 1 — exactly a registered page no row
            # holds
            self.alloc.register_prefix(key, pid)
            self.alloc.decref(pid)
            out[key] = pid
        return out

    def _spill_finished_row(self, row: int, req: Request):
        """Cold-row spill at finish: a cleanly-finished row's decode
        pages hold the KV of prompt+output — the prefix a multi-turn
        follow-up request will arrive with.  Full pages past the prompt
        registration bound demote to the host store (keyed by the chain
        hash over prompt+output, the identity a future prompt computes)
        just before ``_finish`` recycles the pool slots; the device
        prefix cache itself keeps only prompt pages, exactly as before.
        Valid KV covers every prompt slot plus outputs[:-1] — the last
        emitted token's KV would have been written by the step that
        never ran — so only pages fully inside that bound spill."""
        ids = np.concatenate([
            np.asarray(req.prompt_ids, np.int32),
            np.asarray(req.output_ids, np.int32)])
        n_p = len(req.prompt_ids)
        ps = self.ec.page_size
        n_valid = n_p + max(len(req.output_ids) - 1, 0)
        reg = (n_p - 1) // ps                   # _finish_prompt's bound
        hi = min(n_valid // ps, self.ec.max_pages)
        if hi <= reg:
            return
        keys = _chain_hashes(ids[: hi * ps], ps)
        picks = [(keys[j], int(self.tables[row, j]))
                 for j in range(reg, hi)
                 if int(self.tables[row, j]) >= 0
                 and keys[j] not in self.alloc.prefix]
        if not picks:
            return
        self._fault_point("spill-store", rows=(row,))
        pids = np.asarray([p for _, p in picks], np.int32)
        k_pages, v_pages = self.cache.gather_pages(pids)
        t0 = time.perf_counter()
        # jaxlint: disable=JL002 -- designed finish-epoch sync: one batched gather spills the finished row's pages before their pool slots are recycled
        k_np = d2h(k_pages)
        v_np = d2h(v_pages)  # jaxlint: disable=JL002 -- rides the same designed finish-epoch sync; already blocked on k_np above
        self._count_sync(time.perf_counter() - t0)
        for i, (key, _) in enumerate(picks):
            self.pagestore.spill(key,
                                 np.ascontiguousarray(k_np[:, i]),
                                 np.ascontiguousarray(v_np[:, i]))

    # -- transportable page sets (serving/kv_transport.py) ------------------

    def _pool_shape(self) -> dict:
        l, _, h, ps, d = self.cache.k.shape
        return {"n_layers": l, "n_kv_heads": h, "page_size": ps,
                "head_dim": d, "v_head_dim": self.cache.v.shape[4]}

    def export_prefix(self, prompt_ids, wire: str = "auto") -> bytes | None:
        """Serialize the cached prefix pages covering ``prompt_ids`` as
        a transportable page set — the disaggregated prefill/decode
        handoff's export half.  Walks the chained-hash prefix exactly
        like admission does, serving each page from the device prefix
        cache or the host spill tier, and stops at the first miss (a
        chain is only useful up to its unbroken head).  ``wire="auto"``
        ships e5m2 codes — an fp8 pool's codes natively (lossless), a
        bf16 pool recoded (half the handoff bytes, lossy exactly like
        fp8 KV storage; pass ``wire="bf16"`` for bit-exact bf16
        handoff).  Returns None when no full page is cached.  Runs on
        the engine thread between ticks (epoch-boundary gathers, not
        tick work — JP106 unchanged)."""
        ids = np.asarray(list(prompt_ids), np.int32)
        return self.run_on_engine(lambda: self._export_prefix_op(ids, wire))

    def _export_prefix_op(self, ids: np.ndarray, wire: str):
        epoch = (self.perf.epoch_window("handoff")
                 if self.perf is not None else nullcontext())
        with epoch:
            return self._export_prefix_inner(ids, wire)

    def _export_prefix_inner(self, ids: np.ndarray, wire: str):
        from ipex_llm_tpu.serving import kv_transport

        if wire == "auto":
            # e5m2 on the wire: native codes for fp8 pools, recoded
            # (halved) handoff bytes for bf16 pools
            wire = "fp8"
        n_p = len(ids)
        keys = _chain_hashes(ids, self.ec.page_size)
        shareable = min(len(keys), (n_p - 1) // self.ec.page_size)
        order: list[tuple[str, bytes, Any]] = []
        for key in keys[:shareable]:
            pid = self.alloc.prefix.get(key)
            if pid is not None:
                order.append(("dev", key, pid))
                continue
            entry = (self.pagestore.peek(key)
                     if self.pagestore is not None else None)
            if entry is None:
                break
            order.append(("host", key, entry))
        if not order:
            return None
        self._fault_point("kv-export")
        pids = np.asarray([p for kind, _, p in order if kind == "dev"],
                          np.int32)
        if len(pids):
            k_all, v_all = self.cache.gather_pages(pids)
            t0 = time.perf_counter()
            # jaxlint: disable=JL002 -- designed export sync: one batched gather materializes the page set for serialization (between-ticks host op)
            k_np = d2h(k_all)
            v_np = d2h(v_all)  # jaxlint: disable=JL002 -- rides the same designed export sync; already blocked on k_np above
            self._count_sync(time.perf_counter() - t0)
        pages, di = [], 0
        for kind, key, payload in order:
            if kind == "dev":
                pages.append((key, k_np[:, di], v_np[:, di]))
                di += 1
            else:
                pages.append((key, payload[0], payload[1]))
        self.metrics["kv_pages_exported"] = (
            self.metrics.get("kv_pages_exported", 0) + len(pages))
        return kv_transport.pack_pages(self._pool_shape(), pages,
                                       wire=wire)

    def import_pages(self, blob: bytes) -> dict:
        """Import a transportable page set into this engine's pool and
        prefix cache — the handoff's import half.  The blob is verified
        first (``TransportError`` on corruption / truncation / version /
        pool-shape mismatch — unverified bytes are never scattered),
        then pages land in chain order: already-cached keys are skipped,
        the rest are allocated (evicting/spilling under pressure like
        any allocation), scattered through the h2d boundary, and
        registered cache-owned — so the next admitted request with this
        prompt prefix-hits them like home-grown pages and joins the
        fused tick with only the uncovered tail left to prefill.  A dry
        pool stops the import early (what fit is registered).  Runs on
        the engine thread between ticks."""
        return self.run_on_engine(lambda: self._import_pages_op(blob))

    def _import_pages_op(self, blob: bytes) -> dict:
        epoch = (self.perf.epoch_window("handoff")
                 if self.perf is not None else nullcontext())
        with epoch:
            return self._import_pages_inner(blob)

    def _import_pages_inner(self, blob: bytes) -> dict:
        from ipex_llm_tpu.serving import kv_transport

        meta, pages = kv_transport.unpack_pages(blob)
        kv_transport.check_pool_shape(meta, **self._pool_shape())
        self._fault_point("kv-import")
        t0 = time.perf_counter()
        # batched import: reserve() pre-evicts for the whole burst (one
        # spill gather instead of one per page — the PageAllocator's
        # allocation-burst contract), allocation stops at the first dry
        # pid (chain order: what fits is the unbroken head), and ONE
        # scatter lands every accepted page — the per-page
        # allocate/scatter loop cost len(pages) dispatches and len(pages)
        # h2d uploads for a blob that arrives as one contiguous set
        fresh = [(key, k_page, v_page) for key, k_page, v_page in pages
                 if key not in self.alloc.prefix]
        skipped = len(pages) - len(fresh)
        self.alloc.reserve(len(fresh))
        pids: list[int] = []
        for _ in fresh:
            pid = self.alloc.alloc()
            if pid is None:
                break                       # dry pool: keep what fit
            pids.append(pid)
        taken = fresh[:len(pids)]
        if taken:
            k_stack = np.stack([k for _, k, _ in taken], axis=1)
            v_stack = np.stack([v for _, _, v in taken], axis=1)
            self.cache = self.cache.scatter_pages(
                np.asarray(pids, np.int32), h2d(k_stack), h2d(v_stack))
            for (key, _, _), pid in zip(taken, pids):
                self.alloc.register_prefix(key, pid)
                self.alloc.decref(pid)      # cache-owned at ref 1
        imported = len(taken)
        self.metrics["kv_pages_imported"] = (
            self.metrics.get("kv_pages_imported", 0) + imported)
        return {"imported_pages": imported, "skipped_pages": skipped,
                "tokens_covered": (imported + skipped) * self.ec.page_size,
                "wire": meta["wire"],
                "import_s": round(time.perf_counter() - t0, 5)}

    # -- device-resident engine state ---------------------------------------

    def _active_mask(self) -> np.ndarray:
        """Rows currently decoding: occupied and past prefill — THE
        host/device activity predicate; the epoch upload and both
        scheduler paths must agree on it exactly.  Rows masked by a
        bisection probe sit the step out (their device row goes inactive,
        so the injector never sees them participate)."""
        return np.array([
            r is not None and i not in self._prefilling
            and r.request_id not in self._masked
            for i, r in enumerate(self.rows)
        ])

    def _upload_row_state(self):
        """Upload the per-row hot state after an epoch (admission / prefill
        progress / finish / page allocation).  Steady-state decode steps
        skip this entirely and reuse the device arrays the previous fused
        step returned — request-static sampling params (temps/top_ps/
        top_ks/seeds) cross the PCIe/tunnel link once per epoch, not once
        per token (the tier-1 re-upload regression test counts calls)."""
        rows = self.rows
        active = self._active_mask()
        steps = np.asarray([len(r.output_ids) if r is not None else 0
                            for r in rows], np.int32)
        remain = np.asarray([
            int(self.row_budget[i]) - len(r.output_ids) if r is not None
            else 0 for i, r in enumerate(rows)
        ], np.int32)
        # per-row EOS ids, -1-padded to a power-of-two width so an unusual
        # request can only ever trigger a bounded number of fused retraces
        e_w = max([1] + [len(r.eos_token_id) for r in rows if r is not None])
        e_w = 1 << (e_w - 1).bit_length()
        eos = np.full((len(rows), e_w), -1, np.int32)
        for i, r in enumerate(rows):
            if r is not None and r.eos_token_id:
                ids = list(r.eos_token_id)
                eos[i, :len(ids)] = ids
        self._dev = {
            "toks": h2d(self.toks),
            "row_lens": h2d(self.row_lens),
            "active": h2d(active),
            "temps": h2d(self.temps),
            "top_ps": h2d(self.top_ps),
            "seeds": h2d(self.seeds),
            "top_ks": h2d(self.top_ks),
            "steps": h2d(steps),
            "remain": h2d(remain),
            "eos": h2d(eos),
        }
        if self._fused_spec:
            # device-resident token history for the on-device prompt-
            # lookup proposer: the FULL prompt lands at the admission
            # epoch (it is known in whole then, so mid-prefill rows need
            # no per-chunk scatter), emitted tokens are appended inside
            # the device loop, and epochs rebuild it from the host's own
            # bookkeeping — the same discipline as toks/row_lens
            hist = np.zeros((len(rows), self.ec.max_seq_len), np.int32)
            for i, r in enumerate(rows):
                if r is None:
                    continue
                ids = list(r.prompt_ids) + list(r.output_ids)
                hist[i, :len(ids)] = ids
            self._dev["hist"] = h2d(hist)
        # tables ride the dirty-row scatter even on full epochs: every
        # mixed tick is an epoch (row_lens advance), and re-uploading the
        # whole [R, maxP] table per chunk is the cost this PR removes
        self._flush_dirty_tables()
        self._dirty = False

    def _flush_dirty_tables(self) -> PagedKVCache:
        """Scatter only the dirty block-table rows into the device-resident
        tables (kv.with_table_rows) and return the current cache — the
        per-chunk full-table re-upload the sequential prefill used to pay,
        reduced to the rows that actually changed."""
        if self._dirty_tables:
            rows = np.array(sorted(self._dirty_tables), np.int32)
            self.cache = self.cache.with_table_rows(
                h2d(rows), h2d(self.tables[rows]))
            self.metrics["table_row_syncs"] += 1
            self._dirty_tables.clear()
        return self.cache

    def _sync_device_state(self) -> dict:
        """The device-resident row state, re-uploading only when dirty.

        A full epoch (admission / prefill progress / finish) re-uploads the
        row vectors AND the whole table; a page-allocation-only epoch (mid-
        decode page boundary) scatters just the dirty table rows."""
        if self._dirty or self._dev is None:
            self.metrics["epoch_syncs"] += 1
            self._upload_row_state()
        else:
            self._flush_dirty_tables()
        return self._dev

    # -- engine loop --------------------------------------------------------

    def _free_row(self) -> int | None:
        for i, r in enumerate(self.rows):
            if r is None:
                return i
        return None

    def _drain_inbox(self):
        """Move submissions from the cross-thread inbox into the engine-
        owned pending FIFO.  Arrivals landing mid-transaction are recorded
        so a rollback re-appends them instead of losing them (the inbox
        itself is never rolled back)."""
        while True:
            try:
                req = self._inbox.get_nowait()
            except queue.Empty:
                return
            self._pending.append(req)
            if self._staging is not None:
                self._tick_arrivals.append(req)

    def _pop_pending(self) -> Request | None:
        """Head of the pending FIFO, skipping bisection-masked requests
        (a masked suspect stays queued, in order, while a probe runs)."""
        for i, req in enumerate(self._pending):
            if req.request_id not in self._masked:
                del self._pending[i]
                return req
        return None

    def _admit(self):
        """Join pending requests into free rows (host-side work only —
        prefix matching + page allocation; prefill happens chunk-wise).
        The plan's ``admit_max`` caps successful admissions this tick
        (None = unbounded, the static planner's choice; the MPC planner
        defers a wave that would blow a critical row's deadline)."""
        cap = self._plan.admit_max if self._plan is not None else None
        admitted = 0
        while cap is None or admitted < cap:
            row = self._free_row()
            if row is None:
                return
            req = self._pop_pending()
            if req is None:
                return
            if req.cancelled:
                req.finish_reason = "abort"
                self._queue_put(req, None)
                continue
            prompt = np.asarray(req.prompt_ids, np.int32)
            n_p = len(prompt)
            ps = self.ec.page_size
            # addressable capacity: the block-table width floors
            # max_seq_len/page_size, and a request can never hold more
            # pages than the pool owns (page 0 is reserved scratch)
            capacity = min(self.ec.max_seq_len, self.ec.max_pages * ps,
                           (self.ec.n_pages - 1) * ps)
            if n_p + req.max_new_tokens > capacity or n_p == 0:
                req.finish_reason = "length"
                self._queue_put(req, None)
                continue

            # prefix cache: reuse the longest chain of full pages covering
            # at most the first n_p - 1 tokens (at least one token must run
            # through the model to produce logits)
            keys = _chain_hashes(prompt, ps)
            shareable = min(len(keys), (n_p - 1) // ps)
            # plan the chain: device prefix hits take their row ref
            # immediately (protecting them from the batched promotion's
            # evictions, exactly like the old sequential addref), store
            # misses are take()n so the spill-tier promotion — a PCIe
            # copy instead of re-prefilling the chunk — lands as ONE
            # batched scatter + barrier for the whole chain
            plan: list[tuple] = []      # ("dev", pid) | ("host", key, entry)
            for i in range(shareable):
                pid = self.alloc.lookup_prefix(keys[i])
                if pid is not None:
                    self.alloc.addref(pid)
                    plan.append(("dev", pid))
                    continue
                entry = (self.pagestore.take(keys[i])
                         if self.pagestore is not None else None)
                if entry is None:
                    break
                plan.append(("host", keys[i], entry))
            promoted = self._swap_in_chain(
                [(e[1], e[2]) for e in plan if e[0] == "host"], req=req)
            shared = 0
            for e in plan:
                if e[0] == "host":
                    pid = promoted.get(e[1])
                    if pid is None:
                        break           # dry pool broke the chain here
                    self.alloc.addref(pid)
                else:
                    pid = e[1]          # row ref taken in the plan walk
                self.tables[row, shared] = pid
                self._dirty_tables.add(row)
                shared += 1
            for e in plan[shared:]:
                if e[0] == "dev":       # past the break: drop the row ref
                    self.alloc.decref(e[1])

            base = shared * ps
            if not self._ensure_pages(row, n_p, req=req):
                # pool dry even after eviction: release everything this row
                # touched (shared refs AND partial fresh allocations)
                self._release_row_pages(row)
                if any(r is not None for r in self.rows) or self._prefilling:
                    # retry once in-flight rows free pages — AT THE HEAD,
                    # preserving arrival order (the old inbox.put sent the
                    # head request behind everything queued after it)
                    self._pending.appendleft(req)
                else:
                    # nothing running, nothing evictable: it will never fit
                    req.finish_reason = "length"
                    self._queue_put(req, None)
                return

            if shared:
                # counted only on successful admission: a dry-pool
                # requeue above releases the shared refs and re-admits
                # the same request later — bumping here would count that
                # request's hits twice (hit rate could exceed 1.0 under
                # exactly the pool pressure the kv sweep measures)
                self.metrics["prefix_hits"] += 1
                self.metrics["prefix_pages_shared"] += shared
            if self.tracer is not None:
                # queue-wait span: submission wall time is reconstructed
                # from the perf_counter stamp submit() recorded
                now_w = time.time()
                sub_w = now_w - (time.perf_counter() - req.submitted_s)
                self._trace(req, "queue", t0=sub_w, t1=now_w,
                            queue_depth=self.queue_depth,
                            prompt_tokens=n_p, shared_pages=shared)
            self.rows[row] = req
            self.row_lens[row] = base
            self.row_budget[row] = req.max_new_tokens
            self.temps[row] = req.temperature
            self.top_ps[row] = req.top_p
            self.seeds[row] = -1 if req.seed is None else int(req.seed)
            self.top_ks[row] = max(0, int(req.top_k or 0))
            self._prefilling[row] = prompt[base:]
            self._row_keys[row] = keys
            self.metrics["requests"] += 1
            admitted += 1
            self._dirty = True  # admission epoch: new row state to upload

    def _prefill_one_chunk(self):
        """Advance ONE prefilling row by one chunk (bounded stall)."""
        if not self._prefilling:
            return
        # first prefilling row not masked out by a bisection probe (stale
        # None-row entries still picked so their cleanup path runs)
        row = next((r for r in self._prefilling
                    if self.rows[r] is None
                    or self.rows[r].request_id not in self._masked), None)
        if row is None:
            return
        req = self.rows[row]
        if req is None or req.cancelled:
            self._prefilling.pop(row, None)
            if req is not None:
                self._finish(row, "abort")
            return
        remaining = self._prefilling[row]
        cp = self.ec.prefill_bucket
        chunk = remaining[:cp]
        n_valid = len(chunk)
        base = int(self.row_lens[row])
        # pages are needed only for real tokens; the right-pad tail
        # lands on the scratch page via update_layer's valid mask
        if not self._ensure_pages(row, base + n_valid):
            self._finish(row, "error")  # pool exhausted mid-prefill
            self._prefilling.pop(row, None)
            return
        toks = np.zeros((1, cp), np.int32)
        toks[0, :n_valid] = chunk
        self._fault_point("prefill-chunk", rows=(row,))
        t0_w = time.time()
        # dirty-row table sync: only the rows whose tables changed since
        # the last device call are scattered in (this row's new pages),
        # not the whole [R, maxP] table per chunk
        cache = self._flush_dirty_tables()
        with self._perf_dispatch("tick.seq_prefill"):
            logits, self.cache = _prefill_chunk(
                self.cfg, self.params, cache, h2d(toks),
                h2d(self.tables[row : row + 1]),
                h2d(base, jnp.int32), h2d(n_valid, jnp.int32),
                mesh=self.mesh,
            )
        self._tick_dispatches += 1
        self.row_lens[row] = base + n_valid
        self._trace(req, "prefill_chunk", t0=t0_w, t1=time.time(),
                    tokens=n_valid, base=base)
        self._dirty = True  # prefill epoch: row_lens advanced
        if n_valid < len(remaining):
            self._prefilling[row] = remaining[n_valid:]
            return
        # prompt complete: sample the first token, enter decode
        self._prefilling.pop(row, None)
        from ipex_llm_tpu.ops.sampling import sample_rows_with_logprobs

        self.key, sub = jax.random.split(self.key)
        first_t, first_lp = sample_rows_with_logprobs(
            logits, h2d([req.temperature], jnp.float32),
            h2d([req.top_p], jnp.float32), sub,
            seeds=h2d([-1 if req.seed is None else int(req.seed)],
                              jnp.int32),
            steps=jnp.zeros((1,), jnp.int32),
            top_ks=h2d([max(0, int(req.top_k or 0))], jnp.int32),
        )
        self._fault_point("sample", rows=(row,))
        t0 = time.perf_counter()
        # jaxlint: disable=JL002 -- designed sync: the first token must reach the host to emit (TTFT); counted via _count_sync
        first = int(d2h(first_t)[0])
        first_lp = d2h(first_lp)  # jaxlint: disable=JL002 -- same designed first-token sync; already blocked on first_t above
        self._count_sync(time.perf_counter() - t0)  # blocking materialization
        self._finish_prompt(row, first, float(first_lp[0]))

    def _finish_prompt(self, row: int, first: int, logprob: float):
        """Prompt-completion bookkeeping shared by the sequential and
        mixed admission paths — ONE definition (prefix-page registration
        bound, TTFT record, first-token emission), so the two paths
        cannot drift apart under the bit-identity contract."""
        n_p = int(self.row_lens[row])
        keys = self._row_keys.pop(row, [])
        for j in range(min(len(keys), (n_p - 1) // self.ec.page_size)):
            self.alloc.register_prefix(keys[j], int(self.tables[row, j]))
        req = self.rows[row]
        if req is None:
            return
        req.first_token_s = time.perf_counter() - req.submitted_s
        self._record_ttft(req.first_token_s)
        self._trace(req, "first_token",
                    ttft_s=round(req.first_token_s, 6))
        self.toks[row] = first
        self._emit(row, first, logprob)

    def _record_ttft(self, seconds: float):
        """Rolling TTFT percentile for /health (128-request window) +
        the fixed-bucket histogram /metrics exposes in Prometheus form."""
        self._ttfts.append(seconds)
        self.hists["ttft_s"].observe(seconds)
        self.metrics["ttft_p95_s"] = round(
            float(np.percentile(np.fromiter(self._ttfts, np.float64), 95)),
            4)

    def _emit(self, row: int, token: int, logprob: float = 0.0):
        req = self.rows[row]
        if req.cancelled:
            self._finish(row, "abort")
            return
        req.output_ids.append(token)
        req.logprobs.append(logprob)
        # client-visible inter-token latency (first token measures TTFT
        # in its own histogram): under a fused horizon this is honestly
        # BURSTY — H tokens drain in one commit, so the distribution
        # shows ~0 within a block and the tick interval between blocks,
        # which is exactly the granularity a streaming client observes
        now = time.perf_counter()
        if req._last_tok_s:
            self.hists["token_latency_s"].observe(now - req._last_tok_s)
        req._last_tok_s = now
        self._queue_put(req, token)
        self.metrics["tokens"] += 1
        if token in req.eos_token_id:
            self._finish(row, "stop")
        elif len(req.output_ids) >= self.row_budget[row]:
            self._finish(row, "length")

    def _finish(self, row: int, reason: str):
        req = self.rows[row]
        # first writer wins: the HTTP handler may have already recorded
        # 'stop' (stop-string truncation) before asking for the abort —
        # overwriting it here would misreport the finish reason
        if req.finish_reason is None:
            req.finish_reason = reason
        if (self.pagestore is not None and req.output_ids
                and req.finish_reason in ("stop", "length",
                                          "stop_string")):
            # cold-row spill: a cleanly-finished row's decode KV is the
            # multi-turn follow-up's prefix — demote it before the pool
            # slots are recycled (aborts/errors spill nothing: their KV
            # may be incomplete)
            self._spill_finished_row(row, req)
        self._trace(req, "finish", reason=req.finish_reason,
                    output_tokens=len(req.output_ids))
        self._queue_put(req, None)
        self.rows[row] = None
        self.row_lens[row] = 0
        self.toks[row] = 0
        self._prefilling.pop(row, None)
        self._row_keys.pop(row, None)
        self._release_row_pages(row)
        self._dirty = True  # finish epoch: row freed

    def _fail_all(self, exc: BaseException):
        """Engine-level failure (the blast-radius backstop — reached only
        when bisection cannot localize a fault to one request, or the
        recovery machinery itself failed): finish every in-flight/queued
        request so no client blocks forever, then keep serving."""
        self._staging = None    # emissions flush directly from here on
        self._span_staging = []
        self._tick_arrivals = []
        self._masked = set()
        self.flight.dump("fail_all",
                         error=f"{type(exc).__name__}: {exc}",
                         **{f"{k}_pending": v for k, v
                            in self._flight_pending().items() if v},
                         **(self.perf.dump_fields()
                            if self.perf is not None else {}))
        for i, req in enumerate(self.rows):
            if req is not None:
                self._finish(i, "error")
        self._drain_inbox()
        while self._pending:
            req = self._pending.popleft()
            if req.finish_reason is None:
                req.finish_reason = "error"
            req.stream_queue.put(None)
        self.metrics["errors"] = self.metrics.get("errors", 0) + 1
        self.metrics["last_error"] = f"{type(exc).__name__}: {exc}"
        self.metrics["queue_depth"] = self.queue_depth

    def _row_spec_k(self, req: Request) -> int:
        """ONE definition of a request's draft width: the engine spec_k,
        capped by Request.spec_k, zero when opted out (speculative=False)
        — every reservation/mask site must agree on it exactly."""
        if req.speculative is False:
            return 0
        return (self.ec.spec_k if req.spec_k is None
                else max(0, min(int(req.spec_k), self.ec.spec_k)))

    def _plan_spec_cap(self, row: int) -> int:
        """The plan's draft-width CAP for a row (composed with the
        per-request knobs via min at every reservation/mask site): the
        static planner caps at spec_k everywhere (a no-op), the MPC
        planner masks drafts off when the measured accept window prices
        them underwater.  Rows admitted after planning take the plan's
        ``spec_cap``."""
        plan = self._plan
        if plan is None:
            return self.ec.spec_k
        if row < len(plan.spec_ks):
            return int(plan.spec_ks[row])
        return plan.spec_cap

    def _spec_widths(self, active: np.ndarray) -> np.ndarray:
        """Per-row draft width for a fused-spec tick — the per-request
        knobs AND the plan's caps as TRACED MASKS, so one compiled
        program serves every opt-out mix."""
        ks = np.zeros((len(self.rows),), np.int32)
        for i, req in enumerate(self.rows):
            if req is None or not active[i]:
                continue
            ks[i] = min(self._row_spec_k(req), self._plan_spec_cap(i))
        return ks

    def _spec_metrics(self, take_block: np.ndarray, s_prop, s_acc,
                      executed: int):
        """Fused-spec tick accounting: the verify-round counters the
        sequential host walk kept (spec_steps/spec_emitted/accept_rate —
        one loop iteration is one verify round) plus the draft-economics
        pair the /health spec block and the bench sweep report.  All of
        it lives in the checkpointed metrics dict / rolling window, so a
        rolled-back tick never double-counts."""
        k = self.ec.spec_k
        emitted = int(take_block.sum())
        row_steps = int((take_block > 0).sum())
        prop = int(d2h(s_prop))  # jaxlint: disable=JL002 -- rides THE per-horizon sync (same dispatched program): draft-economics scalars
        acc = int(d2h(s_acc))  # jaxlint: disable=JL002 -- rides the same per-horizon sync as s_prop above
        m = self.metrics
        m["spec_steps"] = m.get("spec_steps", 0) + executed
        m["spec_ticks"] = m.get("spec_ticks", 0) + 1
        m["spec_emitted"] = m.get("spec_emitted", 0) + emitted
        m["spec_row_steps"] = m.get("spec_row_steps", 0) + row_steps
        m["spec_accept_rate"] = round(
            m["spec_emitted"] / ((k + 1) * max(m["spec_row_steps"], 1)), 4)
        m["draft_proposed"] = m.get("draft_proposed", 0) + prop
        m["draft_accepted"] = m.get("draft_accepted", 0) + acc
        m["spec_tokens_per_dispatch"] = round(
            m["spec_emitted"] / max(m["spec_ticks"], 1), 2)
        self._spec_window.append((prop, acc))

    def _drain_spec_block(self, tok_block, lp_block, take_block,
                          active: np.ndarray, h: int):
        """Walk an [R, h, k+1] spec token/logprob block through the exact
        per-token emission path: iteration j of row i emitted
        ``take_block[i, j]`` tokens (device-truncated at the same
        EOS/budget boundary the host's _emit walks)."""
        for i in range(len(self.rows)):
            if not active[i] or self.rows[i] is None:
                continue
            for j in range(h):
                for t in range(int(take_block[i, j])):
                    self.row_lens[i] += 1
                    tok = int(tok_block[i, j, t])
                    self.toks[i] = tok
                    self._emit(i, tok, float(lp_block[i, j, t]))
                    if self.rows[i] is None:   # finished mid-run
                        break
                if self.rows[i] is None:
                    break

    def _spec_step(self, active: np.ndarray):
        """One speculative (prompt-lookup verify) step over the active rows."""
        k = self.ec.spec_k
        n_rows = len(self.rows)
        # each row may write up to k+1 fresh KV slots this step; a row that
        # can't get the k+1 slots under pool contention falls back to a
        # plain single-token step (advisor r4 finding #4: finishing with
        # 'length' truncated requests the plain engine could still serve) —
        # its draft KV writes past the allocated page land on the scratch
        # page via update_layer's valid mask
        no_spec = np.zeros((n_rows,), bool)
        for i in range(n_rows):
            if not active[i]:
                continue
            if not self._ensure_pages(i, int(self.row_lens[i]) + k + 1):
                if self._ensure_pages(i, int(self.row_lens[i]) + 1):
                    no_spec[i] = True
                else:
                    self._finish(i, "length")
                    active[i] = False
        if not active.any():
            return
        drafts = np.zeros((n_rows, k), np.int32)
        n_prop = np.zeros((n_rows,), np.int32)
        for i in range(n_rows):
            req = self.rows[i]
            if not active[i] or req is None:
                continue
            # acceptance covers ALL temperatures (every verify position
            # samples with the row's params — see _verify_step); a request
            # can opt out (speculative=False) or cap its own draft width
            # (spec_k), the reference ipex_llm_worker.py:57 per-load knobs
            # made per-request
            if req.speculative is not False and not no_spec[i]:
                k_req = k if req.spec_k is None else max(
                    0, min(int(req.spec_k), k))
                if k_req == 0:
                    continue
                hist = np.concatenate([
                    np.asarray(req.prompt_ids, np.int32),
                    np.asarray(req.output_ids, np.int32),
                ])
                d = _propose_ngram(hist, k_req, self.ec.spec_ngram)
                valid = d >= 0
                n_prop[i] = k_req if valid.all() else int(valid.argmin())
                drafts[i, :k_req] = np.where(valid, d, 0)
        self._fault_point("decode-dispatch",
                          rows=[i for i in range(n_rows) if active[i]])
        t0_w = time.time()
        cache = self._flush_dirty_tables()
        steps = np.asarray([
            len(r.output_ids) if r is not None else 0 for r in self.rows
        ], np.int32)
        verify_fn, extra = _verify_step, {}
        if self._pp_mode:
            verify_fn = _pp_verify_step
            extra = {"n_micro": self.mesh.shape["pp"]}
        with self._perf_dispatch("tick.spec_host"):
            t_all, lp_all, self.cache, self.key = verify_fn(
                self.cfg, self.params, cache,
                h2d(self.toks), h2d(drafts),
                h2d(self.row_lens), h2d(active),
                h2d(self.temps), h2d(self.top_ps), self.key,
                h2d(self.seeds), h2d(steps),
                h2d(self.top_ks), k=k, mesh=self.mesh, **extra,
            )
        self._tick_dispatches += 1
        t0 = time.perf_counter()
        # jaxlint: disable=JL002 -- designed sync: the verify round's accepted tokens must reach the host to walk acceptance chains; counted via _count_sync
        t_all, lp_all = d2h(t_all), d2h(lp_all)
        self._count_sync(time.perf_counter() - t0)
        self.metrics["steps"] += 1
        self.metrics["pages_in_use"] = self.alloc.pages_in_use
        self._dirty = True  # host walks acceptance chains: state diverges
        emitted_total = 0
        for i in range(n_rows):
            if not active[i] or self.rows[i] is None:
                continue
            req_i = self.rows[i]
            emitted = [(int(t_all[i, 0]), float(lp_all[i, 0]))]
            for j in range(int(n_prop[i])):
                # the draft fed at position j+1 must equal the token just
                # emitted for logits[j+1] (and thus sample s_{j+1}) to be a
                # draw from the true conditional
                if int(drafts[i, j]) != emitted[-1][0]:
                    break
                emitted.append((int(t_all[i, j + 1]),
                                float(lp_all[i, j + 1])))
            # KV for every emitted token except the last is already in the
            # pool (the forward wrote slots row_len..row_len+k); the last
            # emitted token is the next step's input, written then
            self.row_lens[i] += len(emitted)
            self.toks[i] = emitted[-1][0]
            emitted_total += len(emitted)
            for tok, lp in emitted:
                self._emit(i, tok, lp)
                if self.rows[i] is None:  # finished (eos/length/abort) mid-chain
                    break
            self._trace(req_i, "spec_round", t0=t0_w, t1=time.time(),
                        rounds=1, tokens=len(emitted),
                        accepted=len(emitted) - 1)
        self.metrics["spec_steps"] = self.metrics.get("spec_steps", 0) + 1
        self.metrics["spec_emitted"] = (
            self.metrics.get("spec_emitted", 0) + emitted_total
        )
        # normalize by ACTIVE ROW-STEPS, not steps: with concurrent rows a
        # per-step divisor both overstated the rate (could exceed 1.0) and
        # understated it when rows sat idle (advisor r4 finding #2)
        self.metrics["spec_row_steps"] = (
            self.metrics.get("spec_row_steps", 0) + int(active.sum())
        )
        self.metrics["spec_accept_rate"] = round(
            self.metrics["spec_emitted"]
            / ((k + 1) * max(self.metrics["spec_row_steps"], 1)), 4)
        # draft economics: the host walk feeds the SAME counters and
        # rolling window the fused tick feeds (_spec_metrics), so
        # /health's spec block is meaningful on the oracle/pp engines too
        prop = int(n_prop.sum())
        acc = emitted_total - int(active.sum())   # each row's free token
        m = self.metrics
        m["spec_ticks"] = m.get("spec_ticks", 0) + 1
        m["draft_proposed"] = m.get("draft_proposed", 0) + prop
        m["draft_accepted"] = m.get("draft_accepted", 0) + acc
        m["spec_tokens_per_dispatch"] = round(
            m["spec_emitted"] / max(m["spec_ticks"], 1), 2)
        self._spec_window.append((prop, acc))
        self.metrics["tokens_per_sync"] = round(
            self.metrics["tokens"] / self.metrics["host_syncs"], 2)

    def _loop(self):
        while not self._stop.is_set():
            self._drain_host_ops()
            try:
                committed = self._tick()
                # a committed tick means the engine recovered: clear the
                # sticky error so /health goes back to "ok" (the isolated
                # error lives on in errors_isolated for chaos tooling)
                if committed and self.metrics.get("last_error"):
                    self.metrics["last_error"] = ""
            except Exception as exc:  # recovery machinery itself failed
                self._fail_all(exc)
        # shutdown drain: host ops enqueued after the loop's last drain
        # must not leave their callers blocked until timeout — fail them
        # with a clean "engine stopped" instead
        while True:
            try:
                _, box = self._host_ops.get_nowait()
            except queue.Empty:
                break
            box.put((False, RuntimeError(
                "engine stopped before servicing the host operation")))

    def _step_once(self):
        """Scheduler: three regimes, ONE dispatch per tick.  Admission
        wave (any row prefilling) → ``_mixed_step`` fuses every prefill
        chunk, on-device first-token merge, and the decode step into the
        single ``_ragged_tick_fn`` program; steady state → the fused
        decode horizon through the SAME entry (bit-identical to the
        historical ``_decode_multi_step``).  ``spec_k`` rides INSIDE that
        one program on the fused engine (on-device draft+verify+accept);
        only the sequential (budget=0) oracle and pp engines keep the
        one-row-one-chunk admission path with the host-walk verify."""
        self._drain_inbox()
        self._expire_deadlines()
        self.metrics["queue_depth"] = self.queue_depth
        self._admit()
        for i, req in enumerate(self.rows):  # drop disconnected clients
            if req is not None and req.cancelled:
                self._finish(i, "abort")
        if self._prefilling and self._mixed_mode:
            self._mixed_step()
            return
        self._prefill_one_chunk()
        active = self._active_mask()
        if not active.any():
            if self._prefilling:
                return  # keep chunking
            self._wait_for_work()
            return
        if self.ec.spec_k > 0 and not self._fused_spec:
            # the host-walk verify step: the sequential (budget=0) oracle
            # and the pp engine's stage-sequential wide step
            self._spec_step(active)
            return
        self._horizon_step(active)

    def _wait_for_work(self, timeout: float = 0.02):
        """Idle sleep that wakes the moment a request arrives WITHOUT
        consuming the inbox: the old get()+put() peek rotated the head
        request behind anything submitted during the peek window, breaking
        FIFO admission order under a burst.  The event is a pure wakeup
        hint — clearing it late never loses work, because the next tick's
        ``_admit`` drains the queue regardless."""
        if self._inbox.empty():
            self._work.wait(timeout)
        self._work.clear()

    def _mixed_step(self):
        """One admission-wave tick = ONE device program
        (``_ragged_tick_fn``): ragged prefill chunks for ALL prefilling
        rows, on-device first-token sampling AND state merge for prompts
        completing this tick, and the decode step for every active row —
        all inside a single jitted entry, so a mixed tick pays one
        dispatch and at most one blocking sync (completion ticks fetch
        first tokens and the decode block from the same program).  The
        JP106 trace gate locks the one-dispatch invariant; the chained
        two-program tick survives only as the equivalence oracle.

        Budget split: the per-tick token budget divides across prefilling
        rows in a power-of-two per-row chunk width (so every joining row
        advances every tick and the tick program retraces at most once
        per width), decode rows keep their [R, 1] step cost inside the
        fused program — one token per tick, the sequential engine's exact
        pace and loop body, so their streams stay trivially
        bit-identical."""
        if not self._prefilling:
            return
        rows = sorted(r for r in self._prefilling
                      if self.rows[r] is not None
                      and self.rows[r].request_id not in self._masked)
        if not rows:
            # every prefilling row is masked by a bisection probe: the
            # decode rows (if any) still take their step below
            active = self._active_mask()
            if active.any():
                self._horizon_step(active)
            return
        # per-row chunk width: the budget fair-shares across joining rows
        # (power-of-two floor, capped at the prefill bucket); width
        # depends only on the row count, so the program set is one trace
        # per power-of-two batch size.  Floored at 4: slivers of 1-2
        # tokens per row make the wave tick-bound (per-dispatch overhead
        # and trace churn dominate), so a huge admission wave briefly
        # overshoots the budget rather than crawling
        budget = (self._plan.chunk_budget if self._plan is not None
                  else self._step_budget)
        share = max(1, budget // len(rows))
        width = min(max(1 << (share.bit_length() - 1), 4),
                    self.ec.prefill_bucket)
        p_b = 1 << (len(rows) - 1).bit_length()        # pow2 batch pad

        toks = np.zeros((p_b, width), np.int32)
        # pad batch slots carry a base past the table width: every write
        # they make routes to the scratch page (update_layer's valid mask)
        base = np.full((p_b,), self.ec.max_pages * self.ec.page_size,
                       np.int32)
        n_valid = np.zeros((p_b,), np.int32)
        emit = np.zeros((p_b,), bool)
        canjoin = np.ones((p_b,), bool)
        # prefill slot -> engine row; pad slots carry R so their on-device
        # state scatters DROP instead of touching row 0
        rowmap = np.full((p_b,), self.ec.max_rows, np.int32)
        chunks: list[tuple[int, int, int]] = []  # (slot, row, n_i)
        for i, row in enumerate(rows):
            rem = self._prefilling[row]
            n_i = min(len(rem), width)
            b = int(self.row_lens[row])
            if not self._ensure_pages(row, b + n_i):
                self._finish(row, "error")  # pool exhausted mid-prefill
                continue
            toks[i, :n_i] = rem[:n_i]
            base[i] = b
            n_valid[i] = n_i
            emit[i] = n_i == len(rem)      # prompt completes this tick
            rowmap[i] = row
            chunks.append((i, row, n_i))
        if not chunks:
            active = self._active_mask()
            if active.any():
                self._horizon_step(active)
            return
        # a completing row's first decode step runs INSIDE this same
        # program and writes KV at slot b+n_i — back it now, or the row
        # sits the decode stage out and finishes 'length' after its
        # first token (the old second dispatch's dry-pool behaviour,
        # decided pre-dispatch).  This runs AFTER every row's chunk
        # pages are ensured: under pool pressure the extra decode page
        # must never starve a later row's prefill chunk (which would
        # turn that request's graceful progress into a hard 'error').
        # Fused-spec joiners additionally reserve their draft window
        # (min(k+1, budget after the first token) slots) so a prompt
        # completing this tick can speculate on its first decode
        # iteration; a pool that can back only the plain slot zeroes the
        # row's traced spec width instead (no_spec as a mask).  The plan
        # can mask speculation off for the tick (draft economics): the
        # spec-free program variant dispatches instead — a locked grid
        # point, not a new trace.
        fused = (self._fused_spec
                 and (self._plan.spec_on if self._plan is not None
                      else True))
        spec_ks = (np.zeros((len(self.rows),), np.int32)
                   if fused else None)
        for i, row, n_i in chunks:
            if not emit[i]:
                continue
            req = self.rows[row]
            k_i = (min(self._row_spec_k(req), self._plan_spec_cap(row))
                   if spec_ks is not None else 0)
            want = max(min(k_i + 1, req.max_new_tokens - 1), 1)
            canjoin[i] = self._ensure_pages(
                row, int(base[i]) + n_i + want, req=req)
            if not canjoin[i] and k_i:
                k_i = 0
                canjoin[i] = self._ensure_pages(
                    row, int(base[i]) + n_i + 1, req=req)
            if spec_ks is not None:
                spec_ks[row] = k_i
        # decode participants need their next KV slot backed BEFORE the
        # single dispatch (the old second dispatch's pre-allocation): a
        # row the pool cannot back finishes 'length' here and is
        # excluded from the uploaded active mask.  (No horizon clamp
        # like _horizon_step's: at want=1 a failed ensure always means
        # zero backed slots remain.)  Fused-spec rows reserve their
        # draft window first and drop to the plain width under pressure.
        active = self._active_mask()
        for i in range(len(self.rows)):
            if not active[i]:
                continue
            k_i = (min(self._row_spec_k(self.rows[i]),
                       self._plan_spec_cap(i))
                   if spec_ks is not None else 0)
            rem_i = (int(self.row_budget[i])
                     - len(self.rows[i].output_ids))
            want = max(min(k_i + 1, rem_i), 1)
            if not self._ensure_pages(i, int(self.row_lens[i]) + want):
                if k_i and self._ensure_pages(i,
                                              int(self.row_lens[i]) + 1):
                    k_i = 0
                else:
                    self._finish(i, "length")
                    active[i] = False
                    continue
            if spec_ks is not None:
                spec_ks[i] = k_i
        # pure-chunk ticks with nothing decoding skip the decode stage
        # entirely (statically): no all-masked forward, and the key chain
        # advances only by the prefill split — the chained path's exact
        # behaviour when it skipped the second dispatch.  ONE known
        # deviation: emit is decidable pre-dispatch but the first token's
        # EOS/budget fate is not, so a completion whose row dies at its
        # first token (no other rows active) still runs an all-dead
        # decode stage and splits the key once more than the chained
        # path did.  Greedy and seeded streams are untouched (seeded
        # rows key on fold_in(seed, step), never the engine chain); only
        # unseeded temperature>0 draws after that corner differ, and
        # those carry no reproducibility contract (same distribution,
        # different stream).
        with_decode = bool(active.any() or emit.any())
        if self._fused_spec and with_decode and not fused:
            # decode emits through the spec-free variant (the plan masked
            # speculation off): the device-resident token history is not
            # maintained this tick
            self._hist_stale = True
        elif fused and with_decode and self._hist_stale:
            # epoch re-upload rebuilds hist from host-side ids before the
            # proposer scans it (the device sync below honors _dirty)
            self._dirty = True
            self._hist_stale = False
        self._fault_point("mixed-step", rows=[r for _, r, _ in chunks])
        # decode participants = rows already decoding PLUS completions
        # that can join the decode stage this tick: a request-scoped
        # fault at this site must fire on the tick its request first
        # decodes (the chained path fired it post-completion), or the
        # rollback contract would let its first tokens commit
        decode_rows = [i for i in range(len(self.rows)) if active[i]]
        decode_rows += [row for s, row, _ in chunks
                        if emit[s] and canjoin[s]]
        if with_decode and decode_rows:
            self._fault_point("decode-dispatch", rows=decode_rows)
        t0_w = time.time()
        cache = self._flush_dirty_tables()
        full_tables = cache.tables
        row_idx = np.zeros((p_b,), np.int32)
        row_idx[:len(rows)] = rows
        # slice the table view to the pages the batch actually uses
        # (power-of-two bucketed): the jnp fallback gathers each row's
        # whole table width per layer, so early chunks of a long
        # prompt would otherwise pay the full-capacity gather; dropped
        # positions are exactly-masked (zero-probability) slots, so
        # chunk values stay bitwise identical.  Narrow tables skip the
        # slicing — the gather saving there is smaller than the cost
        # of extra program traces per width bucket
        if self.ec.max_pages > 8:
            ps = self.ec.page_size
            maxp_used = max(-(-(int(base[i]) + int(n_valid[i])) // ps)
                            for i, _, _ in chunks)
            maxp_b = min(1 << (max(maxp_used, 1) - 1).bit_length(),
                         self.ec.max_pages)
        else:
            maxp_b = self.ec.max_pages
        p_tables = full_tables[h2d(row_idx)][:, :maxp_b]
        dev = self._sync_device_state()
        prefill = (h2d(toks), p_tables, h2d(base), h2d(n_valid),
                   h2d(emit), h2d(canjoin), h2d(rowmap))
        # a pure-chunk tick (with_decode=False) has no decode stage for
        # spec to ride, so it dispatches the spec-free program variant —
        # the device history needs no maintenance there (prompts land
        # whole at epoch uploads, and nothing is emitted)
        tick_spec = fused and with_decode
        take_block = s_prop = s_acc = None
        perf_pt = self._perf_point(
            1, width=width, with_decode=with_decode, spec=tick_spec,
            pb=p_b, maxp=maxp_b, ew=int(dev["eos"].shape[1]))
        if tick_spec:
            with self._perf_dispatch("tick.spec", point=perf_pt):
                (first_t, first_lp, tok_block, lp_block, n_exec,
                 self.cache, dev["toks"], dev["row_lens"], dev["active"],
                 dev["steps"], dev["remain"], self.key, take_block,
                 dev["hist"], s_prop, s_acc) = _ragged_tick_fn(
                    self.cfg, self.params, self.cache, dev["toks"],
                    dev["row_lens"], dev["active"], dev["temps"],
                    dev["top_ps"], self.key, dev["seeds"], dev["steps"],
                    dev["top_ks"], dev["eos"], dev["remain"],
                    prefill=prefill, horizon=1, with_decode=True,
                    hist=dev["hist"], spec_ks=h2d(spec_ks),
                    spec_k=self.ec.spec_k, spec_ngram=self.ec.spec_ngram,
                    mesh=self.mesh, tp_manual=self._tp_manual,
                    collective_qtype=self._collective_qtype)
            self._tick_dispatches += 1
        else:
            with self._perf_dispatch("tick.admission", point=perf_pt):
                (first_t, first_lp, tok_block, lp_block, n_exec,
                 self.cache, dev["toks"], dev["row_lens"], dev["active"],
                 dev["steps"], dev["remain"], self.key) = _ragged_tick_fn(
                    self.cfg, self.params, self.cache, dev["toks"],
                    dev["row_lens"], dev["active"], dev["temps"],
                    dev["top_ps"], self.key, dev["seeds"], dev["steps"],
                    dev["top_ks"], dev["eos"], dev["remain"],
                    prefill=prefill, horizon=1,
                    with_decode=with_decode, mesh=self.mesh,
                    tp_manual=self._tp_manual,
                    collective_qtype=self._collective_qtype)
            self._tick_dispatches += 1
        # advance bookkeeping; completed prompts run the shared
        # completion path (_finish_prompt) once their token arrives
        completing: list[tuple[int, int]] = []   # (slot, row)
        for i, row, n_i in chunks:
            self.row_lens[row] += n_i
            self._trace(self.rows[row], "prefill_chunk", t0=t0_w,
                        t1=time.time(), tokens=n_i,
                        base=int(base[i]), fused=True)
            rem = self._prefilling[row]
            if n_i == len(rem):
                self._prefilling.pop(row)
                completing.append((i, row))
            else:
                self._prefilling[row] = rem[n_i:]
        self.metrics["mixed_steps"] += 1
        self.metrics["mixed_prefill_tokens"] += sum(
            n for _, _, n in chunks)
        self.metrics["prefill_tokens_per_step"] = round(
            self.metrics["mixed_prefill_tokens"]
            / self.metrics["mixed_steps"], 2)
        self.metrics["pages_in_use"] = self.alloc.pages_in_use
        if not with_decode:
            # pure-chunk tick, nothing decoding: no sync at all — the
            # program advanced every prefill row's device length in
            # place, so this is not even an epoch
            return
        if completing:
            self._dirty = True
            self._fault_point("sample",
                              rows=[row for _, row in completing])
        t0 = time.perf_counter()
        if completing:
            # jaxlint: disable=JL002 -- designed sync: first tokens of prompts completing this tick must reach the host to emit; rides THE one per-tick sync, counted via _count_sync
            nxt, lp = d2h(first_t), d2h(first_lp)
        tok_np = d2h(tok_block)  # jaxlint: disable=JL002 -- THE per-tick designed sync: one blocking materialization for the whole fused tick
        lp_np = d2h(lp_block)  # jaxlint: disable=JL002 -- rides THE per-tick sync above (same dispatched program)
        executed = int(d2h(n_exec))  # jaxlint: disable=JL002 -- rides THE per-tick sync: 0 only when no row decoded
        self._count_sync(time.perf_counter() - t0)
        for i, row in completing:
            self._finish_prompt(row, int(nxt[i]), float(lp[i]))
            if not canjoin[i] and self.rows[row] is not None:
                # the pool could not back its decode slot: the program
                # kept it out of the decode stage; finish like the old
                # second dispatch's dry-pool path
                self._finish(row, "length")
        self.metrics["steps"] += executed
        self.metrics["decode_horizon_effective"] = 1
        # the drain walk covers the decode participants: rows already
        # decoding plus completions that joined on device; rows finished
        # above (first-token EOS/budget/length) are None and skip
        mask = self._active_mask()
        parts = self._decode_parts(mask)
        if tick_spec:
            take_np = d2h(take_block)  # jaxlint: disable=JL002 -- rides THE per-tick sync: per-iteration accepted counts for the drain walk
            self._spec_metrics(take_np, s_prop, s_acc, executed)
            self._drain_spec_block(tok_np, lp_np, take_np, mask, executed)
        else:
            take_np = None
            self._drain_block(tok_np, lp_np, mask, executed)
        self._trace_decode(parts, t0_w, executed, take_np)
        self.metrics["tokens_per_sync"] = round(
            self.metrics["tokens"] / max(self.metrics["host_syncs"], 1), 2)

    def _horizon_step(self, active: np.ndarray):
        """Fused decode: up to ``decode_horizon`` decode+sample steps in one
        device program, drained token-by-token through ``_emit`` so SSE
        streaming and finish semantics are exactly the H=1 path's."""
        # the horizon target comes from the tick's plan (serving/
        # planner.py), which owns the old inline heuristics: the static
        # planner folds the admission-wave clamp (streams joining => H=1,
        # so a joining row never waits out a horizon and the batch fills
        # at the H=1 engine's pace; a full house with a queue keeps the
        # full horizon) over PRE-TICK queue state, the MPC planner
        # additionally caps the horizon a deadline-critical row rides.
        # One visible difference from the inline era: an arrival racing
        # into the inbox AFTER planning waits out at most one
        # already-planned horizon (streams stay bit-identical either way
        # — the H8==H1 contract).  pp meshes cannot fuse a horizon
        # (GPipe pipelines T=1 steps only).
        plan = self._plan
        H = 1 if self._pp_mode else (plan.horizon if plan is not None
                                     else self.ec.decode_horizon)
        # pre-allocate pages for the whole horizon; a tight pool shortens
        # the horizon for the step (power-of-two buckets bound recompiles)
        # instead of truncating requests the plain engine could still
        # serve — the mid-tick safety clamp under the planner: page-pool
        # reality outranks any prediction, and a cut planned horizon is
        # recorded for the flight ring (plan_clamped).
        # A fused-spec row wants min(H * (k_row+1), remaining budget)
        # slots — accepted tokens never outrun the budget, and writes past
        # the backed range are rejected drafts the scratch page absorbs —
        # and falls back to the plain width (spec off for this tick, the
        # traced-mask form of _spec_step's no_spec fallback) before the
        # whole tick's horizon is clamped on its account.
        h = H
        fused_spec = (self._fused_spec
                      and (plan.spec_on if plan is not None else True))
        if self._fused_spec and not fused_spec:
            # the plan masked speculation off: this tick emits through
            # the plain steady program, which does not maintain the
            # device-resident token history
            self._hist_stale = True
        elif fused_spec and self._hist_stale:
            # epoch re-upload rebuilds hist from host-side ids before
            # the proposer scans it (_sync_device_state honors _dirty)
            self._dirty = True
            self._hist_stale = False
        spec_ks = self._spec_widths(active) if fused_spec else None
        for i in range(len(self.rows)):
            if not active[i]:
                continue
            lens = int(self.row_lens[i])
            # a near-finished row only reserves what its budget can write —
            # never H-1 dead slots that could starve another row's ensure
            # (its post-death masked rewrites route to the scratch page)
            rem_i = int(self.row_budget[i]) - len(self.rows[i].output_ids)
            k_i = int(spec_ks[i]) if spec_ks is not None else 0
            want = min(H * (k_i + 1), rem_i)
            if self._ensure_pages(i, lens + max(want, 1)):
                continue
            if k_i:
                spec_ks[i] = 0      # pool pressure: plain step this tick
                if self._ensure_pages(i, lens + max(min(H, rem_i), 1)):
                    continue
            backed = (int((self.tables[i] >= 0).sum()) * self.ec.page_size
                      - lens)
            if backed < 1:
                self._finish(i, "length")
                active[i] = False
            else:
                h = min(h, backed)
        if not active.any():
            return
        if h < H:
            h = 1 << (h.bit_length() - 1)      # largest power of two <= h
            self.metrics["horizon_clamped"] = (
                self.metrics.get("horizon_clamped", 0) + 1)
            self._plan_overrun = True   # the pool cut the planned horizon
        self._fault_point("decode-dispatch",
                          rows=[i for i in range(len(self.rows))
                                if active[i]])
        t0_w = time.time()
        dev = self._sync_device_state()
        perf_pt = self._perf_point(h, width=0, spec=fused_spec,
                                   ew=int(dev["eos"].shape[1]))
        if self._pp_mode:
            with self._perf_dispatch("tick.pp"):
                nxt, lp, self.cache, self.key = _pp_decode_sample(
                    self.cfg, self.params, self.cache, dev["toks"],
                    dev["row_lens"], dev["active"], dev["temps"],
                    dev["top_ps"], self.key, dev["seeds"], dev["steps"],
                    dev["top_ks"],
                    mesh=self.mesh, n_micro=self.mesh.shape["pp"])  # jaxlint: disable=JL003 -- pp mesh shape is fixed for the engine lifetime: exactly one compiled program
            self._tick_dispatches += 1
            tok_block, lp_block = nxt[:, None], lp[:, None]
            # the pp schedule stays H=1 for now (a horizon scan would nest
            # the GPipe fill/drain per step); it still routes through this
            # entry but re-uploads per step until it learns the epoch sync
            self._dirty = True
            executed = 1
        elif fused_spec:
            # the spec-enabled form of the SAME single entry: drafting,
            # the [R, k+1] verify, and acceptance all ride inside the
            # horizon loop — still one dispatch (JP106 unchanged)
            with self._perf_dispatch("tick.spec", point=perf_pt):
                (_, _, tok_block, lp_block, n_exec, self.cache,
                 dev["toks"], dev["row_lens"], dev["active"],
                 dev["steps"], dev["remain"], self.key, take_block,
                 dev["hist"], s_prop, s_acc) = _ragged_tick_fn(
                    self.cfg, self.params, self.cache, dev["toks"],
                    dev["row_lens"], dev["active"], dev["temps"],
                    dev["top_ps"], self.key, dev["seeds"], dev["steps"],
                    dev["top_ks"], dev["eos"], dev["remain"],
                    prefill=None, horizon=h, hist=dev["hist"],
                    spec_ks=h2d(spec_ks), spec_k=self.ec.spec_k,
                    spec_ngram=self.ec.spec_ngram, mesh=self.mesh,
                    tp_manual=self._tp_manual,
                    collective_qtype=self._collective_qtype)
            self._tick_dispatches += 1
        else:
            # the steady-state tick is the SAME single jitted entry the
            # mixed tick uses, with no prefill block: one program either
            # way, which is what lets JP106 pin the tick dispatch count
            # to exactly 1 (the decode stage traces _decode_horizon_loop,
            # so output is bit-identical to the historical
            # _decode_multi_step program)
            with self._perf_dispatch("tick.steady", point=perf_pt):
                (_, _, tok_block, lp_block, n_exec, self.cache,
                 dev["toks"], dev["row_lens"], dev["active"],
                 dev["steps"], dev["remain"], self.key) = _ragged_tick_fn(
                    self.cfg, self.params, self.cache, dev["toks"],
                    dev["row_lens"], dev["active"], dev["temps"],
                    dev["top_ps"], self.key, dev["seeds"], dev["steps"],
                    dev["top_ks"], dev["eos"], dev["remain"],
                    prefill=None, horizon=h, mesh=self.mesh,
                    tp_manual=self._tp_manual,
                    collective_qtype=self._collective_qtype)
            self._tick_dispatches += 1
            # the returned cache owns the (donated) tables buffer now
        t0 = time.perf_counter()
        tok_block = d2h(tok_block)   # jaxlint: disable=JL002 -- THE per-horizon designed sync: h tokens per host round trip, counted via _count_sync
        lp_block = d2h(lp_block)  # jaxlint: disable=JL002 -- rides THE per-horizon sync above (same dispatched program)
        if not self._pp_mode:
            # jaxlint: disable=JL002 -- rides THE per-horizon sync: < h only if every row died early
            executed = int(d2h(n_exec))
        self._count_sync(time.perf_counter() - t0)
        if self.perf is not None:
            # the MFU join's loop multiplier: XLA's cost analysis counts
            # the horizon body once, the tick executed it `executed` times
            self.perf.note_executed(executed)
        self.metrics["steps"] += executed
        self.metrics["decode_horizon_effective"] = h
        self.metrics["pages_in_use"] = self.alloc.pages_in_use
        parts = self._decode_parts(active)
        if fused_spec and not self._pp_mode:
            take_block = d2h(take_block)  # jaxlint: disable=JL002 -- rides THE per-horizon sync: per-iteration accepted counts for the drain walk
            self._spec_metrics(take_block, s_prop, s_acc, executed)
            self._drain_spec_block(tok_block, lp_block, take_block,
                                   active, executed)
            take_np = take_block
        else:
            take_np = None
            self._drain_block(tok_block, lp_block, active, executed)
        self._trace_decode(parts, t0_w, executed, take_np)
        self.metrics["tokens_per_sync"] = round(
            self.metrics["tokens"] / self.metrics["host_syncs"], 2)

    def _decode_parts(self, active: np.ndarray):
        """Tracing pre-capture for a decode drain: the participating
        (row, request, tokens-so-far) triples, so the per-request
        decode-horizon span can report the tokens THIS tick emitted.
        None when tracing is off (zero cost)."""
        if self.tracer is None:
            return None
        return [(i, self.rows[i], len(self.rows[i].output_ids))
                for i in range(len(self.rows))
                if active[i] and self.rows[i] is not None]

    def _trace_decode(self, parts, t0_w: float, executed: int, take_np):
        """Per-request span for one committed decode tick: the fused
        horizon (`decode_horizon`, steps + tokens) or the speculative
        loop (`spec_round`, iterations + accept counts from the device's
        take block).  Timestamps are the tick's own host window — the
        existing one-per-tick sync, no new device reads."""
        if not parts:
            return
        t1 = time.time()
        for i, req, n0 in parts:
            toks = len(req.output_ids) - n0
            if toks == 0 and req.finish_reason is None:
                continue            # masked out / spec-width-0 idle row
            if take_np is not None:
                row = take_np[i]
                rounds = int((row > 0).sum())
                self._trace(req, "spec_round", t0=t0_w, t1=t1,
                            rounds=rounds, tokens=toks,
                            accepted=max(int(row.sum()) - rounds, 0))
            else:
                self._trace(req, "decode_horizon", t0=t0_w, t1=t1,
                            steps=executed, tokens=toks)

    def _drain_block(self, tok_block, lp_block, active: np.ndarray, h: int):
        """Walk an [R, h] token/logprob block through the exact per-token
        emission path: the host stops a row at its EOS/budget/abort
        boundary, which is the same boundary the device masked at."""
        for i in range(len(self.rows)):
            if not active[i] or self.rows[i] is None:
                continue
            for j in range(h):
                self.row_lens[i] += 1
                tok = int(tok_block[i, j])
                self.toks[i] = tok
                self._emit(i, tok, float(lp_block[i, j]))
                if self.rows[i] is None:   # finished mid-block
                    break

    def _count_sync(self, seconds: float):
        """One blocking device->host materialization (the per-step cost the
        fused horizon amortizes over H tokens)."""
        self.metrics["host_syncs"] += 1
        self.hists["tick_sync_s"].observe(seconds)
        if self.perf is not None:
            self.perf.note_sync(seconds)
        self.metrics["host_sync_s"] = round(
            self.metrics["host_sync_s"] + seconds, 6)


def next_stream_item(engine: "ServingEngine", req: Request,
                     poll_s: float = 0.5) -> int | None:
    """Blocking fetch of one stream item, waiting in bounded slices so a
    dead engine thread fails the request (``finish_reason="error"``)
    instead of hanging the consumer forever.  The shared dead-engine
    detection protocol for every HTTP frontend — returns the next token,
    or None at end of stream / engine death."""
    while True:
        try:
            return req.stream_queue.get(timeout=poll_s)
        except queue.Empty:
            t = engine._thread
            if t is None or not t.is_alive():
                if req.finish_reason is None:
                    req.finish_reason = "error"
                return None


def stream_tokens(req: Request, timeout: float | None = None):
    """Yield tokens from a submitted request until completion.

    ``timeout`` is the max wait between tokens; None aligns it with the
    request's own deadline (plus grace for the engine's timeout tick to
    land) when one is set, else the historical 120 s."""
    if timeout is None:
        timeout = (req.deadline_s + 30.0) if req.deadline_s else 120.0
    while True:
        tok = req.stream_queue.get(timeout=timeout)
        if tok is None:
            return
        yield tok
