"""Zero-dependency tracing + telemetry core for the serving tier.

Three primitives, shared by the engine, the router, and both HTTP
frontends (PR 13 — the observability tentpole):

- **Per-request spans** (``Tracer``): a bounded LRU of traces, each a
  flat list of span dicts ``{"name", "t0", "t1", "attrs", "origin"}``
  with wall-clock (epoch) timestamps so spans recorded in DIFFERENT
  processes (router + replicas) merge into one coherent timeline.  The
  engine stages spans inside its transactional tick and flushes them
  only on ``_commit`` — a rolled-back tick never leaks a span — while
  the router records its own spans (route attempts, failover replays,
  handoff legs) directly.  A W3C ``traceparent`` (``00-<trace>-<span>-
  01``) propagated router → replica keys both stores to ONE trace id, so
  ``/trace/{id}`` assembles the request's whole life across processes.
  Traces export as Chrome trace-event JSON (``chrome.tracing`` /
  Perfetto loadable).
- **Tick flight recorder** (``FlightRecorder``): a bounded ring of
  recent per-tick records (sync duration, rows active/prefilling, pages
  allocated/spilled, retries, fault sites hit).  ``dump(reason)``
  freezes the ring — the engine calls it automatically on ``_fail_all``
  and quarantine, so the postmortem artifact exists the moment the
  blast radius is decided, not when an operator remembers to ask.
  ``/debug/flight`` exposes ring + dumps on demand.
- **Honest histograms** (``Histogram``): fixed-bucket latency
  distributions with true Prometheus ``_bucket``/``_sum``/``_count``
  exposition, O(buckets) ``snapshot``/``restore`` (so the engine's
  checkpoint/rollback covers them like every PR 3 counter), and
  ``merge`` for the router's fleet sums — replacing the ad-hoc rolling
  p95 scalars that could not be aggregated or bucketed honestly.

Everything here is pure-host bookkeeping: no jax, no device calls, no
syncs — timestamps are ``time.time()`` reads at points the host already
visits (JP106's one-dispatch tick is untouched, and tracing disabled
costs one ``is None`` check per site).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque

__all__ = [
    "Histogram",
    "Tracer",
    "FlightRecorder",
    "LATENCY_BUCKETS_S",
    "FAST_LATENCY_BUCKETS_S",
    "new_trace_id",
    "new_span_id",
    "make_traceparent",
    "parse_traceparent",
    "span",
]

# Prometheus-style latency bounds (seconds).  LATENCY covers request-
# scale times (TTFT, per-token under load, handoff legs); FAST covers
# device-sync-scale times (tick sync, swap-in).  Fixed at construction:
# bucket identity is what makes fleet sums and cross-round comparisons
# meaningful.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
FAST_LATENCY_BUCKETS_S = (0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                          0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)


# ---------------------------------------------------------------------------
# W3C traceparent (https://www.w3.org/TR/trace-context/)


def new_trace_id() -> str:
    return uuid.uuid4().hex            # 32 lowercase hex chars


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]       # 16 lowercase hex chars


def make_traceparent(trace_id: str, span_id: str | None = None) -> str:
    return f"00-{trace_id}-{span_id or new_span_id()}-01"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """``(trace_id, parent_span_id)`` from a ``traceparent`` header, or
    None when absent/malformed (a bad header must never fail a request —
    tracing degrades to a fresh trace instead)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32:
        return None
    return trace_id, span_id


def span(name: str, t0: float, t1: float | None = None,
         origin: str = "", **attrs) -> dict:
    """One span record.  ``t0``/``t1`` are epoch seconds (``time.time``)
    so spans from different processes order on one timeline; ``t1`` is
    None for instant events (rendered zero-width)."""
    return {"name": name, "t0": round(t0, 6),
            "t1": round(t1, 6) if t1 is not None else None,
            "origin": origin, "attrs": attrs}


# ---------------------------------------------------------------------------
# Histogram


class Histogram:
    """Fixed-bucket histogram with Prometheus semantics.

    ``bounds`` are the inclusive upper bounds of the finite buckets (the
    ``le`` labels); one implicit +Inf bucket catches the rest.  State is
    (counts, sum, count) — O(len(bounds)) to snapshot, which is what
    lets the engine checkpoint its histograms EVERY tick (PR 3's
    rollback contract) without tick latency scaling with history.
    """

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds=LATENCY_BUCKETS_S):
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self.counts = [0] * (len(self.bounds) + 1)   # +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        v = float(value)
        lo, hi = 0, len(self.bounds)
        while lo < hi:                       # first bound >= v
            mid = (lo + hi) // 2
            if v <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.counts[lo] += 1
        self.sum += v
        self.count += 1

    def percentile(self, q: float) -> float:
        """Bucket-interpolated percentile (q in [0, 100]); the honest
        caveat of any fixed-bucket scheme: resolution is the bucket
        width, and the +Inf bucket reports its lower bound."""
        if self.count == 0:
            return 0.0
        rank = max(1, -(-self.count * q // 100))   # ceil
        acc = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            lo = self.bounds[i - 1] if i > 0 else 0.0
            hi = self.bounds[i] if i < len(self.bounds) else lo
            if acc + c >= rank:
                frac = (rank - acc) / c
                return lo + (hi - lo) * frac
            acc += c
        return self.bounds[-1]

    # -- exposition ---------------------------------------------------------

    def prometheus_lines(self, name: str, labels: str = "") -> list[str]:
        """Real ``_bucket``/``_sum``/``_count`` series.  ``labels`` is a
        pre-rendered ``key="value"`` list (no braces) merged with the
        ``le`` label on bucket lines; counts are CUMULATIVE per the
        exposition format."""
        out, acc = [], 0
        for i, b in enumerate(self.bounds):
            acc += self.counts[i]
            le = f'le="{b:g}"'
            lab = f"{{{labels},{le}}}" if labels else f"{{{le}}}"
            out.append(f"{name}_bucket{lab} {acc}")
        acc += self.counts[-1]
        lab = f'{{{labels},le="+Inf"}}' if labels else '{le="+Inf"}'
        out.append(f"{name}_bucket{lab} {acc}")
        lab = f"{{{labels}}}" if labels else ""
        out.append(f"{name}_sum{lab} {round(self.sum, 6)}")
        out.append(f"{name}_count{lab} {acc}")
        return out

    def to_dict(self) -> dict:
        """Machine shape for ``/metrics?format=json`` — what the router
        fetches and fleet-sums."""
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "sum": round(self.sum, 6), "count": self.count}

    def merge(self, other: dict) -> bool:
        """Fold another histogram's ``to_dict`` shape in (the fleet
        sum).  Returns False — and folds nothing — on a bucket-bound
        mismatch: summing differently-bucketed series would fabricate a
        distribution neither replica measured."""
        if tuple(other.get("bounds", ())) != self.bounds:
            return False
        for i, c in enumerate(other.get("counts", ())):
            self.counts[i] += int(c)
        self.sum += float(other.get("sum", 0.0))
        self.count += int(other.get("count", 0))
        return True

    # -- transactionality ---------------------------------------------------

    def snapshot(self) -> tuple:
        return (tuple(self.counts), self.sum, self.count)

    def copy(self) -> "Histogram":
        """Independent frozen copy (the engine publishes one per commit
        so /metrics never observes mid-tick state a rollback would
        subtract — scraped series must stay monotonic)."""
        h = Histogram(self.bounds)
        h.counts = list(self.counts)
        h.sum = self.sum
        h.count = self.count
        return h

    def restore(self, snap: tuple):
        counts, s, c = snap
        self.counts = list(counts)
        self.sum = s
        self.count = c


# ---------------------------------------------------------------------------
# Tracer


class Tracer:
    """Bounded LRU of traces (trace_id → span list).

    Thread-safe around a plain lock: the engine thread appends committed
    spans while HTTP threads read ``/trace/{id}`` — span lists are
    copied out under the lock, never handed out live.  Per-trace span
    count is capped too (``max_spans``): a pathological 100k-token
    stream degrades to a truncated trace with a ``spans_dropped`` count,
    never unbounded memory.
    """

    def __init__(self, max_traces: int = 256, max_spans: int = 512):
        self.max_traces = max(1, int(max_traces))
        self.max_spans = max(16, int(max_spans))
        self._traces: "OrderedDict[str, list[dict]]" = OrderedDict()
        self._dropped: dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, trace_id: str, *spans: dict):
        if not trace_id or not spans:
            return
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                tr = self._traces[trace_id] = []
            self._traces.move_to_end(trace_id)
            for s in spans:
                if len(tr) >= self.max_spans:
                    self._dropped[trace_id] = (
                        self._dropped.get(trace_id, 0) + 1)
                else:
                    tr.append(s)
            while len(self._traces) > self.max_traces:
                old, _ = self._traces.popitem(last=False)
                self._dropped.pop(old, None)

    def get(self, trace_id: str) -> dict | None:
        """``{"trace_id", "spans", "spans_dropped"}`` or None.  Spans
        come back sorted by start time (the assembly order a reader
        wants; insertion order is commit order, which interleaves)."""
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            spans = sorted(tr, key=lambda s: (s["t0"], s["name"]))
            return {"trace_id": trace_id, "spans": spans,
                    "spans_dropped": self._dropped.get(trace_id, 0)}

    def trace_ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    # -- Chrome trace-event export ------------------------------------------

    @staticmethod
    def chrome_events(traces: list[dict], pid: int = 1) -> dict:
        """Render assembled traces as Chrome trace-event JSON (load in
        ``chrome://tracing`` or Perfetto).  Spans become complete ("X")
        events in microseconds since the earliest span; instant events
        ("i") keep zero duration.  Each trace gets its own tid row, each
        origin its own pid row, so a router+replicas trace reads as a
        swimlane per process."""
        events = []
        t_base = min((s["t0"] for tr in traces for s in tr["spans"]),
                     default=0.0)
        origins = {}
        for tid_i, tr in enumerate(traces, start=1):
            for s in tr["spans"]:
                org = s.get("origin") or "serving"
                o_pid = origins.setdefault(org, len(origins) + pid)
                ts = (s["t0"] - t_base) * 1e6
                args = dict(s.get("attrs") or {})
                args["trace_id"] = tr["trace_id"]
                ev = {"name": s["name"], "cat": org, "pid": o_pid,
                      "tid": tid_i, "ts": round(ts, 1), "args": args}
                if s.get("t1") is not None:
                    ev["ph"] = "X"
                    ev["dur"] = round((s["t1"] - s["t0"]) * 1e6, 1)
                else:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                events.append(ev)
        meta = [{"ph": "M", "pid": o_pid, "tid": 0,
                 "name": "process_name", "args": {"name": org}}
                for org, o_pid in origins.items()]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def export_chrome(self, trace_ids=None, pid: int = 1) -> dict:
        """Whole-window (or selected) export in one call."""
        ids = trace_ids if trace_ids is not None else self.trace_ids()
        traces = [t for t in (self.get(i) for i in ids) if t is not None]
        return self.chrome_events(traces, pid=pid)


# ---------------------------------------------------------------------------
# Flight recorder


class FlightRecorder:
    """Bounded ring of recent tick records + frozen postmortem dumps.

    A record is one small dict per COMMITTED working tick (the engine
    skips pure idle ticks so the ring holds the last N units of real
    work, not the last N/50 seconds of idling).  ``dump(reason)``
    freezes a copy of the ring with its reason and timestamp — called
    automatically at the engine's blast-radius decisions (_fail_all,
    quarantine) so the evidence is captured at the moment of failure.
    """

    def __init__(self, size: int = 256, max_dumps: int = 8):
        self.ring: "deque[dict]" = deque(maxlen=max(8, int(size)))
        self.dumps: "deque[dict]" = deque(maxlen=max(1, int(max_dumps)))
        self.idle_skipped = 0
        self.recorded = 0
        self._lock = threading.Lock()

    def record(self, rec: dict):
        with self._lock:
            self.recorded += 1
            self.ring.append(rec)

    def skip_idle(self):
        self.idle_skipped += 1

    def dump(self, reason: str, **extra) -> dict:
        with self._lock:
            d = {"t": round(time.time(), 3), "reason": reason,
                 "ring": list(self.ring), **extra}
            self.dumps.append(d)
            return d

    def view(self) -> dict:
        """The ``/debug/flight`` payload."""
        with self._lock:
            return {"ring": list(self.ring),
                    "ring_size": self.ring.maxlen,
                    "recorded": self.recorded,
                    "idle_skipped": self.idle_skipped,
                    "dumps": list(self.dumps)}
