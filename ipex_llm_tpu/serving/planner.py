"""Model-predictive tick planner: spend the cost model on goodput.

PR 15 built the observe half of the loop — manifest ``cost_analysis``
flops/bytes per locked grid point, perfwatch's per-family wall-clock
attribution, the recompile sentinel — while every engine control knob
stayed static config or an ad-hoc heuristic scattered through
``engine.py``.  This module closes the loop: ONE host-side decision
function runs once per tick (pure bookkeeping, zero new device programs,
JP106's one-dispatch tick untouched) and picks the tick's whole shape —
prefill chunk budget, decode horizon H, per-row speculative draft caps,
and admission count — to maximize predicted goodput (completed-under-
deadline tok/s) subject to per-request deadlines.

The predictor joins three sources:

- the manifest's ``cost_analysis`` for each candidate grid point
  (``PerfWatch.cost_for`` — the analytic roofline seconds), so a cold
  engine plans sensibly before it has measured anything;
- perfwatch's measured per-family tick history, folded into per-step /
  per-prefill-token EWMA rates (``observe`` — called from the flight
  recorder on committed working ticks only), so the plan tracks the real
  machine, not the analytic model;
- the rolling speculative accept-rate window, which prices draft
  economics: a verify round costs about one weight pass either way, so
  speculation pays iff the measured acceptance buys more than the spec
  program's measured per-round premium.

Candidates are drawn ONLY from shapes the engine's own config already
bounds (pow2 horizons up to ``decode_horizon``, pow2 chunk widths up to
``prefill_bucket``, spec widths up to ``spec_k``) and, when a manifest is
loaded, filtered to the locked grid (``point_in_grid``) — the planner
SELECTS among existing lowerings, it never creates one, which is why the
recompile sentinel stays structurally quiet under it and the manifest
``--update`` check is a byte-identical no-op.

Two planners share the interface:

- :class:`StaticPlanner` (``EngineConfig.planner="static"``) reproduces
  the pre-planner engine's decisions exactly — the fixed
  ``step_token_budget`` chunk budget, the admission-wave H-clamp
  (streams joining ⇒ H=1), static per-request spec widths, unbounded
  admission — as ONE plan object, so the escape hatch is bit-identical
  to the PR 15 engine by construction.
- :class:`MPCPlanner` (the default) deviates from those decisions only
  on evidence: deadline slack caps the horizon of the tick a
  latency-sensitive row rides (batch rows keep H×(k+1)); a measured
  accept-rate window that prices drafts underwater masks speculation off
  (re-probing periodically so the window never goes stale); admission is
  deferred for a tick when the wave would blow a critical row's
  deadline; the TTFT budget escalates the chunk share of deadline-bound
  joiners.  With no deadlines and no adverse spec evidence it makes the
  static choices, which is what keeps the equivalence suites green with
  the planner on by default.

Plan timing and fault replay: the engine computes the plan at the top of
``_tick`` BEFORE the checkpoint, snapshots it with the tick state, and
reuses it verbatim across transient-retry re-runs and bisection probes —
a rolled-back tick replays the SAME plan (``tests/test_serving_faults``
pins this).  Decision counters here are sentinel-style monotonic (a
rolled-back tick's planning really happened), mirroring perfwatch's
compile counters.

The plan's horizon is a PRE-TICK decision from pre-tick queue state; the
allocation walk in ``_horizon_step`` remains as the mid-tick safety
clamp (page-pool reality outranks any prediction) and records a
``plan_clamped`` flight-ring field when it cuts a planned horizon.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

__all__ = ["TickPlan", "StaticPlanner", "MPCPlanner", "make_planner"]

# EWMA smoothing for the measured per-family rates: light enough to track
# a regime change inside a few ticks, heavy enough that one noisy tick
# (a GC pause, a cold page fault) does not whipsaw the plan
_EWMA_ALPHA = 0.25

# spec economics: don't judge draft acceptance before the window holds
# this many proposals (a handful of unlucky rounds must not mask spec
# off), and once masked off, re-probe every N planned decode ticks so
# the accept window tracks the workload instead of fossilizing
_SPEC_MIN_PROPOSALS = 64
_SPEC_REPROBE_TICKS = 64
# speculation stays on while measured tokens-per-round beats the spec
# program's measured cost premium by this margin
_SPEC_MARGIN = 1.05


@dataclass(frozen=True)
class TickPlan:
    """One tick's decided shape — immutable, so the checkpoint can hold
    a reference and a rolled-back tick replays it verbatim.

    ``spec_ks`` are per-row CAPS composed with the per-request knobs at
    use time (``min(_row_spec_k(req), cap)``), never replacements — a
    row admitted after planning takes ``spec_cap``.  ``admit_max=None``
    is unbounded (the static engine's behaviour); 0 defers the whole
    wave to a later tick."""
    horizon: int                     # decode-horizon target (pow2, >= 1)
    chunk_budget: int                # mixed-step prefill token budget
    spec_ks: tuple[int, ...]         # per-row draft-width caps [R]
    spec_cap: int                    # cap for rows admitted after planning
    admit_max: int | None = None     # admissions allowed this tick
    predicted_s: float = 0.0         # predicted tick wall seconds (0 = n/a)
    predicted_tok_s: float = 0.0     # predicted aggregate tok/s (0 = n/a)
    clamped: bool = False            # desired point cut to the locked grid
    reason: str = "static"           # decision tag (/health + flight ring)

    @property
    def spec_on(self) -> bool:
        """Whether this tick's fused program carries the spec stage at
        all — the per-tick form of the engine's ``_fused_spec``."""
        return self.spec_cap > 0 or any(self.spec_ks)

    def flight_fields(self) -> dict:
        """Compact plan stamp for the flight-recorder record."""
        out = {"h": self.horizon, "cb": self.chunk_budget,
               "sk": max(self.spec_ks) if self.spec_ks else 0,
               "why": self.reason}
        if self.admit_max is not None:
            out["admit"] = self.admit_max
        return out

    def view(self) -> dict:
        """The /health ``planner.last`` block."""
        out = {"horizon": self.horizon, "chunk_budget": self.chunk_budget,
               "spec_cap": self.spec_cap, "reason": self.reason,
               "clamped": self.clamped}
        if self.admit_max is not None:
            out["admit_max"] = self.admit_max
        if self.predicted_s:
            out["predicted_s"] = round(self.predicted_s, 6)
        if self.predicted_tok_s:
            out["predicted_tok_s"] = round(self.predicted_tok_s, 2)
        return out


class _PlannerBase:
    """Shared bookkeeping: decision counters (monotonic, sentinel-style
    — a rolled-back tick's plan really was computed) and the measured
    per-family EWMA rates the flight recorder feeds after every
    committed working tick."""

    mode = "base"

    def __init__(self, ec):
        self.ec = ec
        self.decisions: dict[str, int] = {}
        self.last_plan: TickPlan | None = None
        # measured rates, EWMA-smoothed: "step" / "step_spec" are wall
        # seconds per executed decode iteration (plain / spec program),
        # "prefill_tok" is wall seconds per prefill token through the
        # admission wave
        self._rates: dict[str, float] = {}
        self.plans = 0

    # -- engine-facing lifecycle -------------------------------------------

    def plan(self, eng) -> TickPlan:
        raise NotImplementedError

    def observe(self, family: str | None, wall_s: float, executed: int,
                prefill_tokens: int):
        """Fold one committed working tick's measured wall clock into the
        EWMA rates (called from ``_flight_record`` — committed ticks
        only, so a rolled-back tick leaves no rate residue)."""
        if not wall_s or wall_s <= 0:
            return
        if prefill_tokens > 0:
            self._ewma("prefill_tok", wall_s / prefill_tokens)
        elif executed > 0:
            key = ("step_spec" if family == "tick.spec" else "step")
            self._ewma(key, wall_s / executed)

    def _ewma(self, key: str, value: float):
        old = self._rates.get(key)
        self._rates[key] = (value if old is None
                            else old + _EWMA_ALPHA * (value - old))

    def _record(self, plan: TickPlan) -> TickPlan:
        self.plans += 1
        self.decisions[plan.reason] = self.decisions.get(plan.reason, 0) + 1
        if plan.clamped:
            self.decisions["grid_clamped"] = (
                self.decisions.get("grid_clamped", 0) + 1)
        self.last_plan = plan
        return plan

    def view(self) -> dict:
        """The /health ``planner`` block body (the engine adds the
        deadline-miss rate from its own metrics)."""
        out = {"mode": self.mode, "plans": self.plans,
               "decisions": dict(self.decisions)}
        if self.last_plan is not None:
            out["last"] = self.last_plan.view()
        if self._rates:
            out["rates"] = {k: round(v, 6) for k, v in self._rates.items()}
        return out

    # -- shared decision inputs --------------------------------------------

    @staticmethod
    def _streams_joining(eng) -> bool:
        """The admission-wave condition, evaluated over PRE-TICK state:
        rows mid-prefill, or queued work (pending FIFO / inbox) a free
        row could take.  This is the pre-planner ``_horizon_step``
        clamp's exact predicate, moved to plan time — the one visible
        difference is an arrival racing into the inbox AFTER planning
        waits out at most one already-planned horizon."""
        if eng._prefilling:
            return True
        return ((bool(eng._pending) or not eng._inbox.empty())
                and eng._free_row() is not None)


class StaticPlanner(_PlannerBase):
    """The escape hatch: today's decisions, verbatim, as one plan.

    Horizon folds the admission-wave clamp (streams joining ⇒ 1, a pp
    mesh ⇒ 1, else ``decode_horizon``); the chunk budget is the resolved
    ``step_token_budget``; spec caps are the no-op ``spec_k`` everywhere
    (per-request opt-outs stay where they always were, in
    ``_row_spec_k``); admission is unbounded.  No prediction, no grid
    filtering, no deviation — bit-identical to the PR 15 engine."""

    mode = "static"

    def plan(self, eng) -> TickPlan:
        ec = self.ec
        if eng._pp_mode:
            h = 1
        else:
            h = ec.decode_horizon
            if h > 1 and self._streams_joining(eng):
                h = 1
        return self._record(TickPlan(
            horizon=max(h, 1),
            chunk_budget=eng._step_budget,
            spec_ks=(ec.spec_k,) * ec.max_rows,
            spec_cap=ec.spec_k,
            admit_max=None,
            reason="static"))


class MPCPlanner(_PlannerBase):
    """Goodput-maximizing planner: model-predictive over one tick.

    The decision order matters — admission first (a deferred wave
    removes the joiners from the horizon condition), then speculation
    (its cost model feeds the per-step rate), then the horizon over the
    grid-filtered candidate ladder under the tightest deadline slack."""

    mode = "mpc"

    def __init__(self, ec):
        super().__init__(ec)
        # spec hysteresis: ticks planned since speculation was masked
        # off (drives the periodic re-probe that keeps the accept
        # window live)
        self._spec_off_ticks = 0

    # -- measured / analytic cost -------------------------------------------

    def _step_rate(self, eng, spec_on: bool, horizon: int) -> float:
        """Predicted wall seconds per decode iteration: the measured
        EWMA when the family has history, else the manifest's analytic
        roofline for the candidate point (EWMA-corrected only in the
        sense that measurement replaces it as soon as one tick lands),
        else 0.0 = unknown (deadline capping disabled rather than
        guessed)."""
        measured = self._rates.get("step_spec" if spec_on else "step")
        if measured:
            return measured
        perf = eng.perf
        if perf is None:
            return 0.0
        point = eng._perf_point(horizon, width=0, spec=spec_on)
        cost = perf.cost_for(point, max(horizon, 1))
        if cost is None:
            return 0.0
        flops, byts = cost
        sec = max(flops / perf.peak_flops, byts / perf.peak_bytes_s)
        return sec / max(horizon, 1)

    # -- sub-decisions -------------------------------------------------------

    def _deadline_slacks(self, eng, now: float) -> list[float]:
        """Wall-clock slack of every in-flight decode row with a
        deadline (queued requests gate admission, not the horizon)."""
        out = []
        for r in eng.rows:
            if r is None:
                continue
            d = eng._deadline_of(r)
            if d is not None:
                out.append(d - (now - r.submitted_s))
        return out

    def _spec_decision(self, eng) -> tuple[int, str | None]:
        """Draft economics from the rolling accept window: speculation
        stays at full width until the window holds enough proposals to
        judge; then tokens-per-round (1 free token + measured accepted
        drafts) must beat the spec program's measured per-round cost
        premium, or the caps mask to 0 (the program drops back to the
        plain steady form — a locked point, not a new one).  Masked-off
        spec re-probes periodically so the window keeps tracking the
        workload."""
        k = self.ec.spec_k
        if not eng._fused_spec or k <= 0:
            return k, None
        window = list(eng._spec_window)
        prop = sum(p for p, _ in window)
        acc = sum(a for _, a in window)
        if prop < _SPEC_MIN_PROPOSALS:
            return k, None
        rounds = max(len(window), 1)
        tokens_per_round = 1.0 + acc / rounds
        s_spec = self._rates.get("step_spec")
        s_plain = self._rates.get("step")
        premium = (s_spec / s_plain if s_spec and s_plain else 1.0)
        if tokens_per_round >= premium * _SPEC_MARGIN:
            self._spec_off_ticks = 0
            return k, None
        self._spec_off_ticks += 1
        if self._spec_off_ticks >= _SPEC_REPROBE_TICKS:
            self._spec_off_ticks = 0
            return k, "spec_probe"
        return 0, "spec_off"

    def _grid_horizons(self, eng, cands: list[int], spec_on: bool
                       ) -> tuple[list[int], bool]:
        """Filter horizon candidates to the manifest-locked grid.  A
        candidate set the grid covers not at all keeps every candidate
        (degraded mode: the sentinel still flags, exactly as the static
        engine would) — the planner must never brick serving over a
        missing lock entry."""
        perf = eng.perf
        if perf is None or perf.grid is None:
            return cands, False
        from ipex_llm_tpu.serving.perfwatch import point_in_grid

        kept = [h for h in cands
                if point_in_grid(eng._perf_point(h, width=0, spec=spec_on),
                                 perf.grid)]
        if not kept:
            return cands, False
        return kept, max(kept) < max(cands)

    # -- the decision function ----------------------------------------------

    def plan(self, eng) -> TickPlan:
        ec = self.ec
        now = time.perf_counter()
        reason = "steady"
        slacks = self._deadline_slacks(eng, now)
        min_slack = max(min(slacks), 0.0) if slacks else None

        # speculation first: its verdict picks which program family's
        # measured rate prices the rest of the tick
        spec_cap, spec_reason = self._spec_decision(eng)
        if spec_reason:
            reason = spec_reason
        s_step = self._step_rate(eng, spec_cap > 0, ec.decode_horizon)

        # admission: normally unbounded (rows are the real limit), but a
        # wave that would turn the next ticks into H=1 mixed ticks is
        # DEFERRED while an in-flight row's deadline cannot absorb even
        # two plain ticks — finish the critical row first, admit next
        # tick (the queued request's own deadline is still enforced at
        # admission by _expire_deadlines)
        admit_max = None
        queued = bool(eng._pending) or not eng._inbox.empty()
        if (queued and min_slack is not None and s_step > 0
                and eng._free_row() is not None
                and min_slack < 2.0 * s_step * max(ec.decode_horizon, 1)):
            admit_max = 0
            reason = "admit_deferred"

        # the admission-wave condition over pre-tick state — a deferred
        # wave is excluded from it on purpose (that IS the deferral)
        joining = (bool(eng._prefilling) if admit_max == 0
                   else self._streams_joining(eng))

        if eng._pp_mode:
            cands = [1]
        elif joining:
            cands = [1]
            if reason == "steady":
                reason = "joining"
        else:
            top = max(ec.decode_horizon, 1)
            cands = sorted({1 << i for i in range(top.bit_length())
                            if (1 << i) <= top} | {top})
        cands, clamped = self._grid_horizons(eng, cands, spec_cap > 0)

        # deadline slack caps the horizon of the tick a latency-bound
        # row rides: the tick must END before the tightest deadline, so
        # its finish/timeout epoch lands in time (batch rows on the same
        # tick simply ride the shorter horizon)
        if min_slack is not None and s_step > 0 and len(cands) > 1:
            cap = max(int(min_slack / s_step), 1)
            feasible = [c for c in cands if c <= cap]
            if feasible and max(feasible) < max(cands):
                reason = "deadline_h_cap"
            cands = feasible or [min(cands)]
        horizon = max(cands)

        # chunk budget: static share unless a deadline-bound joiner is
        # mid-prefill with more prompt left than its share advances per
        # tick — then every joining row gets the full bucket (TTFT
        # escalation; widths stay pow2 <= prefill_bucket, so no new
        # program shapes)
        budget = eng._step_budget
        if eng._mixed_mode and eng._prefilling:
            n_join = len(eng._prefilling)
            share = max(1, budget // max(n_join, 1))
            tight = False
            for row, rem in eng._prefilling.items():
                req = eng.rows[row]
                if req is None or len(rem) <= share:
                    continue
                d = eng._deadline_of(req)
                if d is not None and (d - (now - req.submitted_s)
                                      < d * 0.5):
                    tight = True
                    break
            if tight:
                budget = min(ec.prefill_bucket * n_join,
                             ec.prefill_bucket * ec.max_rows)
                reason = "ttft_escalate"

        # predicted economics for the chosen shape (flight ring + the
        # perf_plan_error histogram measure the model against reality)
        n_active = sum(1 for i, r in enumerate(eng.rows)
                       if r is not None and i not in eng._prefilling)
        predicted_s = horizon * s_step if s_step else 0.0
        predicted_tok = float(horizon * max(n_active, 0))
        predicted_tok_s = (predicted_tok / predicted_s
                           if predicted_s and predicted_tok else 0.0)

        return self._record(TickPlan(
            horizon=max(horizon, 1),
            chunk_budget=budget,
            spec_ks=(spec_cap,) * ec.max_rows,
            spec_cap=spec_cap,
            admit_max=admit_max,
            predicted_s=predicted_s,
            predicted_tok_s=predicted_tok_s,
            clamped=clamped,
            reason=reason))


def make_planner(ec) -> _PlannerBase:
    """Resolve ``EngineConfig.planner`` — "mpc" (the default) or the
    "static" escape hatch."""
    mode = getattr(ec, "planner", "mpc") or "mpc"
    if mode == "mpc":
        return MPCPlanner(ec)
    if mode == "static":
        return StaticPlanner(ec)
    raise ValueError(
        f"unknown EngineConfig.planner {mode!r}: expected 'mpc' or "
        "'static'")
