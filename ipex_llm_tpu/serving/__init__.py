"""Serving: OpenAI-compatible HTTP API over a continuous-batching engine.

Reference counterparts: the FastAPI server (reference
serving/fastapi/api_server.py:90, openai_protocol.py), the vLLM integration
(vllm/, 4.5k LoC) and the PPModelWorker batch scheduler
(pipeline_parallel.py:482-928).  TPU-native design: ONE static-shape jitted
decode step over a fixed row pool; requests join/leave rows between steps
(continuous batching) with per-row cache offsets instead of paged KV.
"""

from ipex_llm_tpu.serving.engine import EngineConfig, Request, ServingEngine
from ipex_llm_tpu.serving.faults import (DeterministicFault, EngineOverloaded,
                                         FaultInjector, ReplicaFault,
                                         TransientFault)
from ipex_llm_tpu.serving.router import (HTTPBackend, InProcessBackend,
                                         Router, RouterConfig)

__all__ = ["ServingEngine", "EngineConfig", "Request", "FaultInjector",
           "EngineOverloaded", "TransientFault", "DeterministicFault",
           "ReplicaFault", "Router", "RouterConfig", "HTTPBackend",
           "InProcessBackend"]
