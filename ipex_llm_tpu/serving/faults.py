"""Fault taxonomy + deterministic fault injection for the serving engine.

The engine's unit of failure is a REQUEST, not the engine (the blast-radius
contract tests/test_serving_faults.py enforces).  Two failure classes drive
the recovery policy in ``ServingEngine._recover``:

- **transient** — the device call would succeed if repeated: preempted
  tunnel, ``RESOURCE_EXHAUSTED``/``UNAVAILABLE`` from the runtime, a
  dropped connection.  Recovery: roll host bookkeeping back to the last
  committed tick, re-upload device state (``_dirty = True`` — the same
  epoch mechanism admission uses), back off exponentially, and re-run the
  step.  The re-run recomputes the identical tick (same key chain, same
  fold_in(seed, step) streams), so retried output is bit-identical.
- **deterministic** — the same inputs fail every time: a poisoned prompt
  hitting a model/kernel edge, a per-request resource bug.  Retrying is
  useless; instead the engine BISECTS the faulted tick's row set
  (re-running the step with suspect rows masked, emissions muted and all
  bookkeeping rolled back between probes) until one culprit row remains,
  quarantines only that row with ``finish_reason="error"``, and replays
  the tick for the survivors — whose tokens and logprobs stay bit-
  identical to an unfaulted run.  ``_fail_all`` remains only as the
  engine-level backstop for when bisection itself cannot localize the
  fault (the fault fires even with every suspect masked — a device-level,
  not request-level, failure).  A fault that VANISHES during bisection
  (does not reproduce on re-run, or stops firing before the culprit is
  confirmed) is treated as transient-resolved: the engine carries on from
  the committed state rather than punishing anyone.  NOTE for scripted
  deterministic faults: use ``times=None`` on a request-scoped spec (a
  poisoned request fails every time it participates); a one-shot
  deterministic spec is indistinguishable from a transient blip and will
  be classified as vanished.

Classification: exception TYPE first (the marker classes below, used by
tests and by code that knows its failure mode), then RUNTIME MESSAGE
markers — the gRPC-style status names JAX runtimes embed in
``XlaRuntimeError`` text (``RESOURCE_EXHAUSTED: ...``), plus OS-level
connection failures from a device tunnel.

``FaultInjector`` is the deterministic test harness for all of the above:
it raises scripted exceptions at named SITES inside the engine step
(page-alloc, prefill-chunk, mixed-step, decode-dispatch, sample) on the
Nth hit of the site, optionally only when a given request participates in
the step — which is exactly the shape of a poisoned-request fault, and
what makes bisection observable.  Sites fire BEFORE the device call they
guard, so an injected fault never leaves a half-donated cache behind (the
recovery contract assumes KV writes beyond the committed row_lens are
scratch, which holds for host-side raises).

One tier up, the REPLICA is the unit of failure (serving/router.py): a
whole engine process can crash, wedge mid-stream, or go slow-loris on its
health endpoint.  The ``ReplicaFault`` family models those transport-level
failures, and the router's backends guard their own sites
(``REPLICA_FAULT_SITES``) with the same ``FaultInjector`` — each backend
carries its OWN injector, so chaos is scripted per-replica and a router
chaos run is deterministic and unit-testable, not only process-kill:

- ``replica-connect`` (``ReplicaConnectRefused``) — fires before a request
  is sent to the replica: the connect-refused shape a SIGKILLed process
  produces.  The router must fail over (the request never reached a row).
- ``replica-stream``  (``ReplicaStreamHang``) — fires before an SSE event
  read: the backend then stalls past the router's stall timeout, the
  mid-stream-wedge shape.  Zero delivered tokens → safe replay; delivered
  tokens → a terminal error event, never a silent truncation.
- ``replica-health``  (``ReplicaSlowHealth``) — fires on a health probe:
  the probe hangs past its budget, the slow-loris shape that must count
  as a failed poll (a wedged replica stops receiving traffic within one
  probe interval).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

__all__ = [
    "TransientFault",
    "DeterministicFault",
    "EngineOverloaded",
    "ReplicaFault",
    "ReplicaConnectRefused",
    "ReplicaStreamHang",
    "ReplicaSlowHealth",
    "FaultInjector",
    "FAULT_SITES",
    "REPLICA_FAULT_SITES",
    "is_transient",
]


class TransientFault(RuntimeError):
    """A step failure expected to succeed on retry (device preemption,
    pool pressure in the runtime, tunnel hiccup)."""


class DeterministicFault(RuntimeError):
    """A step failure that will recur on identical inputs (poisoned
    request); retry is useless, isolation is the remedy."""


class EngineOverloaded(RuntimeError):
    """Raised by ``ServingEngine.submit`` when the bounded inbox is full
    or the engine is draining — the load-shedding signal the HTTP
    surfaces map to 429/503."""

    def __init__(self, message: str, queue_depth: int = 0,
                 draining: bool = False):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.draining = draining


class ReplicaFault(RuntimeError):
    """Base of the replica-tier fault family: transport-level failures of
    a whole engine replica, injected into the ROUTER's backends (not the
    engine step) via ``REPLICA_FAULT_SITES``."""


class ReplicaConnectRefused(ReplicaFault):
    """The replica refuses connections (crashed / SIGKILLed process); the
    router backend translates it into its connect-failure path."""


class ReplicaStreamHang(ReplicaFault):
    """The replica stops producing SSE events mid-stream (wedged engine
    thread, dead tunnel with the socket still open); the backend stalls
    until the router's stall timeout trips."""


class ReplicaSlowHealth(ReplicaFault):
    """The replica's /health answers slower than the probe budget
    (slow-loris): the probe must count as a failed poll."""


# Status markers JAX device runtimes embed in XlaRuntimeError messages
# (absl::Status names), plus tunnel/transport failures: all are
# retry-worthy.  Deliberately NOT here: INVALID_ARGUMENT, INTERNAL,
# FAILED_PRECONDITION — those recur on identical inputs.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "connection reset",
    "connection refused",
    "broken pipe",
)


def is_transient(exc: BaseException) -> bool:
    """Classify a step exception: True = bounded retry, False = isolate."""
    if isinstance(exc, TransientFault):
        return True
    if isinstance(exc, DeterministicFault):
        return False
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(exc)
    return any(m.lower() in msg.lower() for m in _TRANSIENT_MARKERS)


# The named sites ``ServingEngine`` guards with ``_fault_point``.  Each
# fires before the operation it names, with the request ids participating
# in that operation.
FAULT_SITES = (
    "page-alloc",        # PageAllocator growth for a row / admission
    "prefill-chunk",     # sequential per-row prefill chunk dispatch
    "mixed-step",        # batched ragged prefill dispatch (admission wave)
    "decode-dispatch",   # fused decode / pp / verify step dispatch
    "sample",            # first-token sampling / blocking result fetch
    # spill-tier / transport sites (host-RAM page store + kv_transport):
    "spill-store",       # page demotion to the host store (pre-gather)
    "swap-in",           # page promotion back into the pool (pre-scatter)
    "kv-export",         # prefix page-set serialization (pre-gather)
    "kv-import",         # page-set import into the pool (pre-scatter)
)

# Replica-tier sites, guarded by the router's backends (one injector per
# backend = per-replica scoping).  Each fires BEFORE the transport
# operation it names, mirroring the engine-site contract.
REPLICA_FAULT_SITES = (
    "replica-connect",   # request send to the replica (connect refused)
    "replica-stream",    # one SSE event read (mid-stream hang)
    "replica-health",    # health probe (slow-loris /health)
    # disaggregated prefill/decode: fires before each handoff leg
    # (/kv/prefill on the prefill replica, /kv/import on the decode
    # replica) — a mid-handoff death is a zero-delivery failover: the
    # client saw nothing, the router falls back to the monolithic path
    "replica-handoff",
)


@dataclass
class _FaultSpec:
    site: str
    exc_factory: "type[BaseException] | Any"
    nth: int = 1              # fire starting at the Nth matching hit
    times: int | None = 1     # how many firings (None = every time)
    request_id: str | None = None  # only when this request participates
    period: int = 0           # >0: re-fire every `period` hits after nth
    hits: int = 0             # matching hits seen so far
    fired: int = 0            # faults actually raised

    def due(self) -> bool:
        if self.hits < self.nth:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.period > 0:
            return (self.hits - self.nth) % self.period == 0
        # one-shot window: fire on hits nth..nth+times-1 (times=None: all)
        return self.times is None or self.hits < self.nth + self.times


@dataclass
class FaultInjector:
    """Deterministic scripted fault source.

    >>> inj = FaultInjector()
    >>> inj.inject("decode-dispatch", TransientFault, nth=3)
    >>> inj.inject("mixed-step", DeterministicFault, request_id=rid,
    ...            times=None)     # poisoned request: fires every time

    The engine calls ``hit(site, request_ids)`` at each guarded site; the
    first matching spec that is due raises.  A ``request_id``-scoped spec
    only counts hits where that request participates, so quarantining the
    request silences the fault — the property the isolation tests lean on.
    """

    specs: list[_FaultSpec] = field(default_factory=list)
    site_hits: dict = field(default_factory=dict)

    def inject(self, site: str, exc=TransientFault, *, nth: int = 1,
               times: int | None = 1, request_id: str | None = None,
               period: int = 0):
        if site not in FAULT_SITES + REPLICA_FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r}; "
                             f"one of {FAULT_SITES + REPLICA_FAULT_SITES}")
        self.specs.append(_FaultSpec(site=site, exc_factory=exc, nth=nth,
                                     times=times, request_id=request_id,
                                     period=period))
        return self

    def hit(self, site: str, request_ids=()):
        """Called by the engine at a guarded site; raises if a spec is due.

        MUST be called before the device/allocator operation it guards so
        a raise leaves no half-committed device state behind.
        """
        self.site_hits[site] = self.site_hits.get(site, 0) + 1
        for spec in self.specs:
            if spec.site != site:
                continue
            if (spec.request_id is not None
                    and spec.request_id not in request_ids):
                continue
            spec.hits += 1
            if not spec.due():
                continue
            spec.fired += 1
            exc = spec.exc_factory
            if isinstance(exc, type):
                exc = exc(f"injected {spec.site} fault"
                          + (f" (request {spec.request_id})"
                             if spec.request_id else ""))
            raise exc

    @property
    def fired(self) -> int:
        return sum(s.fired for s in self.specs)


def rate_injector(site: str, every: int, exc=TransientFault,
                  limit: int | None = None) -> FaultInjector:
    """Chaos-mode helper (benchmark/serving_bench.py --inject-faults):
    fire ``exc`` on every ``every``-th hit of ``site``, up to ``limit``
    total — deterministic, so a chaos bench run is reproducible."""
    return FaultInjector().inject(site, exc, nth=every, period=every,
                                  times=limit)
