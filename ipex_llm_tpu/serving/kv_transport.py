"""Transportable KV page sets: the wire format for moving paged KV
between engines.

One serialization serves both halves of ROADMAP item 1's substrate: the
host spill tier persists pool bytes locally (serving/pagestore.py keeps
them as arrays — this module is for crossing a process/host boundary),
and **disaggregated prefill/decode** ships a finished prefill's pages
from a prefill-heavy replica to a decode-heavy one (the router's handoff
orchestration, serving/router.py), where the importer seeds its prefix
cache and the admitted request prefills only the uncovered tail.

Format (little-endian, versioned, checksummed):

    magic   8  b"IPLTKV01"
    hlen    4  u32: header length
    header     JSON: version, model/pool shape (n_layers, n_kv_heads,
               page_size, head_dim, v_head_dim), wire storage
               ("fp8" e5m2 codes | "bf16"), page keys (hex chain
               hashes, in chain order), per-page k/v byte sizes
    payload    for each page, k bytes then v bytes ([L, Hkv, page, D]
               row-major in the wire dtype)
    digest  32 sha256 over everything before it

``wire="fp8"`` serializes e5m2 codes — HALF the handoff bytes of a bf16
pool (an fp8 pool's codes ship natively, losslessly).  Recoding a bf16
pool to e5m2 wire is lossy exactly like fp8 KV storage is; fleets that
need bit-exact bf16 handoff pass ``wire="bf16"``.

Every malformed blob — truncated, bit-flipped, wrong magic, unknown
version, or shape-incompatible with the importing pool — raises
``TransportError``; the importer never scatters unverified bytes.
"""

from __future__ import annotations

import hashlib
import json
import struct

import numpy as np

from ipex_llm_tpu.kv import kv_storage_dtype

__all__ = ["TransportError", "pack_pages", "unpack_pages", "WIRE_MAGIC"]

WIRE_MAGIC = b"IPLTKV01"
WIRE_VERSION = 1
_DIGEST_LEN = 32


class TransportError(ValueError):
    """A KV page blob that must not be imported: truncated, corrupted
    (checksum mismatch), wrong format/version, or shaped for a different
    pool than the importer's."""


def _np_dtype(storage: str) -> np.dtype:
    # jax's storage dtypes are ml_dtypes-backed numpy dtypes, so they
    # round-trip through tobytes/frombuffer bitwise
    return np.dtype(kv_storage_dtype(storage))


def pack_pages(shape: dict, pages, wire: str = "fp8") -> bytes:
    """Serialize ``pages`` — an iterable of ``(key_bytes, k_page,
    v_page)`` with arrays shaped [L, Hkv, page, D] in either storage
    dtype — under ``shape`` (n_layers / n_kv_heads / page_size /
    head_dim / v_head_dim), recoding to the ``wire`` storage."""
    wdt = _np_dtype(wire)
    keys, chunks = [], []
    k_bytes = v_bytes = 0
    for key, k_page, v_page in pages:
        k_w = np.ascontiguousarray(np.asarray(k_page).astype(wdt))
        v_w = np.ascontiguousarray(np.asarray(v_page).astype(wdt))
        k_bytes, v_bytes = k_w.nbytes, v_w.nbytes
        keys.append(key.hex())
        chunks.append(k_w.tobytes())
        chunks.append(v_w.tobytes())
    header = json.dumps({
        "version": WIRE_VERSION,
        "wire": wire,
        "n_layers": int(shape["n_layers"]),
        "n_kv_heads": int(shape["n_kv_heads"]),
        "page_size": int(shape["page_size"]),
        "head_dim": int(shape["head_dim"]),
        "v_head_dim": int(shape["v_head_dim"]),
        "keys": keys,
        "k_page_bytes": k_bytes,
        "v_page_bytes": v_bytes,
    }, sort_keys=True).encode()
    body = (WIRE_MAGIC + struct.pack("<I", len(header)) + header
            + b"".join(chunks))
    return body + hashlib.sha256(body).digest()


def unpack_pages(blob: bytes):
    """Verify + parse a blob: returns ``(meta, [(key_bytes, k_page,
    v_page)])`` with arrays in the wire dtype, shaped [L, Hkv, page, D].
    Raises :class:`TransportError` on any malformation."""
    if len(blob) < len(WIRE_MAGIC) + 4 + _DIGEST_LEN:
        raise TransportError(
            f"blob too short ({len(blob)} bytes) to be a KV page set")
    if blob[: len(WIRE_MAGIC)] != WIRE_MAGIC:
        raise TransportError("bad magic: not a KV page-set blob")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if hashlib.sha256(body).digest() != digest:
        raise TransportError("checksum mismatch: corrupted or truncated "
                             "KV page set")
    (hlen,) = struct.unpack_from("<I", body, len(WIRE_MAGIC))
    hstart = len(WIRE_MAGIC) + 4
    if hstart + hlen > len(body):
        raise TransportError("truncated header")
    try:
        meta = json.loads(body[hstart: hstart + hlen])
    except ValueError as e:
        raise TransportError(f"unparseable header: {e}") from None
    if meta.get("version") != WIRE_VERSION:
        raise TransportError(
            f"unsupported KV transport version {meta.get('version')!r} "
            f"(this build speaks {WIRE_VERSION})")
    try:
        wdt = _np_dtype(meta["wire"])
        keys = [bytes.fromhex(k) for k in meta["keys"]]
        kb, vb = int(meta["k_page_bytes"]), int(meta["v_page_bytes"])
        shp_k = (meta["n_layers"], meta["n_kv_heads"], meta["page_size"],
                 meta["head_dim"])
        shp_v = (meta["n_layers"], meta["n_kv_heads"], meta["page_size"],
                 meta["v_head_dim"])
    except (KeyError, ValueError, TypeError) as e:
        raise TransportError(f"malformed header: {e}") from None
    payload = body[hstart + hlen:]
    if len(payload) != len(keys) * (kb + vb):
        raise TransportError(
            f"payload size {len(payload)} does not match "
            f"{len(keys)} pages of {kb}+{vb} bytes")
    pages, off = [], 0
    for key in keys:
        try:
            k_page = np.frombuffer(payload, wdt, count=kb // wdt.itemsize,
                                   offset=off).reshape(shp_k)
            off += kb
            v_page = np.frombuffer(payload, wdt, count=vb // wdt.itemsize,
                                   offset=off).reshape(shp_v)
            off += vb
        except ValueError as e:
            raise TransportError(f"page payload reshape failed: {e}") \
                from None
        pages.append((key, k_page, v_page))
    return meta, pages


def check_pool_shape(meta: dict, *, n_layers: int, n_kv_heads: int,
                     page_size: int, head_dim: int, v_head_dim: int):
    """Importer-side compatibility gate: the blob's pages must be shaped
    for THIS pool (storage width may differ — the scatter casts — but
    geometry may not).  Raises :class:`TransportError` listing the
    mismatches."""
    want = {"n_layers": n_layers, "n_kv_heads": n_kv_heads,
            "page_size": page_size, "head_dim": head_dim,
            "v_head_dim": v_head_dim}
    bad = [f"{k}: blob {meta.get(k)!r} != pool {v!r}"
           for k, v in want.items() if meta.get(k) != v]
    if bad:
        raise TransportError(
            "incompatible page set for this pool — " + "; ".join(bad))
