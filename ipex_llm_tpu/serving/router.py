"""Replica-fault-tolerant serving tier: a front router over N engine
replicas.

PR 3 made the REQUEST the unit of failure inside one engine; this tier
makes the REPLICA the next blast-radius boundary up (ROADMAP item 3 — the
millions-of-users shape).  The reference stack's FastChat controller +
worker tier load-balances but has no failover semantics: a dead worker
drops its streams.  Here, losing a replica mid-wave is an observable,
bounded, mostly-invisible event:

- **Health state machine** per replica (healthy → suspect → ejected →
  probing → reinstated), driven by periodic ``/health`` polls AND
  per-request transport outcomes, with exponential probe backoff — a
  circuit breaker: a crashed or wedged replica stops receiving traffic
  within one probe interval, and a restarted one reinstates itself via
  the probe loop without operator action.
- **Failover with a safe-replay contract**: a request that fails before
  any token was delivered replays on another replica under its REMAINING
  deadline budget (the deadline spans attempts; attempts are bounded); a
  mid-stream death surfaces the same terminal SSE/JSON error objects the
  engine tier defined (PR 3) — never a silent truncation, and never a
  duplicated token (at-most-once delivery: the router only replays
  streams that have delivered nothing).
- **Backpressure propagation**: replica 429/503 responses feed routing —
  a shedding replica is skipped for a cooloff (and its ``Retry-After``
  hint honored) instead of ejected; routing is least-loaded with
  prefix-affinity (prompt-prefix hash → the replica that last served the
  prefix, validated against its ``/health`` kv block: if the replica has
  since evicted prefix pages or its pool is under pressure, affinity
  gracefully spills to least-loaded — soft affinity, never a hard pin).
  The router's own inbox is bounded (``max_inflight``); beyond it the
  router sheds with 429 + ``Retry-After``.
- **Rolling drain orchestration**: ``drain_replica(i)`` stops routing to
  a replica, drains it, and (for in-process backends) ``restart_replica``
  rebuilds it — the probe loop reinstates it when its ``/health`` comes
  back, while the other replicas absorb the load.

Two backends behind one protocol: ``InProcessBackend`` (N engines in THIS
process, each behind its own ``OpenAIServer`` on a loopback port — one
weight upload serves the whole fleet, and tests/chaos can crash, drain,
and restart replicas deterministically) and ``HTTPBackend`` (remote
``api_server`` processes — the multi-process / multi-host deployment).
Both speak the existing OpenAI/TGI surface, so the router is transparent:
clients point at the router port and see the same endpoints, the same SSE
framing, and the same error objects as a single replica.

All host-side: no new jitted programs; the per-engine tick stays one
dispatch (JP106).

Run (in-process fleet):
    python -m ipex_llm_tpu.serving.router --model <ckpt> \
        --replicas 3 --router-port 8080
Run (fronting remote replicas):
    python -m ipex_llm_tpu.serving.router \
        --replicas http://h1:8000,http://h2:8000 --router-port 8080
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable

try:
    import aiohttp
    from aiohttp import web
except ImportError as _e:  # pragma: no cover
    aiohttp = None
    web = None
    _AIOHTTP_ERR = _e

from ipex_llm_tpu.serving.faults import (FaultInjector, ReplicaConnectRefused,
                                         ReplicaFault, ReplicaSlowHealth,
                                         ReplicaStreamHang)
from ipex_llm_tpu.serving.observe import (LATENCY_BUCKETS_S, Histogram,
                                          Tracer, make_traceparent,
                                          new_trace_id, parse_traceparent,
                                          span)

__all__ = [
    "Backend",
    "BackendError",
    "HTTPBackend",
    "InProcessBackend",
    "Router",
    "RouterConfig",
    "RouterResponse",
    "RouterStream",
    "HEALTHY", "SUSPECT", "EJECTED", "PROBING", "DRAINING",
]

# Replica health states.  HEALTHY/SUSPECT are routable; EJECTED/PROBING/
# DRAINING receive no traffic.  SUSPECT is the one-strike warning state:
# still routable (a single transport blip must not halve a two-replica
# fleet), but one more failure ejects.
HEALTHY = "healthy"
SUSPECT = "suspect"
EJECTED = "ejected"
PROBING = "probing"
DRAINING = "draining"
ROUTABLE_STATES = (HEALTHY, SUSPECT)


class BackendError(RuntimeError):
    """Transport-level replica failure (connect refused/reset, mid-stream
    drop, stall past the router's silence budget) — the failures the
    ROUTER owns, as opposed to replica-AUTHORED error responses (408/500
    JSON bodies, in-stream error events), which are forwarded verbatim."""

    def __init__(self, message: str, stage: str = "connect"):
        super().__init__(message)
        self.stage = stage   # "connect" | "read" | "stall"


@dataclass
class SSEOpen:
    """Outcome of opening a streaming request against a replica: either a
    live SSE event iterator (``events``) or a complete non-SSE response
    the replica answered instead (shed/error — ``payload``)."""

    status: int
    headers: dict
    payload: bytes | None = None
    events: AsyncIterator[bytes] | None = None


@dataclass(frozen=True)
class RouterConfig:
    # health machinery
    probe_interval_s: float = 1.0    # /health poll period per routable replica
    probe_timeout_s: float = 2.0     # poll/probe budget (slow-loris guard)
    suspect_after: int = 1           # consecutive failures → suspect
    eject_after: int = 2             # consecutive failures → ejected
    probe_backoff_s: float = 0.5     # first re-probe delay after ejection
    probe_backoff_max_s: float = 8.0
    reinstate_after: int = 1         # consecutive probe successes → healthy
    wedge_timeout_s: float = 300.0   # a replica whose /health answers ok
    #                                  but whose `ticks` counter stays
    #                                  frozen this long (uptime advancing)
    #                                  counts as a FAILED poll: the engine
    #                                  loop ticks even when idle, so a
    #                                  frozen tick = a wedged engine with
    #                                  a live HTTP thread.  Generous by
    #                                  default because one tick can
    #                                  legitimately stall through a long
    #                                  jit compile.  0 disables.
    # failover
    max_attempts: int = 3            # replicas tried per request (transport)
    stall_timeout_s: float = 60.0    # max mid-stream silence before the
    #                                  stream counts as a replica death
    first_event_timeout_s: float = 300.0  # separate (larger) silence
    #                                  budget for the FIRST event: cold
    #                                  TTFT includes jit compilation, and
    #                                  a healthy-but-compiling replica
    #                                  must not read as a death
    request_timeout_s: float = 600.0  # non-streaming total budget when the
    #                                   request carries no deadline
    request_deadline_s: float = 0.0  # default end-to-end budget spanning
    #                                  ALL attempts (0 = none; per-request
    #                                  body["deadline_s"] overrides)
    # backpressure + routing
    max_inflight: int = 0            # router inbox bound (0 = unbounded)
    shed_cooloff_s: float = 0.25     # skip a 429/503 replica this long when
    #                                  it sent no Retry-After hint
    affinity_prefix_chars: int = 64  # prompt-prefix window the key hashes
    affinity_max_entries: int = 4096
    affinity_free_frac: float = 0.05  # kv pool pressure spill threshold:
    #                                   below this free-page fraction the
    #                                   prefix is likely evicted soon —
    #                                   spill to least-loaded (unless the
    #                                   replica's host spill tier covers
    #                                   the demoted pages; see
    #                                   _affinity_fresh)
    # disaggregated prefill/decode (requires per-replica roles): a
    # streaming request whose prompt text is at least this many chars
    # hands off — a prefill-role replica computes the pages
    # (/kv/prefill), a decode-role replica imports them (/kv/import) and
    # inherits the prompt's affinity, so the stream routed there joins
    # the fused tick with only the uncovered tail left to prefill.
    # Every handoff failure is a zero-delivery fallback to the
    # monolithic path.  0 disables handoff.
    disagg_prefill_chars: int = 0
    handoff_timeout_s: float = 120.0  # per-handoff-leg budget
    # request-lifecycle tracing (serving/observe.py): the router records
    # its OWN spans per request — route attempts, backpressure
    # re-routes, failover replays, both disagg handoff legs — keyed by
    # the W3C traceparent trace id it either receives from the client or
    # mints, and propagates the traceparent to the replica (carried in
    # the forwarded body; HTTPBackend promotes it to a real HTTP
    # header), so /trace/{id} assembles the request's whole life across
    # processes.  Pure host bookkeeping per attempt; False turns the
    # router tracer off entirely.
    tracing: bool = True
    trace_buffer: int = 512          # traces the router retains (LRU)
    # shared-token authn for the /kv/import handoff leg: forwarded as
    # the X-KV-Import-Token header so replicas started with
    # --kv-import-token accept the router's page sets while rejecting
    # unauthenticated callers (integrity != authn: a checksum-consistent
    # blob from anyone would otherwise poison the shared prefix cache).
    kv_import_token: str | None = None


class _Replica:
    """Router-side record of one backend: health state machine, load and
    backpressure signals, and the transition log the aggregated /health
    view exposes."""

    def __init__(self, idx: int, backend: "Backend", rc: RouterConfig,
                 role: str = "any"):
        self.idx = idx
        self.backend = backend
        self.rc = rc
        # disaggregation role: "any" serves everything (the default —
        # a monolithic fleet), "prefill" only takes /kv/prefill handoff
        # legs, "decode" only client streams/completions.  Advisory
        # under degradation: with no decode-capable replica routable,
        # a prefill replica still serves rather than shedding.
        self.role = role
        self.state = HEALTHY
        self.fails = 0             # consecutive poll/request failures
        self.probe_ok = 0          # consecutive successful probes (ejected)
        self.backoff_s = rc.probe_backoff_s
        self.next_probe_t = 0.0
        self.last_poll_t = -1e9
        self.polling = False       # a poll/probe coroutine is in flight
        self.inflight = 0          # requests the router routed here, live
        self.shed_until = 0.0      # backpressure memory (429/503 cooloff)
        self.last_health: dict | None = None
        # handoff capability memory: set when this replica proved unable
        # to import a page set (no binary transport, or a permanent
        # shape/format 400) — the handoff orchestration stops paying a
        # full prefill leg just to throw its blob at a replica that
        # cannot take it.  Cleared on reinstatement (a restart may fix
        # shape/version skew).
        self.handoff_broken = False
        self.transitions: "deque[dict]" = deque(maxlen=64)
        # wedge detection: the last distinct `ticks` value seen in a
        # healthy poll and when it changed (per replica_id incarnation)
        self.ticks_seen: tuple[str, int, float] | None = None
        self.counters = {"requests": 0, "failures": 0, "shed": 0,
                         "probes": 0}

    def routable(self, now: float) -> bool:
        return self.state in ROUTABLE_STATES and now >= self.shed_until

    def load(self) -> float:
        """Least-loaded signal: what the router routed here and hasn't
        seen finish, plus the replica's own reported admission backlog."""
        depth = 0
        if self.last_health:
            depth = self.last_health.get("fault_domain", {}).get(
                "queue_depth", 0)
        return self.inflight + depth

    def _move(self, to: str, reason: str):
        if to == self.state:
            return
        self.transitions.append({"t": round(time.time(), 3),
                                 "from": self.state, "to": to,
                                 "reason": reason})
        self.state = to

    # -- state machine inputs ------------------------------------------------

    def on_success(self, now: float, health: dict | None = None):
        self.fails = 0
        if health is not None:
            self.last_health = health
        if self.state == SUSPECT:
            self._move(HEALTHY, "recovered")

    def on_failure(self, now: float, reason: str):
        self.counters["failures"] += 1
        self.fails += 1
        if self.state in ROUTABLE_STATES:
            if self.fails >= self.rc.eject_after:
                self.eject(now, reason)
            elif self.state == HEALTHY and self.fails >= self.rc.suspect_after:
                self._move(SUSPECT, reason)

    def eject(self, now: float, reason: str):
        """Circuit open: no traffic until the probe loop reinstates."""
        self._move(EJECTED, reason)
        self.probe_ok = 0
        self.backoff_s = self.rc.probe_backoff_s
        self.next_probe_t = now + self.backoff_s

    def wedged(self, health: dict, now: float) -> bool:
        """True when this ok-answering replica's engine loop is frozen:
        `ticks` unchanged for ``wedge_timeout_s`` while the HTTP thread
        keeps serving /health — the wedge shape a liveness-only check
        can't see (the engine loop ticks even when idle, so a healthy
        replica's counter always moves)."""
        if self.rc.wedge_timeout_s <= 0:
            return False
        blk = health.get("replica") or {}
        rid, ticks = blk.get("replica_id"), blk.get("ticks")
        if rid is None or ticks is None:
            return False
        if (self.ticks_seen is None or self.ticks_seen[0] != rid
                or self.ticks_seen[1] != ticks):
            self.ticks_seen = (rid, ticks, now)
            return False
        return now - self.ticks_seen[2] > self.rc.wedge_timeout_s

    def on_probe_result(self, now: float, health: dict | None):
        """Ejected-replica probe outcome: success counts toward
        reinstatement, failure doubles the backoff (bounded)."""
        if health is not None:
            self.last_health = health
            self.probe_ok += 1
            if self.probe_ok >= self.rc.reinstate_after:
                self.fails = 0
                self.backoff_s = self.rc.probe_backoff_s
                self.handoff_broken = False   # a restart may have fixed it
                self._move(HEALTHY, "reinstated")
                return
            self._move(EJECTED, "probe_ok")   # more successes required
        else:
            self.probe_ok = 0
            self.backoff_s = min(self.backoff_s * 2,
                                 self.rc.probe_backoff_max_s)
            self._move(EJECTED, "probe_failed")
        self.next_probe_t = now + self.backoff_s

    def view(self, now: float) -> dict:
        """The aggregated-/health row for this replica."""
        out = {
            "idx": self.idx,
            "target": self.backend.target,
            "role": self.role,
            "state": self.state,
            "routable": self.routable(now),
            "inflight": self.inflight,
            "consecutive_failures": self.fails,
            "shed_cooloff": self.shed_until > now,
            "counters": dict(self.counters),
            "transitions": list(self.transitions),
        }
        if self.last_health is not None:
            out["replica"] = self.last_health.get("replica", {})
            out["status"] = self.last_health.get("status")
            out["kv"] = self.last_health.get("kv", {})
            out["fault_domain"] = self.last_health.get("fault_domain", {})
        return out


# ---------------------------------------------------------------------------
# Backends


class Backend:
    """Protocol one replica speaks to the router (duck-typed; subclass or
    imitate — the unit tests drive the router with scripted fakes):

    - ``target``: human-readable address for logs and /health
    - ``probe()``             -> parsed /health dict (raises on failure)
    - ``fetch_metrics()``     -> parsed /metrics?format=json dict
    - ``send_json(path, body, timeout)`` -> (status, headers, payload)
    - ``open_sse(path, body, stall_timeout_s, first_event_timeout_s)``
      -> SSEOpen (the first-event bound covers cold-compile TTFT)
    - ``get_json(path)``      -> (status, payload)  (GET passthrough)
    - ``drain(timeout)``      -> bool (best-effort; HTTP backends rely on
                                  the replica's own SIGTERM handler)
    - ``close()``

    Transport failures raise ``BackendError``; anything the replica
    ANSWERS (any HTTP status, any SSE event) is returned, not raised.
    Each backend may carry its own ``FaultInjector`` scoped to the
    replica-tier sites (``REPLICA_FAULT_SITES``) — deterministic chaos
    without killing processes."""

    target = "?"
    injector: FaultInjector | None = None
    # shared-token authn for /kv/import, set by the router from its
    # config: transports that speak real HTTP forward it as the
    # X-KV-Import-Token header
    kv_import_token: str | None = None

    def _fault(self, site: str):
        """Guarded replica-tier site: translate an injected ReplicaFault
        into the transport behaviour it models.  ``ReplicaStreamHang``
        and ``ReplicaSlowHealth`` are raised through to the call sites
        that know how to stall; connect faults become BackendError
        here."""
        if self.injector is None:
            return
        try:
            self.injector.hit(site, (self.target,))
        except ReplicaConnectRefused as e:
            raise BackendError(f"injected: {e}", stage="connect")

    async def send_bytes(self, path: str, data: bytes,
                         timeout: float) -> tuple[int, dict, bytes]:
        """Binary POST (the /kv/import handoff leg).  Backends that
        don't speak it surface an "unsupported"-stage BackendError: the
        handoff orchestration treats that as a capability gap (no
        health strike — the replica is healthy, just not
        binary-capable) and falls back to the monolithic path."""
        raise BackendError(f"{type(self).__name__} does not support "
                           "binary transport", stage="unsupported")

    async def drain(self, timeout: float = 30.0) -> bool:
        return False

    async def close(self):
        pass


class HTTPBackend(Backend):
    """A remote ``api_server`` replica reached over HTTP (the
    multi-process / multi-host deployment)."""

    def __init__(self, base_url: str,
                 injector: FaultInjector | None = None):
        if aiohttp is None:  # pragma: no cover
            raise ImportError(
                f"aiohttp is required for the router: {_AIOHTTP_ERR}")
        self.base_url = base_url.rstrip("/")
        self.target = self.base_url
        self.injector = injector
        self._session: aiohttp.ClientSession | None = None

    async def _sess(self) -> "aiohttp.ClientSession":
        # created lazily inside the running loop (a session binds to it)
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def probe(self, timeout: float = 2.0) -> dict:
        try:
            self._fault("replica-health")
        except ReplicaSlowHealth:
            # slow-loris: the probe outlives any reasonable budget; the
            # router's wait_for() is what trips (sleep is cancellable)
            await asyncio.sleep(max(timeout, 1.0) * 10)
            raise BackendError("injected slow-loris /health", stage="stall")
        sess = await self._sess()
        try:
            async with sess.get(
                f"{self.base_url}/health",
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                body = await resp.json()
                if resp.status != 200:
                    raise BackendError(
                        f"/health {resp.status}: {body}", stage="read")
                return body
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            raise BackendError(f"/health: {type(e).__name__}: {e}",
                               stage="connect")

    async def fetch_metrics(self, timeout: float = 2.0) -> dict:
        sess = await self._sess()
        try:
            async with sess.get(
                f"{self.base_url}/metrics?format=json",
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                return await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            raise BackendError(f"/metrics: {type(e).__name__}: {e}",
                               stage="connect")

    async def get_json(self, path: str, timeout: float = 10.0):
        sess = await self._sess()
        try:
            async with sess.get(
                f"{self.base_url}{path}",
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                return resp.status, await resp.read()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            raise BackendError(f"GET {path}: {type(e).__name__}: {e}",
                               stage="connect")

    @staticmethod
    def _tp_headers(body: dict) -> dict:
        """Promote a forwarded-body ``traceparent`` to the real W3C HTTP
        header (the Backend protocol stays body-shaped so scripted test
        backends need no transport knowledge; the wire speaks the
        standard header either way)."""
        tp = body.get("traceparent")
        return {"traceparent": str(tp)} if tp else {}

    async def send_json(self, path: str, body: dict,
                        timeout: float) -> tuple[int, dict, bytes]:
        """Non-streaming request: the whole response body is read before
        anything reaches the client, so the caller may always replay."""
        self._fault("replica-connect")
        sess = await self._sess()
        try:
            async with sess.post(
                f"{self.base_url}{path}", json=body,
                headers=self._tp_headers(body),
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                payload = await resp.read()
                return resp.status, dict(resp.headers), payload
        except asyncio.TimeoutError:
            raise BackendError("response timed out", stage="stall")
        except (aiohttp.ClientError, OSError) as e:
            raise BackendError(f"{type(e).__name__}: {e}", stage="connect")

    async def send_bytes(self, path: str, data: bytes,
                         timeout: float) -> tuple[int, dict, bytes]:
        self._fault("replica-connect")
        sess = await self._sess()
        hdrs = {"Content-Type": "application/octet-stream"}
        if self.kv_import_token:
            hdrs["X-KV-Import-Token"] = self.kv_import_token
        try:
            async with sess.post(
                f"{self.base_url}{path}", data=data,
                headers=hdrs,
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                payload = await resp.read()
                return resp.status, dict(resp.headers), payload
        except asyncio.TimeoutError:
            raise BackendError("response timed out", stage="stall")
        except (aiohttp.ClientError, OSError) as e:
            raise BackendError(f"{type(e).__name__}: {e}", stage="connect")

    async def open_sse(self, path: str, body: dict,
                       stall_timeout_s: float,
                       first_event_timeout_s: float | None = None) -> SSEOpen:
        self._fault("replica-connect")
        sess = await self._sess()
        try:
            # headers are bounded by the STALL budget, not the first-event
            # one: our replicas prepare the SSE response before any model
            # work, so headers not arriving means a wedged process (the
            # SIGSTOP shape), not a cold compile — and an unbounded wait
            # here would hold a router inflight slot forever
            resp = await asyncio.wait_for(
                sess.post(
                    f"{self.base_url}{path}", json=body,
                    headers=self._tp_headers(body),
                    # no total timeout: a stream lives as long as it
                    # emits; silence is bounded per-read below instead
                    timeout=aiohttp.ClientTimeout(
                        total=None, sock_connect=5.0),
                ),
                stall_timeout_s)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            raise BackendError(f"{type(e).__name__}: {e}", stage="connect")
        ctype = resp.headers.get("Content-Type", "")
        if resp.status != 200 or "text/event-stream" not in ctype:
            # the non-SSE body read is bounded and wrapped too: a replica
            # that sends shed/error headers then wedges (or dies, RST)
            # mid-body must surface as a replayable transport failure,
            # not an unbounded await or a naked aiohttp exception
            try:
                payload = await asyncio.wait_for(resp.read(),
                                                 stall_timeout_s)
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                resp.release()
                raise BackendError(f"{type(e).__name__}: {e}",
                                   stage="read")
            resp.release()
            return SSEOpen(resp.status, dict(resp.headers), payload=payload)
        return SSEOpen(resp.status, dict(resp.headers),
                       events=self._events(resp, stall_timeout_s,
                                           first_event_timeout_s))

    async def _events(self, resp, stall_timeout_s: float,
                      first_event_timeout_s: float | None = None):
        """Yield raw SSE event blocks (``data: ...\\n\\n``) with a
        per-read silence bound: a replica that stops mid-stream (wedged
        process, dead socket) surfaces as a stall BackendError instead
        of a client hang.  The FIRST event gets its own (larger) bound —
        cold TTFT includes jit compiles, which must not read as death."""
        first_bound = max(first_event_timeout_s or 0.0, stall_timeout_s)
        buf = b""
        yielded = False
        try:
            while True:
                bound = stall_timeout_s if yielded else first_bound
                try:
                    self._fault("replica-stream")
                except ReplicaStreamHang:
                    # wedge emulation with the same latency as a real
                    # stall: silence for exactly the bound, then the
                    # same BackendError the wait_for below raises
                    await asyncio.sleep(bound)
                    raise BackendError("injected mid-stream hang",
                                       stage="stall")
                try:
                    chunk = await asyncio.wait_for(resp.content.readany(),
                                                   bound)
                except asyncio.TimeoutError:
                    raise BackendError(
                        f"stream stalled > {bound}s", stage="stall")
                except (aiohttp.ClientError, OSError,
                        ConnectionResetError) as e:
                    raise BackendError(f"{type(e).__name__}: {e}",
                                       stage="read")
                if not chunk:
                    if buf.strip():
                        # FIN mid-event: the replica died while writing a
                        # block.  Forwarding the fragment as a "clean end"
                        # would be exactly the silent truncation the
                        # failover contract forbids — surface it as a
                        # read-stage death instead (zero-delivery streams
                        # then fail over; committed ones get the terminal
                        # error event)
                        raise BackendError(
                            "connection closed mid-event "
                            f"({len(buf)} bytes of unframed trailing "
                            "data)", stage="read")
                    return
                buf += chunk
                while b"\n\n" in buf:
                    block, buf = buf.split(b"\n\n", 1)
                    yield block + b"\n\n"
                    yielded = True
        finally:
            resp.release()

    async def close(self):
        if self._session is not None and not self._session.closed:
            await self._session.close()


class InProcessBackend(HTTPBackend):
    """N engines in ONE process: each replica is a real ``OpenAIServer``
    on its own loopback port around an engine built by ``engine_factory``
    — one weight upload serves the whole fleet, and the router (or a
    test/chaos harness) can ``crash()``, ``drain()`` and ``restart()``
    replicas deterministically.  Transport is the same HTTP/SSE path as
    a remote replica, so behaviour matches the multi-process deployment
    byte-for-byte."""

    def __init__(self, engine_factory: Callable[[], Any], tokenizer,
                 model_name: str = "fleet",
                 injector: FaultInjector | None = None,
                 kv_import_token: str | None = None):
        super().__init__("http://127.0.0.1:0", injector=injector)
        self.engine_factory = engine_factory
        self.tokenizer = tokenizer
        self.model_name = model_name
        # token the replica's /kv/import REQUIRES (distinct from the
        # inherited kv_import_token attr the router sets for sending)
        self.require_kv_import_token = kv_import_token
        self.engine = None
        self.server = None
        self._runner = None
        self._site = None
        self.port = 0

    async def start(self):
        from ipex_llm_tpu.serving.api_server import OpenAIServer

        self.engine = self.engine_factory()
        self.server = OpenAIServer(self.engine, self.tokenizer,
                                   self.model_name,
                                   kv_import_token=self
                                   .require_kv_import_token)
        self._runner = web.AppRunner(self.server.app, shutdown_timeout=1.0)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, "127.0.0.1", self.port)
        await self._site.start()
        self.port = self._site._server.sockets[0].getsockname()[1]
        self.base_url = f"http://127.0.0.1:{self.port}"
        self.target = self.base_url
        return self

    async def crash(self):
        """SIGKILL emulation: stop accepting connections, ABORT every
        established connection (RST, the way a killed process drops
        them — closing only the listening socket would leave keep-alive
        clients talking to handlers with a dead engine), and kill the
        engine thread.  No drain, no goodbyes."""
        if self._site is not None and self._site._server is not None:
            self._site._server.close()
        if self.engine is not None:
            self.engine._stop.set()
        server = getattr(self._runner, "server", None)
        for conn in list(getattr(server, "connections", []) or []):
            transport = getattr(conn, "transport", None)
            if transport is not None:
                transport.abort()

    async def drain(self, timeout: float = 30.0) -> bool:
        """Graceful engine drain off the event loop (the engine's drain
        blocks); /health reports "draining" for the duration, so the
        poll loop sees the replica leaving."""
        if self.engine is None:
            return False
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.engine.drain, timeout)

    async def restart(self):
        """Tear down whatever is left (crashed or drained) and bring up a
        fresh engine + server on the SAME port, so the router's probe
        loop finds the replica where it left it."""
        if self.engine is not None:
            self.engine.stop()
        if self._runner is not None:
            try:
                await self._runner.cleanup()
            except Exception:
                pass   # a crashed site may already be half-closed
        await self.start()
        return self

    async def close(self):
        if self.engine is not None:
            self.engine._stop.set()
        if self._runner is not None:
            try:
                await self._runner.cleanup()
            except Exception:
                pass
        await super().close()


# ---------------------------------------------------------------------------
# Router


@dataclass
class RouterResponse:
    """A complete (non-streaming) outcome to relay to the client."""

    status: int
    payload: bytes
    headers: dict = field(default_factory=dict)


class RouterStream:
    """A live SSE stream to relay: ``events`` yields raw event blocks
    (the first one already acquired — failover is settled by the time a
    RouterStream exists).  ``close()`` abandons the stream and releases
    its router bookkeeping even if the relay never started (an unstarted
    async generator's ``finally`` does NOT run on ``aclose`` — the
    idempotent ``release`` closure is what guarantees the inflight slot
    comes back)."""

    def __init__(self, events: AsyncIterator[bytes], release=None,
                 upstream: AsyncIterator[bytes] | None = None):
        self.events = events
        self._release = release
        self._upstream = upstream

    async def close(self):
        await self.events.aclose()
        if self._upstream is not None:
            # the relay's finally closes the upstream too, but only if
            # the relay STARTED; closing an already-closed generator is a
            # no-op, so this covers the never-iterated case (client gone
            # before the first write) without double-close hazards —
            # releasing the replica's SSE response aborts its engine row
            await self._upstream.aclose()
        if self._release is not None:
            self._release()


def _surface(path: str) -> str:
    return "tgi" if path.startswith("/generate") else "openai"


def _error_payload(surface: str, message: str, code: str,
                   err_type: str) -> bytes:
    if surface == "tgi":
        return json.dumps({"error": message,
                           "error_type": code}).encode()
    return json.dumps({"error": {"message": message, "type": err_type,
                                 "code": code}}).encode()


# Replica series whose fleet-wide SUM is meaningful (true counters /
# occupancy).  Gauges and ratios (uptime_s, tokens_per_sync,
# accept rates, ttft percentiles...) are exported per-replica only —
# summing them across a fleet reads as nonsense on a dashboard.
_FLEET_SUMMABLE = frozenset({
    "requests", "tokens", "steps", "ticks", "retries", "rejected",
    "timeouts", "errors_isolated", "host_syncs", "mixed_steps",
    "draft_proposed", "draft_accepted", "queue_depth",
    "kv_pages_in_use", "kv_pages_total", "kv_pool_bytes",
    "kv_prefix_evictions", "kv_alloc_fail_clamps",
    # spill tier + transport (the kv_ prefix is the replica's kv_stats
    # exposition; pages_imported/exported live in engine.metrics)
    "kv_spill_pages", "kv_spill_bytes", "kv_spills", "kv_swap_ins",
    "kv_swap_in_lookups", "kv_pages_imported", "kv_pages_exported",
    # device-time observatory (serving/perfwatch.py): the recompile-
    # sentinel series are true counters — a fleet sum of
    # perf_compiles_warm/out_of_grid > 0 is the one-glance "somebody is
    # recompiling mid-serving" signal; attributed ticks/compile seconds
    # sum the same way (MFU and per-family device_s are ratios/gauges,
    # per-replica only)
    "perf_compiles_total", "perf_compiles_warm",
    "perf_compiles_out_of_grid", "perf_compile_s_total",
    "perf_ticks_attributed", "perf_dispatch_mismatches",
})


class Router:
    """Front-tier async router: load-balances the OpenAI/TGI surface over
    N replicas with health-driven ejection, safe failover replay,
    backpressure propagation and prefix-affinity routing.  See the module
    docstring for the four robustness contracts."""

    def __init__(self, backends: list, rc: RouterConfig | None = None,
                 roles: list[str] | None = None):
        if not backends:
            raise ValueError("router needs at least one backend")
        self.rc = rc or RouterConfig()
        if roles is None:
            roles = ["any"] * len(backends)
        if len(roles) != len(backends):
            raise ValueError(
                f"{len(roles)} roles for {len(backends)} backends")
        bad = [r for r in roles if r not in ("any", "prefill", "decode")]
        if bad:
            raise ValueError(f"unknown replica roles {bad!r}: each must "
                             "be 'any', 'prefill', or 'decode'")
        self.replicas = [_Replica(i, b, self.rc, role=role)
                         for i, (b, role) in enumerate(zip(backends,
                                                           roles))]
        self.router_id = uuid.uuid4().hex
        # request-lifecycle tracing (observe.py): the router's own spans,
        # keyed by the traceparent trace id it receives or mints; the
        # /trace/{id} endpoint merges these with every replica's spans
        self.tracer = (Tracer(self.rc.trace_buffer)
                       if self.rc.tracing else None)
        # honest handoff-leg latency histograms (Prometheus
        # _bucket/_sum/_count on /metrics) — the two legs are the disagg
        # path's operational cost and had no distribution until now
        self.hists = {
            "handoff_prefill_s": Histogram(LATENCY_BUCKETS_S),
            "handoff_import_s": Histogram(LATENCY_BUCKETS_S),
        }
        for b in backends:
            # transports forward this as the X-KV-Import-Token header
            b.kv_import_token = self.rc.kv_import_token
        self._inflight = 0
        self._affinity: "OrderedDict[str, tuple[int, int]]" = OrderedDict()
        self._poll_task: asyncio.Task | None = None
        self._closed = False
        self.counters = {
            "requests": 0,          # requests accepted into the router
            "shed": 0,              # shed at the router (inbox/no replica)
            "failovers": 0,         # zero-token replays on another replica
            "rerouted_backpressure": 0,   # replica 429/503 -> other replica
            "midstream_errors": 0,  # terminal error events the router wrote
            "affinity_hits": 0,
            "affinity_spills": 0,   # stale/pressured affinity → least-loaded
            "probes": 0,
            "ejections": 0,
            "reinstated": 0,
            # disaggregated prefill/decode handoffs: completed page-set
            # moves, zero-delivery fallbacks to the monolithic path, and
            # the wire bytes shipped (the e5m2-halving story's meter)
            "handoffs": 0,
            "handoff_failures": 0,
            "handoff_bytes": 0,
        }

    # -- lifecycle -----------------------------------------------------------

    async def start(self):
        for rep in self.replicas:
            b = rep.backend
            if isinstance(b, InProcessBackend) and b.engine is None:
                await b.start()
        self._poll_task = asyncio.ensure_future(self._poll_loop())
        return self

    async def close(self):
        self._closed = True
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except (asyncio.CancelledError, Exception):
                pass
        for rep in self.replicas:
            await rep.backend.close()

    # -- health machinery ----------------------------------------------------

    async def _probe_backend(self, rep: _Replica) -> dict | None:
        """One bounded health fetch; None = failed (timeout counts — the
        slow-loris /health shape must read as a failed poll)."""
        self.counters["probes"] += 1
        rep.counters["probes"] += 1
        try:
            return await asyncio.wait_for(
                rep.backend.probe(self.rc.probe_timeout_s),
                self.rc.probe_timeout_s)
        except (BackendError, asyncio.TimeoutError, Exception):
            return None

    async def poll_once(self, now: float | None = None):
        """One deterministic pass of the health loop: poll every routable
        replica whose last poll aged out, probe every ejected replica past
        its backoff.  Unit tests drive this directly; ``_poll_loop`` just
        repeats it."""
        now = time.monotonic() if now is None else now

        async def poll(rep: _Replica):
            rep.polling = True
            try:
                h = await self._probe_backend(rep)
                t = time.monotonic()
                if h is not None and rep.wedged(h, t):
                    # 200-ok with a frozen engine loop: the wedge shape —
                    # a failed poll, not a healthy one
                    h = None
                    reason = "wedged_ticks"
                else:
                    reason = "health_poll_failed"
                if h is None:
                    self._note_transport_failure(rep, reason)
                elif h.get("status") == "draining":
                    # a replica that reports "draining" is leaving on its
                    # own terms: stop routing, let the probe loop bring it
                    # back post-restart (the rolling-restart handshake).
                    # Checked BEFORE on_success so a SUSPECT replica's
                    # transition log never records a spurious "recovered"
                    # hop on its way out
                    rep.last_health = h
                    rep.eject(t, "replica_draining")
                    self.counters["ejections"] += 1
                else:
                    rep.on_success(t, health=h)
            finally:
                rep.polling = False

        async def probe(rep: _Replica):
            rep.polling = True
            rep._move(PROBING, "probe")
            try:
                h = await self._probe_backend(rep)
                t = time.monotonic()
                # a probed replica reporting "draining" is not back yet,
                # and neither is one whose engine loop is still frozen
                if h is not None and (h.get("status") == "draining"
                                      or rep.wedged(h, t)):
                    h = None
                rep.on_probe_result(t, h)
                if rep.state == HEALTHY:
                    self.counters["reinstated"] += 1
            finally:
                rep.polling = False

        tasks = []
        for rep in self.replicas:
            if rep.polling or rep.state == DRAINING:
                continue
            if rep.state in ROUTABLE_STATES:
                if now - rep.last_poll_t >= self.rc.probe_interval_s:
                    rep.last_poll_t = now
                    tasks.append(poll(rep))
            elif rep.state == EJECTED and now >= rep.next_probe_t:
                tasks.append(probe(rep))
        if tasks:
            await asyncio.gather(*tasks)

    async def _poll_loop(self):
        # tick at a quarter interval so "stops receiving traffic within
        # one probe interval" holds with poll scheduling jitter included
        tick = max(0.02, self.rc.probe_interval_s / 4)
        while not self._closed:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass   # the poll loop must survive any backend weirdness
            await asyncio.sleep(tick)

    # -- routing -------------------------------------------------------------

    def _prompt_text(self, path: str, body: dict) -> str:
        """The request's prompt as one string — the affinity key's
        source and the disaggregation threshold's yardstick."""
        if "chat/completions" in path:
            return json.dumps(body.get("messages", []), sort_keys=True)
        if "completions" in path:
            p = body.get("prompt", "")
            return str(p[0] if isinstance(p, list) and p else p)
        return str(body.get("inputs", ""))

    def _prefix_key(self, path: str, body: dict) -> str | None:
        src = self._prompt_text(path, body)[
            : self.rc.affinity_prefix_chars]
        if not src:
            return None
        return hashlib.sha1(src.encode()).hexdigest()

    @staticmethod
    def _spill_covers(kv: dict) -> bool:
        """Does the replica's host spill tier plausibly cover pages the
        device pool let go?  With a spill tier, ``prefix_evictions``
        advancing means DEMOTION, not loss — the page swaps back on the
        next hit — so affinity should hold.  True only when the tier is
        enabled, actually holds pages (or has proven swap-ins), and is
        retaining what it is given: a tier whose own byte budget is
        dropping most of its demoted pages (``spill_lru_evictions``
        running at the spill rate) really IS losing prefixes, and
        affinity should degrade exactly as it would untiered.  (The
        swap-in hit RATE is deliberately not the signal here: every
        novel-prompt admission probes the store and counts a miss, so
        mixed traffic dilutes it without a single page being lost.)"""
        if not kv.get("spill_enabled"):
            return False
        if kv.get("spill_pages", 0) <= 0 and kv.get("swap_ins", 0) == 0:
            return False
        spills = kv.get("spills", 0)
        lost = kv.get("spill_lru_evictions", 0)
        return not (spills >= 8 and lost > spills * 0.5)

    def _affinity_fresh(self, rep: _Replica, evict_mark: int) -> bool:
        """Is the remembered prefix likely still SERVABLE there?  The
        replica's /health kv block is the signal: prefix evictions since
        the mark mean the cached pages may be gone, a nearly-dry pool
        means they soon will be — UNLESS the replica runs a spill tier
        whose /health block shows it holding up, in which case an
        eviction is a demotion the next hit swaps back.  Only a genuine
        loss degrades affinity to least-loaded."""
        h = rep.last_health
        if not h or "kv" not in h:
            return True   # no signal yet: assume resident
        kv = h["kv"]
        if (kv.get("prefix_evictions", 0) > evict_mark
                and not self._spill_covers(kv)):
            return False
        total = kv.get("pages_total", 0)
        if (total and kv.get("pages_free", 0) < total
                * self.rc.affinity_free_frac
                and not self._spill_covers(kv)):
            return False
        return True

    def _pick(self, key: str | None, exclude: set[int], now: float,
              role: str = "decode") -> _Replica | None:
        cands = [r for r in self.replicas
                 if r.routable(now) and r.idx not in exclude]
        # role preference (disaggregated fleets): client traffic goes to
        # decode-capable replicas, handoff prefills to prefill-capable
        # ones — advisory, so a degraded fleet serves from whatever is
        # left rather than shedding on principle
        preferred = [r for r in cands if r.role in (role, "any")]
        cands = preferred or cands
        if not cands:
            return None
        if key is not None and key in self._affinity:
            idx, mark = self._affinity[key]
            rep = self.replicas[idx]
            if rep in cands:
                if self._affinity_fresh(rep, mark):
                    self.counters["affinity_hits"] += 1
                    self._affinity.move_to_end(key)
                    return rep
                # stale: drop the entry and spill (graceful degradation)
                self.counters["affinity_spills"] += 1
                del self._affinity[key]
            elif rep.state not in ROUTABLE_STATES:
                # ejected/draining owner: spill AND forget, so the prefix
                # re-homes wherever least-loaded sends it next
                self.counters["affinity_spills"] += 1
                del self._affinity[key]
        return min(cands, key=lambda r: (r.load(), r.idx))

    def _record_affinity(self, key: str | None, rep: _Replica):
        if key is None:
            return
        mark = 0
        if rep.last_health and "kv" in rep.last_health:
            mark = rep.last_health["kv"].get("prefix_evictions", 0)
        self._affinity[key] = (rep.idx, mark)
        self._affinity.move_to_end(key)
        while len(self._affinity) > self.rc.affinity_max_entries:
            self._affinity.popitem(last=False)

    def _shed_retry_after(self, now: float) -> int:
        """Honest Retry-After when the router sheds: the soonest moment a
        replica might return to rotation (next probe / cooloff expiry),
        clamped to [1, 30]."""
        horizons = []
        for rep in self.replicas:
            if rep.state in (EJECTED, PROBING):
                horizons.append(rep.next_probe_t - now)
            elif rep.state in ROUTABLE_STATES and rep.shed_until > now:
                horizons.append(rep.shed_until - now)
            elif rep.state == DRAINING:
                horizons.append(self.rc.probe_backoff_s)
        soonest = min(horizons) if horizons else 1.0
        return max(1, min(30, int(soonest) + 1))

    def _replica_retry_after(self, headers: dict) -> float:
        try:
            return float(headers.get("Retry-After", ""))
        except (TypeError, ValueError):
            return self.rc.shed_cooloff_s

    # -- the attempt loop ----------------------------------------------------

    def _deadline(self, body: dict) -> float | None:
        budget = body.get("deadline_s") or self.rc.request_deadline_s
        try:
            budget = float(budget)
        except (TypeError, ValueError):
            budget = 0.0
        return (time.monotonic() + budget) if budget > 0 else None

    def _fwd_body(self, body: dict, deadline: float | None,
                  tid: str | None = None) -> dict:
        """Per-attempt forwarded body: the REMAINING deadline budget is
        stamped so a failover attempt runs under what is left, not a
        fresh allowance — and the traceparent rides along (HTTPBackend
        promotes it to the real W3C header) so the replica's spans key
        to the same trace the router's do."""
        fwd = dict(body)
        if deadline is not None:
            fwd["deadline_s"] = max(0.001,
                                    round(deadline - time.monotonic(), 3))
        else:
            fwd.pop("deadline_s", None)
        if tid is not None:
            fwd["traceparent"] = make_traceparent(tid)
        else:
            fwd.pop("traceparent", None)
        return fwd

    def _trace_tid(self, body: dict,
                   trace_id: str | None = None) -> str | None:
        """The request's trace id: caller-passed (the HTTP handlers parse
        the client's traceparent header), or the body's own traceparent,
        or freshly minted when the router traces — None only with
        tracing off and no inherited id (then nothing propagates)."""
        if trace_id:
            return trace_id
        parsed = parse_traceparent(body.get("traceparent"))
        if parsed is not None:
            return parsed[0]
        return new_trace_id() if self.tracer is not None else None

    def _rspan(self, tid: str | None, name: str, t0: float | None = None,
               t1: float | None = None, **attrs):
        if self.tracer is None or tid is None:
            return
        self.tracer.add(tid, span(name, time.time() if t0 is None else t0,
                                  t1, origin="router", **attrs))

    def _admit(self, surface: str) -> RouterResponse | None:
        """Bounded router inbox: beyond ``max_inflight`` the router sheds
        immediately with 429 + Retry-After instead of queueing."""
        if self.rc.max_inflight and self._inflight >= self.rc.max_inflight:
            self.counters["shed"] += 1
            ra = self._shed_retry_after(time.monotonic())
            return RouterResponse(
                429,
                _error_payload(surface,
                               "router overloaded "
                               f"({self._inflight} requests in flight)",
                               "router_overloaded", "overloaded_error"),
                {"Retry-After": str(ra)})
        return None

    def _give_up(self, surface: str, reason: str, code: str,
                 now: float) -> RouterResponse:
        self.counters["shed"] += 1
        return RouterResponse(
            503, _error_payload(surface, reason, code,
                                "overloaded_error"),
            {"Retry-After": str(self._shed_retry_after(now))})

    def _timed_out(self, surface: str) -> RouterResponse:
        return RouterResponse(
            408, _error_payload(
                surface,
                "request deadline exceeded (spanning failover attempts)",
                "timeout", "timeout_error"))

    def _next_replica(self, surface: str, key: str | None, tried: set[int],
                      attempts: int, deadline: float | None):
        """Shared per-attempt gate for both dispatch paths: returns
        ``(replica, None)`` to try, or ``(None, RouterResponse)`` when
        the request is over — deadline spent, no routable replica left,
        or the failover bound hit."""
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            return None, self._timed_out(surface)
        rep = self._pick(key, tried, now)
        if rep is None:
            return None, self._give_up(
                surface, "no replica available (all ejected, draining, "
                "or shedding)", "no_replica_available", now)
        if attempts >= self.rc.max_attempts:
            return None, self._give_up(
                surface, f"failover attempts exhausted ({attempts})",
                "failover_exhausted", now)
        return rep, None

    def _note_shed(self, rep: _Replica, headers: dict, tried: set[int]):
        """Replica 429/503: backpressure, not death — cooloff (honoring
        its Retry-After hint) + re-route; the replica stays in rotation
        for later requests."""
        rep.counters["shed"] += 1
        rep.shed_until = time.monotonic() + self._replica_retry_after(
            headers)
        self.counters["rerouted_backpressure"] += 1
        tried.add(rep.idx)

    def _note_transport_failure(self, rep: _Replica, reason: str,
                                tried: set[int] | None = None):
        """Health-signal a transport-level failure; counts the ejection
        only when THIS failure caused it (an already-ejected replica's
        other dying streams must not double-count)."""
        was = rep.state
        rep.on_failure(time.monotonic(), reason)
        if rep.state == EJECTED and was != EJECTED:
            self.counters["ejections"] += 1
        if tried is not None:
            tried.add(rep.idx)

    @staticmethod
    def _fwd_headers(headers: dict) -> dict:
        return {k: v for k, v in headers.items()
                if k.lower() in ("content-type", "retry-after")}

    # -- disaggregated prefill/decode handoff --------------------------------

    def _disagg_eligible(self, path: str, body: dict) -> bool:
        return (self.rc.disagg_prefill_chars > 0
                and len(self._prompt_text(path, body))
                >= self.rc.disagg_prefill_chars)

    def _handoff_strike(self, rep: _Replica, e, deadline: float | None,
                        leg: str):
        """Health accounting for a failed handoff leg — with the PR 10
        no-strike-on-deadline rule restored for disagg: a leg whose
        budget was clamped to a nearly-spent CLIENT deadline and that
        timed out AT that deadline says nothing about the replica
        (short-deadline clients must not be able to eject healthy
        prefill/decode replicas), so it counts a handoff failure but no
        strike.  Anything else is a genuine transport death."""
        stage = getattr(e, "stage", "fault")
        if (deadline is not None and stage == "stall"
                and time.monotonic() >= deadline):
            return "deadline"
        self._note_transport_failure(rep, f"handoff_{stage}")
        return stage

    async def _handoff(self, path: str, body: dict, key: str | None,
                       deadline: float | None, tid: str | None = None):
        """Disaggregated prefill: compute the prompt's KV pages on a
        prefill-role replica (/kv/prefill), import them into a
        decode-role replica (/kv/import), and home the prompt's affinity
        there — the stream dispatched next lands on the importer and
        prefills only the uncovered tail.

        EVERY failure here is a zero-delivery failover by construction:
        nothing has reached the client yet, so a mid-handoff death just
        notes the health strike (the state machine ejects a dying
        replica exactly as it would for a failed request), counts
        ``handoff_failures``, and the caller falls back to the
        monolithic path — no lost, hung, or duplicated stream."""
        now = time.monotonic()
        # the prefill leg requires an EXPLICIT prefill-role replica —
        # _pick's advisory fallback would otherwise "hand off" between
        # two ordinary replicas, silently doubling prefill compute on a
        # monolithic fleet with disagg_prefill_chars set
        pre_cands = [r for r in self.replicas
                     if r.role == "prefill" and r.routable(now)]
        pre = (min(pre_cands, key=lambda r: (r.load(), r.idx))
               if pre_cands else None)
        # the decode replica is picked LEAST-LOADED, deliberately
        # ignoring affinity: transportable pages are what make the
        # affinity pin obsolete — the prefix moves to wherever capacity
        # is (the prefill replica's own prefix cache makes repeat
        # exports nearly free), so a shared hot prefix spreads across
        # decode replicas instead of hot-spotting its first home.
        # Import-incapable replicas (handoff_broken) are excluded UP
        # FRONT: discovering that only after paying the prefill leg
        # would tax every eligible request for a blob nobody can take.
        skip = {pre.idx} if pre is not None else set()
        skip |= {r.idx for r in self.replicas if r.handoff_broken}
        dec = self._pick(None, skip, now, role="decode")
        if pre is None or dec is None or pre.idx == dec.idx:
            return     # no split fleet to hand off across
        if key is not None and key in self._affinity:
            idx, mark = self._affinity[key]
            if idx == dec.idx and self._affinity_fresh(dec, mark):
                # the least-loaded decode pick ALREADY holds this
                # prefix (a prior handoff or admission homed it there):
                # re-shipping the blob would import zero pages — skip
                # the legs and let the dispatch loop route by affinity
                self._affinity.move_to_end(key)
                return
        budget = self.rc.handoff_timeout_s
        if deadline is not None:
            budget = min(budget, max(deadline - now, 0.001))
        pre.inflight += 1
        t_leg = time.time()
        try:
            pre.backend._fault("replica-handoff")
            status, headers, blob = await pre.backend.send_json(
                "/kv/prefill", self._fwd_body(body, deadline, tid), budget)
        except (BackendError, ReplicaFault) as e:
            # ReplicaFault covers injected shapes _fault does not
            # translate (e.g. a scripted stream-hang at this site): any
            # of them is still just a zero-delivery handoff death —
            # unless the leg merely ran out of the CLIENT's nearly-spent
            # deadline, which is no evidence against the replica
            outcome = self._handoff_strike(pre, e, deadline, "prefill")
            self.counters["handoff_failures"] += 1
            self._rspan(tid, "handoff_prefill", t0=t_leg, t1=time.time(),
                        replica=pre.idx, outcome=outcome)
            return
        finally:
            pre.inflight -= 1
        self.hists["handoff_prefill_s"].observe(time.time() - t_leg)
        self._rspan(tid, "handoff_prefill", t0=t_leg, t1=time.time(),
                    replica=pre.idx, status=status,
                    bytes=len(blob) if status == 200 else 0)
        if status != 200:
            # replica-authored refusal (shed / nothing-to-export): no
            # health strike, just no handoff this time
            if status in (429, 503):
                pre.shed_until = time.monotonic() + \
                    self._replica_retry_after(headers)
            self.counters["handoff_failures"] += 1
            return
        dec.inflight += 1
        t_leg = time.time()
        try:
            dec.backend._fault("replica-handoff")
            s2, _, _ = await dec.backend.send_bytes("/kv/import", blob,
                                                    budget)
        except (BackendError, ReplicaFault) as e:
            if getattr(e, "stage", None) == "unsupported":
                # a capability gap is not a death: no health strike,
                # but remember it so later handoffs skip this replica
                dec.handoff_broken = True
                outcome = "unsupported"
            else:
                # same no-strike-on-client-deadline rule as leg 1
                outcome = self._handoff_strike(dec, e, deadline, "import")
            self.counters["handoff_failures"] += 1
            self._rspan(tid, "handoff_import", t0=t_leg, t1=time.time(),
                        replica=dec.idx, outcome=outcome)
            return
        finally:
            dec.inflight -= 1
        self.hists["handoff_import_s"].observe(time.time() - t_leg)
        self._rspan(tid, "handoff_import", t0=t_leg, t1=time.time(),
                    replica=dec.idx, status=s2, bytes=len(blob))
        if s2 != 200:
            if s2 == 400:
                # the importer REJECTED the page set (shape/format skew
                # — permanent until the replica is rebuilt): stop
                # re-shipping blobs it will keep refusing
                dec.handoff_broken = True
            self.counters["handoff_failures"] += 1
            return
        self.counters["handoffs"] += 1
        self.counters["handoff_bytes"] += len(blob)
        # home the prompt on the importer: the dispatch loop's affinity
        # pick routes the stream (and future same-prefix requests) there
        self._record_affinity(key, dec)

    async def dispatch_json(self, path: str, body: dict,
                            trace_id: str | None = None) -> RouterResponse:
        """Non-streaming request through the fleet.  Nothing reaches the
        client until a replica's full response is in hand, so EVERY
        transport failure is safely replayable (bounded attempts, the
        deadline spanning them); replica-authored responses — including
        its own 408/500 error objects — are forwarded verbatim, and
        replica 429/503 re-routes with the shed replica in cooloff."""
        surface = _surface(path)
        shed = self._admit(surface)
        if shed is not None:
            return shed
        self.counters["requests"] += 1
        self._inflight += 1
        try:
            return await self._json_attempts(path, body, surface,
                                             self._trace_tid(body, trace_id))
        finally:
            self._inflight -= 1

    async def _json_attempts(self, path, body, surface,
                             tid=None) -> RouterResponse:
        deadline = self._deadline(body)
        key = self._prefix_key(path, body)
        tried: set[int] = set()
        attempts = 0
        replay_pending = False   # a transport failure happened: the NEXT
        #                          attempt is the failover (a backpressure
        #                          re-route in between is not one)
        while True:
            rep, done = self._next_replica(surface, key, tried, attempts,
                                           deadline)
            if rep is None:
                return done
            attempts += 1
            if replay_pending:
                self.counters["failovers"] += 1
                self._rspan(tid, "failover", attempt=attempts)
                replay_pending = False
            timeout = (deadline - time.monotonic() if deadline is not None
                       else self.rc.request_timeout_s)
            rep.counters["requests"] += 1
            rep.inflight += 1
            t_a = time.time()
            try:
                status, headers, payload = await rep.backend.send_json(
                    path, self._fwd_body(body, deadline, tid), timeout)
            except BackendError as e:
                self._rspan(tid, "route_attempt", t0=t_a, t1=time.time(),
                            replica=rep.idx, outcome=f"transport_{e.stage}")
                if (deadline is not None and e.stage == "stall"
                        and time.monotonic() >= deadline):
                    # the REQUEST ran out of budget mid-generation — that
                    # is a client deadline, not replica death: no health
                    # strike (short-deadline clients must not be able to
                    # eject healthy replicas); the stamped deadline_s
                    # expires the row server-side
                    return self._timed_out(surface)
                self._note_transport_failure(rep, f"request_{e.stage}",
                                             tried)
                replay_pending = True
                continue
            finally:
                rep.inflight -= 1
            if status in (429, 503):
                self._note_shed(rep, headers, tried)
                self._rspan(tid, "backpressure_reroute", t0=t_a,
                            t1=time.time(), replica=rep.idx, status=status)
                attempts -= 1   # backpressure re-route is not a failover
                continue
            rep.on_success(time.monotonic())
            self._record_affinity(key, rep)
            self._rspan(tid, "route_attempt", t0=t_a, t1=time.time(),
                        replica=rep.idx, status=status, outcome="ok")
            return RouterResponse(status, payload, self._fwd_headers(headers))

    async def dispatch_stream(self, path: str, body: dict,
                              trace_id: str | None = None,
                              ) -> RouterResponse | RouterStream:
        """Streaming request through the fleet.  Failover runs until the
        FIRST event is acquired from a replica (nothing delivered ⇒ replay
        is safe and invisible); from then on the stream is committed to
        that replica, and a mid-stream death becomes a terminal error
        event in the surface's own shape — never a silent truncation,
        never a replayed (duplicated) token."""
        surface = _surface(path)
        shed = self._admit(surface)
        if shed is not None:
            return shed
        self.counters["requests"] += 1
        self._inflight += 1
        deadline = self._deadline(body)
        key = self._prefix_key(path, body)
        tid = self._trace_tid(body, trace_id)
        tried: set[int] = set()
        attempts = 0
        committed = False   # a RouterStream owns the _inflight slot; every
        #                     other exit releases it in the finally below
        replay_pending = False
        try:
            if self._disagg_eligible(path, body):
                # the handoff is pure pre-work: success homes the
                # prompt's affinity on the importing decode replica,
                # any failure falls through to the ordinary loop below
                # with zero tokens delivered
                await self._handoff(path, body, key, deadline, tid)
            while True:
                rep, done = self._next_replica(surface, key, tried,
                                               attempts, deadline)
                if rep is None:
                    return done
                attempts += 1
                if replay_pending:
                    self.counters["failovers"] += 1
                    self._rspan(tid, "failover", attempt=attempts)
                    replay_pending = False
                rep.counters["requests"] += 1
                rep.inflight += 1
                t_a = time.time()
                try:
                    opened = await rep.backend.open_sse(
                        path, self._fwd_body(body, deadline, tid),
                        self.rc.stall_timeout_s,
                        self.rc.first_event_timeout_s)
                    if opened.events is None:
                        if opened.status in (429, 503):
                            self._note_shed(rep, opened.headers, tried)
                            self._rspan(tid, "backpressure_reroute",
                                        t0=t_a, t1=time.time(),
                                        replica=rep.idx,
                                        status=opened.status)
                            attempts -= 1
                            continue
                        # replica-authored pre-stream outcome (408/500/
                        # 400...): forwarded verbatim, like one replica
                        rep.on_success(time.monotonic())
                        self._rspan(tid, "route_attempt", t0=t_a,
                                    t1=time.time(), replica=rep.idx,
                                    status=opened.status, outcome="ok")
                        return RouterResponse(
                            opened.status, opened.payload or b"",
                            self._fwd_headers(opened.headers))
                    # acquire the first event BEFORE committing: a replica
                    # that dies between accept and first token is still a
                    # zero-delivery failover
                    gen = opened.events
                    try:
                        first = await gen.__anext__()
                    except StopAsyncIteration:
                        raise BackendError("stream closed with no events",
                                           stage="read")
                    rep.on_success(time.monotonic())
                    self._record_affinity(key, rep)
                    self._rspan(tid, "route_attempt", t0=t_a,
                                t1=time.time(), replica=rep.idx,
                                outcome="stream_committed")
                    committed = True
                    release = self._release_once(rep)
                    return RouterStream(
                        self._relay(rep, gen, first, surface, release,
                                    tid=tid),
                        release, upstream=gen)
                except BackendError as e:
                    self._rspan(tid, "route_attempt", t0=t_a,
                                t1=time.time(), replica=rep.idx,
                                outcome=f"transport_{e.stage}")
                    self._note_transport_failure(rep, f"stream_{e.stage}",
                                                 tried)
                    replay_pending = True
                    continue
                finally:
                    if not committed:
                        rep.inflight -= 1
        finally:
            # a committed stream's slot is released by the RouterStream's
            # release closure (via _relay's finally, or close() if the
            # relay never starts); every other exit releases it here
            if not committed:
                self._inflight -= 1

    def _release_once(self, rep: _Replica):
        """Idempotent release of a committed stream's inflight slots —
        callable from _relay's finally AND RouterStream.close() without
        double-decrement."""
        released = [False]

        def release():
            if not released[0]:
                released[0] = True
                rep.inflight -= 1
                self._inflight -= 1

        return release

    async def _relay(self, rep: _Replica, gen, first: bytes, surface: str,
                     release, tid: str | None = None):
        """Forward events from the committed replica; on mid-stream death
        append the surface's terminal error object (+ [DONE] on the
        OpenAI framing) so the client always sees a terminal event."""
        delivered = 0
        try:
            yield first
            delivered += 1
            async for ev in gen:
                yield ev
                delivered += 1
            rep.on_success(time.monotonic())
        except BackendError as e:
            self._note_transport_failure(rep, f"midstream_{e.stage}")
            self.counters["midstream_errors"] += 1
            self._rspan(tid, "midstream_error", replica=rep.idx,
                        delivered=delivered, stage=e.stage)
            err = _error_payload(
                surface,
                f"replica died mid-stream after {delivered} events "
                f"({e})", "replica_died_midstream", "server_error")
            yield b"data: " + err + b"\n\n"
            if surface == "openai":
                yield b"data: [DONE]\n\n"
        finally:
            release()
            await gen.aclose()

    # -- drain / restart orchestration --------------------------------------

    async def drain_replica(self, idx: int, timeout: float = 30.0) -> bool:
        """Rolling-restart step: stop routing to replica ``idx``, drain
        it gracefully (in-flight requests finish inside ``timeout``),
        and leave it EJECTED with an imminent probe — ``restart_replica``
        (or the process supervisor) brings it back and the probe loop
        reinstates it while the other replicas absorb the load."""
        rep = self.replicas[idx]
        rep._move(DRAINING, "drain_replica")
        ok = await rep.backend.drain(timeout)
        deadline = time.monotonic() + timeout
        while rep.inflight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        rep.eject(time.monotonic(), "drained")
        self.counters["ejections"] += 1
        return ok and rep.inflight == 0

    async def restart_replica(self, idx: int, timeout: float = 60.0) -> bool:
        """Restart an (in-process) replica and wait for the probe loop to
        reinstate it.  HTTP backends have no restart lever — the process
        supervisor restarts them and this just waits for reinstatement."""
        rep = self.replicas[idx]
        if hasattr(rep.backend, "restart"):
            await rep.backend.restart()
        rep.next_probe_t = 0.0   # probe immediately
        deadline = time.monotonic() + timeout
        while rep.state != HEALTHY and time.monotonic() < deadline:
            await self.poll_once()
            await asyncio.sleep(0.02)
        return rep.state == HEALTHY

    async def rolling_restart(self, timeout_per_replica: float = 60.0):
        """Drain → restart → reinstate each replica in turn; the fleet
        keeps serving throughout (the runbook's one-liner)."""
        results = []
        for idx in range(len(self.replicas)):
            ok = await self.drain_replica(idx, timeout_per_replica)
            ok = await self.restart_replica(idx, timeout_per_replica) and ok
            results.append(ok)
        return results

    # -- aggregated observability -------------------------------------------

    async def assemble_trace(self, trace_id: str) -> dict | None:
        """One end-to-end trace: the router's own spans merged with every
        replica's ``/trace/{id}`` spans (re-tagged ``replicaN:engine``),
        sorted on the shared wall-clock timeline — the cross-process
        assembly the propagated traceparent exists for.  None when no
        process holds the trace."""
        own = self.tracer.get(trace_id) if self.tracer is not None else None
        spans = list(own["spans"]) if own else []
        dropped = own["spans_dropped"] if own else 0

        # concurrent fan-out under the probe budget (metrics_text's
        # pattern): one wedged replica must not stall the postmortem
        # surface for its whole 10 s default — traces are fetched
        # exactly when a replica is sick
        async def fetch(rep: _Replica):
            try:
                return await rep.backend.get_json(
                    f"/trace/{trace_id}", timeout=self.rc.probe_timeout_s)
            except BackendError:
                return None   # an unreachable replica costs spans, not a 500

        got = await asyncio.gather(*(fetch(r) for r in self.replicas))
        for rep, res in zip(self.replicas, got):
            if res is None or res[0] != 200:
                continue
            try:
                data = json.loads(res[1])
            except ValueError:
                continue
            for s in data.get("spans", []):
                s = dict(s)
                s["origin"] = (f"replica{rep.idx}:"
                               f"{s.get('origin') or 'engine'}")
                spans.append(s)
            dropped += data.get("spans_dropped", 0)
        if not spans:
            return None
        spans.sort(key=lambda s: (s["t0"], s["name"]))
        return {"trace_id": trace_id, "spans": spans,
                "spans_dropped": dropped}

    def health_view(self) -> dict:
        now = time.monotonic()
        routable = sum(1 for r in self.replicas if r.routable(now))
        status = ("ok" if routable == len(self.replicas)
                  else "degraded" if routable else "unavailable")
        return {
            "status": status,
            "router": {
                "router_id": self.router_id,
                "inflight": self._inflight,
                "replicas_total": len(self.replicas),
                "replicas_routable": routable,
                "affinity_entries": len(self._affinity),
                **self.counters,
            },
            "replicas": [r.view(now) for r in self.replicas],
        }

    async def metrics_text(self) -> str:
        """Prometheus-style aggregation: the router's own counters plus
        every reachable replica's counters re-labelled per replica,
        fleet-wide sums — and real histogram series: the router's
        handoff-leg histograms, plus fleet-SUMMED latency histograms
        (bucket counts are true counters, so summing them across
        replicas is the one honest fleet aggregation; the old rolling
        p95 scalars could not be combined at all)."""
        lines = []
        view = self.health_view()["router"]
        for name in sorted(view):
            v = view[name]
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                lines.append(f"ipex_llm_tpu_router_{name} {v}")
        for name in sorted(self.hists):
            lines.extend(self.hists[name].prometheus_lines(
                f"ipex_llm_tpu_router_{name}"))

        async def fetch(rep: _Replica):
            try:
                return rep, await rep.backend.fetch_metrics(
                    self.rc.probe_timeout_s)
            except Exception:
                return rep, None

        got = await asyncio.gather(*(fetch(r) for r in self.replicas))
        sums: dict[str, float] = {}
        hist_sums: dict[str, Histogram] = {}
        for rep, res in got:
            if not res:
                continue
            rid = res.get("replica_id", "?")
            vals = res.get("metrics", {})
            for name in sorted(vals):
                v = vals[name]
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    continue
                lines.append(
                    f'ipex_llm_tpu_{name}{{replica="{rep.idx}",'
                    f'replica_id="{rid}"}} {v}')
                if name in _FLEET_SUMMABLE:
                    sums[name] = sums.get(name, 0) + v
            for hname, hd in sorted((res.get("histograms") or {}).items()):
                agg = hist_sums.get(hname)
                if agg is None:
                    try:
                        agg = hist_sums[hname] = Histogram(
                            hd.get("bounds") or LATENCY_BUCKETS_S)
                    except ValueError:
                        continue
                agg.merge(hd)   # refuses (skips) mismatched buckets
        for name in sorted(sums):
            lines.append(f"ipex_llm_tpu_fleet_{name} "
                         f"{round(sums[name], 6)}")
        for hname in sorted(hist_sums):
            lines.extend(hist_sums[hname].prometheus_lines(
                f"ipex_llm_tpu_fleet_{hname}"))
        return "\n".join(lines) + "\n"

    # -- aiohttp surface ------------------------------------------------------

    def build_app(self) -> "web.Application":
        if web is None:  # pragma: no cover
            raise ImportError(
                f"aiohttp is required for the router: {_AIOHTTP_ERR}")
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._h_openai)
        app.router.add_post("/v1/completions", self._h_openai)
        app.router.add_post("/generate", self._h_tgi)
        app.router.add_post("/generate_stream", self._h_tgi_stream)
        app.router.add_get("/v1/models", self._h_models)
        app.router.add_get("/health", self._h_health)
        app.router.add_get("/metrics", self._h_metrics)
        app.router.add_get("/trace/{trace_id}", self._h_trace)
        app.router.add_get("/debug/traces", self._h_traces)
        app.router.add_get("/debug/flight", self._h_flight)
        return app

    @staticmethod
    def _respond(res: RouterResponse) -> "web.Response":
        headers = dict(res.headers)
        ctype = headers.pop("Content-Type", "application/json")
        return web.Response(status=res.status, body=res.payload,
                            content_type=ctype.split(";")[0],
                            headers=headers)

    def _req_trace_id(self, request) -> str | None:
        """Trace id for one HTTP request: the client's traceparent header
        when present (so callers control/correlate their own traces),
        else freshly minted when the router traces.  Echoed back as
        X-Trace-Id so a client that did NOT send a traceparent can still
        fetch /trace/{id}."""
        parsed = parse_traceparent(request.headers.get("traceparent"))
        if parsed is not None:
            return parsed[0]
        return new_trace_id() if self.tracer is not None else None

    async def _stream_out(self, request, res: RouterStream,
                          trace_id: str | None = None):
        headers = {
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        }
        if trace_id:
            headers["X-Trace-Id"] = trace_id
        resp = web.StreamResponse(headers=headers)
        # prepare() is inside the guarded region: a client that
        # disconnects before (or while) headers go out must still close
        # the committed upstream and release its inflight slots
        try:
            await resp.prepare(request)
            async for ev in res.events:
                await resp.write(ev)
            await resp.write_eof()
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: close the upstream so the replica's
            # engine aborts the row instead of decoding into the void
            await res.close()
            raise
        return resp

    @staticmethod
    def _with_trace_header(resp: "web.Response",
                           trace_id: str | None) -> "web.Response":
        if trace_id:
            resp.headers["X-Trace-Id"] = trace_id
        return resp

    async def _h_openai(self, request):
        body = await request.json()
        tid = self._req_trace_id(request)
        if body.get("stream"):
            res = await self.dispatch_stream(request.path, body,
                                             trace_id=tid)
            if isinstance(res, RouterStream):
                return await self._stream_out(request, res, trace_id=tid)
            return self._with_trace_header(self._respond(res), tid)
        return self._with_trace_header(
            self._respond(await self.dispatch_json(request.path, body,
                                                   trace_id=tid)), tid)

    async def _h_tgi(self, request):
        tid = self._req_trace_id(request)
        return self._with_trace_header(
            self._respond(await self.dispatch_json(
                "/generate", await request.json(), trace_id=tid)), tid)

    async def _h_tgi_stream(self, request):
        tid = self._req_trace_id(request)
        res = await self.dispatch_stream("/generate_stream",
                                         await request.json(),
                                         trace_id=tid)
        if isinstance(res, RouterStream):
            return await self._stream_out(request, res, trace_id=tid)
        return self._with_trace_header(self._respond(res), tid)

    async def _h_models(self, request):
        now = time.monotonic()
        for rep in self.replicas:
            if rep.routable(now):
                try:
                    status, payload = await rep.backend.get_json(
                        "/v1/models")
                    return web.Response(status=status, body=payload,
                                        content_type="application/json")
                except BackendError:
                    continue
        return self._respond(self._give_up(
            "openai", "no replica available", "no_replica_available", now))

    async def _h_health(self, request):
        view = self.health_view()
        status = 200 if view["status"] != "unavailable" else 503
        return web.json_response(view, status=status)

    async def _h_metrics(self, request):
        return web.Response(text=await self.metrics_text(),
                            content_type="text/plain")

    async def _h_trace(self, request):
        """One assembled end-to-end trace; ``?format=chrome`` renders it
        as Chrome trace-event JSON (chrome://tracing / Perfetto)."""
        tid = request.match_info["trace_id"]
        tr = await self.assemble_trace(tid)
        if tr is None:
            return web.json_response(
                {"error": {"message": f"unknown trace {tid!r} (tracing "
                                      "off, or aged out of the LRU)",
                           "type": "invalid_request_error",
                           "code": "unknown_trace"}}, status=404)
        if request.query.get("format") == "chrome":
            return web.json_response(Tracer.chrome_events([tr]))
        return web.json_response(tr)

    async def _h_traces(self, request):
        """Whole-window export of the router's own spans (per-request
        assembly across replicas rides /trace/{id}); ``?format=chrome``
        for the Perfetto shape."""
        if self.tracer is None:
            return web.json_response(
                {"error": {"message": "router tracing is disabled",
                           "type": "invalid_request_error",
                           "code": "tracing_disabled"}}, status=404)
        if request.query.get("format") == "chrome":
            return web.json_response(self.tracer.export_chrome())
        return web.json_response({"trace_ids": self.tracer.trace_ids()})

    async def _h_flight(self, request):
        """Every reachable replica's tick flight recorder, keyed by
        replica index — the fleet-wide postmortem fetch."""
        async def fetch(rep: _Replica):
            try:
                return await rep.backend.get_json(
                    "/debug/flight", timeout=self.rc.probe_timeout_s)
            except BackendError:
                return None

        got = await asyncio.gather(*(fetch(r) for r in self.replicas))
        out = {}
        for rep, res in zip(self.replicas, got):
            if res is None or res[0] != 200:
                continue
            try:
                out[str(rep.idx)] = json.loads(res[1])
            except ValueError:
                continue
        return web.json_response({"replicas": out})


# ---------------------------------------------------------------------------
# CLI


def build_inprocess_fleet(model_path: str, n_replicas: int,
                          low_bit: str = "sym_int4",
                          engine_config=None,
                          kv_import_token: str | None = None) -> list:
    """N in-process engine replicas over ONE loaded copy of the weights
    (params are read-only device arrays — every engine shares them; each
    replica has its own KV pool, queue, and fault domain).
    ``kv_import_token`` makes every replica's loopback /kv/import
    REQUIRE the shared token — the in-process replicas listen on real
    TCP ports, so the poisoning exposure is the same as the
    multi-process deployment's."""
    from transformers import AutoTokenizer

    from ipex_llm_tpu.serving.engine import ServingEngine
    from ipex_llm_tpu.transformers import AutoModelForCausalLM

    import os
    if os.path.exists(f"{model_path}/bigdl_config.json"):
        model = AutoModelForCausalLM.load_low_bit(model_path)
    else:
        model = AutoModelForCausalLM.from_pretrained(
            model_path, load_in_low_bit=low_bit)
    tok = AutoTokenizer.from_pretrained(model_path, trust_remote_code=True)
    eos = model.generation_config.eos_token_id

    def factory():
        return ServingEngine(model.config, model.params, engine_config,
                             default_eos=eos).start()

    return [InProcessBackend(factory, tok, model_name=model_path,
                             kv_import_token=kv_import_token)
            for _ in range(n_replicas)]


def main(argv=None):
    ap = argparse.ArgumentParser(
        "ipex-llm-tpu replica router (OpenAI/TGI-transparent)")
    ap.add_argument("--replicas", required=True,
                    help="fleet spec: an integer N (spawn N in-process "
                         "engine replicas over --model) or a comma-"
                         "separated list of replica base URLs "
                         "(http://host:port) to front")
    ap.add_argument("--model", default=None,
                    help="checkpoint for the in-process fleet form")
    ap.add_argument("--low-bit", default="sym_int4")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--router-port", type=int, default=8080)
    ap.add_argument("--probe-interval", type=float, default=1.0,
                    metavar="S", help="per-replica /health poll period — "
                    "also the bound on how long a dead replica keeps "
                    "receiving traffic")
    ap.add_argument("--probe-timeout", type=float, default=2.0, metavar="S",
                    help="health poll budget; a slower /health (slow-"
                         "loris) counts as a failed poll")
    ap.add_argument("--eject-after", type=int, default=2, metavar="N",
                    help="consecutive failures before a replica is "
                         "ejected (1 = eject on first failure)")
    ap.add_argument("--probe-backoff", type=float, default=0.5, metavar="S",
                    help="first re-probe delay after ejection; doubles "
                         "per failed probe up to --probe-backoff-max")
    ap.add_argument("--probe-backoff-max", type=float, default=8.0,
                    metavar="S")
    ap.add_argument("--max-attempts", type=int, default=3,
                    help="bounded failover: replicas tried per request")
    ap.add_argument("--stall-timeout", type=float, default=60.0,
                    metavar="S", help="max mid-stream silence before a "
                    "stream counts as a replica death")
    ap.add_argument("--first-event-timeout", type=float, default=300.0,
                    metavar="S", help="separate silence budget for a "
                    "stream's FIRST event (cold TTFT includes jit "
                    "compiles — a compiling replica is not a dead one)")
    ap.add_argument("--max-inflight", type=int, default=0,
                    help="router inbox bound: beyond this many in-flight "
                         "requests the router sheds with 429 + "
                         "Retry-After (0 = unbounded)")
    ap.add_argument("--request-deadline", type=float, default=0.0,
                    metavar="S", help="default end-to-end deadline "
                    "spanning ALL failover attempts (0 = none)")
    ap.add_argument("--roles", default=None,
                    help="comma-separated per-replica roles "
                         "(any|prefill|decode), one per replica — the "
                         "disaggregated-fleet shape, e.g. "
                         "'prefill,decode,decode'")
    ap.add_argument("--disagg-prefill-chars", type=int, default=0,
                    metavar="N",
                    help="disaggregated prefill/decode: streaming "
                         "prompts of at least N characters hand off — "
                         "a prefill-role replica computes the KV pages, "
                         "a decode-role replica imports them and serves "
                         "the stream (0 = off; requires --roles)")
    ap.add_argument("--kv-import-token", default=None, metavar="TOKEN",
                    help="shared token forwarded on the /kv/import "
                         "handoff leg (X-KV-Import-Token); replicas "
                         "started with the same --kv-import-token "
                         "reject unauthenticated page-set imports")
    ap.add_argument("--no-trace", action="store_true",
                    help="disable router-side request-lifecycle tracing "
                         "(spans, /trace/{id} assembly, traceparent "
                         "minting; client-supplied traceparents still "
                         "propagate)")
    args = ap.parse_args(argv)

    rc = RouterConfig(
        probe_interval_s=args.probe_interval,
        probe_timeout_s=args.probe_timeout,
        eject_after=args.eject_after,
        probe_backoff_s=args.probe_backoff,
        probe_backoff_max_s=args.probe_backoff_max,
        max_attempts=args.max_attempts,
        stall_timeout_s=args.stall_timeout,
        first_event_timeout_s=args.first_event_timeout,
        max_inflight=args.max_inflight,
        request_deadline_s=args.request_deadline,
        disagg_prefill_chars=args.disagg_prefill_chars,
        kv_import_token=args.kv_import_token,
        tracing=not args.no_trace,
    )
    if args.replicas.isdigit():
        if not args.model:
            ap.error("--model is required for the in-process fleet form")
        backends = build_inprocess_fleet(
            args.model, int(args.replicas), args.low_bit,
            kv_import_token=args.kv_import_token)
    else:
        backends = [HTTPBackend(u.strip())
                    for u in args.replicas.split(",") if u.strip()]
    roles = ([r.strip() for r in args.roles.split(",")]
             if args.roles else None)
    router = Router(backends, rc, roles=roles)

    async def on_startup(app):
        await router.start()   # starts any un-started in-process backend

    async def on_shutdown(app):
        await router.close()

    app = router.build_app()
    app.on_startup.append(on_startup)
    app.on_shutdown.append(on_shutdown)
    web.run_app(app, host=args.host, port=args.router_port)


if __name__ == "__main__":
    main()
