"""``llm-cli``: one-shot prompt completion from the terminal.

Reference counterpart: cli/llm-cli:26-40, which execs a native
``main-<family>`` binary with -m/-p/-n flags.  The flag names are kept so
reference invocations work unchanged: ``llm-cli -m <model_dir> -p "..." -n 64``.
"""

from __future__ import annotations

import argparse
import os
import sys


def _load(model_path: str, low_bit: str):
    from ipex_llm_tpu.transformers import AutoModelForCausalLM

    if os.path.exists(os.path.join(model_path, "bigdl_config.json")):
        return AutoModelForCausalLM.load_low_bit(model_path)
    return AutoModelForCausalLM.from_pretrained(model_path, load_in_low_bit=low_bit)


def _tokenizer(model_path: str):
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_path, trust_remote_code=True)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="llm-cli", description="ipex-llm-tpu one-shot text generation"
    )
    ap.add_argument("-m", "--model", required=True, help="model directory")
    ap.add_argument("-p", "--prompt", required=True)
    ap.add_argument("-n", "--n-predict", type=int, default=128)
    ap.add_argument("-x", "--low-bit", default="sym_int4",
                    help="load_in_low_bit qtype (default sym_int4)")
    ap.add_argument("-t", "--threads", type=int, default=0,
                    help="accepted for reference-CLI parity; unused on TPU")
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--top-k", type=int, default=0)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    tok = _tokenizer(args.model)
    model = _load(args.model, args.low_bit)
    ids = tok(args.prompt, return_tensors="np").input_ids
    out = model.generate(
        ids,
        max_new_tokens=args.n_predict,
        do_sample=args.temperature > 0,
        temperature=args.temperature or 1.0,
        top_p=args.top_p,
        top_k=args.top_k,
    )
    text = tok.decode(out[0], skip_special_tokens=True)
    print(text)
    if model.first_cost is not None:
        print(
            f"[ttft {model.first_cost * 1e3:.1f} ms, "
            f"decode {1.0 / max(model.rest_cost_mean, 1e-9):.1f} tok/s]",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
