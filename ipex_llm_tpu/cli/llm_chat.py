"""``llm-chat``: interactive chat loop (reference: cli/llm-chat, portable-zip
chat.py).  Uses the tokenizer's chat template when present, streams tokens as
they decode."""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv: list[str] | None = None) -> int:
    from ipex_llm_tpu.cli.llm_cli import _load, _tokenizer

    ap = argparse.ArgumentParser(prog="llm-chat")
    ap.add_argument("-m", "--model", required=True)
    ap.add_argument("-n", "--n-predict", type=int, default=256)
    ap.add_argument("-x", "--low-bit", default="sym_int4")
    args = ap.parse_args(argv)

    tok = _tokenizer(args.model)
    model = _load(args.model, args.low_bit)
    history: list[dict] = []
    print("llm-chat — empty line or Ctrl-D to exit")
    while True:
        try:
            user = input("you> ").strip()
        except EOFError:
            break
        if not user:
            break
        history.append({"role": "user", "content": user})
        if tok.chat_template:
            ids = tok.apply_chat_template(
                history, add_generation_prompt=True, return_tensors="np"
            )
        else:
            flat = "\n".join(m["content"] for m in history) + "\n"
            ids = tok(flat, return_tensors="np").input_ids

        pieces: list[str] = []

        class _Streamer:
            def put(self, row):
                t = tok.decode(np.asarray(row).reshape(-1), skip_special_tokens=True)
                pieces.append(t)
                print(t, end="", flush=True)

            def end(self):
                print()

        model.generate(ids, max_new_tokens=args.n_predict, streamer=_Streamer())
        history.append({"role": "assistant", "content": "".join(pieces)})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
