"""Command-line front ends (reference: cli/llm-cli, cli/llm-chat).

The reference's CLI picks a prebuilt native ``main-<family>`` binary; here
both commands drive the one TPU generation engine directly.
"""
