"""jaxlint — JAX-aware static analysis for this repo's hazard classes.

Usage::

    python -m ipex_llm_tpu.analysis [paths...]       # human output
    python -m ipex_llm_tpu.analysis --json ipex_llm_tpu/
    scripts/jaxlint ipex_llm_tpu/                     # same thing

Programmatic::

    from ipex_llm_tpu.analysis import analyze_paths, analyze_source
    findings = analyze_paths(["ipex_llm_tpu/"])

The rule catalog lives in ``ipex_llm_tpu/analysis/rules/`` and the long
form in ``docs/quickstart/static_analysis.md``.  Zero unsuppressed
error-tier findings over ``ipex_llm_tpu/`` is a tier-1 gate
(``tests/test_static_analysis.py``).
"""

from ipex_llm_tpu.analysis.config import Config, DEFAULT_CONFIG, relkey
from ipex_llm_tpu.analysis.core import (Finding, all_rules, analyze_paths,
                                        analyze_source, counts, exit_code,
                                        to_json)

__all__ = [
    "Config", "DEFAULT_CONFIG", "Finding", "all_rules", "analyze_paths",
    "analyze_source", "counts", "exit_code", "relkey", "to_json",
]
