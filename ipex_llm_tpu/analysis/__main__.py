"""CLI for both analysis tiers.

``python -m ipex_llm_tpu.analysis [paths...]``   AST tier (jaxlint)
``python -m ipex_llm_tpu.analysis --trace``      trace tier (jaxprcheck):
    abstract-trace the registered hot-path jitted programs and gate their
    compiled-program properties against analysis/programs.lock.json.

Exit codes (both tiers): 0 clean (warnings allowed), 1 unsuppressed
error-tier findings, 2 usage error, 3 internal analyzer error — CI can
tell "the gate failed" from "the gate itself is broken".
"""

from __future__ import annotations

import argparse
import sys
import traceback
from pathlib import Path

from ipex_llm_tpu.analysis import core


def _run_ast(args) -> int:
    # a typo'd path (or running from the wrong cwd) must not pass the
    # gate green by linting zero files
    missing = [p for p in args.paths if not Path(p).exists()]
    files = [str(f) for f in core.iter_py_files(args.paths)]
    if missing or not files:
        what = (f"path(s) do not exist: {', '.join(missing)}" if missing
                else f"no .py files found under: {', '.join(args.paths)}")
        print(f"jaxlint: {what}", file=sys.stderr)
        return 2

    findings = core.analyze_paths(files)
    if args.json:
        print(core.to_json(findings))
    else:
        core.render_human(findings, show_suppressed=args.show_suppressed)
    return core.exit_code(findings)


def _run_trace(args) -> int:
    from ipex_llm_tpu.analysis.trace import runner

    if args.list_programs:
        runner.list_programs()
        return 0
    findings = runner.audit(manifest_path=args.manifest,
                            update=args.update)
    if args.json:
        print(core.to_json(findings))
    else:
        core.render_human(findings, show_suppressed=args.show_suppressed,
                          prog="jaxprcheck")
        if args.update:
            print("jaxprcheck: manifest written")
    return core.exit_code(findings)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="JAX-aware static analysis.  Default: AST rules "
                    "(aliasing, syncs, recompiles, tracer leaks, PRNG, "
                    "donation).  --trace: abstract-trace the hot-path "
                    "jitted programs and gate donation maps, fp8 "
                    "integrity, callbacks, the recompile surface, and the "
                    "per-tick dispatch count against a locked manifest.")
    ap.add_argument("paths", nargs="*", default=["ipex_llm_tpu"],
                    help="files or directories to lint "
                         "(AST tier; default: ipex_llm_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report (stable schema v%d; "
                         "findings carry tier='ast'|'trace')"
                         % core.SCHEMA_VERSION)
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog (both tiers) and exit")
    ap.add_argument("--trace", action="store_true",
                    help="run the trace tier (jaxprcheck) over the "
                         "program registry instead of AST rules")
    ap.add_argument("--update", action="store_true",
                    help="(--trace) regenerate analysis/programs.lock.json "
                         "from the current tree instead of diffing it")
    ap.add_argument("--manifest", default=None, metavar="PATH",
                    help="(--trace) manifest path override")
    ap.add_argument("--list-programs", action="store_true",
                    help="(--trace) print the program registry and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(core.all_rules().values(), key=lambda r: r.code):
            print(f"{rule.code}  {rule.name:<22} [{rule.severity:<5}] "
                  f"{rule.doc}")
        return 0

    if not args.trace and (args.update or args.list_programs
                           or args.manifest):
        print("jaxlint: --update/--manifest/--list-programs need --trace",
              file=sys.stderr)
        return 2

    try:
        return _run_trace(args) if args.trace else _run_ast(args)
    except Exception:
        # the analyzer itself failed — distinct from "findings" so CI can
        # page on a broken gate instead of blaming the tree
        traceback.print_exc()
        return 3


if __name__ == "__main__":
    sys.exit(main())
