"""CLI for jaxlint: ``python -m ipex_llm_tpu.analysis [paths...]``.

Exit codes: 0 clean (warnings allowed), 1 unsuppressed error-tier
findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from ipex_llm_tpu.analysis import core


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="JAX-aware static analysis: host/device aliasing, "
                    "hidden syncs, recompile hazards, tracer leaks, "
                    "PRNG misuse.")
    ap.add_argument("paths", nargs="*", default=["ipex_llm_tpu"],
                    help="files or directories to lint "
                         "(default: ipex_llm_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report (stable schema v%d)"
                         % core.SCHEMA_VERSION)
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in human output")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(core.all_rules().values(), key=lambda r: r.code):
            print(f"{rule.code}  {rule.name:<22} [{rule.severity:<5}] "
                  f"{rule.doc}")
        return 0

    # a typo'd path (or running from the wrong cwd) must not pass the
    # gate green by linting zero files
    missing = [p for p in args.paths if not Path(p).exists()]
    files = [str(f) for f in core.iter_py_files(args.paths)]
    if missing or not files:
        what = (f"path(s) do not exist: {', '.join(missing)}" if missing
                else f"no .py files found under: {', '.join(args.paths)}")
        print(f"jaxlint: {what}", file=sys.stderr)
        return 2

    findings = core.analyze_paths(files)
    if args.json:
        print(core.to_json(findings))
    else:
        core.render_human(findings, show_suppressed=args.show_suppressed)
    return core.exit_code(findings)


if __name__ == "__main__":
    sys.exit(main())
