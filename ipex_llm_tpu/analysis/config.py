"""jaxlint configuration: where each hazard class is load-bearing.

Rules deliberately do NOT run everywhere.  JL001 (aliasing uploads) only
matters in modules that dispatch asynchronously against host buffers the
caller or engine keeps mutating; JL002 (hidden host syncs) only matters
in the serving hot path, and is *relaxed to warn* in benches and tests,
which legitimately sync.  Paths are matched as glob patterns against a
repo-anchored posix key (see :func:`relkey`), so the analyzer behaves
identically whether invoked on ``ipex_llm_tpu/`` from the repo root or
on absolute paths.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field

# path components we anchor relative keys to — the repo's top-level
# source roots.  An unanchored file keeps its given path.
_ANCHORS = ("ipex_llm_tpu", "tests", "benchmark", "examples", "scripts")


def relkey(path: str) -> str:
    # anchor on the LAST matching component: a checkout that happens to
    # live under a directory named "tests"/"benchmark"/... must not have
    # its package files keyed (and rule-scoped) as that outer tree
    parts = path.replace("\\", "/").strip("/").split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] in _ANCHORS:
            return "/".join(parts[i:])
    return "/".join(parts)


def match(key: str, patterns: tuple[str, ...]) -> bool:
    return any(fnmatch.fnmatch(key, pat) for pat in patterns)


@dataclass(frozen=True)
class Config:
    # JL001: modules where dispatch is asynchronous against mutable host
    # state — every numpy->device upload must copy (hostutil.h2d)
    async_modules: tuple[str, ...] = (
        "ipex_llm_tpu/serving/*",
        "ipex_llm_tpu/transformers/multimodal.py",
        "ipex_llm_tpu/speculative.py",
        "ipex_llm_tpu/offload.py",
    )
    # JL002/JL003: hot paths where a hidden blocking sync or a retrace is
    # a tail-latency cliff, plus benches/tests (relaxed below)
    hot_modules: tuple[str, ...] = (
        "ipex_llm_tpu/serving/*",
        "ipex_llm_tpu/speculative.py",
        "benchmark/*",
        "tests/*",
    )
    # (path-glob, rule, severity) — first match wins.  Benches and tests
    # legitimately block on device results; keep the findings visible but
    # non-fatal there.
    severity_overrides: tuple[tuple[str, str, str], ...] = (
        ("benchmark/*", "JL002", "warn"),
        ("tests/*", "JL002", "warn"),
        ("benchmark/*", "JL003", "warn"),
        ("tests/*", "JL003", "warn"),
    )
    # blessed copying-upload helpers (JL001 passes these through)
    upload_helpers: frozenset = frozenset({
        "h2d", "_h2d", "hostutil.h2d",
        "ipex_llm_tpu.hostutil.h2d",
    })
    # blessed shape-bucketing helpers (JL003 accepts dims wrapped in these)
    bucket_helpers: frozenset = frozenset({
        "_round_up", "round_up", "_bucket", "bucket", "next_pow2",
        "pad_batch", "pad_to",
    })
    # JL007: modules whose jitted entries carry persistent device buffers
    # across calls — a wrapper there that donates nothing doubles peak HBM
    # for its cache/pool args (the trace tier, JP101, checks the actual
    # lowered aliases; this is the cheap AST companion)
    donation_modules: tuple[str, ...] = (
        "ipex_llm_tpu/serving/*",
        "ipex_llm_tpu/generation.py",
        "ipex_llm_tpu/speculative.py",
        "ipex_llm_tpu/structured.py",
        "ipex_llm_tpu/transformers/multimodal.py",
        "ipex_llm_tpu/parallel/pipeline.py",
    )
    # parameter names that mark a large persistent device buffer (JL007)
    donation_hint_params: frozenset = frozenset({
        "cache", "draft_cache", "row_cache", "kv", "kv_cache", "pool",
        "prev_ring", "prev", "ring", "carry",
    })

    def severity_for(self, key: str, rule: str, default: str) -> str:
        for pat, r, sev in self.severity_overrides:
            if r == rule and fnmatch.fnmatch(key, pat):
                return sev
        return default

    def in_async(self, key: str) -> bool:
        return match(key, self.async_modules)

    def in_hot(self, key: str) -> bool:
        return match(key, self.hot_modules)

    def in_donation(self, key: str) -> bool:
        return match(key, self.donation_modules)


DEFAULT_CONFIG = Config()
