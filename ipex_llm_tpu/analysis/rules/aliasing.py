"""JL001 aliasing-upload: the PR 2 stream-corruption race, as a rule.

In a module that dispatches asynchronously (the serving engine, the
multimodal/speculative generation loops, the expert offload store), a
zero-copy upload — ``jnp.asarray`` / ``jax.device_put`` on a host
buffer — hands the device a *live view* of memory the host may mutate
while the program is still in flight.  Whether a given numpy array
actually aliases depends on allocator placement, so the corruption is
alignment- and history-dependent.

The contract this rule enforces: inside the configured async-dispatch
modules, ``jnp.asarray``/``jax.device_put`` may only take

- literal constants (scalars, tuples/lists of literals) — nothing to
  alias, and inside traced code ``jnp.asarray(0, ...)`` must stay
  ``asarray`` (a copy op on a tracer would change the program), or
- values that are already jax arrays (a direct ``jnp.*``/``jax.*`` call).

Everything else — names, attributes, subscripts, ``np.asarray(...)``
pass-throughs — must go through the copying helper
``ipex_llm_tpu.hostutil.h2d`` (or carry a suppression explaining why the
buffer provably outlives the dispatch unmutated).
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import ERROR, register

_UPLOADS = {"jax.numpy.asarray", "jax.device_put"}


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_literal(e) for e in node.elts)
    return False


def _is_device_valued(node: ast.AST, aliases: dict[str, str]) -> bool:
    """Already a jax value: a direct jnp.* / jax.* call result."""
    if isinstance(node, ast.Call):
        tgt = astutil.call_target(node, aliases)
        return bool(tgt and tgt.startswith("jax."))
    return False


@register("JL001", "aliasing-upload", ERROR,
          "zero-copy upload of a possibly-mutable host buffer in an "
          "async-dispatch module; use hostutil.h2d")
def check(ctx, config):
    if not config.in_async(ctx.key):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        tgt = astutil.call_target(node, ctx.aliases)
        if tgt not in _UPLOADS:
            continue
        arg = node.args[0]
        if _is_literal(arg) or _is_device_valued(arg, ctx.aliases):
            continue
        # already routed through a blessed copying helper (h2d(x) is a
        # fresh device array; re-wrapping it is pointless but not a race)
        if isinstance(arg, ast.Call):
            an = astutil.dotted_name(arg.func)
            if an in config.upload_helpers:
                continue
        fn = tgt.rsplit(".", 1)[-1]
        yield ctx.finding(
            "JL001", ERROR, node,
            f"{fn}() on a possibly-mutable host buffer in an async-dispatch "
            f"module zero-copy-aliases live memory (alignment-dependent "
            f"stream corruption); upload via hostutil.h2d (copying)")
