"""jaxlint rule modules — importing this package registers every rule.

Rule catalog (docs/quickstart/static_analysis.md has the long form):

- JL000 bad-suppression   suppression comment malformed / reasonless
- JL001 aliasing-upload   zero-copy upload of a mutable host buffer in an
                          async-dispatch module (the PR 2 race class)
- JL002 hidden-host-sync  blocking device sync in a hot path
- JL003 recompile-hazard  fresh jit per call / unbucketed dynamic dim
- JL004 tracer-leak       side effects escaping traced code
- JL005 nondeterminism    wall-clock / host RNG / set-order in traced code
- JL006 prng-key-reuse    one PRNG key consumed twice without split/fold_in
- JL007 missing-donation  hot-path jit wrapper with cache/pool args and no
                          donate_argnums (the AST companion to JP101)

The trace tier's rules (JP100-JP106, ``analysis/trace/``) are registered
here as catalog stubs so ``--list-rules`` shows the full inventory and
suppression comments naming JP codes validate; their checks run in the
jaxprcheck runner, not per source file.
"""

from ipex_llm_tpu.analysis.core import register
from ipex_llm_tpu.analysis.trace.catalog import TRACE_RULES

from ipex_llm_tpu.analysis.rules import (  # noqa: F401  (register on import)
    aliasing,
    donation,
    hostsync,
    nondeterminism,
    prng,
    recompile,
    tracer,
)


@register("JL000", "bad-suppression", "error",
          "jaxlint suppression comment is malformed, reasonless, or names "
          "an unknown rule")
def _jl000(ctx, config):
    # emitted by core.parse_suppressions, never by a rule body; registered
    # so the code renders in --list-rules and "disable=JL000" resolves
    return iter(())


def _register_trace_stubs():
    for code, (name, severity, doc) in TRACE_RULES.items():
        @register(code, name, severity, doc)
        def _stub(ctx, config):
            # trace rules audit lowered programs, not source files: the
            # jaxprcheck runner (analysis/trace/runner.py) executes them
            return iter(())


_register_trace_stubs()
