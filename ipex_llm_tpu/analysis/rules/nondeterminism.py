"""JL005 nondeterminism-in-jit: trace-time entropy baked into programs.

Anything evaluated inside traced code runs ONCE, at trace time, and its
value is burned into the compiled program: ``time.time()`` becomes a
constant timestamp, ``np.random.*``/``random.*`` draws one host sample
shared by every subsequent step, and iterating a ``set`` bakes an
arbitrary (hash-seed-dependent) pytree order into the jaxpr — the
bit-identity contracts the serving equivalence suites enforce
(test_serving_mixed/horizon) cannot survive any of these.

Inside traced scopes this rule flags:

- calls into ``time.*``, stdlib ``random.*``, ``np.random.*``,
  ``datetime.*``, ``uuid.*``, ``secrets.*``, ``os.urandom`` — on-device
  randomness must come from ``jax.random`` with an explicit key,
- iteration over a ``set`` literal / ``set(...)`` call (arbitrary order
  changes pytree structure between processes).
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import ERROR, register

_BANNED_PREFIXES = ("time.", "random.", "numpy.random.", "datetime.",
                    "uuid.", "secrets.")
_BANNED_EXACT = {"os.urandom"}


def _banned(target: str | None) -> bool:
    return bool(target) and (target in _BANNED_EXACT
                             or target.startswith(_BANNED_PREFIXES))


def _is_set_expr(node: ast.AST, aliases) -> bool:
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call):
        tgt = astutil.call_target(node, aliases)
        return tgt in {"set", "frozenset"}
    return False


@register("JL005", "nondeterminism-in-jit", ERROR,
          "wall-clock / host RNG / set-iteration inside traced code is "
          "evaluated once at trace time and baked into the program")
def check(ctx, config):
    for scope in astutil.traced_scopes(ctx.tree, ctx.aliases):
        where = f"traced code ({scope.reason}, '{scope.name}')"
        walk_root = scope.node.body if isinstance(scope.node, ast.Lambda) \
            else scope.node
        for node in ast.walk(walk_root):
            if isinstance(node, ast.Call):
                tgt = astutil.call_target(node, ctx.aliases)
                if _banned(tgt):
                    yield ctx.finding(
                        "JL005", ERROR, node,
                        f"{tgt}() inside {where} evaluates once at trace "
                        "time and is baked into the compiled program — use "
                        "jax.random with an explicit key / pass host values "
                        "as arguments")
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if _is_set_expr(it, ctx.aliases):
                    yield ctx.finding(
                        "JL005", ERROR, it,
                        f"iterating a set inside {where} bakes an arbitrary "
                        "hash order into the traced program — sort it or "
                        "use a tuple/list")
