"""JL004 tracer-leak: side effects escaping traced code.

A function running under ``jax.jit`` / ``lax.while_loop`` / ``lax.scan``
executes its Python body ONCE, at trace time.  Anything it writes to
``self``, a global, a closed-over list — happens once with abstract
tracers (or stale trace-time values), not per step: state silently
freezes, or a tracer leaks out and explodes later with the infamous
"leaked tracer" error far from the cause.

Flagged inside traced scopes (jit-decorated functions, bodies passed to
lax control flow, vmapped/grad'd functions, and everything nested in
them):

- assignment to ``self.*`` (or any attribute of a non-local object),
- ``global`` / ``nonlocal`` declarations,
- subscript stores to non-local names (``table[i] = ...``),
- mutating method calls (``.append``/``.extend``/``.add``/``.update``/
  ``.pop``/``.setdefault``) on non-local names.

Locals are fine — a list built and consumed within one trace is just
staging (the unrolled-loop idiom).
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import ERROR, register

_MUTATORS = {"append", "extend", "insert", "add", "update", "pop",
             "setdefault", "popitem", "remove", "discard", "clear"}


def _local_names(fn) -> set[str]:
    out: set[str] = set()
    a = fn.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        out.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    out.update(e.id for e in t.elts
                               if isinstance(e, ast.Name))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
        elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            out.add(node.target.id)
        elif isinstance(node, ast.comprehension):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.optional_vars, ast.Name):
                    out.add(item.optional_vars.id)
    return out


def _root_name(node: ast.AST) -> str | None:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _scope_body(scope) -> list[ast.AST]:
    """Statements of the scope, not descending into nested function defs
    (those are their own TracedScope entries)."""
    nodes: list[ast.AST] = []
    body = scope.node.body if not isinstance(scope.node, ast.Lambda) \
        else [ast.Expr(scope.node.body)]

    def rec(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            nodes.append(st)
            for field_ in ("body", "orelse", "finalbody"):
                sub = getattr(st, field_, None)
                if isinstance(sub, list):
                    rec([s for s in sub if isinstance(s, ast.stmt)])
            for h in getattr(st, "handlers", []):
                rec(h.body)
    rec([s for s in body if isinstance(s, ast.stmt)])
    return nodes


@register("JL004", "tracer-leak", ERROR,
          "side effect (self/global/closure mutation) inside jit- or "
          "lax-traced code runs once at trace time, not per step")
def check(ctx, config):
    for scope in astutil.traced_scopes(ctx.tree, ctx.aliases):
        locals_ = _local_names(scope.node)
        where = f"traced code ({scope.reason}, '{scope.name}')"
        for st in _scope_body(scope):
            if isinstance(st, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(st, ast.Global) else "nonlocal"
                yield ctx.finding(
                    "JL004", ERROR, st,
                    f"'{kw} {', '.join(st.names)}' inside {where} — writes "
                    "land at trace time, not per executed step")
                continue
            targets = []
            if isinstance(st, ast.Assign):
                targets = st.targets
            elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
                targets = [st.target]
            for t in targets:
                if isinstance(t, ast.Attribute):
                    root = _root_name(t)
                    if root == "self" or (root and root not in locals_):
                        yield ctx.finding(
                            "JL004", ERROR, t,
                            f"assignment to {ast.unparse(t)} inside {where} "
                            "— attribute writes escape the trace (state "
                            "freezes / tracer leak); return the value "
                            "through the carry instead")
                elif isinstance(t, ast.Subscript):
                    root = _root_name(t)
                    if root and root not in locals_:
                        yield ctx.finding(
                            "JL004", ERROR, t,
                            f"subscript store to non-local '{root}' inside "
                            f"{where} — use functional .at[].set() on a "
                            "carried array")
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                f = st.value.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    root = _root_name(f.value)
                    if root and root not in locals_ and root != "self":
                        yield ctx.finding(
                            "JL004", ERROR, st.value,
                            f"'{root}.{f.attr}(...)' mutates a non-local "
                            f"inside {where} — happens once at trace time")
