"""JL002 hidden-host-sync: blocking device syncs in hot paths.

The serving tick loop's whole performance model is "one designed sync
per horizon" (see ``_decode_multi_step``); an accidental ``int(x)``,
``.item()``, ``np.asarray(device_value)`` or ``.block_until_ready()``
anywhere in the tick/decode/mixed-step path collapses pipelining and is
invisible in review — the code *works*, just 10x slower under load.

Detection is a small per-function dataflow: names assigned from
``jnp.*``/``jax.*`` calls (or calls to jit-bound names in the module)
are device values; converting one to host (``int``/``float``/``bool``/
``np.asarray``/``np.array``/``.item()``) is a blocking sync and gets
flagged.  ``.block_until_ready()`` / ``jax.block_until_ready`` is flagged
unconditionally — syncing is its only purpose.  Reassignment from a host
expression launders the name (the conversion site was the sync; the
result is host data).

Designed syncs stay, with a suppression naming WHY the block is the
intended one (e.g. "THE per-horizon sync").  Benches and tests are
relaxed to warn via config — they legitimately block on results.
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import ERROR, register

_CONVERTERS = {"int", "float", "bool"}
_NP_CONVERTERS = {"numpy.asarray", "numpy.array"}


def _jit_bound_names(tree, aliases) -> set[str]:
    names = astutil.module_jit_names(tree, aliases)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if astutil.jit_decorated(node, aliases):
                names.add(node.name)
        elif isinstance(node, ast.Assign) and astutil.is_jit_expr(
                node.value, aliases):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
    return names


def _sync_findings(ctx, expr, flow):
    """Findings for sync patterns inside one expression."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        # x.block_until_ready() / jax.block_until_ready(x)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            yield ctx.finding("JL002", ERROR, node,
                              "block_until_ready() in a hot path — a "
                              "deliberate full sync; hoist out of the tick "
                              "loop or suppress with the reason it is the "
                              "designed sync point")
            continue
        tgt = astutil.call_target(node, ctx.aliases)
        if tgt == "jax.block_until_ready":
            yield ctx.finding("JL002", ERROR, node,
                              "jax.block_until_ready() in a hot path")
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args and flow._expr_is_device(node.func.value):
            yield ctx.finding("JL002", ERROR, node,
                              ".item() on a device value blocks until the "
                              "dispatched program finishes")
            continue
        if tgt and tgt.rsplit(".", 1)[-1] == "d2h" and node.args and \
                flow._expr_is_device(node.args[0]):
            yield ctx.finding(
                "JL002", ERROR, node,
                "d2h() is a designed blocking sync — keep it, with a "
                "suppression naming why this is the intended sync point")
            continue
        if tgt in _NP_CONVERTERS and node.args and \
                flow._expr_is_device(node.args[0]):
            yield ctx.finding(
                "JL002", ERROR, node,
                f"{tgt.rsplit('.', 1)[-1]}() materialises a device value on "
                "host (blocking sync) in a hot path")
            continue
        if isinstance(node.func, ast.Name) and node.func.id in _CONVERTERS \
                and len(node.args) == 1 and flow._expr_is_device(node.args[0]):
            yield ctx.finding(
                "JL002", ERROR, node,
                f"{node.func.id}() on a device value is a hidden blocking "
                "sync in a hot path")


def _walk_function(ctx, fn, jit_names):
    flow = astutil.DeviceFlow(ctx.aliases, jit_names)

    def visit(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested scopes analysed separately
            if isinstance(st, ast.Assign):
                yield from _sync_findings(ctx, st.value, flow)
                flow.assign(st.targets, st.value)
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                yield from _sync_findings(ctx, st.value, flow)
                flow.assign([st.target], st.value)
            elif isinstance(st, ast.AugAssign):
                yield from _sync_findings(ctx, st.value, flow)
            elif isinstance(st, (ast.If, ast.While)):
                yield from _sync_findings(ctx, st.test, flow)
                yield from visit(st.body)
                yield from visit(st.orelse)
            elif isinstance(st, ast.For):
                yield from _sync_findings(ctx, st.iter, flow)
                yield from visit(st.body)
                yield from visit(st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    yield from _sync_findings(ctx, item.context_expr, flow)
                yield from visit(st.body)
            elif isinstance(st, ast.Try):
                yield from visit(st.body)
                for h in st.handlers:
                    yield from visit(h.body)
                yield from visit(st.orelse)
                yield from visit(st.finalbody)
            else:
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        yield from _sync_findings(ctx, child, flow)

    yield from visit(fn.body)


@register("JL002", "hidden-host-sync", ERROR,
          "blocking device->host sync (.item/int/float/np.asarray/"
          "block_until_ready) in a hot code path")
def check(ctx, config):
    if not config.in_hot(ctx.key):
        return
    jit_names = _jit_bound_names(ctx.tree, ctx.aliases)
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from _walk_function(ctx, node, jit_names)
