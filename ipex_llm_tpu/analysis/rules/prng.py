"""JL006 prng-key-reuse: one key, two draws, correlated "randomness".

``jax.random`` keys are pure values: feeding the SAME key to two sampler
calls yields two *identical* (or correlated) draws — temperature
sampling that silently repeats tokens, speculative accept tests that
correlate with the draft's proposals.  The engine's seeded-stream
contract (fold_in(seed, output_index), split-per-step chain) exists
precisely so every draw has a fresh key.

Per function scope, straight-line dataflow over key-typed names:

- a name becomes FRESH when assigned from ``PRNGKey``/``split``/
  ``fold_in``/``clone`` (or any reassignment),
- a sampler call (``categorical``/``uniform``/``normal``/...) CONSUMES
  the key name it is passed; a second consumption without an intervening
  reassignment is flagged,
- ``split``/``fold_in`` take a key WITHOUT consuming it (deriving new
  keys is the blessed way to reuse),
- a sampler consuming a loop-invariant key inside a ``for``/``while``
  body (key never reassigned in the body) is flagged — every iteration
  would draw the same sample.

Only bare names and ``self.*`` attributes are tracked; aggregate/indexed
keys (``keys[i]``) are out of scope for the heuristic.
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import ERROR, register

_CONSUMERS = {
    "categorical", "uniform", "normal", "bernoulli", "gumbel", "exponential",
    "laplace", "logistic", "randint", "truncated_normal", "choice",
    "permutation", "shuffle", "bits", "poisson", "gamma", "beta", "dirichlet",
    "multivariate_normal", "rademacher", "cauchy", "maxwell", "orthogonal",
    "t", "ball", "loggamma", "binomial", "geometric",
}
_DERIVERS = {"split", "fold_in", "clone", "wrap_key_data", "key", "PRNGKey",
             "key_data"}


def _key_token(node: ast.AST) -> str | None:
    """Trackable key expression -> stable token ('key', 'self.key')."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _random_call_kind(call: ast.Call, aliases) -> tuple[str, str] | None:
    """("consume"|"derive", short_name) for jax.random.* calls."""
    tgt = astutil.call_target(call, aliases)
    if not tgt or not tgt.startswith("jax.random."):
        return None
    short = tgt.rsplit(".", 1)[-1]
    if short in _CONSUMERS:
        return ("consume", short)
    if short in _DERIVERS:
        return ("derive", short)
    return None


def _key_arg(call: ast.Call) -> ast.AST | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    return None


def _assigned_tokens(node: ast.AST) -> set[str]:
    """Tokens (re)bound anywhere under ``node``."""
    out: set[str] = set()
    for sub in ast.walk(node):
        targets = []
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
            targets = [sub.target]
        elif isinstance(sub, ast.For):
            targets = [sub.target]
        for t in targets:
            stack = [t]
            while stack:
                e = stack.pop()
                if isinstance(e, (ast.Tuple, ast.List)):
                    stack.extend(e.elts)
                else:
                    tok = _key_token(e)
                    if tok:
                        out.add(tok)
    return out


class _Scope:
    def __init__(self, ctx):
        self.ctx = ctx
        self.used: dict[str, str] = {}   # token -> sampler that consumed it

    def clear(self, tok: str) -> None:
        self.used.pop(tok, None)

    def fork(self) -> "_Scope":
        child = _Scope(self.ctx)
        child.used = dict(self.used)
        return child


@register("JL006", "prng-key-reuse", ERROR,
          "a jax.random key consumed by two draws without an intervening "
          "split/fold_in — correlated samples")
def check(ctx, config):
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            continue
        scope = _Scope(ctx)
        body = fn.body if not isinstance(fn, ast.Lambda) \
            else [ast.Expr(fn.body)]
        yield from _visit(ctx, scope, body, loop_reassigned=None)


def _visit(ctx, scope, stmts, loop_reassigned):
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue          # separate scope
        # expressions first (RHS evaluates before the binding lands)
        for f in _expr_findings(ctx, scope, st, loop_reassigned):
            yield f
        # then clear anything this statement rebinds
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.For)):
            for tok in _assigned_tokens(st):
                scope.clear(tok)
        if isinstance(st, (ast.For, ast.While)):
            body_assigned = _assigned_tokens(st)
            yield from _visit(ctx, scope, st.body, body_assigned)
            yield from _visit(ctx, scope, st.orelse, loop_reassigned)
        elif isinstance(st, ast.If):
            # branches are mutually exclusive per execution (and often per
            # PROGRAM — static python flags select one at trace time), so
            # consumption in one branch must not taint the other; state
            # after the if is the union of both arms
            body_scope = scope.fork()
            else_scope = scope.fork()
            yield from _visit(ctx, body_scope, st.body, loop_reassigned)
            yield from _visit(ctx, else_scope, st.orelse, loop_reassigned)
            scope.used = {**body_scope.used, **else_scope.used}
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            yield from _visit(ctx, scope, st.body, loop_reassigned)
        elif isinstance(st, ast.Try):
            yield from _visit(ctx, scope, st.body, loop_reassigned)
            for h in st.handlers:
                yield from _visit(ctx, scope, h.body, loop_reassigned)
            yield from _visit(ctx, scope, st.orelse, loop_reassigned)
            yield from _visit(ctx, scope, st.finalbody, loop_reassigned)


def _walk_no_lambda(node):
    """ast.walk that does not descend into nested lambdas/defs (they are
    their own key scopes)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.Lambda, ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                stack.append(child)


def _expr_findings(ctx, scope, st, loop_reassigned):
    # don't descend into nested statements (handled by _visit) or defs
    exprs = []
    for child in ast.iter_child_nodes(st):
        if isinstance(child, ast.expr):
            exprs.append(child)
    for expr in exprs:
        for node in _walk_no_lambda(expr):
            if not isinstance(node, ast.Call):
                continue
            kind = _random_call_kind(node, ctx.aliases)
            if not kind or kind[0] != "consume":
                continue
            key = _key_arg(node)
            tok = _key_token(key) if key is not None else None
            if tok is None:
                continue
            if tok in scope.used:
                yield ctx.finding(
                    "JL006", ERROR, node,
                    f"key '{tok}' already consumed by jax.random."
                    f"{scope.used[tok]}() — a second jax.random.{kind[1]}() "
                    "draw with the same key is correlated; split/fold_in "
                    "first")
            elif loop_reassigned is not None and tok not in loop_reassigned:
                yield ctx.finding(
                    "JL006", ERROR, node,
                    f"key '{tok}' is consumed by jax.random.{kind[1]}() "
                    "inside a loop but never reassigned in the loop body — "
                    "every iteration draws the same sample; split/fold_in "
                    "per iteration")
            else:
                scope.used[tok] = kind[1]
