"""JL003 recompile-hazard: silent retraces that turn jit into a no-op.

Two shapes this repo has been bitten by (see generation.py's bucketing
and the pow2 chunk widths in the mixed step):

- **fresh jit per call**: ``jax.jit(f)(x)`` or ``jax.jit(lambda ...)``
  evaluated inside a function body builds a NEW wrapper every call —
  jit's cache is keyed on the wrapper, so every invocation retraces
  (and recompiles unless the persistent cache saves you).  Hoist to
  module level or cache the wrapper.
- **unbucketed dynamic dim**: a ``len(...)``- or ``.shape``-derived
  value fed straight into a known-jitted callable compiles one program
  per distinct value.  Dims must pass through a bucketing helper
  (``_round_up`` / ``_bucket`` / ``pad_batch`` — config.bucket_helpers)
  so the program count stays bounded.

Heuristic tier (warn): the second shape can't see through call chains,
so it only checks direct calls to names jit-bound in the same module,
inside the configured hot modules.
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import WARN, register
from ipex_llm_tpu.analysis.rules.hostsync import _jit_bound_names


def _contains_dynamic_dim(node: ast.AST, aliases, bucket_helpers) -> bool:
    """len()/.shape-derived value not routed through a bucket helper."""
    dyn = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in bucket_helpers:
                return False          # bucketed somewhere in the expression
            if isinstance(f, ast.Name) and f.id == "len":
                dyn = True
        elif isinstance(sub, ast.Attribute) and sub.attr == "shape":
            dyn = True
    return dyn


@register("JL003", "recompile-hazard", WARN,
          "fresh jax.jit wrapper per call, or an unbucketed dynamic "
          "dimension feeding a jitted function")
def check(ctx, config):
    # (a) fresh jit wrapper built inside a function body
    for outer in ast.walk(ctx.tree):
        if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(outer):
            if not isinstance(node, ast.Call):
                continue
            # jax.jit(f)(args): the callee itself is a jit(...) call
            if isinstance(node.func, ast.Call) and astutil.is_jit_expr(
                    node.func, ctx.aliases):
                yield ctx.finding(
                    "JL003", WARN, node,
                    "jax.jit(...)(...) builds and discards a fresh jit "
                    "wrapper every call — every invocation retraces; hoist "
                    "the wrapper to module level or cache it")
            # jax.jit(lambda ...) evaluated per call
            elif astutil.is_jit_expr(node, ctx.aliases) and node.args and \
                    isinstance(node.args[0], ast.Lambda):
                yield ctx.finding(
                    "JL003", WARN, node,
                    "jax.jit of a lambda inside a function body makes a new "
                    "wrapper (new cache key) per call — name the function "
                    "and jit it once")

    # (b) unbucketed dynamic dims into same-module jitted callables
    if not config.in_hot(ctx.key):
        return
    jit_names = _jit_bound_names(ctx.tree, ctx.aliases)
    if not jit_names:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name not in jit_names:
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if _contains_dynamic_dim(arg, ctx.aliases, config.bucket_helpers):
                yield ctx.finding(
                    "JL003", WARN, arg,
                    f"dynamic dimension ({ast.unparse(arg)}) feeds jitted "
                    f"'{name}' without a bucketing helper — one compiled "
                    "program per distinct value; wrap in "
                    "_round_up/_bucket/pad_batch")
