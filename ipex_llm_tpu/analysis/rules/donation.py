"""JL007 missing-donation: a hot-path jit wrapper that donates nothing.

The cheap AST companion to the trace-level JP101 gate: a ``jax.jit``
wrapper in a hot-path module whose signature takes a large persistent
buffer (a KV cache, pool, or sampling ring — recognized by parameter
name) but carries neither ``donate_argnums`` nor ``donate_argnames``
forces XLA to keep input AND output copies live — for a KV pool that is
the whole pool twice, the classic silent peak-HBM doubling.

Warn tier: parameter names are a heuristic (the trace tier proves the
actual aliasing).  The rule goes quiet as soon as the wrapper donates
*anything* — which arguments should alias is JP101's job.
"""

from __future__ import annotations

import ast

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.core import WARN, register

_DONATE_KEYWORDS = {"donate_argnums", "donate_argnames"}


def _donates(expr: ast.AST) -> bool:
    """Any donate_* keyword anywhere in the decorator/value expression
    (covers ``jax.jit(..., donate_argnums=...)`` and both partial
    spellings)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if any(k.arg in _DONATE_KEYWORDS for k in node.keywords):
                return True
    return False


def _param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    a = fn.args
    return {p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}


@register("JL007", "missing-donation", WARN,
          "hot-path jax.jit wrapper takes large persistent-buffer args "
          "(cache/pool/ring) but donates nothing")
def check(ctx, config):
    if not config.in_donation(ctx.key):
        return
    defs = {n.name: n for n in ctx.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ctx.tree.body:
        fn, jit_expr = None, None
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if astutil.is_jit_expr(dec, ctx.aliases):
                    fn, jit_expr = node, dec
                    break
        elif isinstance(node, ast.Assign) and astutil.is_jit_expr(
                node.value, ctx.aliases) and isinstance(
                    node.value, ast.Call):
            # g = jax.jit(impl, ...): resolve impl if defined in-module
            inner = node.value.args[0] if node.value.args else None
            if isinstance(inner, ast.Name) and inner.id in defs:
                fn, jit_expr = defs[inner.id], node.value
        if fn is None:
            continue
        hints = _param_names(fn) & config.donation_hint_params
        if hints and not _donates(jit_expr):
            yield ctx.finding(
                "JL007", WARN, fn,
                f"jitted '{fn.name}' takes persistent-buffer arg(s) "
                f"{sorted(hints)} but the jit wrapper has no donate_"
                "argnums/donate_argnames — input and output buffers both "
                "stay live (peak-HBM doubles for a KV pool); donate the "
                "dead-after-call inputs (trace rule JP101 verifies the "
                "aliases actually survive lowering)")
