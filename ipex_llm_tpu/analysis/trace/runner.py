"""jaxprcheck runner: trace the registry, run JP rules, gate the manifest.

Backend pinning: manifests must be reproducible, so the audit always runs
against the CPU backend with the test suite's 8 virtual devices —
mirroring tests/conftest.py, including the config-API override that
outranks the axon plugin's sitecustomize.  An environment where that
cannot be arranged raises (CLI exit 3: the analyzer is broken, the tree
is not).
"""

from __future__ import annotations

import os
import sys
from dataclasses import asdict
from pathlib import Path

# everything imported at module level here must stay jax-free: the CLI
# imports this module BEFORE jax so ensure_cpu_backend can still set
# XLA_FLAGS (the 8-virtual-device pin must precede backend init); the
# jax-heavy tracer/rules/registry modules are imported inside audit()
from ipex_llm_tpu.analysis.core import ERROR, Finding
from ipex_llm_tpu.analysis.trace import manifest as manifest_mod
from ipex_llm_tpu.analysis.trace.tickaudit import (TickSpec,
                                                   discover_tick_dispatches,
                                                   mixed_tick_spec)


def ensure_cpu_backend():
    if "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() != "cpu":   # pragma: no cover - env guard
        raise RuntimeError(
            "jaxprcheck needs the CPU backend for a reproducible manifest; "
            f"got {jax.default_backend()!r} (jax was initialized before "
            "the audit could pin it)")


def _jp100(path: str, line: int, message: str) -> Finding:
    return Finding(rule="JP100", severity=ERROR, path=path, line=line,
                   col=1, message=message, tier="trace")


def _apply_spec_suppressions(spec, findings: list[Finding]) -> list[Finding]:
    """Registry-level suppressions, under the jaxlint policy: every one
    needs a written reason; a reasonless entry is itself a JP100 error."""
    table: dict[str, str] = {}
    out: list[Finding] = []
    for code, reason in spec.suppress:
        if not (reason or "").strip():
            out.append(_jp100(
                spec.source, getattr(spec, "lineno", 1),
                f"[{spec.name}] suppression of {code} has no reason — "
                "give ProgramSpec.suppress a written 'why this is safe'"))
        else:
            table[code] = reason
    for f in findings:
        if f.rule in table:
            out.append(Finding(**{**asdict(f), "suppressed": True,
                                  "reason": table[f.rule]}))
        else:
            out.append(f)
    return out


def audit(specs=None, ticks=None, manifest_path=None, update: bool = False,
          tick_source: str | None = None) -> list[Finding]:
    """Run the full trace-tier audit.  Returns findings (suppressed ones
    included, marked); the caller derives the exit code.

    ``update=True`` rewrites the manifest from the built inventory
    instead of diffing against it (rule findings still report, so an
    --update on a tree with real JP101/JP102 bugs still fails)."""
    ensure_cpu_backend()
    from ipex_llm_tpu.analysis.trace import rules as trace_rules
    from ipex_llm_tpu.analysis.trace.registry import (real_registry,
                                                      requirement_met)
    from ipex_llm_tpu.analysis.trace.tracer import signature, trace_entry

    specs = real_registry() if specs is None else specs
    ticks = (mixed_tick_spec(),) if ticks is None else ticks
    path = Path(manifest_path) if manifest_path else manifest_mod.DEFAULT_PATH
    locked = None if update else manifest_mod.load(path)

    findings: list[Finding] = []
    program_results = []
    for spec in specs:
        if not requirement_met(spec.requires):
            program_results.append(
                (spec, None, f"requires {spec.requires} (unavailable in "
                             "this jax)"))
            continue
        entries, seen = [], set()
        spec_findings: list[Finding] = []
        for point in spec.grid:
            try:
                args, kwargs = spec.build(dict(point))
                sig = signature(args, kwargs)
            except Exception as exc:
                spec_findings.append(_jp100(
                    spec.source, spec.lineno,
                    f"[{spec.name}] input builder failed at {point}: "
                    f"{type(exc).__name__}: {exc}"))
                continue
            if sig in seen:   # two grid points sharing one compiled program
                continue
            seen.add(sig)
            try:
                entry = trace_entry(spec, point, prebuilt=(args, kwargs))
            except Exception as exc:
                spec_findings.append(_jp100(
                    spec.source, spec.lineno,
                    f"[{spec.name}] failed to trace/lower at {point}: "
                    f"{type(exc).__name__}: {exc}"))
                continue
            entries.append(entry)
            spec_findings.extend(trace_rules.check_donation(spec, entry))
            spec_findings.extend(
                trace_rules.check_fp8_integrity(spec, entry))
            spec_findings.extend(
                trace_rules.check_weight_integrity(spec, entry))
            spec_findings.extend(trace_rules.check_callbacks(spec, entry))
            spec_findings.extend(
                trace_rules.check_constant_bloat(spec, entry))
        locked_count = None
        if locked is not None:
            locked_count = (locked.get("programs", {})
                            .get(spec.name, {}).get("lowerings"))
        # lowering-count drift is JP104's alone; the generic manifest
        # diff below skips the "lowerings" key so one drifted count
        # yields one finding, not a JP104+JP100 pair
        spec_findings.extend(trace_rules.check_recompile_surface(
            spec, len(entries), locked_count))
        findings.extend(_apply_spec_suppressions(spec, spec_findings))
        program_results.append((spec, entries, None))

    tick_results = []
    for tick in ticks:
        discovered = discover_tick_dispatches(tick, tick_source)
        tick_findings = list(
            trace_rules.check_tick_dispatches(tick, discovered))
        findings.extend(_apply_spec_suppressions(
            _TickShim(tick), tick_findings))
        tick_results.append((tick, discovered - set(tick.alternates)))

    built = manifest_mod.build(program_results, tick_results)
    if update:
        manifest_mod.save(built, path)
    elif locked is None:
        findings.append(_jp100(
            manifest_mod_relkey(path), 1,
            "manifest missing — run `scripts/jaxprcheck --update` and "
            "commit analysis/programs.lock.json"))
    else:
        for line in manifest_mod.diff(locked, built,
                                      ignore_keys=("lowerings",)):
            findings.append(_jp100(
                manifest_mod_relkey(path), 1,
                f"manifest drift: {line} — review, then "
                "`scripts/jaxprcheck --update`"))

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


class _TickShim:
    """Adapts a TickSpec to _apply_spec_suppressions' spec interface."""

    def __init__(self, tick: TickSpec):
        self.suppress = tick.suppress
        self.source = tick.module.replace(".", "/") + ".py"
        self.lineno = 1
        self.name = f"tick:{tick.name}"


def manifest_mod_relkey(path: Path) -> str:
    from ipex_llm_tpu.analysis.config import relkey

    return relkey(str(path))


def list_programs(out=sys.stdout):
    ensure_cpu_backend()
    from ipex_llm_tpu.analysis.trace.registry import (real_registry,
                                                      requirement_met)

    for spec in real_registry():
        status = ("" if requirement_met(spec.requires)
                  else f"  [skipped: requires {spec.requires}]")
        print(f"{spec.name:<32} {len(spec.grid):>2} grid point(s)  "
              f"{spec.source}:{spec.lineno}{status}", file=out)
    tick = mixed_tick_spec()
    print(f"tick:{tick.name:<27} <= {tick.max_dispatches} dispatches  "
          f"{tick.module}", file=out)
