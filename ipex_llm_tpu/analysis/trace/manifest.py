"""programs.lock.json: the locked inventory of hot-path compiled programs.

Per program, per grid point: the donation map (which input leaves alias
which outputs), input/output aval summaries, ``cost_analysis`` flops and
bytes-accessed, callback/constant facts; per program: the distinct
lowering count; plus the tick dispatch chains.  ``--update`` regenerates
the file; on a clean tree that is a no-op (everything serialized here is
a deterministic function of the registry and the pinned CPU backend).
Any drift between the built inventory and the checked-in file fails CI
with a readable path-by-path diff.
"""

from __future__ import annotations

import json
from pathlib import Path

SCHEMA = 1

DEFAULT_PATH = Path(__file__).resolve().parent.parent / "programs.lock.json"


def _leaf_summary(leaves) -> dict:
    """arg name -> compact aval summary (single leaf: the aval; pytrees:
    leaf count + total bytes — stable, diff-friendly)."""
    by_arg: dict[str, list] = {}
    for leaf in leaves:
        by_arg.setdefault(leaf.arg, []).append(leaf)
    out = {}
    for arg, ls in by_arg.items():
        if len(ls) == 1:
            out[arg] = f"{ls[0].dtype}{list(ls[0].shape)}"
        else:
            out[arg] = (f"pytree({len(ls)} leaves, "
                        f"{sum(l.nbytes for l in ls)}B)")
    return out


def entry_record(entry) -> dict:
    return {
        "aliases": {l.label: l.alias for l in entry.leaves
                    if l.alias is not None},
        "donated": sorted(l.label for l in entry.leaves if l.donated),
        "inputs": _leaf_summary(entry.leaves),
        "outputs": [f"{d}{list(s)}" for s, d in entry.out_avals],
        "flops": entry.flops,
        "bytes_accessed": entry.bytes_accessed,
        "const_bytes": entry.const_bytes,
        "callbacks": list(entry.callbacks),
    }


def build(program_results: list, tick_results: list) -> dict:
    """``program_results``: (spec, entries|None, skip_reason|None);
    ``tick_results``: (tick_spec, effective_dispatch_set)."""
    programs = {}
    for spec, entries, skipped in program_results:
        rec: dict = {"source": spec.source}
        if skipped:
            rec["skipped"] = skipped
        else:
            rec["lowerings"] = len(entries)
            rec["entries"] = {e.point_key: entry_record(e) for e in entries}
        programs[spec.name] = rec
    ticks = {
        t.name: {"programs": sorted(dispatches),
                 "dispatches": len(dispatches),
                 "max_dispatches": t.max_dispatches}
        for t, dispatches in tick_results
    }
    return {"schema": SCHEMA, "backend": "cpu",
            "programs": programs, "ticks": ticks}


def save(manifest: dict, path: Path | str = DEFAULT_PATH):
    Path(path).write_text(json.dumps(manifest, indent=2, sort_keys=True)
                          + "\n", encoding="utf-8")


def load(path: Path | str = DEFAULT_PATH) -> dict | None:
    p = Path(path)
    if not p.exists():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def diff(locked: dict, built: dict, prefix: str = "",
         limit: int = 60, ignore_keys: tuple[str, ...] = ()) -> list[str]:
    """Readable path-by-path differences (locked -> built).

    ``ignore_keys``: dict keys whose value changes are reported elsewhere
    (the runner passes "lowerings" — count drift is JP104's finding, and
    double-reporting it here would cost a second suppression per known
    drift)."""
    lines: list[str] = []
    _diff_into(locked, built, prefix, lines, ignore_keys)
    if len(lines) > limit:
        lines = lines[:limit] + [f"... {len(lines) - limit} more"]
    return lines


def _diff_into(a, b, prefix: str, out: list[str],
               ignore_keys: tuple[str, ...] = ()):
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            if k in ignore_keys and k in a and k in b:
                continue
            p = f"{prefix}.{k}" if prefix else str(k)
            if k not in a:
                out.append(f"+ {p} = {_short(b[k])}")
            elif k not in b:
                out.append(f"- {p} (was {_short(a[k])})")
            else:
                _diff_into(a[k], b[k], p, out, ignore_keys)
    elif a != b:
        out.append(f"~ {prefix}: {_short(a)} -> {_short(b)}")


def _short(v) -> str:
    s = json.dumps(v, sort_keys=True, default=str)
    return s if len(s) <= 80 else s[:77] + "..."
