"""Trace-rule catalog: codes, default severities, one-line docs.

Kept jax-free so ``--list-rules`` and the suppression validator can name
JP codes without importing the (jax-heavy) tracer.  The long-form catalog
with before/after examples lives in docs/quickstart/static_analysis.md.
"""

from __future__ import annotations

from ipex_llm_tpu.analysis.core import ERROR, WARN

# code -> (name, severity, doc)
TRACE_RULES: dict[str, tuple[str, str, str]] = {
    "JP100": (
        "audit-integrity", ERROR,
        "program failed to trace, manifest missing/drifted, or a registry "
        "suppression has no written reason"),
    "JP101": (
        "donation-coverage", ERROR,
        "large dead-after-call input aval absent from the lowered "
        "input_output_aliases (re-uploaded rather than donated), or a "
        "host-held buffer donated"),
    "JP102": (
        "fp8-pool-integrity", ERROR,
        "an e5m2 pool-resident aval is upcast wholesale inside the lowered "
        "program (breaks the dequant-at-read contract)"),
    "JP103": (
        "host-callback", ERROR,
        "pure_callback/io_callback/debug_print/infeed-outfeed primitive "
        "inside a lowered hot-path program"),
    "JP104": (
        "recompile-surface", ERROR,
        "distinct lowerings over the enumerated bucket grid exceed the "
        "spec bound or disagree with the locked manifest"),
    "JP105": (
        "constant-bloat", WARN,
        "closure-captured constant above the byte threshold baked into "
        "the jaxpr"),
    "JP106": (
        "tick-dispatch-count", ERROR,
        "the mixed prefill+decode tick issues more device dispatches than "
        "the gate allows, or its program set drifted from the registry"),
    "JP107": (
        "packed-weight-integrity", ERROR,
        "a stacked packed-weight plane (the 4/5/8-bit block serving "
        "formats) is dequantized wholesale inside the lowered program "
        "instead of per-layer next to its matmul (a 4x HBM regression)"),
}


def severity_of(code: str) -> str:
    return TRACE_RULES[code][1]
