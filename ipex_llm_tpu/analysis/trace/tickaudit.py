"""JP106 groundwork: static dispatch-count audit of an engine tick.

One engine tick's device-dispatch count is THE quantity the ragged-paged-
attention superkernel roadmap item must drive to one — so it is locked
here, statically.  We cannot count dispatches of an abstract trace (no
execution), but we can enumerate which module-level jitted entries a
tick's scheduler functions can possibly call: the tick functions are
plain host Python, so every device dispatch they issue is a call to a
module-level jit-bound name, which plain AST walking finds exactly.

Kept jax-free so benchmark/serving_bench.py can stamp the audited count
into its output rows without paying a tracer import.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from importlib import import_module

from ipex_llm_tpu.analysis import astutil


@dataclass(frozen=True)
class TickSpec:
    """What one engine tick is allowed to dispatch.

    ``entries`` are the scheduler functions that make up the tick (host
    Python, searched by name anywhere in the module — methods included);
    ``programs`` the jitted callees that ARE the tick's dispatch chain;
    ``alternates`` jitted callees reachable from the same source but on a
    different engine mode's path (they don't count against this tick).
    """
    name: str
    module: str                       # import path of the engine module
    entries: tuple[str, ...]
    programs: tuple[str, ...]
    alternates: tuple[str, ...] = ()
    max_dispatches: int = 2
    suppress: tuple[tuple[str, str], ...] = ()   # (code, reason)


def mixed_tick_spec() -> TickSpec:
    """The mixed prefill+decode tick: ONE fused program
    (``_ragged_tick_fn`` — ragged prefill, on-device first-token merge,
    and the decode horizon in a single jitted entry; the steady-state
    tick is the same entry with no prefill block).  The gate is EXACTLY
    1 dispatch per tick — the ragged paged-attention superkernel
    invariant (ROADMAP item 1, landed); the chained
    ``_mixed_prefill_fn`` + ``_decode_multi_step`` pair survives only as
    the equivalence oracle, unreachable from the tick entries."""
    return TickSpec(
        name="mixed",
        module="ipex_llm_tpu.serving.engine",
        entries=("_mixed_step", "_horizon_step"),
        programs=("_ragged_tick_fn",),
        alternates=("_pp_decode_sample",),   # pp engines route H=1 here
        max_dispatches=1,
    )


def _module_source(module: str) -> str:
    import inspect

    return inspect.getsource(import_module(module))


def discover_tick_dispatches(spec: TickSpec,
                             source: str | None = None) -> set[str]:
    """Module-level jit-bound names callable from the tick's entry
    functions (alternates included — the caller subtracts them)."""
    src = source if source is not None else _module_source(spec.module)
    tree = ast.parse(src)
    aliases = astutil.import_aliases(tree)
    jit_names = astutil.module_jit_names(tree, aliases)
    found: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in spec.entries:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in jit_names:
                found.add(name)
    return found


def mixed_tick_dispatch_count(source: str | None = None) -> int:
    """Dispatches one mixed tick issues on the non-pp path — the number
    serving_bench stamps into its rows so BENCH artifacts track it
    against the JP106 gate."""
    spec = mixed_tick_spec()
    return len(discover_tick_dispatches(spec, source) - set(spec.alternates))
