"""jaxprcheck — trace-level program audit (the second static-analysis tier).

jaxlint (``analysis/rules/``) machine-checks hazards at the AST level; it
cannot see what XLA actually compiles.  This package abstract-traces the
repo's hot-path jitted programs (``jax.jit(...).trace(...).lower()`` on
CPU ShapeDtypeStructs — no execution, no real weights) and gates their
compiled-program properties:

- JP101 donation-coverage: large dead-after-call inputs must appear in
  the lowered ``input_output_aliases``; donated-but-held buffers flagged;
- JP102 fp8-pool dtype integrity: e5m2 pool avals stay e5m2 end to end
  (PR 5's dequant-at-read contract, machine-checked);
- JP103 host-callback freedom in the lowered hot programs;
- JP104 recompile-surface: the lowering count over the enumerated bucket
  grid is bounded and matches the manifest;
- JP105 constant-bloat: closure-captured constants baked into the jaxpr;
- JP106 tick-dispatch-count: a mixed prefill+decode tick issues at most
  2 device dispatches (the gate ROADMAP item 1 tightens to 1).

The audited inventory is locked in ``analysis/programs.lock.json``; drift
fails CI with a readable diff and ``scripts/jaxprcheck --update``
regenerates it.  Submodules that need jax (`registry`, `tracer`, `rules`,
`runner`) are imported lazily so the AST tier stays jax-free.
"""

from ipex_llm_tpu.analysis.trace.catalog import TRACE_RULES  # noqa: F401
from ipex_llm_tpu.analysis.trace.tickaudit import (  # noqa: F401
    TickSpec,
    discover_tick_dispatches,
    mixed_tick_dispatch_count,
    mixed_tick_spec,
)
