"""The program registry: every hot-path jitted entry, with its abstract-
input builder over the real bucket grid and its expected contracts.

A ProgramSpec names the jitted callable, how to build abstract inputs for
one grid point (ShapeDtypeStructs over a tiny audit model — lowering cost
is shape-independent-enough that tiny dims keep the audit fast while the
*grid* axes stay the engine's real ones: prefill buckets x horizons x row
counts x kv_storage in {bf16, fp8}), and the donation contract: which
dynamic args are DEAD after the call (the host overwrites its handle —
donation candidates, JP101 demands aliases for the large ones) and which
are HELD (the host re-passes the same buffer next call — donation there
is a use-after-donate bug, also JP101).

Registering a new program (docs/quickstart/static_analysis.md has the
worked example): write a builder returning the exact ``(args, kwargs)``
the real call site passes (statics included), list the dynamic arg names
in signature order, declare dead/held, pick the grid, append the spec in
``real_registry``, then run ``scripts/jaxprcheck --update`` and commit
the manifest diff.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ipex_llm_tpu.analysis.config import relkey


@dataclass(frozen=True)
class ProgramSpec:
    name: str
    fn: Any                                   # the jitted callable
    build: Callable[[dict], tuple[tuple, dict]]
    grid: tuple[dict, ...]
    arg_names: tuple[str, ...]                # dynamic args, in order
    dead: frozenset = frozenset()             # dead-after-call arg names
    held: frozenset = frozenset()             # host-reused arg names
    min_donate_bytes: int = 2048              # JP101 floor at audit shapes
    max_lowerings: int = 8                    # JP104 bound
    const_bytes_limit: int = 1 << 16          # JP105 threshold
    suppress: tuple[tuple[str, str], ...] = ()   # (code, written reason)
    requires: str | None = None               # e.g. "jax.shard_map"
    source: str = field(default="", compare=False)
    lineno: int = field(default=1, compare=False)

    def __post_init__(self):
        if not self.source:
            import inspect

            fn = inspect.unwrap(self.fn)
            wrapped = getattr(fn, "__wrapped__", fn)
            object.__setattr__(self, "source",
                               relkey(inspect.getsourcefile(wrapped)))
            object.__setattr__(self, "lineno",
                               wrapped.__code__.co_firstlineno)


def requirement_met(requires: str | None) -> bool:
    """'jax.shard_map'-style dotted attribute probe."""
    if not requires:
        return True
    obj: Any = __import__(requires.split(".", 1)[0])
    for part in requires.split(".")[1:]:
        obj = getattr(obj, part, None)
        if obj is None:
            return False
    return True


# --------------------------------------------------------------------------
# the audit model: tiny dims, real param-tree structure
# --------------------------------------------------------------------------

def _sds(tree):
    return jax.tree_util.tree_map(
        lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)
                   if hasattr(x, "shape") else x), tree)


def audit_model(wq: str = "bf16"):
    """(cfg, abstract params) for a tiny llama through the REAL build
    path, so the param tree the audit lowers against is structurally the
    tree every engine entry actually takes.  ``wq`` is the weight-qtype
    axis (EngineConfig.weight_qtype): "sym_int4" lowers against stacked
    packed-code planes — the tree JP107's packed-weight protection and
    the int4 donation contracts are audited on.  The quantized variant
    widens hidden/ffn (128/256 vs 32/64) AND the query-head count (16 vs
    4, so the o-projection's contraction dim num_heads*head_dim is 128,
    not one lone block) to keep every stacked weight at >= 4 quantization
    blocks per matrix: at <= 2 blocks the per-layer ``[n_blocks, block,
    out]`` dequant view inside the scan body (the DESIGN) shape-collides
    with the full-stack ``[L, in, out]`` form JP107 forbids — a
    toy-model-only ambiguity (real serving weights run thousands of
    contraction rows), kept out of the audit by construction.  KV-head
    and head dims stay equal across variants so both share one
    paged-cache shape.  (Thin wrapper so ``audit_model()`` and
    ``audit_model("bf16")`` normalize to ONE lru_cache key — the real
    quantize work in random_params must not run twice per audit.)"""
    return _audit_model(wq)


def audit_cfg(wq: str = "bf16"):
    """The audit model's ModelConfig ALONE — no param tree, no random
    init.  Split out of :func:`audit_model` so runtime consumers (the
    perfwatch MFU join needs the audit dims to scale the manifest's
    cost_analysis to the serving model) can read the audit shape without
    paying the quantize/random-params build."""
    from ipex_llm_tpu.models.random_init import llama_config

    wide = wq != "bf16"
    return llama_config(hidden_size=128 if wide else 32,
                        intermediate_size=256 if wide else 64, num_layers=2,
                        num_heads=16 if wide else 4, num_kv_heads=2,
                        head_dim=8, vocab_size=97,
                        max_position_embeddings=256)


@lru_cache(maxsize=4)
def _audit_model(wq: str):
    from ipex_llm_tpu.models.random_init import random_params

    cfg = audit_cfg(wq)
    return cfg, _sds(random_params(cfg, qtype=wq, seed=0))


def audit_cfg_tp():
    """The manual-TP audit model's ModelConfig alone (see
    :func:`audit_cfg`)."""
    from ipex_llm_tpu.models.random_init import llama_config

    return llama_config(hidden_size=32, intermediate_size=64, num_layers=2,
                        num_heads=8, num_kv_heads=8, head_dim=8,
                        vocab_size=96, max_position_embeddings=256)


@lru_cache(maxsize=1)
def audit_model_tp():
    """(cfg, abstract params) for the MANUAL-TP tick grid: every sharded
    axis — q/kv heads, the packed qkv/gate_up out widths, the ffn
    contraction, the vocab — divides by 8, so one model lowers the
    sharded tick at tp in {1, 2, 4, 8} on the audit's 8 virtual CPU
    devices."""
    from ipex_llm_tpu.models.random_init import random_params

    cfg = audit_cfg_tp()
    return cfg, _sds(random_params(cfg, qtype="bf16", seed=0))


def _tp_mesh(tp: int):
    from ipex_llm_tpu.parallel import MeshSpec, make_mesh

    return make_mesh(MeshSpec(tp=tp))


_POOL_PAGES = 18      # audit pool: pages, page size, table width
_PAGE = 16
_MAXP = 4


def _tp_paged_cache(tp: int, rows: int, storage: str,
                    max_pages: int = _MAXP):
    """Abstract paged pool WITH the real placement's shardings: the
    engine's cache arrives kv-head-sharded (shard_paged_cache), and the
    donation alias only forms when the lowered input sharding matches the
    output's — an unsharded abstract pool would audit a program the
    engine never dispatches (and falsely flag the pool copy JP101
    protects against)."""
    from dataclasses import replace as _dc_replace

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ipex_llm_tpu.kv import PagedKVCache

    cfg, _ = audit_model_tp()
    cache = _sds(PagedKVCache.init(
        cfg.num_layers, _POOL_PAGES, rows, max_pages, cfg.num_kv_heads,
        _PAGE, cfg.head_dim, v_head_dim=cfg.v_dim, storage=storage))
    mesh = _tp_mesh(tp)
    pool = NamedSharding(mesh, P(None, None, "tp", None, None))
    rep = NamedSharding(mesh, P())

    def sh(x, s):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    return _dc_replace(cache, k=sh(cache.k, pool), v=sh(cache.v, pool),
                       tables=sh(cache.tables, rep),
                       length=sh(cache.length, rep))


def _paged_cache(rows: int, storage: str, max_pages: int = _MAXP):
    from ipex_llm_tpu.kv import PagedKVCache

    cfg, _ = audit_model()
    return _sds(PagedKVCache.init(
        cfg.num_layers, _POOL_PAGES, rows, max_pages, cfg.num_kv_heads,
        _PAGE, cfg.head_dim, v_head_dim=cfg.v_dim, storage=storage))


def _dense_cache(batch: int, capacity: int):
    from ipex_llm_tpu.kv import make_cache

    cfg, _ = audit_model()
    return _sds(make_cache("normal", cfg.num_layers, batch, capacity,
                           cfg.num_kv_heads, cfg.head_dim,
                           v_head_dim=cfg.v_dim))


def _key():
    return jax.eval_shape(lambda: jax.random.PRNGKey(0))


def _i32(*s):
    return jax.ShapeDtypeStruct(s, jnp.int32)


def _f32(*s):
    return jax.ShapeDtypeStruct(s, jnp.float32)


def _bool(*s):
    return jax.ShapeDtypeStruct(s, jnp.bool_)


def _grid(**axes) -> tuple[dict, ...]:
    """Cartesian product of named axes, insertion-ordered."""
    points: list[dict] = [{}]
    for name, values in axes.items():
        points = [{**p, name: v} for p in points for v in values]
    return tuple(points)


# --------------------------------------------------------------------------
# builders — one per registered program, mirroring the real call sites
# --------------------------------------------------------------------------

def _build_decode_multi_step(pt):
    cfg, params = audit_model(pt.get("wq", "bf16"))
    r = pt["rows"]
    return (cfg, params, _paged_cache(r, pt["kv"]), _i32(r), _i32(r),
            _bool(r), _f32(r), _f32(r), _key(), _i32(r), _i32(r), _i32(r),
            _i32(r, 2), _i32(r)), {"horizon": pt["horizon"], "mesh": None}


def _tp_stamped_params(tp: int):
    """The abstract audit_model_tp tree with the manual layout's
    ``tp_mode`` stamps (the static aux parallel/manual.py's in_specs are
    derived from) — shapes are unchanged by the relayout permutation, so
    the abstract tree lowers exactly like a placed one."""
    from dataclasses import replace as _dc_replace

    from ipex_llm_tpu.parallel.shard import param_shardings
    from ipex_llm_tpu.quantize.core import QTensor

    cfg, params = audit_model_tp()
    mesh = _tp_mesh(tp)
    sh = param_shardings(params, mesh)

    def stamp(p, s, key):
        if isinstance(p, QTensor) and isinstance(s, QTensor):
            # the manual layout replicates the embed table
            return _dc_replace(p, tp_mode=None if key == "embed"
                               else s.tp_mode)
        return p

    out = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {kk: stamp(vv, sh[k][kk], kk) for kk, vv in v.items()}
        elif isinstance(v, (int, float)):
            out[k] = v
        else:
            out[k] = stamp(v, sh[k], k)
    return cfg, out, mesh


def _build_ragged_tick(pt):
    tp = pt.get("tp", 0)
    if tp > 1:
        # manual-mesh form: the whole tick inside one fully-manual
        # shard_map region (parallel/manual.py) over a pure-tp mesh
        cfg, params, mesh = _tp_stamped_params(tp)
        cache = _tp_paged_cache(tp, pt["rows"], pt["kv"])
    else:
        # tp=1 IS the single-chip program (the engine routes tp<=1 to
        # the plain path — manual.ineligible_reason): the grid point
        # exists so the tp axis reads {1, 2, 4, 8}, and dedups against
        # the matching single-chip row by signature
        cfg, params = audit_model(pt.get("wq", "bf16"))
        mesh = None
        cache = _paged_cache(pt["rows"], pt["kv"])
    r = pt["rows"]
    base = (cfg, params, cache, _i32(r), _i32(r),
            _bool(r), _f32(r), _f32(r), _key(), _i32(r), _i32(r), _i32(r),
            _i32(r, 2), _i32(r))
    w = pt["width"]
    if w:   # admission-wave form: pow2-padded prefill block rides along
        p = 2
        prefill = (_i32(p, w), _i32(p, 2), _i32(p), _i32(p), _bool(p),
                   _bool(p), _i32(p))
    else:   # steady-state form: pure decode horizon, no prefill block
        prefill = None
    kw = {"prefill": prefill, "horizon": pt["horizon"],
          "with_decode": pt.get("wd", True), "mesh": mesh}
    if tp > 1:
        kw.update(tp_manual=True,
                  collective_qtype=pt.get("cq", "bf16"))
    if pt.get("spec"):
        # speculative form: the device token-history ring (donated, the
        # proposer's input) and the per-row traced draft-width caps ride
        # as dynamic kwargs; spec_k/spec_ngram are statics
        kw.update(hist=_i32(r, _MAXP * _PAGE), spec_ks=_i32(r),
                  spec_k=pt["spec"], spec_ngram=3)
    return base, kw


def _build_mixed_prefill(pt):
    cfg, params = audit_model()
    p = 2   # pow2-padded prefilling-row batch
    return (cfg, params, _paged_cache(p, pt["kv"], max_pages=2),
            _i32(p, pt["width"]), _i32(p), _i32(p), _bool(p), _f32(p),
            _f32(p), _key(), _i32(p), _i32(p)), {"mesh": None}


def _build_prefill_chunk(pt):
    cfg, params = audit_model()
    return (cfg, params, _paged_cache(4, pt["kv"]), _i32(1, pt["bucket"]),
            _i32(1, _MAXP), _i32(), _i32()), {"mesh": None}


def _build_verify_step(pt):
    cfg, params = audit_model()
    r, k = 4, 3
    return (cfg, params, _paged_cache(r, pt["kv"]), _i32(r), _i32(r, k),
            _i32(r), _bool(r), _f32(r), _f32(r), _key(), _i32(r), _i32(r),
            _i32(r)), {"k": k, "mesh": None}


def _pp_mesh():
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("tp", "pp"))


def _build_pp_decode_sample(pt):
    cfg, params = audit_model()
    r = 4
    return (cfg, params, _paged_cache(r, "bf16"), _i32(r), _i32(r),
            _bool(r), _f32(r), _f32(r), _key(), _i32(r), _i32(r),
            _i32(r)), {"mesh": _pp_mesh(), "n_micro": 2}


def _build_pp_verify_step(pt):
    cfg, params = audit_model()
    r, k = 4, 3
    return (cfg, params, _paged_cache(r, "bf16"), _i32(r), _i32(r, k),
            _i32(r), _bool(r), _f32(r), _f32(r), _key(), _i32(r), _i32(r),
            _i32(r)), {"k": k, "mesh": _pp_mesh(), "n_micro": 2}


def _build_gen_prefill(pt):
    cfg, params = audit_model()
    b = pt["batch"]
    return (cfg, params, _dense_cache(b, pt["bucket"] + 32),
            _i32(b, pt["bucket"]), _i32(b)), {}


def _gen_config():
    from ipex_llm_tpu.generation import GenerationConfig

    return GenerationConfig(max_new_tokens=32, eos_token_id=(1,))


def _build_decode_loop(pt):
    cfg, params = audit_model()
    b = pt["batch"]
    return (cfg, params, _dense_cache(b, 160), _i32(b), _i32(b), _i32(b),
            _i32(b, 512), _key(), _gen_config(), 32), {}


def _build_decode_one(pt):
    cfg, params = audit_model()
    b = pt["batch"]
    return (cfg, params, _dense_cache(b, 160), _i32(b), _i32(b), _i32(b),
            _i32(b, 512), _i32(b), _key(), _gen_config()), {}


def _build_mm_prefill(pt):
    cfg, params = audit_model()
    t = pt["bucket"]
    return (cfg, params, _dense_cache(1, t + 32), _i32(1, t), _i32(1, t),
            _f32(1, t, cfg.hidden_size)), {}


def _build_mm_decode(pt):
    cfg, params = audit_model()
    return (cfg, params, _dense_cache(1, 96), _i32(1, 1), _i32(1, 1)), {}


def _build_json_decode_step(pt):
    cfg, params = audit_model()
    return (cfg, params, _dense_cache(1, 96), _i32(1, 1), _i32(1, 1),
            _i32(1)), {}


# --------------------------------------------------------------------------
# the registry
# --------------------------------------------------------------------------

@lru_cache(maxsize=1)
def real_registry() -> tuple[ProgramSpec, ...]:
    from ipex_llm_tpu import generation, structured
    from ipex_llm_tpu.serving import engine
    from ipex_llm_tpu.transformers import multimodal

    kv_axis = ("bf16", "fp8")
    return (
        # -- serving/engine.py ------------------------------------------
        ProgramSpec(
            # THE tick program (JP106's one allowed dispatch): the grid
            # covers the steady-state form (width=0: pure decode horizon,
            # the _decode_multi_step-shaped program), the admission-wave
            # form (prefill block at both pow2 chunk widths), the
            # pure-chunk form (wd=False: prefill+merge with the decode
            # stage statically skipped — a distinct jit variant with the
            # same donation contract), AND the speculative forms
            # (spec_k=4: on-device draft+verify+accept inside the horizon
            # loop, steady-state at both horizons plus the admission-wave
            # joiner tick), each over bf16 and fp8 pools
            name="serving.ragged_tick",
            fn=engine._ragged_tick_fn,
            build=_build_ragged_tick,
            grid=(_grid(rows=(4, 8), width=(0,), horizon=(1, 8),
                        kv=kv_axis)
                  + _grid(rows=(4,), width=(8, 128), horizon=(1,),
                          kv=kv_axis)
                  + _grid(rows=(4,), width=(8,), horizon=(1,),
                          wd=(False,), kv=kv_axis)
                  + _grid(rows=(4,), width=(0,), horizon=(1, 8),
                          spec=(4,), kv=kv_axis)
                  # spec admission joiner at BOTH pow2 chunk widths: the
                  # runtime recompile sentinel bounds the engine's pow2
                  # width family by the widest point sampled here, so a
                  # spec engine's wide admission wave must be priced or
                  # it flags out-of-grid on its first burst
                  + _grid(rows=(4,), width=(8, 128), horizon=(1,),
                          spec=(4,), kv=kv_axis)
                  # weight-qtype axis (EngineConfig.weight_qtype): the
                  # tick over stacked int4-packed weight planes — steady
                  # decode at both horizons on bf16+fp8 pools plus the
                  # admission-wave joiner tick; JP107 protects the packed
                  # stacks, JP101 re-verifies the donation map with the
                  # params held (packed planes are never donated)
                  + _grid(rows=(4,), width=(0,), horizon=(1, 8),
                          wq=("sym_int4",), kv=kv_axis)
                  # int4 admission joiner over BOTH pool storages: the
                  # int4+fp8KV pairing is the fixed-HBM serving config
                  # bench_weight_qtype gates, and the runtime sentinel
                  # requires the structural (wq, kv) form to be locked
                  # or every such admission wave flags out-of-grid.
                  # Width stays at the 8 representative only: on the
                  # widened int4 AUDIT model (hidden=128) a width-128
                  # chunk's [p=2, 128, out] activation shape-collides
                  # with the [L=2, 128, out] packed gate_up stack and
                  # false-fires JP107 (the documented toy-model
                  # ambiguity); the sentinel's width bound spans the wq
                  # axis (perfwatch._mag_group), so real engines' wider
                  # int4 waves are bounded by the bf16 rows' 128
                  + _grid(rows=(4,), width=(8,), horizon=(1,),
                          wq=("sym_int4",), kv=kv_axis)
                  # ...and the int4 pure-chunk form (wd=False): a
                  # distinct jit variant with its own donation map, and
                  # a structural form the sentinel must find locked —
                  # an int4 engine's admission wave with nothing yet
                  # decoding dispatches exactly this program
                  + _grid(rows=(4,), width=(8,), horizon=(1,),
                          wd=(False,), wq=("sym_int4",), kv=kv_axis)
                  # manual-mesh tp axis (parallel/manual.py): the whole
                  # tick inside ONE fully-manual shard_map region over a
                  # pure-tp CPU mesh, per-shard pools, explicit
                  # collectives.  tp=1 is the single-chip program by
                  # construction (dedups by signature); tp in {2, 4, 8}
                  # lower the sharded steady-state tick, tp=2 also the
                  # admission-wave and speculative forms, the quantized
                  # collective families (cq: EQuARX e5m2/int8 wires) and
                  # the fp8 pool — donation aliases verified per point
                  # like every other row
                  + _grid(rows=(4,), width=(0,), horizon=(1,),
                          tp=(1, 2, 4, 8), kv=("bf16",))
                  + _grid(rows=(4,), width=(8,), horizon=(1,),
                          tp=(2,), kv=("bf16",))
                  + _grid(rows=(4,), width=(0,), horizon=(8,),
                          spec=(4,), tp=(2,), kv=("bf16",))
                  + _grid(rows=(4,), width=(0,), horizon=(1,),
                          tp=(2,), cq=("e5m2", "int8"), kv=("bf16",))
                  + _grid(rows=(4,), width=(0,), horizon=(1,),
                          tp=(2,), kv=("fp8",))),
            arg_names=("params", "cache", "toks", "row_lens", "active",
                       "temps", "top_ps", "key", "seeds", "steps",
                       "top_ks", "eos", "remain"),
            # hist (spec forms only) is device-resident dead-after-call
            # state like toks: the host rebinds _dev["hist"] per tick
            dead=frozenset({"cache", "toks", "row_lens", "active",
                            "steps", "remain", "hist"}),
            # key is HELD (checkpoint-by-reference, the PR 6 rule);
            # sampling params/eos are epoch-held; the prefill block's
            # arrays and spec_ks are fresh per-tick uploads, unlisted on
            # purpose
            held=frozenset({"params", "temps", "top_ps", "seeds",
                            "top_ks", "eos", "key"}),
            max_lowerings=38,
        ),
        ProgramSpec(
            name="serving.decode_multi_step",
            fn=engine._decode_multi_step,
            build=_build_decode_multi_step,
            # + one int4-weight point: the chained-program oracle the
            # low-bit equivalence suite drives must lower (and keep its
            # donation map) over packed planes too
            grid=(_grid(rows=(4, 8), horizon=(1, 8), kv=kv_axis)
                  + _grid(rows=(4,), horizon=(1,), wq=("sym_int4",),
                          kv=("bf16",))),
            arg_names=("params", "cache", "toks", "row_lens", "active",
                       "temps", "top_ps", "key", "seeds", "steps",
                       "top_ks", "eos", "remain"),
            dead=frozenset({"cache", "toks", "row_lens", "active",
                            "steps", "remain"}),
            # key is HELD, not dead: the engine's _checkpoint snapshots
            # self.key by reference for bit-identical transient retry —
            # donating it would let a rollback restore a deleted buffer
            held=frozenset({"params", "temps", "top_ps", "seeds", "top_ks",
                            "eos", "key"}),
            max_lowerings=9,
        ),
        ProgramSpec(
            name="serving.mixed_prefill",
            fn=engine._mixed_prefill_fn,
            build=_build_mixed_prefill,
            grid=_grid(width=(8, 128), kv=kv_axis),
            arg_names=("params", "cache", "tokens", "base_lens", "n_valid",
                       "emit", "temps", "top_ps", "key", "seeds", "top_ks"),
            dead=frozenset({"cache"}),
            held=frozenset({"params", "key"}),   # key: checkpoint-held
            max_lowerings=4,
        ),
        ProgramSpec(
            name="serving.prefill_chunk",
            fn=engine._prefill_chunk,
            build=_build_prefill_chunk,
            grid=_grid(bucket=(128,), kv=kv_axis),
            arg_names=("params", "cache", "tokens", "table_row", "base_len",
                       "n_valid"),
            dead=frozenset({"cache"}),
            held=frozenset({"params"}),
            max_lowerings=2,
        ),
        ProgramSpec(
            name="serving.verify_step",
            fn=engine._verify_step,
            build=_build_verify_step,
            grid=_grid(kv=kv_axis),
            arg_names=("params", "cache", "toks", "drafts", "row_lens",
                       "active", "temps", "top_ps", "key", "seeds", "steps",
                       "top_ks"),
            dead=frozenset({"cache"}),
            held=frozenset({"params", "temps", "top_ps", "seeds",
                            "top_ks", "key"}),   # key: checkpoint-held
            max_lowerings=2,
        ),
        ProgramSpec(
            name="serving.pp_decode_sample",
            fn=engine._pp_decode_sample,
            build=_build_pp_decode_sample,
            grid=_grid(kv=("bf16",)),
            arg_names=("params", "cache", "toks", "row_lens", "active",
                       "temps", "top_ps", "key", "seeds", "steps",
                       "top_ks"),
            dead=frozenset({"cache"}),
            held=frozenset({"params", "key"}),   # key: checkpoint-held
            max_lowerings=1,
        ),
        ProgramSpec(
            name="serving.pp_verify_step",
            fn=engine._pp_verify_step,
            build=_build_pp_verify_step,
            grid=_grid(kv=("bf16",)),
            arg_names=("params", "cache", "toks", "drafts", "row_lens",
                       "active", "temps", "top_ps", "key", "seeds", "steps",
                       "top_ks"),
            dead=frozenset({"cache"}),
            held=frozenset({"params", "key"}),   # key: checkpoint-held
            max_lowerings=1,
        ),
        # -- generation.py ----------------------------------------------
        ProgramSpec(
            name="generation.prefill_step",
            fn=generation.prefill_step,
            build=_build_gen_prefill,
            grid=_grid(batch=(1, 2), bucket=(128,)),
            arg_names=("params", "cache", "tokens", "lengths"),
            dead=frozenset({"cache"}),
            held=frozenset({"params"}),
            max_lowerings=2,
        ),
        ProgramSpec(
            name="generation.decode_loop",
            fn=generation.decode_loop,
            build=_build_decode_loop,
            grid=_grid(batch=(2,)),
            arg_names=("params", "cache", "first_tokens", "lengths",
                       "kv_start", "prev_ring", "key"),
            dead=frozenset({"cache", "first_tokens", "prev_ring", "key"}),
            held=frozenset({"params"}),
            max_lowerings=1,
        ),
        ProgramSpec(
            name="generation.decode_one",
            fn=generation._decode_one,
            build=_build_decode_one,
            grid=_grid(batch=(2,)),
            arg_names=("params", "cache", "tok", "pos", "kv_start", "prev",
                       "ring_idx", "key"),
            dead=frozenset({"cache", "tok", "prev", "key"}),
            held=frozenset({"params"}),
            max_lowerings=1,
        ),
        # -- transformers/multimodal.py ---------------------------------
        ProgramSpec(
            name="multimodal.mm_prefill",
            fn=multimodal._mm_prefill,
            build=_build_mm_prefill,
            grid=_grid(bucket=(64,)),
            arg_names=("params", "cache", "tokens", "pos", "embeds"),
            dead=frozenset({"cache"}),
            held=frozenset({"params"}),
            max_lowerings=1,
        ),
        ProgramSpec(
            name="multimodal.mm_decode",
            fn=multimodal._mm_decode,
            build=_build_mm_decode,
            grid=_grid(bucket=(1,)),
            arg_names=("params", "cache", "tok", "pos"),
            dead=frozenset({"cache"}),
            held=frozenset({"params"}),
            max_lowerings=1,
        ),
        # -- structured.py ----------------------------------------------
        ProgramSpec(
            name="structured.json_decode_step",
            fn=structured._json_decode_step,
            build=_build_json_decode_step,
            grid=_grid(bucket=(1,)),
            arg_names=("params", "cache", "tok", "pos", "kv_start"),
            dead=frozenset({"cache"}),
            held=frozenset({"params"}),
            max_lowerings=1,
        ),
    )
