"""Abstract tracing machinery: lower one registered program, no execution.

``trace_entry`` takes a ProgramSpec and one grid point, builds the
abstract inputs (ShapeDtypeStructs — nothing touches a device buffer),
runs ``fn.trace(...).lower()`` on the pinned CPU backend, and distils the
lowered program into the facts the JP rules and the manifest consume:

- per-input-leaf: arg label, aval, whether donation was *requested*
  (``lowered.args_info``) and whether an alias actually *survived*
  lowering (the ``tf.aliasing_output`` arg attributes in the StableHLO
  main signature — jax drops unusable donations with only a warning, so
  the request alone proves nothing);
- output avals, closure-captured constant bytes, callback primitives
  found anywhere in the (recursively walked) jaxpr, and the pre-compile
  ``cost_analysis`` flops / bytes-accessed estimates.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any

import jax
from jax.tree_util import keystr, tree_leaves_with_path

try:  # jax >= 0.4.33 moves core types under jax.extend
    from jax.extend.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover - older jax
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

# primitives that re-enter the host from inside a lowered program (JP103)
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_callback_call",
})


@dataclass(frozen=True)
class LeafInfo:
    """One flattened dynamic input of a lowered program."""
    label: str            # "cache[0]", "params['embed']", "toks"
    arg: str              # top-level dynamic arg name ("cache", "toks")
    shape: tuple[int, ...]
    dtype: str
    nbytes: int
    donated: bool         # donation *requested* (donate_argnums)
    alias: int | None     # output index the lowering actually aliased to


@dataclass(frozen=True)
class TracedEntry:
    """Everything the rules/manifest need about one lowering."""
    point_key: str                    # "horizon=8,kv=fp8,rows=4"
    leaves: tuple[LeafInfo, ...]
    out_avals: tuple[tuple[tuple[int, ...], str], ...]   # (shape, dtype)
    const_bytes: int
    callbacks: tuple[str, ...]
    flops: int
    bytes_accessed: int
    eqn_avals: tuple[tuple[tuple[int, ...], str], ...]   # every eqn output


def point_key(point: dict) -> str:
    return ",".join(f"{k}={point[k]}" for k in sorted(point))


def signature(args: tuple, kwargs: dict) -> tuple:
    """jit-cache-key proxy: dynamic leaf avals + static arg reprs.  Two
    grid points with equal signatures share one compiled program — the
    unit JP104 counts."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = [str(treedef)]
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            sig.append(repr(leaf))
    return tuple(sig)


_MAIN_ARG_RE = re.compile(r"%arg(\d+):")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
# compiled-HLO alias entry: "{out_idx}: (param_number, {}, may-alias)"
_COMPILED_ALIAS_RE = re.compile(
    r"\{\s*(\d*)\s*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may|must)-alias\)")


def parse_output_aliases(mlir_text: str) -> dict[int, int]:
    """MLIR-arg-position -> output index, from the public @main signature.

    jax records surviving donations as ``tf.aliasing_output`` arg
    attributes at lowering (platform-independently, CPU included); parsing
    the signature is the only stable way to see which donations the
    lowering actually kept.  The attr dict is scanned per-arg *segment*
    (from one ``%argN:`` marker to the next) rather than with a brace
    regex: sharded programs carry ``mhlo.sharding = "{...}"`` attrs whose
    quoted nested braces a flat brace match silently truncates — which
    would drop real aliases and fail JP101 on a correct tree."""
    for line in mlir_text.splitlines():
        if "func.func public @main(" in line:
            marks = [(int(m.group(1)), m.start())
                     for m in _MAIN_ARG_RE.finditer(line)]
            out: dict[int, int] = {}
            for (argn, start), (_, end) in zip(
                    marks, marks[1:] + [(-1, len(line))]):
                am = _ALIAS_RE.search(line, start, end)
                if am:
                    out[argn] = int(am.group(1))
            return out
    raise ValueError("no public @main function in lowered module")


def parse_compiled_aliases(hlo_text: str) -> dict[int, int]:
    """MLIR-arg-position -> output index, from the COMPILED module's
    ``input_output_alias`` header — the sharded-program fallback.

    jax 0.4.37 omits the ``tf.aliasing_output`` attrs from the StableHLO
    whenever an input carries a sharding (manual-mesh programs: the tp
    tick's head-sharded KV pools), yet the donation is real — XLA
    establishes the alias at compile time and stamps it on the entry
    module as ``{out_idx}: (param, {}, may-alias)``.  Parsing that header
    is the only way to verify a sharded program's donation contract, and
    compiling costs ~1 s on top of the (already-paid) lowering."""
    return {int(m.group(2)): int(m.group(1) or 0)
            for m in _COMPILED_ALIAS_RE.finditer(hlo_text)}


def _walk_jaxpr(jaxpr: Jaxpr, callbacks: list[str],
                avals: list[tuple[tuple[int, ...], str]]):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in CALLBACK_PRIMITIVES:
            callbacks.append(eqn.primitive.name)
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                avals.append((tuple(aval.shape), str(aval.dtype)))
        for sub in eqn.params.values():
            for j in _iter_subjaxprs(sub):
                _walk_jaxpr(j, callbacks, avals)


def _iter_subjaxprs(v: Any):
    if isinstance(v, ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _iter_subjaxprs(x)


def _leaf_aval(info: Any):
    return getattr(info, "aval", None) or info._aval


def trace_entry(spec, point: dict, prebuilt=None) -> TracedEntry:
    """Lower ``spec.fn`` at ``point`` and distil the audit facts.

    ``prebuilt``: the (args, kwargs) the caller already built for this
    point (the runner builds them once for the dedupe signature — no need
    to pay the builder twice)."""
    args, kwargs = prebuilt if prebuilt is not None \
        else spec.build(dict(point))
    traced = spec.fn.trace(*args, **kwargs)
    lowered = traced.lower()

    ai_args, ai_kwargs = lowered.args_info
    if len(ai_args) != len(spec.arg_names):
        raise ValueError(
            f"{spec.name}: arg_names has {len(spec.arg_names)} entries but "
            f"the lowering reports {len(ai_args)} dynamic args — keep the "
            "registry's arg_names aligned with the jitted signature")
    flat = tree_leaves_with_path((ai_args, dict(ai_kwargs)))

    # flattened dynamic leaves -> MLIR @main args: lowering drops unused
    # inputs; kept_var_idx names the survivors, in flat order
    kept = lowered._lowering.compile_args.get("kept_var_idx")
    kept = sorted(kept) if kept is not None else list(range(len(flat)))
    mlir_pos = {flat_idx: i for i, flat_idx in enumerate(kept)}
    aliases = parse_output_aliases(lowered.as_text())
    if not aliases and any(getattr(i, "donated", False)
                           for _, i in flat):
        # donation requested but the StableHLO shows zero aliases: the
        # sharded-lowering gap (see parse_compiled_aliases) — pay the
        # compile and read the aliases XLA actually established
        aliases = parse_compiled_aliases(lowered.compile().as_text())

    leaves = []
    for flat_idx, (path, info) in enumerate(flat):
        top = path[1]
        if hasattr(top, "idx"):          # positional arg
            name = spec.arg_names[top.idx]
        else:                            # dynamic kwarg
            name = str(getattr(top, "key", top))
        aval = _leaf_aval(info)
        label = name + keystr(tuple(path[2:]))
        leaves.append(LeafInfo(
            label=label, arg=name, shape=tuple(aval.shape),
            dtype=str(aval.dtype),
            nbytes=int(aval.size * aval.dtype.itemsize),
            donated=bool(getattr(info, "donated", False)),
            alias=aliases.get(mlir_pos.get(flat_idx, -1)),
        ))

    callbacks: list[str] = []
    eqn_avals: list[tuple[tuple[int, ...], str]] = []
    closed = traced.jaxpr
    _walk_jaxpr(closed.jaxpr, callbacks, eqn_avals)
    const_bytes = sum(int(getattr(c, "nbytes", 0)) for c in closed.consts)

    cost = lowered.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):   # compiled-style shape, just in case
        cost = cost[0] if cost else {}

    return TracedEntry(
        point_key=point_key(point),
        leaves=tuple(leaves),
        out_avals=tuple((tuple(v.aval.shape), str(v.aval.dtype))
                        for v in closed.jaxpr.outvars),
        const_bytes=const_bytes,
        callbacks=tuple(sorted(set(callbacks))),
        flops=int(cost.get("flops", 0) or 0),
        bytes_accessed=int(cost.get("bytes accessed", 0) or 0),
        eqn_avals=tuple(eqn_avals),
    )
