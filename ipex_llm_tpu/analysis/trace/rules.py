"""JP101-JP106: rules over lowered programs (tracer.TracedEntry facts).

Each rule yields ``core.Finding`` objects with ``tier="trace"``, anchored
at the jitted function's def site so findings are clickable.  Spec-level
suppressions (``ProgramSpec.suppress``) are applied by the runner, under
the same loud policy as jaxlint: a suppression without a written reason
is itself a JP100 error.
"""

from __future__ import annotations

from collections import Counter

from ipex_llm_tpu.analysis.core import Finding
from ipex_llm_tpu.analysis.trace.catalog import severity_of
from ipex_llm_tpu.analysis.trace.tracer import TracedEntry

# dtypes a pool upcast would land in (JP102)
_WIDE_FLOATS = {"float32", "bfloat16", "float16", "float64"}
_FP8 = ("float8_e5m2", "float8_e4m3")


def finding(spec, code: str, message: str, at: str = "") -> Finding:
    where = f" @ {at}" if at else ""
    return Finding(rule=code, severity=severity_of(code), path=spec.source,
                   line=spec.lineno, col=1, tier="trace",
                   message=f"[{spec.name}{where}] {message}")


def check_donation(spec, entry: TracedEntry):
    """JP101: every large dead-after-call input with a matching output
    aval must hold a lowered alias; a donated-but-held buffer is a
    use-after-donate hazard either way."""
    # outputs not already consumed by a surviving alias are the slots a
    # missing donation wastes (matched by aval: XLA aliases exact
    # shape+dtype pairs only)
    free_outs = Counter(entry.out_avals)
    for leaf in entry.leaves:
        if leaf.alias is not None and leaf.alias < len(entry.out_avals):
            free_outs[entry.out_avals[leaf.alias]] -= 1
    for leaf in entry.leaves:
        if leaf.arg in spec.held and leaf.alias is not None:
            yield finding(
                spec, "JP101",
                f"host-held input {leaf.label} ({leaf.dtype}"
                f"{list(leaf.shape)}) is donated: the host keeps using "
                "this buffer across calls — donation here is a "
                "use-after-donate time bomb", entry.point_key)
            continue
        if leaf.arg not in spec.dead or leaf.alias is not None:
            continue
        if leaf.nbytes < spec.min_donate_bytes:
            continue
        sig = (leaf.shape, leaf.dtype)
        if leaf.donated:
            yield finding(
                spec, "JP101",
                f"donation of {leaf.label} ({leaf.dtype}{list(leaf.shape)}, "
                f"{leaf.nbytes}B) was requested but survived lowering with "
                "no alias — shape/dtype matches no output, so the donated "
                "buffer is silently copied anyway", entry.point_key)
        elif free_outs.get(sig, 0) > 0:
            free_outs[sig] -= 1
            yield finding(
                spec, "JP101",
                f"dead-after-call input {leaf.label} ({leaf.dtype}"
                f"{list(leaf.shape)}, {leaf.nbytes}B) has a matching "
                "output aval but no input_output_alias — the buffer is "
                "re-uploaded/copied every call; add it to donate_argnums",
                entry.point_key)


def check_fp8_integrity(spec, entry: TracedEntry):
    """JP102: pool-resident e5m2 avals stay e5m2 end to end.  Protected
    shapes are the fp8 input avals (the pool and its per-layer slices);
    any value of a protected shape materializing in a wide float dtype is
    a wholesale upcast — the dequant-at-read contract says only *gathered
    tiles* (different shapes) ever widen."""
    protected: set[tuple[int, ...]] = set()
    for leaf in entry.leaves:
        if leaf.dtype.startswith(_FP8) and len(leaf.shape) >= 3:
            protected.add(leaf.shape)
            protected.add(leaf.shape[1:])           # per-layer slice
            protected.add((1,) + leaf.shape[1:])    # dynamic_slice form
    if not protected:
        return
    seen: set[tuple[tuple[int, ...], str]] = set()
    for shape, dtype in entry.eqn_avals + entry.out_avals:
        if shape in protected and dtype in _WIDE_FLOATS \
                and (shape, dtype) not in seen:
            seen.add((shape, dtype))
            yield finding(
                spec, "JP102",
                f"pool-shaped value {dtype}{list(shape)} materializes "
                "inside the lowered program — a wholesale upcast of the "
                "e5m2 pool (2x the bytes the fp8 contract paid for); "
                "widen gathered tiles at the read site instead",
                entry.point_key)


def check_weight_integrity(spec, entry: TracedEntry):
    """JP107: stacked packed-weight planes stay packed end to end — the
    weight twin of JP102's fp8-pool rule.

    Protected inputs are the uint8 code planes of stacked quantized
    weights (``params`` leaves with >= 3 dims: ``[L, in_packed, out]``
    layer stacks, ``[L, E, in_packed, out]`` expert stacks).  The
    dequant-fused contract says a layer's weights widen only INSIDE the
    scan body, per layer, right next to the matmul that consumes them —
    per-layer 2-D wide tiles are the design, on both backends.  What must
    never appear is the FULL-STACK wide form: a wide-float value of the
    dense stack shape a wholesale dequant of the plane would produce —
    ``lead + (in_pad, out)`` for in_pad/data-rows ratios 1 (byte-per-code
    sym_int8/fp8/fp6), 2 (the nibble-packed 4-bit family, the serving
    headline), and 8/5 (the dual-plane 5-bit layout, when the row count
    divides).  That value is a full-width copy of every layer resident
    in HBM: ~4x the bytes the packing paid for, silently, on every tick.
    The two-level iquant/kquant layouts (non-integral row ratios over
    256-row superblocks) are outside this shape protection — they are
    import/offline formats, not the requantize-at-build serving family.

    Known blind zone: a weight with <= 2 quantization blocks per matrix
    whose block count equals the stack depth makes the per-layer
    ``[n_blocks, block, out]`` view ambiguous with the full-stack form —
    toy shapes only (real serving weights carry thousands of contraction
    rows); the audit model keeps every weight at >= 4 blocks by
    construction (registry.audit_model)."""
    protected: set[tuple[int, ...]] = set()
    for leaf in entry.leaves:
        if leaf.arg == "params" and leaf.dtype == "uint8" \
                and len(leaf.shape) >= 3:
            lead, kp, n = leaf.shape[:-2], leaf.shape[-2], leaf.shape[-1]
            for m in (1, 2):
                protected.add(lead + (m * kp, n))
            if kp * 8 % 5 == 0:    # _pack_5bit dual-plane rows = 5*in/8
                protected.add(lead + (kp * 8 // 5, n))
    if not protected:
        return
    seen: set[tuple[tuple[int, ...], str]] = set()
    for shape, dtype in entry.eqn_avals + entry.out_avals:
        if shape in protected and dtype in _WIDE_FLOATS \
                and (shape, dtype) not in seen:
            seen.add((shape, dtype))
            yield finding(
                spec, "JP107",
                f"stacked-weight-shaped value {dtype}{list(shape)} "
                "materializes inside the lowered program — a wholesale "
                "dequant-upcast of a packed weight stack (~4x the HBM "
                "bytes the packing bought); dequantize per layer inside "
                "the scan body, next to the consuming matmul",
                entry.point_key)


def check_callbacks(spec, entry: TracedEntry):
    """JP103: hot programs must be host-callback-free."""
    if entry.callbacks:
        yield finding(
            spec, "JP103",
            f"host callback primitive(s) {list(entry.callbacks)} in the "
            "lowered program — each one stalls the device on a host round "
            "trip; move the logic out of the jitted hot path",
            entry.point_key)


def check_recompile_surface(spec, n_lowerings: int,
                            manifest_count: int | None):
    """JP104: the grid's distinct-lowering count is bounded and matches
    the locked manifest (the trace-level teeth behind AST rule JL003)."""
    if n_lowerings > spec.max_lowerings:
        yield finding(
            spec, "JP104",
            f"the enumerated grid produces {n_lowerings} distinct "
            f"lowerings, above the spec bound {spec.max_lowerings} — an "
            "axis leaked into the trace key; bucket it or raise the bound "
            "deliberately")
    if manifest_count is not None and n_lowerings != manifest_count:
        yield finding(
            spec, "JP104",
            f"distinct lowerings = {n_lowerings} but the manifest locks "
            f"{manifest_count} — the compiled-program inventory drifted; "
            "review and run scripts/jaxprcheck --update")


def check_constant_bloat(spec, entry: TracedEntry):
    """JP105: closure-captured constants baked into the jaxpr."""
    if entry.const_bytes > spec.const_bytes_limit:
        yield finding(
            spec, "JP105",
            f"{entry.const_bytes}B of closure-captured constants baked "
            f"into the jaxpr (limit {spec.const_bytes_limit}B) — every "
            "retrace re-uploads them; pass them as arguments instead",
            entry.point_key)


def check_tick_dispatches(tick, discovered: set[str]):
    """JP106: the tick's reachable jitted-callee set equals the declared
    program chain and stays within the dispatch gate."""
    effective = discovered - set(tick.alternates)
    declared = set(tick.programs)
    if effective != declared:
        extra = sorted(effective - declared)
        gone = sorted(declared - effective)
        parts = []
        if extra:
            parts.append(f"undeclared dispatch(es) {extra}")
        if gone:
            parts.append(f"declared program(s) {gone} no longer reachable")
        yield _tick_finding(
            tick, "JP106",
            f"tick '{tick.name}' program set drifted: {'; '.join(parts)} — "
            "update the TickSpec if this is intentional")
    if len(effective) > tick.max_dispatches:
        yield _tick_finding(
            tick, "JP106",
            f"tick '{tick.name}' can issue {len(effective)} device "
            f"dispatches ({sorted(effective)}), above the gate of "
            f"{tick.max_dispatches} — the mixed tick's dispatch budget is "
            "a locked serving invariant")


def _tick_finding(tick, code: str, message: str) -> Finding:
    path = tick.module.replace(".", "/") + ".py"
    return Finding(rule=code, severity=severity_of(code), path=path,
                   line=1, col=1, tier="trace", message=message)
