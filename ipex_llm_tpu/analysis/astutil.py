"""Shared AST machinery for jaxlint rules.

Everything here is heuristic *local* analysis — no cross-module type
inference.  Rules buy precision by scoping themselves to the modules
where a hazard class is load-bearing (see ``config.py``) and by keeping
the per-module reasoning simple enough to audit: import-alias
resolution, "which functions run under trace", and a small
device-value dataflow.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator


# --------------------------------------------------------------------------
# import-alias resolution
# --------------------------------------------------------------------------

def import_aliases(tree: ast.Module) -> dict[str, str]:
    """Map local names to the canonical dotted path they import.

    ``import jax.numpy as jnp`` -> {"jnp": "jax.numpy"};
    ``from jax import lax`` -> {"lax": "jax.lax"};
    ``from functools import partial`` -> {"partial": "functools.partial"}.
    """
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    # the conventional roots, in case a file uses them without importing
    # (fixture snippets); real modules override via their own imports
    out.setdefault("jnp", "jax.numpy")
    out.setdefault("np", "numpy")
    out.setdefault("jax", "jax")
    return out


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` attribute/name chain -> "a.b.c" (None for anything else)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Canonical dotted path of a name/attribute chain, alias-expanded.

    With ``import jax.numpy as jnp``: ``jnp.asarray`` -> "jax.numpy.asarray".
    """
    dn = dotted_name(node)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    root = aliases.get(head, head)
    return f"{root}.{rest}" if rest else root


def call_target(call: ast.Call, aliases: dict[str, str]) -> str | None:
    return resolve(call.func, aliases)


# --------------------------------------------------------------------------
# jit detection
# --------------------------------------------------------------------------

_JIT_PATHS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}

# callables whose function-valued arguments run under trace
_TRACING_CALLERS = {
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.scan",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.vmap", "jax.pmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.custom_jvp", "jax.custom_vjp",
} | _JIT_PATHS


def is_jit_expr(node: ast.AST, aliases: dict[str, str]) -> bool:
    """True if ``node`` evaluates to a jit-wrapped callable.

    Covers ``jax.jit``, ``jax.jit(f, ...)`` and the two partial spellings
    ``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``.
    """
    if resolve(node, aliases) in _JIT_PATHS:
        return True
    if isinstance(node, ast.Call):
        tgt = call_target(node, aliases)
        if tgt in _JIT_PATHS:
            return True
        if tgt == "functools.partial" and node.args:
            return is_jit_expr(node.args[0], aliases)
    return False


def jit_decorated(fn: ast.FunctionDef | ast.AsyncFunctionDef,
                  aliases: dict[str, str]) -> bool:
    return any(is_jit_expr(d, aliases) for d in fn.decorator_list)


def module_jit_names(tree: ast.Module, aliases: dict[str, str]) -> set[str]:
    """Module-level names bound to jit-wrapped callables.

    ``@partial(jax.jit, ...) def f(...)`` and ``g = jax.jit(impl)``.
    """
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if jit_decorated(node, aliases):
                names.add(node.name)
        elif isinstance(node, ast.Assign) and is_jit_expr(node.value, aliases):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


@dataclass
class TracedScope:
    """A function body that runs under jax tracing."""
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    reason: str        # "jit-decorated" | "passed to jax.lax.while_loop" | ...
    name: str          # display name ("<lambda>" for lambdas)


def traced_scopes(tree: ast.Module, aliases: dict[str, str]) -> list[TracedScope]:
    """Every function/lambda in the module whose body is traced.

    Two ways in: a jit decorator, or being passed (by local name or
    inline) to a tracing caller like ``lax.while_loop``.  Nested defs
    inside a traced function are traced too.
    """
    scopes: list[TracedScope] = []
    local_defs: dict[int, dict[str, ast.AST]] = {}

    # defs by enclosing scope so "passed by name" resolves locally
    def collect_defs(body: list[ast.stmt], bag: dict[str, ast.AST]) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bag[st.name] = st
            elif isinstance(st, ast.Assign) and isinstance(st.value, ast.Lambda):
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        bag[t.id] = st.value

    all_defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef)):
            collect_defs(node.body, all_defs)

    seen: set[int] = set()

    def add(fn: ast.AST, reason: str) -> None:
        if id(fn) in seen or not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        seen.add(id(fn))
        name = getattr(fn, "name", "<lambda>")
        scopes.append(TracedScope(fn, reason, name))
        # nested defs/lambdas inherit the trace
        for sub in ast.walk(fn):
            if sub is fn:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                add(sub, reason)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if jit_decorated(node, aliases):
                add(node, "jit-decorated")
        elif isinstance(node, ast.Call):
            tgt = call_target(node, aliases)
            if tgt in _TRACING_CALLERS:
                short = tgt.rsplit(".", 1)[-1]
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        add(arg, f"passed to {short}")
                    elif isinstance(arg, ast.Name) and arg.id in all_defs:
                        add(all_defs[arg.id], f"passed to {short}")
            elif is_jit_expr(node, aliases) and node.args:
                f = node.args[0]
                if isinstance(f, ast.Lambda):
                    add(f, "jit of lambda")
                elif isinstance(f, ast.Name) and f.id in all_defs:
                    add(all_defs[f.id], "jit-wrapped")
    return scopes


# --------------------------------------------------------------------------
# device-value dataflow (local, per-function)
# --------------------------------------------------------------------------

_HOST_ROOTS = ("numpy.",)


@dataclass
class DeviceFlow:
    """Names in one function that (heuristically) hold device arrays.

    A name becomes "device" when assigned from a ``jnp.*``/``jax.*`` call
    or from a call to a known jit-bound callable; it reverts to host when
    reassigned from anything else (``np.asarray(x)`` launders on purpose:
    the *conversion itself* is the sync JL002 reports, the result is a
    host array).
    """
    aliases: dict[str, str]
    jit_names: set[str] = field(default_factory=set)
    device: set[str] = field(default_factory=set)

    def _is_device_call(self, call: ast.Call) -> bool:
        tgt = call_target(call, self.aliases)
        if tgt is None:
            # self._decode_fn(...) style: attribute call on self with a
            # name we were told is jit-bound
            dn = dotted_name(call.func)
            return bool(dn and dn.startswith("self.")
                        and dn.split(".", 1)[1] in self.jit_names)
        if tgt.startswith(_HOST_ROOTS):
            return False
        if tgt.startswith(("jax.numpy.", "jax.lax.", "jax.random.",
                           "jax.nn.")) or tgt in {"jax.device_put"}:
            return True
        head = tgt.split(".")[0]
        return head in self.jit_names or tgt in self.jit_names

    def _expr_is_device(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                if self._is_device_call(sub):
                    return True
                tgt = call_target(sub, self.aliases)
                if tgt and (tgt.startswith(_HOST_ROOTS)
                            or tgt in ("int", "float", "bool")
                            or tgt.rsplit(".", 1)[-1] == "d2h"):
                    # np.asarray/int()/float()/bool()/hostutil.d2h launder
                    # to host — the conversion site was the sync (JL002
                    # reports it); the result is host data
                    return False
            elif isinstance(sub, ast.Name) and sub.id in self.device:
                return True
        return False

    def assign(self, targets: list[ast.expr], value: ast.AST) -> None:
        names: list[str] = []
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
        if not names:
            return
        # function-valued alias: `verify_fn = _verify_step` (a jit-bound
        # name, bare or self.-qualified) makes calls through the alias
        # device-producing too — otherwise a sync on the aliased call's
        # result escapes JL002 via one level of indirection
        dn = dotted_name(value)
        if dn is not None:
            ref = dn.split(".", 1)[1] if dn.startswith("self.") else dn
            if ref in self.jit_names or dn in self.jit_names:
                self.jit_names.update(names)
                self.device.difference_update(names)
                return
        is_dev = self._expr_is_device(value)
        for n in names:
            (self.device.add if is_dev else self.device.discard)(n)
