"""jaxlint core: findings, rule registry, suppressions, runner, reports.

The analyzer is a tier-1 gate (``tests/test_static_analysis.py``): a new
unsuppressed error-tier finding anywhere in ``ipex_llm_tpu/`` fails CI.
Suppressions are therefore *loud*: every ``jaxlint: disable=CODE``
comment must carry a written reason (``-- why it is safe``); one without
a reason is itself an error (JL000), so the inventory of waived hazards
stays reviewable.  A suppression on its own line covers the statement
starting on the next line; one trailing a statement covers that whole
statement (all its lines, so multi-line calls work).  Only real COMMENT
tokens count — a marker inside a string literal is data.
"""

from __future__ import annotations

import ast
import io
import json
import re
import sys
import tokenize
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

from ipex_llm_tpu.analysis import astutil
from ipex_llm_tpu.analysis.config import Config, DEFAULT_CONFIG, relkey

SCHEMA_VERSION = 1

ERROR = "error"
WARN = "warn"


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str            # "error" | "warn"
    path: str                # repo-anchored key (config.relkey)
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str | None = None    # suppression reason, when suppressed
    # which analyzer produced it: "ast" (jaxlint source rules) or "trace"
    # (jaxprcheck program audit).  Additive schema-v1 field: consumers that
    # predate the trace tier ignore it.
    tier: str = "ast"

    def render(self) -> str:
        sup = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}{sup}")


@dataclass(frozen=True)
class Rule:
    code: str                # "JL001"
    name: str                # "aliasing-upload"
    severity: str            # default tier
    doc: str                 # one-line description (shown in --list-rules)
    check: Callable[["ModuleCtx", Config], Iterator[Finding]]


_REGISTRY: dict[str, Rule] = {}


def register(code: str, name: str, severity: str, doc: str):
    """Decorator: register ``fn(ctx, config) -> iterator of findings``."""
    def deco(fn):
        _REGISTRY[code] = Rule(code, name, severity, doc, fn)
        return fn
    return deco


def all_rules() -> dict[str, Rule]:
    from ipex_llm_tpu.analysis import rules as _rules  # noqa: F401  (registers)
    return dict(_REGISTRY)


@dataclass
class ModuleCtx:
    """Everything a rule needs about one source file."""
    path: str                        # as given
    key: str                         # repo-anchored (config.relkey)
    source: str
    tree: ast.Module
    aliases: dict[str, str]
    lines: list[str] = field(default_factory=list)

    @classmethod
    def from_source(cls, source: str, path: str) -> "ModuleCtx":
        tree = ast.parse(source, filename=path)
        return cls(path=path, key=relkey(path), source=source, tree=tree,
                   aliases=astutil.import_aliases(tree),
                   lines=source.splitlines())

    def finding(self, rule: str, severity: str, node: ast.AST,
                message: str) -> Finding:
        return Finding(rule=rule, severity=severity, path=self.key,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message)


# --------------------------------------------------------------------------
# suppressions
# --------------------------------------------------------------------------

# marker that a line is *trying* to be a suppression (malformed or not)
_SUPPRESS_MARK = re.compile(r"#\s*jaxlint:\s*disable")
# the well-formed shape: "# jaxlint: disable=JL001,JL002 -- reason text"
_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9,\s]+?)\s*(?:--\s*(.*\S))?\s*$")


@dataclass(frozen=True)
class Suppression:
    line: int                    # the comment's own line
    codes: tuple[str, ...]
    reason: str | None
    span: tuple[int, int] = (0, 0)   # lines covered (inclusive)

    def covers(self, line: int) -> bool:
        return self.span[0] <= line <= self.span[1]


def _stmt_spans(tree: ast.Module) -> list[tuple[int, int]]:
    """(lineno, end_lineno) of every statement, headers-only for compounds.

    Findings anchor to the line their AST node *starts* on, but a
    trailing suppression comment sits on the line the statement *ends*
    on — for a multi-line call those differ, so suppression coverage
    must span the whole statement.  Compound statements (if/for/while/
    with) contribute only their header span: a comment trailing an
    ``if cond:`` line must not blanket the body.
    """
    spans: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        if isinstance(node, (ast.If, ast.While)):
            hdr = node.test
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            hdr = node.iter
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            hdr = node.items[-1].context_expr
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef, ast.Try)):
            continue
        else:
            spans.append((node.lineno, node.end_lineno or node.lineno))
            continue
        spans.append((node.lineno, hdr.end_lineno or node.lineno))
    return spans


def _coverage(spans: list[tuple[int, int]], line: int,
              standalone: bool) -> tuple[int, int]:
    if standalone:
        # covers the statement STARTING on the next line (full span)
        nxt = [s for s in spans if s[0] == line + 1]
        return min(nxt, key=lambda s: s[1]) if nxt else (line + 1, line + 1)
    # trailing: covers the innermost statement containing this line
    hit = [s for s in spans if s[0] <= line <= s[1]]
    return max(hit, key=lambda s: (s[0], -s[1])) if hit else (line, line)


def _iter_comments(ctx: ModuleCtx) -> Iterator[tuple[int, str, bool]]:
    """(line, comment_text, standalone) for every real COMMENT token.

    Tokenizing (rather than regex-scanning raw lines) means a
    ``jaxlint: disable`` marker inside a string literal or docstring is
    just data — it can neither suppress a genuine finding on its line
    nor fail the gate as a malformed suppression (JL000).
    """
    try:
        for tok in tokenize.generate_tokens(io.StringIO(ctx.source).readline):
            if tok.type == tokenize.COMMENT:
                yield (tok.start[0], tok.string,
                       not tok.line[:tok.start[1]].strip())
    except tokenize.TokenError:
        return   # unterminated construct past the last comment; AST parsed


def parse_suppressions(ctx: ModuleCtx) -> tuple[list[Suppression], list[Finding]]:
    """Per-line suppressions + JL000 findings for malformed ones."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    known = set(all_rules())
    spans = _stmt_spans(ctx.tree)
    for i, text, standalone in _iter_comments(ctx):
        if not _SUPPRESS_MARK.search(text):
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            bad.append(Finding("JL000", ERROR, ctx.key, i, 1,
                               "malformed jaxlint suppression (expected "
                               "'jaxlint: disable=CODE -- reason')"))
            continue
        codes = tuple(c.strip().upper() for c in m.group(1).split(",")
                      if c.strip())
        reason = m.group(2)
        if not reason:
            bad.append(Finding("JL000", ERROR, ctx.key, i, 1,
                               f"suppression of {','.join(codes)} has no "
                               "reason — append '-- why this is safe'"))
            continue
        unknown = [c for c in codes if c not in known]
        if unknown:
            bad.append(Finding("JL000", ERROR, ctx.key, i, 1,
                               f"suppression names unknown rule(s) "
                               f"{','.join(unknown)}"))
        sups.append(Suppression(i, codes, reason,
                                span=_coverage(spans, i, standalone)))
    return sups, bad


def apply_suppressions(findings: list[Finding],
                       sups: list[Suppression]) -> list[Finding]:
    out: list[Finding] = []
    for f in findings:
        s = next((s for s in sups
                  if s.covers(f.line) and f.rule in s.codes), None)
        if s:
            out.append(Finding(**{**asdict(f), "suppressed": True,
                                  "reason": s.reason}))
        else:
            out.append(f)
    return out


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------

def analyze_source(source: str, path: str,
                   config: Config = DEFAULT_CONFIG) -> list[Finding]:
    """Lint one source string as if it lived at ``path``."""
    try:
        ctx = ModuleCtx.from_source(source, path)
    except SyntaxError as e:
        return [Finding("JL000", ERROR, relkey(path), e.lineno or 1, 1,
                        f"syntax error: {e.msg}")]
    sups, bad = parse_suppressions(ctx)
    findings: list[Finding] = list(bad)
    for rule in all_rules().values():
        if rule.code == "JL000":
            continue
        for f in rule.check(ctx, config):
            sev = config.severity_for(ctx.key, f.rule, f.severity)
            if sev != f.severity:
                f = Finding(**{**asdict(f), "severity": sev})
            findings.append(f)
    findings = apply_suppressions(findings, sups)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    # overlapping scope walks (e.g. a def nested in a traced def) can
    # report one site twice — collapse exact duplicates
    seen: set[tuple] = set()
    deduped: list[Finding] = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.col)
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    return deduped


def iter_py_files(paths: Iterable[str]) -> Iterator[Path]:
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            yield from sorted(pp.rglob("*.py"))
        elif pp.suffix == ".py":
            yield pp


def analyze_paths(paths: Iterable[str],
                  config: Config = DEFAULT_CONFIG) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(analyze_source(
            f.read_text(encoding="utf-8"), str(f), config))
    return findings


# --------------------------------------------------------------------------
# reports
# --------------------------------------------------------------------------

def counts(findings: list[Finding]) -> dict[str, int]:
    live = [f for f in findings if not f.suppressed]
    return {
        "errors": sum(1 for f in live if f.severity == ERROR),
        "warnings": sum(1 for f in live if f.severity == WARN),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }


def to_json(findings: list[Finding]) -> str:
    return json.dumps({
        "version": SCHEMA_VERSION,
        "counts": counts(findings),
        "findings": [asdict(f) for f in findings],
    }, indent=2)


def render_human(findings: list[Finding], show_suppressed: bool = False,
                 out=sys.stdout, prog: str = "jaxlint") -> None:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    for f in shown:
        print(f.render(), file=out)
    c = counts(findings)
    print(f"{prog}: {c['errors']} error(s), {c['warnings']} warning(s), "
          f"{c['suppressed']} suppressed", file=out)


def exit_code(findings: list[Finding]) -> int:
    """0 = clean (warnings allowed), 1 = unsuppressed error-tier findings.

    The CLI adds 2 = usage error and 3 = internal analyzer error (the
    analyzer itself crashed — NOT a statement about the tree), so CI can
    distinguish "the gate failed" from "the gate is broken"."""
    return 1 if counts(findings)["errors"] else 0
