"""LangChain embeddings over the TPU BERT encoder.

Reference counterpart: ``TransformersEmbeddings`` / ``TransformersBgeEmbeddings``
(reference langchain/embeddings/transformersembeddings.py:59,188 —
from_model_id classmethod, embed_documents/embed_query).  Backed by
models/bert.py's jitted encoder + mean/cls pooling; works without langchain
installed (plain duck-typed class, same pattern as langchain/llms.py).
"""

from __future__ import annotations

from typing import Any, List


class TransformersEmbeddings:
    """Mean-pooled sentence embeddings (bge/gte/e5-class encoders)."""

    pooling = "mean"

    def __init__(self, model, tokenizer, model_kwargs: dict | None = None,
                 encode_kwargs: dict | None = None):
        self.model = model
        self.tokenizer = tokenizer
        self.model_kwargs = model_kwargs or {}
        self.encode_kwargs = encode_kwargs or {}

    @classmethod
    def from_model_id(cls, model_id: str, model_kwargs: dict | None = None,
                      encode_kwargs: dict | None = None, **kwargs: Any):
        from transformers import AutoTokenizer

        from ipex_llm_tpu.transformers import AutoModel

        mk = dict(model_kwargs or {})
        low_bit = mk.pop("load_in_low_bit", kwargs.pop("load_in_low_bit",
                                                       "sym_int4"))
        model = AutoModel.from_pretrained(model_id, load_in_low_bit=low_bit)
        tok = AutoTokenizer.from_pretrained(model_id, trust_remote_code=True)
        return cls(model, tok, mk, encode_kwargs)

    def embed(self, text: str) -> List[float]:
        enc = self.tokenizer(text, **self.encode_kwargs)
        import numpy as np

        ids = np.asarray(enc["input_ids"], np.int32).reshape(1, -1)
        mask = np.asarray(enc.get("attention_mask",
                                  np.ones_like(ids)), np.int32).reshape(1, -1)
        # pad to a power-of-two length bucket so varying document lengths
        # reuse a handful of compiled encoder programs instead of one XLA
        # compile per unique length (mean pooling is mask-aware; CLS is
        # position 0 — padding is invisible to both)
        t = ids.shape[1]
        max_t = getattr(self.model.config, "max_position_embeddings", 512)
        bucket = 16
        while bucket < t:
            bucket *= 2
        bucket = min(bucket, max_t)
        if t > bucket:       # over-long input: truncate to the model window
            ids, mask = ids[:, :bucket], mask[:, :bucket]
        elif t < bucket:
            pad = bucket - t
            ids = np.pad(ids, ((0, 0), (0, pad)))
            mask = np.pad(mask, ((0, 0), (0, pad)))
        return self.model.embed(ids, attention_mask=mask,
                                pooling=self.pooling)[0].tolist()

    def embed_documents(self, texts: List[str]) -> List[List[float]]:
        return [self.embed(t) for t in texts]

    def embed_query(self, text: str) -> List[float]:
        return self.embed(text)


class TransformersBgeEmbeddings(TransformersEmbeddings):
    """BGE-style: CLS pooling (reference transformersembeddings.py:188)."""

    pooling = "cls"
