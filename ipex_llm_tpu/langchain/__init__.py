"""LangChain adapters (reference langchain/llms/transformersllm.py:61).

Import-guarded: langchain is an optional dependency; the classes raise a
clear error at construction when it is absent.
"""

from ipex_llm_tpu.langchain.embeddings import (
    TransformersBgeEmbeddings,
    TransformersEmbeddings,
)
from ipex_llm_tpu.langchain.llms import TransformersLLM, TransformersPipelineLLM

__all__ = [
    "TransformersLLM",
    "TransformersPipelineLLM",
    "TransformersEmbeddings",
    "TransformersBgeEmbeddings",
]
