"""LangChain LLM wrappers over the TPU model.

Reference counterpart: ``TransformersLLM`` (reference
langchain/llms/transformersllm.py:61 — from_model_id / from_model_id_low_bit
classmethods, `_call` running HF generate).  The adapter keeps that exact
call shape; when langchain isn't installed the class still works as a plain
callable LLM (duck-typed), so the adapter logic is testable without the
dependency.
"""

from __future__ import annotations

from typing import Any, Optional

try:  # langchain >= 0.1 layout, else legacy, else stub
    from langchain_core.language_models.llms import LLM as _LCBase
except ImportError:
    try:
        from langchain.llms.base import LLM as _LCBase
    except ImportError:
        class _LCBase:  # minimal duck-typed stand-in
            def __init__(self, **kwargs):
                for k, v in kwargs.items():
                    object.__setattr__(self, k, v)

            def __call__(self, prompt: str, stop=None, **kw) -> str:
                return self._call(prompt, stop=stop, **kw)

            def invoke(self, prompt: str, stop=None, **kw) -> str:
                return self._call(prompt, stop=stop, **kw)


class TransformersLLM(_LCBase):
    """LangChain LLM backed by ipex_llm_tpu (reference transformersllm.py:61)."""

    model: Any = None
    tokenizer: Any = None
    model_kwargs: Optional[dict] = None
    streaming: bool = False

    @classmethod
    def from_model_id(cls, model_id: str, model_kwargs: dict | None = None,
                      **kwargs):
        from transformers import AutoTokenizer

        from ipex_llm_tpu.transformers import AutoModelForCausalLM

        mk = dict(model_kwargs or {})
        mk.setdefault("load_in_4bit", True)
        model = AutoModelForCausalLM.from_pretrained(model_id, **mk)
        tokenizer = AutoTokenizer.from_pretrained(model_id,
                                                  trust_remote_code=True)
        return cls(model=model, tokenizer=tokenizer, model_kwargs=mk, **kwargs)

    @classmethod
    def from_model_id_low_bit(cls, model_id: str,
                              model_kwargs: dict | None = None, **kwargs):
        from transformers import AutoTokenizer

        from ipex_llm_tpu.transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.load_low_bit(model_id)
        tokenizer = AutoTokenizer.from_pretrained(model_id,
                                                  trust_remote_code=True)
        return cls(model=model, tokenizer=tokenizer,
                   model_kwargs=model_kwargs, **kwargs)

    @property
    def _llm_type(self) -> str:
        return "ipex_llm_tpu_transformers"

    def _call(self, prompt: str, stop=None, run_manager=None, **kwargs) -> str:
        import numpy as np

        ids = np.asarray(self.tokenizer(prompt)["input_ids"], np.int32)
        out = self.model.generate(
            ids, max_new_tokens=int(kwargs.get("max_new_tokens", 128))
        )
        text = self.tokenizer.decode(
            out[0][len(ids):], skip_special_tokens=True
        )
        if stop:
            cuts = [text.find(s) for s in stop if text.find(s) >= 0]
            if cuts:
                text = text[: min(cuts)]
        return text


class TransformersPipelineLLM(TransformersLLM):
    """Pipeline-flavored alias (reference transformersllm.py sibling class)."""

    @property
    def _llm_type(self) -> str:
        return "ipex_llm_tpu_transformers_pipeline"
