"""Training checkpoint/resume via orbax (async, multi-host-aware).

Reference counterpart: the reference's finetuning examples rely on HF
Trainer/PEFT checkpointing (SURVEY §5 checkpoint/resume); the r2 repo only
had low-bit model save/load.  This adds full TRAINING-state checkpoints —
params (QTensor pytrees included), optimizer state, adapters, step counter
— through ``orbax.checkpoint``, the JAX-ecosystem standard that handles
sharded arrays (multi-host meshes write cooperatively) and atomic
directory commits.
"""

from __future__ import annotations

from typing import Any

import jax


class TrainCheckpointer:
    """Thin CheckpointManager wrapper for (params, opt_state, extras).

    QTensor leaves ride along transparently: they are registered pytree
    nodes, so orbax sees their packed planes as ordinary arrays and the
    static qtype metadata stays in the treedef supplied at restore.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.manager = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, params: Any, opt_state: Any = None,
             extras: dict | None = None, wait: bool = False) -> None:
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        args = {"state": self._ocp.args.StandardSave(state)}
        if extras:
            # free-form JSON metadata (strings etc. — StandardSave is
            # arrays-only)
            args["extras"] = self._ocp.args.JsonSave(extras)
        self.manager.save(step, args=self._ocp.args.Composite(**args))
        if wait:
            self.manager.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def restore(self, template: Any, step: int | None = None) -> Any:
        """Restore into the structure of ``template`` (same pytree as was
        saved — e.g. freshly initialized params/opt_state)."""
        step = self.manager.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        template = dict(template)
        template.pop("extras", None)
        abstract = jax.tree_util.tree_map(
            lambda x: x if not hasattr(x, "shape")
            else jax.ShapeDtypeStruct(x.shape, x.dtype,
                                      sharding=getattr(x, "sharding", None)),
            template,
        )
        args = {"state": self._ocp.args.StandardRestore(abstract)}
        try:
            has_extras = "extras" in (self.manager.item_metadata(step) or {})
        except (KeyError, FileNotFoundError):
            has_extras = False
        if has_extras:
            args["extras"] = self._ocp.args.JsonRestore()
        out = self.manager.restore(step,
                                   args=self._ocp.args.Composite(**args))
        state = dict(out["state"])
        if out.get("extras") is not None:
            state["extras"] = out["extras"]
        return state

    def close(self):
        self.manager.wait_until_finished()
        self.manager.close()
