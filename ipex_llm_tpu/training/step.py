"""Shared training step: causal-LM loss + optimizer update as ONE jitted fn.

The reference delegates its training loop to HF Trainer + DeepSpeed and
patches modules underneath (training_patch.py:68-223); here the whole step —
forward, backward, optimizer — is a single XLA program.  Under a sharded
param pytree (parallel/shard.py) the same program runs dp/tp/cp-parallel with
XLA-inserted collectives: grads are psum'd over ``dp`` automatically because
the loss averages over the batch axis.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ipex_llm_tpu.kv import KVCache
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.decoder import decoder_forward


def causal_lm_loss(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,          # [B, T] int32
    loss_mask: jnp.ndarray | None = None,  # [B, T-1] 1.0 where target counts
) -> jnp.ndarray:
    """Mean next-token cross-entropy over the batch (fp32 softmax)."""
    b, t = tokens.shape
    cache = KVCache.init(cfg.num_layers, b, t, cfg.num_kv_heads,
                         cfg.head_dim, v_head_dim=cfg.v_dim)
    pos = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    logits, _ = decoder_forward(cfg, params, tokens, cache, pos)
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if loss_mask is not None:
        return (nll * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)
    return nll.mean()


# stop_gradient in decoder_forward zeroes the frozen buffers' grads, but
# adamw's decoupled weight decay would still step them — so their optimizer
# updates are zeroed too, from the same key list the decoder owns.
from ipex_llm_tpu.models.decoder import FROZEN_BUFFER_KEYS


def freeze_buffer_updates(updates: dict) -> dict:
    out = dict(updates)
    for k in FROZEN_BUFFER_KEYS:
        if k in out and not isinstance(out[k], (float, int)):
            out[k] = jax.tree_util.tree_map(jnp.zeros_like, out[k])
    return out


def make_train_step(
    cfg: ModelConfig,
    optimizer: Any,
    loss_fn: Callable | None = None,
    ring_mesh=None,
) -> Callable:
    """Build a jitted ``step(params, opt_state, tokens) -> (params, opt_state,
    loss)``.  ``optimizer`` is any optax GradientTransformation.

    ``ring_mesh``: a mesh with a ``cp`` axis — attention runs as ring
    attention with the sequence sharded over it (ops/ring_attention.py),
    the long-context training mode the reference lacks entirely.
    """
    import optax

    from ipex_llm_tpu.ops import dispatch

    base_loss = loss_fn or causal_lm_loss

    def loss_with_ring(cfg, params, tokens):
        if ring_mesh is not None and ring_mesh.shape.get("cp", 1) > 1:
            with dispatch.ring(ring_mesh):
                return base_loss(cfg, params, tokens)
        return base_loss(cfg, params, tokens)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_with_ring, argnums=1)(
            cfg, params, tokens
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, freeze_buffer_updates(updates))
        return params, opt_state, loss

    return step
