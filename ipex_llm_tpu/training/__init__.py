"""Training: QLoRA / ReLoRA / LISA and the shared train-step builder.

Reference counterparts: qlora.py (LoraLowBitLinear :66, get_peft_model :254),
relora.py:64, lisa.py:23, plus the straight-through dequant backward of
``MatMulLowBit`` (low_bit_linear.py:552-573).  TPU-native design: training is
a pure jitted step function over a param pytree — no Trainer monkey-patching;
parallelism comes from the same mesh shardings as inference.
"""

from ipex_llm_tpu.training.step import (
    causal_lm_loss,
    make_train_step,
)
from ipex_llm_tpu.training.qlora import (
    LoraConfig,
    LoraWeight,
    attach_lora,
    get_peft_model,
    init_lora,
    make_qlora_train_step,
    merge_lora,
)
from ipex_llm_tpu.training.checkpoint import TrainCheckpointer
from ipex_llm_tpu.training.relora import ReLoRATrainer, jagged_cosine_schedule
from ipex_llm_tpu.training.lisa import LisaTrainer, make_lisa_train_step
from ipex_llm_tpu.training.hf_trainer import TPUTrainer, patch_transformers_trainer

__all__ = [
    "TPUTrainer", "patch_transformers_trainer",
    "causal_lm_loss", "make_train_step",
    "LoraConfig", "LoraWeight", "attach_lora", "get_peft_model",
    "init_lora", "make_qlora_train_step", "merge_lora",
    "ReLoRATrainer", "jagged_cosine_schedule", "TrainCheckpointer",
    "LisaTrainer", "make_lisa_train_step",
]
