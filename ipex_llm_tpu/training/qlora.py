"""QLoRA: LoRA adapters over a frozen block-quantized base.

Reference counterpart: ``LoraLowBitLinear`` (reference qlora.py:66 — LoRA on
an NF4/INT4 base whose backward dequantizes the base,
low_bit_linear.py:552-573 ``MatMulLowBit.backward``) and the patched
``get_peft_model``/``LoraConfig`` (qlora.py:254-352).

TPU-native design: no module patching — a ``LoraWeight`` pytree node wraps
the frozen QTensor with the (A, B) adapters, and ``ops.linear`` applies
``y = base(x) + (x·A)·B · α/r``.  The base stays packed; autodiff through
the dequant-matmul gives exactly the straight-through dequant gradient the
reference implements by hand, but only the adapter leaves are optimizer
targets, so the train step's grad pytree is just the adapters.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ipex_llm_tpu.models.config import ModelConfig


@dataclass(frozen=True)
class LoraConfig:
    """Reference qlora.py:254 ``LoraConfig`` equivalent."""

    r: int = 8
    lora_alpha: int = 16
    target_modules: tuple[str, ...] = ("qkv", "o", "gate_up", "down")
    lora_dropout: float = 0.0  # applied by the caller's data pipeline
    train_embeddings: bool = False

    @property
    def scale(self) -> float:
        return self.lora_alpha / self.r


@jax.tree_util.register_pytree_node_class
@dataclass
class LoraWeight:
    """Frozen base weight + trainable LoRA adapters (a pytree node)."""

    base: Any               # QTensor or dense array, frozen
    a: jnp.ndarray          # [..., in, r]
    b: jnp.ndarray          # [..., r, out]
    scale: float = 1.0      # static aux

    def tree_flatten(self):
        return (self.base, self.a, self.b), (self.scale,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        base, a, b = children
        return cls(base, a, b, scale=aux[0])


def _slot_dims(qt) -> tuple[int, int]:
    from ipex_llm_tpu.quantize.core import QTensor

    if isinstance(qt, QTensor):
        return qt.in_features, qt.out_features
    return qt.shape[-2], qt.shape[-1]


def init_lora(
    key: jax.Array,
    cfg: ModelConfig,
    params: dict,
    lora_cfg: LoraConfig,
    dtype=jnp.float32,
) -> dict:
    """Build the trainable adapter pytree: {slot: {"a": [L,in,r], "b": [L,r,out]}}.

    A ~ N(0, 1/r) (kaiming-ish), B = 0 — so the merged model starts exactly
    equal to the base (reference peft init).
    """
    adapters: dict[str, dict[str, jnp.ndarray]] = {}
    n_l = cfg.num_layers
    for slot in lora_cfg.target_modules:
        if slot not in params["layers"]:
            continue
        d_in, d_out = _slot_dims(params["layers"][slot])
        key, sub = jax.random.split(key)
        adapters[slot] = {
            "a": (jax.random.normal(sub, (n_l, d_in, lora_cfg.r), dtype)
                  / jnp.sqrt(lora_cfg.r)),
            "b": jnp.zeros((n_l, lora_cfg.r, d_out), dtype),
        }
    return adapters


def attach_lora(params: dict, adapters: dict, lora_cfg: LoraConfig) -> dict:
    """Wrap target slots with LoraWeight (pure; base leaves are shared)."""
    layers = dict(params["layers"])
    for slot, ab in adapters.items():
        layers[slot] = LoraWeight(
            base=params["layers"][slot], a=ab["a"], b=ab["b"],
            scale=lora_cfg.scale,
        )
    out = dict(params)
    out["layers"] = layers
    return out


def merge_lora(params: dict, adapters: dict, lora_cfg: LoraConfig) -> dict:
    """Fold adapters into the base weights (dequant → add → requantize).

    Reference counterpart: peft merge / ReLoRA's merge-and-reset
    (relora.py:383-455).  Quantized slots are requantized to their own
    qtype; dense slots are added in place.
    """
    import numpy as np

    from ipex_llm_tpu.quantize import core as qcore
    from ipex_llm_tpu.quantize.core import QTensor

    layers = dict(params["layers"])
    for slot, ab in adapters.items():
        base = layers[slot]
        delta = jnp.einsum("lir,lro->lio", ab["a"], ab["b"]) * lora_cfg.scale
        if isinstance(base, QTensor):
            merged = []
            n_l = delta.shape[0]
            for i in range(n_l):
                qt_i = jax.tree_util.tree_map(lambda x: x[i], base)
                w = qcore.dequantize(qt_i) + delta[i]
                # error-compensated requant: per-block scale search keeps
                # the merged model close to the attached-adapter model
                merged.append(qcore.quantize(np.asarray(w), base.qtype,
                                             base.block_size or None,
                                             optimize=True))
            layers[slot] = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *merged
            )
        else:
            layers[slot] = (base.astype(jnp.float32) + delta).astype(base.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def make_qlora_train_step(cfg: ModelConfig, optimizer, lora_cfg: LoraConfig,
                          loss_fn=None):
    """Jitted ``step(adapters, opt_state, tokens, base_params)``.

    Gradients flow ONLY into the adapter pytree; the quantized base rides
    along as a closed-over constant input (frozen by construction, the
    ``requires_grad=False`` of the reference's prepare_model_for_kbit_training).
    """
    import optax

    from ipex_llm_tpu.training.step import causal_lm_loss

    loss_fn = loss_fn or causal_lm_loss

    def lora_loss(adapters, tokens, base_params):
        p = attach_lora(base_params, adapters, lora_cfg)
        return loss_fn(cfg, p, tokens)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(adapters, opt_state, tokens, base_params):
        loss, grads = jax.value_and_grad(lora_loss)(adapters, tokens,
                                                    base_params)
        updates, opt_state = optimizer.update(grads, opt_state, adapters)
        adapters = optax.apply_updates(adapters, updates)
        return adapters, opt_state, loss

    return step


# ---------------------------------------------------------------------------
# model-level convenience (the reference get_peft_model shape)
# ---------------------------------------------------------------------------


class PeftModel:
    """Thin trainable wrapper (reference qlora.py:254 ``get_peft_model``)."""

    def __init__(self, model, lora_cfg: LoraConfig, seed: int = 0):
        self.model = model
        self.lora_cfg = lora_cfg
        self.adapters = init_lora(
            jax.random.PRNGKey(seed), model.config, model.params, lora_cfg
        )
        self._step = None
        self._opt_state = None
        self._optimizer = None

    def compile(self, optimizer):
        self._optimizer = optimizer
        self._opt_state = optimizer.init(self.adapters)
        self._step = make_qlora_train_step(
            self.model.config, optimizer, self.lora_cfg
        )
        return self

    def train_step(self, tokens) -> float:
        self.adapters, self._opt_state, loss = self._step(
            self.adapters, self._opt_state, jnp.asarray(tokens),
            self.model.params,
        )
        return float(loss)

    def merge_and_unload(self):
        self.model.params = merge_lora(self.model.params, self.adapters,
                                       self.lora_cfg)
        return self.model


def get_peft_model(model, lora_cfg: LoraConfig, seed: int = 0) -> PeftModel:
    return PeftModel(model, lora_cfg, seed)
