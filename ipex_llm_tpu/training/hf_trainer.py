"""HF-Trainer-compatible bridge (reference training_patch.py:68-223 +
docs/mddocs/Quickstart/axolotl_quickstart.md).

The reference patches ``transformers.Trainer`` so existing finetune recipes
run on XPU.  Here the same recipe surface — ``Trainer(model, args,
train_dataset, data_collator)`` with HF ``TrainingArguments`` — drives the
TPU-native step functions instead: QLoRA adapters (training/qlora.py) when
given a ``PeftModel``, full-parameter bf16 training otherwise.  Batches pad
to power-of-two length buckets so XLA compiles a handful of step programs,
not one per sequence length.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Iterable

import jax
import jax.numpy as jnp
import numpy as np


def _bucket(n: int, lo: int = 32) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _get(args: Any, name: str, default):
    v = getattr(args, name, default)
    return default if v is None else v


def _lr_schedule(args: Any, total_steps: int):
    import optax

    lr = float(_get(args, "learning_rate", 5e-5))
    warmup = int(_get(args, "warmup_steps", 0))
    kind = str(_get(args, "lr_scheduler_type", "linear"))
    if warmup:
        ramp = optax.linear_schedule(0.0, lr, warmup)
    if "cosine" in kind:
        tail = optax.cosine_decay_schedule(lr, max(total_steps - warmup, 1))
    elif "constant" in kind:
        tail = optax.constant_schedule(lr)
    else:  # linear decay, the HF default
        tail = optax.linear_schedule(lr, 0.0, max(total_steps - warmup, 1))
    if warmup:
        return optax.join_schedules([ramp, tail], [warmup])
    return tail


class TPUTrainer:
    """Drop-in for the ``transformers.Trainer`` finetune surface.

    model: ``TPUModelForCausalLM`` (full bf16 training) or
    ``training.qlora.PeftModel`` (QLoRA adapters over the frozen quantized
    base — the reference's get_peft_model flow).
    """

    def __init__(self, model, args=None, train_dataset=None,
                 data_collator=None, tokenizer=None, optimizers=(None, None),
                 **kwargs: Any):
        self.model = model
        self.args = args
        self.train_dataset = train_dataset
        self.data_collator = data_collator
        self.tokenizer = tokenizer
        self._optimizer = optimizers[0]
        self.state_log: list[dict] = []

    # -- data ---------------------------------------------------------------

    def _batches(self) -> Iterable[np.ndarray]:
        """Yield (tokens [B, T], mask [B, T]) per step, padded to buckets."""
        bsz = int(_get(self.args, "per_device_train_batch_size", 4))
        seed = int(_get(self.args, "seed", 0))
        data = list(self.train_dataset)
        order = np.random.default_rng(seed).permutation(len(data))
        for s in range(0, len(data) - bsz + 1, bsz):
            rows = [data[int(i)] for i in order[s:s + bsz]]
            if self.data_collator is not None:
                feats = self.data_collator(rows)
                ids = np.asarray(feats["input_ids"])
                labels = np.asarray(
                    feats.get("labels", feats["input_ids"]))
            else:
                seqs = [np.asarray(r["input_ids"]).reshape(-1) for r in rows]
                lab = [np.asarray(r.get("labels", r["input_ids"])).reshape(-1)
                       for r in rows]
                t = _bucket(max(len(x) for x in seqs))
                ids = np.zeros((bsz, t), np.int64)
                labels = np.full((bsz, t), -100, np.int64)
                for j, (q, l) in enumerate(zip(seqs, lab)):
                    ids[j, : len(q)] = q
                    labels[j, : len(l)] = l
            t = _bucket(ids.shape[1])
            if ids.shape[1] != t:
                pad = t - ids.shape[1]
                ids = np.pad(ids, ((0, 0), (0, pad)))
                labels = np.pad(labels, ((0, 0), (0, pad)),
                                constant_values=-100)
            yield ids.astype(np.int32), (labels != -100).astype(np.float32)

    def _n_steps(self) -> int:
        bsz = int(_get(self.args, "per_device_train_batch_size", 4))
        per_epoch = max(len(self.train_dataset) // bsz, 1)
        max_steps = int(_get(self.args, "max_steps", -1))
        if max_steps and max_steps > 0:
            return max_steps
        return per_epoch * int(_get(self.args, "num_train_epochs", 1))

    # -- training -----------------------------------------------------------

    def _build(self, total_steps: int):
        import optax

        from ipex_llm_tpu.training.qlora import (PeftModel,
                                                 make_qlora_train_step)
        from ipex_llm_tpu.training.step import (causal_lm_loss,
                                                make_train_step)

        opt = self._optimizer or optax.adamw(
            _lr_schedule(self.args, total_steps),
            weight_decay=float(_get(self.args, "weight_decay", 0.0)),
        )

        # the step fns take one `tokens` pytree: pack (ids, mask) and let
        # the loss unpack, so the HF labels==-100 convention flows through
        def masked_loss(cfg, params, pack):
            ids, mask = pack
            return causal_lm_loss(cfg, params, ids, loss_mask=mask[:, 1:])

        if isinstance(self.model, PeftModel):
            step = make_qlora_train_step(self.model.model.config, opt,
                                         self.model.lora_cfg,
                                         loss_fn=masked_loss)
            train_tree = self.model.adapters

            def run(tree, opt_state, ids, mask):
                return step(tree, opt_state, (ids, mask),
                            self.model.model.params)

            def commit(tree):
                self.model.adapters = tree
        else:
            step = make_train_step(self.model.config, opt,
                                   loss_fn=masked_loss)
            train_tree = self.model.params

            def run(tree, opt_state, ids, mask):
                return step(tree, opt_state, (ids, mask))

            def commit(tree):
                self.model.params = tree
        return opt, train_tree, run, commit

    def train(self):
        total = self._n_steps()
        opt, tree, run, commit = self._build(total)
        opt_state = opt.init(tree)
        log_every = int(_get(self.args, "logging_steps", 10)) or 10
        out_dir = _get(self.args, "output_dir", None)
        save_steps = int(_get(self.args, "save_steps", 0) or 0)
        epochs = int(_get(self.args, "num_train_epochs", 1))

        n = 0
        t0 = time.perf_counter()
        done = False
        for _ in range(max(epochs, 1)):
            if done:
                break
            for ids, mask in self._batches():
                tree, opt_state, loss = run(tree, opt_state,
                                            jnp.asarray(ids),
                                            jnp.asarray(mask))
                n += 1
                if n % log_every == 0 or n == total:
                    rec = {"step": n, "loss": float(loss),
                           "elapsed_s": round(time.perf_counter() - t0, 2)}
                    self.state_log.append(rec)
                    print(f"step {n}/{total} loss {rec['loss']:.4f}")
                if save_steps and out_dir and n % save_steps == 0:
                    commit(tree)
                    self.save_model(os.path.join(out_dir,
                                                 f"checkpoint-{n}"))
                if n >= total:
                    done = True
                    break
        commit(tree)
        if out_dir:
            self.save_model(out_dir)
        return {"global_step": n,
                "train_loss": (self.state_log[-1]["loss"]
                               if self.state_log else float("nan"))}

    def save_model(self, output_dir: str):
        os.makedirs(output_dir, exist_ok=True)
        from ipex_llm_tpu.training.qlora import PeftModel

        if isinstance(self.model, PeftModel):
            # adapters-only checkpoint, the peft convention
            from ipex_llm_tpu.training.checkpoint import TrainCheckpointer

            TrainCheckpointer(os.path.abspath(output_dir)).save(
                0, self.model.adapters, wait=True)
        else:
            self.model.save_low_bit(output_dir)


def patch_transformers_trainer():
    """One-line recipe port (the llm_patch(train=True) companion,
    reference llm_patching.py:35-71): existing code that builds a
    ``transformers.Trainer`` gets this TPU trainer instead when the model
    is one of ours."""
    import transformers

    orig = transformers.Trainer

    class _Switch:
        def __new__(cls, model=None, *a, **kw):
            from ipex_llm_tpu.training.qlora import PeftModel
            from ipex_llm_tpu.transformers.model import TPUModelForCausalLM

            if isinstance(model, (PeftModel, TPUModelForCausalLM)):
                return TPUTrainer(model, *a, **kw)
            return orig(model, *a, **kw)

    transformers.Trainer = _Switch
    return orig
