"""LISA: layerwise importance sampling — train a random layer subset.

Reference counterpart: ``DynamicLayerActivationCallback`` (reference
lisa.py:23): every ``interval`` steps freeze all decoder layers, then
unfreeze ``n_layers`` randomly chosen ones (embed/head stay trainable).

TPU-native: our layers are ONE stacked pytree ``[L, ...]``, so
(un)freezing is a gradient mask over the leading axis — no module
iteration, and the jitted train step never recompiles when the active set
changes (the mask is a traced input).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ipex_llm_tpu.models.config import ModelConfig


def sample_active_layers(key: jax.Array, num_layers: int,
                         n_active: int) -> jnp.ndarray:
    """Boolean mask [L] with exactly ``n_active`` True entries."""
    perm = jax.random.permutation(key, num_layers)
    return jnp.zeros((num_layers,), bool).at[perm[:n_active]].set(True)


def mask_layer_grads(grads: dict, layer_mask: jnp.ndarray) -> dict:
    """Zero gradients of frozen layers; embed/head/final_norm untouched
    (the reference always keeps embedding + lm_head active, lisa.py:32)."""

    def mask_leaf(g):
        if getattr(g, "ndim", 0) >= 1 and g.shape[0] == layer_mask.shape[0]:
            shape = (-1,) + (1,) * (g.ndim - 1)
            return g * layer_mask.reshape(shape).astype(g.dtype)
        return g

    out = dict(grads)
    out["layers"] = jax.tree_util.tree_map(mask_leaf, grads["layers"])
    return out


def make_lisa_train_step(cfg: ModelConfig, optimizer, loss_fn=None):
    """Jitted ``step(params, opt_state, tokens, layer_mask)``."""
    import optax

    from ipex_llm_tpu.training.step import causal_lm_loss

    loss_fn = loss_fn or causal_lm_loss

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, layer_mask):
        from ipex_llm_tpu.training.step import freeze_buffer_updates

        loss, grads = jax.value_and_grad(loss_fn, argnums=1)(cfg, params,
                                                             tokens)
        grads = mask_layer_grads(grads, layer_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, freeze_buffer_updates(updates))
        return params, opt_state, loss

    return step


class LisaTrainer:
    """Step-driven trainer resampling the active layer set every interval
    (reference lisa.py:23 ``DynamicLayerActivationCallback``)."""

    def __init__(self, model, optimizer, n_active_layers: int = 2,
                 interval: int = 20, seed: int = 0):
        self.model = model
        self.optimizer = optimizer
        self.n_active = n_active_layers
        self.interval = interval
        self.key = jax.random.PRNGKey(seed)
        self.opt_state = optimizer.init(model.params)
        self._step_fn = make_lisa_train_step(model.config, optimizer)
        self.step_count = 0
        self._resample()

    def _resample(self):
        self.key, sub = jax.random.split(self.key)
        self.layer_mask = sample_active_layers(
            sub, self.model.config.num_layers, self.n_active
        )

    def step(self, tokens) -> float:
        if self.step_count and self.step_count % self.interval == 0:
            self._resample()
        self.model.params, self.opt_state, loss = self._step_fn(
            self.model.params, self.opt_state, jnp.asarray(tokens),
            self.layer_mask,
        )
        self.step_count += 1
        return float(loss)
