"""ReLoRA: periodic merge-and-reset of LoRA adapters into the base.

Reference counterpart: ``ReLoRATrainer``/``ReLoRACallback``/``ReLoRAScheduler``
(reference relora.py:64,149,286): every ``relora_steps`` the adapters are
merged into the base weights, re-initialized, the optimizer state for the
adapters is (mostly) zeroed, and the LR follows a jagged-cosine restart
schedule.  Functional TPU version: the trainer object owns no modules —
merge/reset are pure pytree transforms.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.training.qlora import (
    LoraConfig,
    init_lora,
    make_qlora_train_step,
    merge_lora,
)


def jagged_cosine_schedule(base_lr: float, total_steps: int,
                           restart_every: int, warmup: int = 10,
                           min_ratio: float = 0.1):
    """Reference ReLoRAScheduler (relora.py:286): cosine with hard restarts,
    each restart preceded by a short linear re-warmup."""

    def lr(step):
        step = jnp.asarray(step)
        in_cycle = step % restart_every
        cos = 0.5 * (1 + jnp.cos(jnp.pi * step / max(total_steps, 1)))
        scale = min_ratio + (1 - min_ratio) * cos
        rewarm = jnp.where(
            step < restart_every,  # first cycle has no restart warmup
            1.0,
            jnp.minimum(1.0, in_cycle / max(warmup, 1)),
        )
        return base_lr * scale * rewarm

    return lr


@dataclass
class ReLoRATrainer:
    """Minimal step-driven trainer with merge-and-reset every N steps."""

    model: object
    lora_cfg: LoraConfig
    optimizer: object
    relora_steps: int = 100
    seed: int = 0

    def __post_init__(self):
        self.adapters = init_lora(
            jax.random.PRNGKey(self.seed), self.model.config,
            self.model.params, self.lora_cfg,
        )
        self.opt_state = self.optimizer.init(self.adapters)
        self._step_fn = make_qlora_train_step(
            self.model.config, self.optimizer, self.lora_cfg
        )
        self.step_count = 0

    def step(self, tokens) -> float:
        self.adapters, self.opt_state, loss = self._step_fn(
            self.adapters, self.opt_state, jnp.asarray(tokens),
            self.model.params,
        )
        self.step_count += 1
        if self.step_count % self.relora_steps == 0:
            self.merge_and_reset()
        return float(loss)

    def merge_and_reset(self):
        """Fold adapters into the base, re-init adapters, reset their
        optimizer state (reference relora.py:149 on_step_begin)."""
        self.model.params = merge_lora(
            self.model.params, self.adapters, self.lora_cfg
        )
        self.seed += 1
        self.adapters = init_lora(
            jax.random.PRNGKey(self.seed), self.model.config,
            self.model.params, self.lora_cfg,
        )
        self.opt_state = self.optimizer.init(self.adapters)
