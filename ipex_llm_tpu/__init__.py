"""ipex_llm_tpu — a TPU-native LLM acceleration framework.

Capability peer of the reference `ipex-llm` (Intel's low-bit LLM library for
XPU/NPU/CPU, see /root/reference/python/llm/src/ipex_llm/__init__.py), rebuilt
from scratch and idiomatically for TPU on JAX/XLA/Pallas:

- block-quantized weights (INT4/INT5/INT8/NF4/NF3/FP4/FP6/FP8, GGUF k-quants)
  stored as packed arrays in a JAX pytree (``QTensor``), instead of the
  reference's ggml C blobs (reference: ggml/quantize.py, low_bit_linear.py);
- a Pallas kernel library for the hot ops (fused dequant-matmul, flash SDPA
  with fp8 KV, fused RoPE, RMS/LayerNorm, MoE routing) replacing the SYCL
  ``xe_linear``/``xe_batch``/``xe_addons`` extensions (reference §2.3);
- native JAX model definitions driven by HF checkpoints as a *weight source*
  rather than monkey-patched torch forwards (reference: transformers/convert.py);
- mesh-based tensor/pipeline/expert/context parallelism over ICI/DCN through
  ``jax.sharding`` (replacing DeepSpeed-AutoTP + oneCCL, reference §2.2).

Public API mirrors the reference's compatibility contract:

    from ipex_llm_tpu import optimize_model
    from ipex_llm_tpu.transformers import AutoModelForCausalLM
    model = AutoModelForCausalLM.from_pretrained(path, load_in_low_bit="sym_int4")
    out = model.generate(input_ids, max_new_tokens=32)
"""

__version__ = "0.1.0"

__all__ = ["optimize_model", "load_low_bit", "low_memory_init",
           "llm_patch", "llm_unpatch", "__version__"]


def _init_compilation_cache() -> None:
    """Point JAX at a persistent on-disk compilation cache.

    The reference's users get first tokens in seconds because SYCL kernels
    are prebuilt; XLA instead compiles per (shape-bucket, capacity) — ~2 min
    cold for a 7B decode program.  A persistent cache makes every process
    after the first start warm.  Opt out / relocate with
    IPEX_LLM_TPU_COMPILE_CACHE (empty string disables); an explicit
    ``jax.config`` setting by the user wins because this only fills the
    default in via env, which jax reads at first use.
    """
    import os

    path = os.environ.get(
        "IPEX_LLM_TPU_COMPILE_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "ipex_llm_tpu", "xla_cache",
        ),
    )
    if not path:
        return
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", path)
    # cache every compilation regardless of compile time / program size
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    import sys

    if "jax" in sys.modules:  # jax read its env already: set via config API
        import jax

        try:
            if jax.config.jax_compilation_cache_dir is None:
                jax.config.update("jax_compilation_cache_dir", path)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.1
                )
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", 0
                )
        except Exception:  # never let cache setup break import
            pass
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        pass


_init_compilation_cache()


def __getattr__(name):
    # lazy: keep `import ipex_llm_tpu` light (no jax trace-time cost) the way
    # the reference keeps its top-level import side-effect free apart from the
    # IPEX auto-import shim (reference: __init__.py:33-47).
    if name in ("optimize_model", "load_low_bit", "low_memory_init"):
        from ipex_llm_tpu import optimize

        return getattr(optimize, name)
    if name in ("llm_patch", "llm_unpatch"):
        from ipex_llm_tpu import llm_patching

        return getattr(llm_patching, name)
    raise AttributeError(name)
