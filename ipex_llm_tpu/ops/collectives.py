"""Quantized cross-chip collectives (the EQuARX family, arxiv 2506.17615).

Decode under tensor parallelism is ALLREDUCE-BOUND: every layer pays two
AllReduces (the o- and down-projection row-parallel combines), each moving
``hidden * rows`` values over ICI while the MXU sits idle.  EQuARX shows
that quantizing the AllReduce PAYLOAD — narrow codes on the wire, full-
precision accumulation at every hop — recovers most of that bandwidth at a
bounded accuracy cost.  This module is that family for the manual-mesh
programs (parallel/manual.py, parallel/pipeline.py): ONE entry point per
collective with a ``qtype`` axis, so call sites select wire width per op
instead of hard-coding a promotion.

Families (``ALLREDUCE_QTYPES``):

- ``"bf16"`` — the EXACT family and the default: partial sums ride at f32
  and accumulate in f32 (``psum_exact``), so a tp-sharded program is
  bit-stable against its single-chip twin at the bf16 output width — the
  tp2==tp1 bit-identity gate runs on this family.  (The name records the
  TENSOR width being reduced; the wire carries the f32 partials, exactly
  what the pre-family code promoted to.)
- ``"e5m2"`` — fp8(e5m2) codes on the wire (4x narrower than f32), f32
  accumulate: pure-rounding loss, no scale bookkeeping.
- ``"int8"`` — blockwise symmetric int8: per-(row-block) f16 scales ride
  beside the codes (EQuARX's block layout), f32 dequant-accumulate.

CPU note, formerly pipeline.py's blanket workaround: XLA:CPU's
AllReducePromotion pass check-fails cloning a sub-f32 all-reduce inside a
partial-auto shard_map region, so every family keeps its on-wire payload
at a promotion-proof dtype on CPU meshes (quantization still happens — the
values are coded and decoded, so the ERROR model is the real one — only
the emulated wire width is f32).  On TPU backends the payload dtypes are
the real ones.  That platform fork lives HERE, inside the family, not at
call sites.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

ALLREDUCE_QTYPES = ("bf16", "e5m2", "int8")

# int8 family: contraction-block size for the per-block scales (the EQuARX
# block layout; small enough to track outliers, large enough that scale
# bytes are <2% of payload)
_INT8_BLOCK = 64


def psum_exact(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """AllReduce with f32 accumulation, returned at ``x.dtype``.

    The exact family's primitive, and the one definition of the CPU
    AllReducePromotion workaround (XLA:CPU check-fails cloning a bf16
    all-reduce inside a partial-auto region): sub-f32 payloads promote to
    f32 BEFORE the psum on every backend — on TPU that is also the
    numerically-right call, f32 accumulation is how the MXU reduces.
    """
    dt = x.dtype
    if dt in (jnp.float32, jnp.float64):
        return jax.lax.psum(x, axis_name)
    return jax.lax.psum(x.astype(jnp.float32), axis_name).astype(dt)


# e5m2's largest finite value: casting anything beyond it yields inf,
# which an AllReduce then spreads over the whole hidden state — saturate
# instead (a clipped outlier is bounded error, an inf is not)
_E5M2_MAX = 57344.0


def _e5m2_code(x32: jnp.ndarray) -> jnp.ndarray:
    """Quantize the payload to fp8 e5m2 codes, decode back to f32 (the
    per-hop dequant-accumulate model), saturating at the format's finite
    max.  On CPU the coded values ride an f32 wire (promotion-proof
    emulation, same error); on TPU the psum payload itself can stay
    e5m2-width upstream of accumulation."""
    x32 = jnp.clip(x32, -_E5M2_MAX, _E5M2_MAX)
    return x32.astype(jnp.float8_e5m2).astype(jnp.float32)


def _int8_code(x32: jnp.ndarray) -> jnp.ndarray:
    """Blockwise symmetric int8 code/decode along the last axis: values in
    each ``_INT8_BLOCK``-wide block share one f16 amax scale.  The scale
    saturates at f16's finite max (65504): an amax beyond scale*127 would
    otherwise round the scale to inf and decode the whole block to
    0*inf = NaN — saturation clips the outliers to ±127*65504 instead,
    bounded error rather than poison."""
    shape = x32.shape
    n = shape[-1]
    bs = _INT8_BLOCK if n % _INT8_BLOCK == 0 else n
    blocks = x32.reshape(*shape[:-1], n // bs, bs)
    amax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scale = jnp.clip(amax / 127.0, 0.0, 65504.0)
    scale = scale.astype(jnp.float16).astype(jnp.float32)
    scale = jnp.where(scale == 0.0, 1.0, scale)
    codes = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return (codes.astype(jnp.float32) * scale).reshape(shape)


def all_reduce(x: jnp.ndarray, axis_name: str, qtype: str = "bf16",
               out_dtype=None) -> jnp.ndarray:
    """The per-op AllReduce entry: reduce ``x`` (a per-shard partial sum,
    any float dtype) over ``axis_name`` under the ``qtype`` wire family.

    Accumulation is ALWAYS f32 (every family); ``qtype`` chooses what the
    wire carries.  Returns ``out_dtype`` (default ``x.dtype``).
    """
    out_dtype = out_dtype or x.dtype
    x32 = x.astype(jnp.float32)
    if qtype == "bf16":
        y = jax.lax.psum(x32, axis_name)
    elif qtype == "e5m2":
        y = jax.lax.psum(_e5m2_code(x32), axis_name)
    elif qtype == "int8":
        y = jax.lax.psum(_int8_code(x32), axis_name)
    else:
        raise ValueError(
            f"unknown collective qtype {qtype!r}: valid families are "
            f"{ALLREDUCE_QTYPES}")
    return y.astype(out_dtype)


# --------------------------------------------------------------------------
# measured family ladder
# --------------------------------------------------------------------------
#
# Like ops/dispatch's pallas-vs-xla ladder, the collective family choice is
# DATA-DRIVEN where it is a pure-speed call: the table records measured
# per-call microseconds for one decode-shaped AllReduce per family
# (benchmark/microbench.py::bench_collectives refreshes it; the builtin
# snapshot is the repo's latest CPU-mesh round).  Unlike the kernel ladder,
# speed alone may not pick a LOSSY family — quantized wires change
# numerics — so resolution is:
#
#   1. an explicit request (EngineConfig.collective_qtype or the
#      IPEX_LLM_TPU_COLLECTIVE_QTYPE env) always wins;
#   2. otherwise the EXACT family ("bf16") stands, whatever the ladder
#      says — operators opt INTO bounded error, it is never inferred.
#
# The ladder's role without an override is observability: bench_tp_scaling
# reports the measured family costs beside the tok/s rows so the operator
# can see what switching buys before flipping the flag.
_BUILTIN_COLLECTIVE_LADDER: dict[str, dict[str, float]] = {
    # CPU 8-virtual-device mesh, tp=4, [8, 4096] f32-equivalent payload
    # (BENCH_r14 round; microbench bench_collectives).  On the emulated
    # CPU wire the quantized families pay their code/decode arithmetic
    # without any byte saving, so bf16-exact winning here is expected —
    # the table exists so that call is DATA, not a guess.
    "cpu": {"bf16": 517.3, "e5m2": 461.5, "int8": 664.4},
    "tpu": {},
}


def ladder() -> dict[str, float]:
    """Measured per-call us for each AllReduce family on this backend."""
    from ipex_llm_tpu.ops.dispatch import backend_platform

    return _BUILTIN_COLLECTIVE_LADDER.get(backend_platform(), {})


def resolve_qtype(requested: str | None = None) -> str:
    """The family an op should use: explicit request (argument, then the
    IPEX_LLM_TPU_COLLECTIVE_QTYPE env) or the exact default."""
    q = requested or os.environ.get("IPEX_LLM_TPU_COLLECTIVE_QTYPE") or "bf16"
    if q not in ALLREDUCE_QTYPES:
        raise ValueError(
            f"unknown collective qtype {q!r}: valid families are "
            f"{ALLREDUCE_QTYPES}")
    return q
