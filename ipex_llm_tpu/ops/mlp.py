"""Fused gated MLP activations.

Reference counterparts: ``xe_linear.mlp_forward_xpu`` (fused gate/up + act,
models/common.py:146-170) and ``xe_addons.mlp_silu_mul_inplaced`` (§2.3).
On TPU the activation+multiply fuses into the surrounding quantized matmuls
under XLA, so the jnp composition below compiles to the same fused program
the reference hand-wrote in SYCL; merged gate_up weights (one matmul instead
of two) are handled at model-build time like the reference's `_optimize_pre`
qkv/gate-up merges (convert.py:890).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

ACT_FNS = {
    "silu": jax.nn.silu,
    # HF "gelu" is the exact erf form; jax.nn.gelu defaults to tanh-approx
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
    # CLIP/qwen2-vl vision towers
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),
}


def act(x: jnp.ndarray, name: str = "gelu_new") -> jnp.ndarray:
    """Plain activation (non-gated MLPs: phi/gpt-neox/starcoder2)."""
    return ACT_FNS[name](x.astype(jnp.float32)).astype(x.dtype)


def gated_act_mul(gate: jnp.ndarray, up: jnp.ndarray, act: str = "silu") -> jnp.ndarray:
    """act(gate) * up — the SwiGLU/GeGLU core."""
    return ACT_FNS[act](gate.astype(jnp.float32)).astype(up.dtype) * up


def split_gate_up(gate_up: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split a merged gate_up projection output into (gate, up)."""
    d = gate_up.shape[-1] // 2
    return gate_up[..., :d], gate_up[..., d:]
