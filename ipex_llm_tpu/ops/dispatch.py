"""Backend dispatch for the op library.

Every hot op has two implementations:
  - a Pallas TPU kernel (the ``xe_linear``/``xe_addons`` equivalent, §2.3), and
  - a pure-jnp XLA reference (the reference's CPU-fallback pattern,
    models/common.py:289-306), which doubles as the test oracle.

Selection is per-process: Pallas on TPU backends, jnp elsewhere, overridable
with IPEX_LLM_TPU_DISABLE_PALLAS=1 (mirrors the reference's env-flag style,
SURVEY.md §5 config system).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax


# When a >1-device mesh drives the model, the compute path must stay at the
# XLA/GSPMD level: a bare ``pallas_call`` inside ``jit`` does not partition
# under sharding propagation (it would need a shard_map wrapper).  The
# generate/forward drivers flip this flag while tracing sharded programs.
_spmd_active: bool = False


def set_spmd(active: bool) -> None:
    global _spmd_active
    _spmd_active = bool(active)


from contextlib import contextmanager


@contextmanager
def spmd(active: bool):
    """Scoped SPMD flag that restores the previous value (nesting-safe)."""
    global _spmd_active
    prev = _spmd_active
    _spmd_active = bool(active) or prev
    try:
        yield
    finally:
        _spmd_active = prev


# Context-parallel ring attention (ops/ring_attention.py): set by the
# training/prefill caller that guarantees full-sequence causal semantics
# (no left-pad, no sliding window).  None = dense attention.
_ring_mesh = None


def ring_mesh():
    return _ring_mesh


@contextmanager
def ring(mesh):
    """Scoped context-parallel mesh for sdpa dispatch."""
    global _ring_mesh
    prev = _ring_mesh
    _ring_mesh = mesh
    try:
        yield
    finally:
        _ring_mesh = prev


def use_pallas() -> bool:
    if _spmd_active:
        return False
    return _use_pallas_env()


@lru_cache(maxsize=None)
def _use_pallas_env() -> bool:
    if os.environ.get("IPEX_LLM_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def clear_cache() -> None:
    _use_pallas_env.cache_clear()
