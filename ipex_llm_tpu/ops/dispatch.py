"""Backend dispatch for the op library.

Every hot op has two implementations:
  - a Pallas TPU kernel (the ``xe_linear``/``xe_addons`` equivalent, §2.3), and
  - a pure-jnp XLA reference (the reference's CPU-fallback pattern,
    models/common.py:289-306), which doubles as the test oracle.

Selection is per-process: Pallas on TPU backends, jnp elsewhere, overridable
with IPEX_LLM_TPU_DISABLE_PALLAS=1 (mirrors the reference's env-flag style,
SURVEY.md §5 config system).
"""

from __future__ import annotations

import json
import os
import threading
from functools import lru_cache

import jax


# When a >1-device mesh drives the model, a bare ``pallas_call`` inside
# ``jit`` does not partition under GSPMD sharding propagation — kernels must
# be wrapped in ``jax.shard_map`` with per-shard block specs.  The generate/
# forward drivers record the active mesh here; the op dispatchers use it to
# emit shard_map-wrapped kernel calls (ops/pallas/*::*_sharded), falling back
# to the jnp/GSPMD path when no sharded wrapper applies.
_spmd_mesh = None
_spmd_active: bool = False


from contextlib import contextmanager


@contextmanager
def spmd(mesh_or_active):
    """Scoped SPMD context (nesting-safe).

    Pass the active ``jax.sharding.Mesh`` so kernel dispatch can emit
    shard_map-wrapped Pallas calls; a bare ``True`` marks SPMD tracing with
    an unknown mesh (kernels then fall back to the jnp path, the pre-r3
    behaviour).  Falsy values are a no-op passthrough.
    """
    global _spmd_mesh, _spmd_active
    prev_mesh, prev_active = _spmd_mesh, _spmd_active
    if mesh_or_active is None or mesh_or_active is False:
        pass
    elif mesh_or_active is True:
        # unknown mesh: kernels must fall back to jnp, so the outer scope's
        # recorded mesh must not leak into this scope
        _spmd_mesh = None
        _spmd_active = True
    else:
        _spmd_mesh = mesh_or_active
        _spmd_active = True
    try:
        yield
    finally:
        _spmd_mesh, _spmd_active = prev_mesh, prev_active


def spmd_mesh():
    """The mesh recorded by the innermost ``spmd(mesh)`` scope (or None)."""
    return _spmd_mesh


# Manual-mesh tensor parallelism (parallel/manual.py): INSIDE the fully-
# manual shard_map region the arrays are per-shard slices and GSPMD sees
# nothing, so dispatch must treat the trace as single-device compute with
# EXPLICIT collectives at the row-parallel combine points.  The record is
# (axis_name, collective_qtype): ops/linear.py reads it to psum row-
# parallel partials through ops/collectives.py under the engine's wire
# family, and models/decoder.logits_tail reads it to all-gather the
# vocab-sharded logits before sampling.
_manual_tp = threading.local()   # thread-local: engines trace on their
# own threads, and a mesh-slice fleet runs several in one process — a
# plain global would leak one engine's manual marker into a concurrent
# non-manual trace


@contextmanager
def manual_tp(axis: str, collective_qtype: str = "bf16"):
    """Scoped manual-TP marker for code tracing INSIDE a fully-manual
    shard_map region (mutually exclusive with ``spmd`` — the manual tick
    never enters the GSPMD dispatch path)."""
    prev = getattr(_manual_tp, "state", None)
    _manual_tp.state = (axis, collective_qtype)
    try:
        yield
    finally:
        _manual_tp.state = prev


def manual_tp_state() -> tuple[str, str] | None:
    """(axis_name, collective_qtype) inside a manual-TP region, else None."""
    return getattr(_manual_tp, "state", None)


# Context-parallel ring attention (ops/ring_attention.py): set by the
# training/prefill caller that guarantees full-sequence causal semantics
# (no left-pad, no sliding window).  None = dense attention.
_ring_mesh = None


def ring_mesh():
    return _ring_mesh


@contextmanager
def ring(mesh):
    """Scoped context-parallel mesh for sdpa dispatch."""
    global _ring_mesh
    prev = _ring_mesh
    _ring_mesh = mesh
    try:
        yield
    finally:
        _ring_mesh = prev


# --------------------------------------------------------------------------
# measured-ladder policy
# --------------------------------------------------------------------------
#
# The auto backend choice is DATA-DRIVEN from the microbench ladder
# (benchmark/microbench.py collect(): pallas_us vs xla_us per op family,
# per backend).  The builtin snapshot below records the repo's latest
# measured rounds; operators can re-point it at a fresh collect() dump via
# IPEX_LLM_TPU_DISPATCH_LADDER=/path/to/microbench.json (either the raw
# collect() row list or the {"backend": {op: {...}}} table form).  Env
# overrides (FORCE/DISABLE) always outrank the ladder.
#
# cpu: Pallas runs in the INTERPRETER, which the ladder shows losing to
# the XLA reference on every decode-path op (BENCH_r05: decode_attn
# 539.9us interpret vs 267.7us XLA bf16; 561.1 vs 493.2 fp8) — so the CPU
# auto policy selects XLA and interpret-mode stays opt-in via
# IPEX_LLM_TPU_FORCE_PALLAS=1.  tpu: compiled kernels beat the fallback
# on the same ladder points (the r01-r04 on-chip rounds, snapshotted
# below); an op family with no recorded pair falls back to the platform
# default.  A fresh on-chip collect() dump pointed at via
# IPEX_LLM_TPU_DISPATCH_LADDER keys under "tpu" automatically (its rows
# carry no "interpret" flag), replacing this snapshot wholesale.
_BUILTIN_LADDER: dict[str, dict[str, dict[str, object]]] = {
    # every row carries a "recorded" bench-round stamp (surfaced via
    # ladder_provenance() in /health's dispatch block): the decision a
    # row drives is only as fresh as the round that measured it, and a
    # stale ladder should be VISIBLE, not silently trusted
    "cpu": {   # interpret-mode records, BENCH_r05 (+ the r06 ragged rows)
        "decode_attn": {"pallas_us": 539.9, "xla_us": 267.7,
                        "recorded": "BENCH_r05"},
        "decode_attn_fp8": {"pallas_us": 561.1, "xla_us": 493.2,
                            "recorded": "BENCH_r05"},
        "paged_decode_attn": {"pallas_us": 540.0, "xla_us": 268.0,
                              "recorded": "BENCH_r05"},
        "paged_decode_attn_fp8": {"pallas_us": 561.0, "xla_us": 493.0,
                                  "recorded": "BENCH_r05"},
        "ragged_attn": {"pallas_us": 540.0, "xla_us": 268.0,
                        "recorded": "BENCH_r06"},
        "ragged_attn_fp8": {"pallas_us": 561.0, "xla_us": 493.0,
                            "recorded": "BENCH_r06"},
        # fused dequant-matmul, decode shape (M=1, the serving weight
        # read): BENCH_r12 interpret rows — the XLA block-dequant path
        # wins at every M in 1..8 (M=1: 64.1 vs 15.1us; M=8: 40.2 vs
        # 30.3us), so an int4-weight serving engine on CPU provably
        # selects XLA instead of inheriting a blanket platform rule
        "qmatmul_sym_int4": {"pallas_us": 64.1, "xla_us": 15.1,
                             "recorded": "BENCH_r12"},
    },
    # compiled-kernel records from the on-chip rounds (the microbench
    # collect() TPU job list measures exactly these families; op names
    # key through _op_family, so a recorded TPU dump lands on the same
    # slots).  Every pair has Pallas ahead — the MXU-adjacent dequant
    # and the ragged/paged gather fusions are the kernels' reason to
    # exist — but the rows are still consulted per family, so a future
    # round where XLA catches up flips that family alone, measured,
    # instead of arguing with a platform default.
    "tpu": {   # compiled records, BENCH_r01-r04 on-chip rounds
        "qmatmul_sym_int4": {"pallas_us": 18.3, "xla_us": 41.7,
                             "recorded": "BENCH_r01"},
        "decode_attn": {"pallas_us": 71.2, "xla_us": 118.4,
                        "recorded": "BENCH_r02"},
        "decode_attn_fp8": {"pallas_us": 48.9, "xla_us": 116.2,
                            "recorded": "BENCH_r02"},
        "paged_gather": {"pallas_us": 33.1, "xla_us": 76.5,
                         "recorded": "BENCH_r03"},
        "paged_gather_fp8": {"pallas_us": 21.7, "xla_us": 74.8,
                             "recorded": "BENCH_r03"},
        "paged_decode_attn": {"pallas_us": 84.6, "xla_us": 210.3,
                              "recorded": "BENCH_r03"},
        "paged_decode_attn_fp8": {"pallas_us": 55.8, "xla_us": 204.9,
                                  "recorded": "BENCH_r03"},
        "ragged_attn": {"pallas_us": 92.4, "xla_us": 231.8,
                        "recorded": "BENCH_r04"},
        "ragged_attn_fp8": {"pallas_us": 61.2, "xla_us": 228.5,
                            "recorded": "BENCH_r04"},
        "spec_verify": {"pallas_us": 118.6, "xla_us": 152.3,
                        "recorded": "BENCH_r04"},
        "spec_verify_fp8": {"pallas_us": 79.4, "xla_us": 149.1,
                            "recorded": "BENCH_r04"},
    },
}


def _op_family(row_op: str) -> str:
    """Microbench row op name -> ladder family key: strip the shape
    suffixes, keep the dtype axis ('decode_attn_b1_h8/4_s256_d64_float8_
    e5m2' -> 'decode_attn_fp8')."""
    fam = row_op.split("_b", 1)[0].split("_r", 1)[0].split("_m", 1)[0]
    if "float8" in row_op or "fp8" in row_op.rsplit("_", 1)[-1]:
        fam += "_fp8"
    return fam


def _override_stamp(path: str, row: dict | None = None) -> str:
    """Recorded-at provenance for an override-ladder row: the row's own
    bench-round stamp when the dump carries one, else the dump file's
    mtime date — an override is a measurement too, and /health must show
    WHEN it was taken, not just that it exists."""
    if row:
        for key in ("recorded", "round", "bench_round"):
            if row.get(key):
                return str(row[key])
    try:
        import datetime

        mtime = os.path.getmtime(path)
        day = datetime.datetime.fromtimestamp(mtime).date().isoformat()
        return f"override:{os.path.basename(path)}@{day}"
    except OSError:
        return f"override:{os.path.basename(path)}"


@lru_cache(maxsize=1)
def _ladder() -> dict[str, dict[str, dict[str, float]]]:
    path = os.environ.get("IPEX_LLM_TPU_DISPATCH_LADDER", "")
    if not path:
        return _BUILTIN_LADDER
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, list):   # raw microbench collect() rows
        table: dict[str, dict[str, float]] = {}
        for row in data:
            if "pallas_us" in row and "xla_us" in row:
                table[_op_family(row.get("op", ""))] = {
                    "pallas_us": float(row["pallas_us"]),
                    "xla_us": float(row["xla_us"]),
                    "recorded": _override_stamp(path, row)}
        # collect() marks interpret-mode rows, so the dump itself records
        # which backend family it measured: interpret rows = CPU, plain
        # rows = compiled TPU.  Keying on the dump, NOT the loading
        # host's platform, means a TPU-recorded dump inspected on a CPU
        # box lands under "tpu" — CPU lookups miss it and fall back to
        # the platform default (XLA) instead of silently applying TPU
        # wins to the interpreter.
        backend = ("cpu" if any(r.get("interpret") for r in data)
                   else "tpu")
        return {backend: table}
    # table form: stamp any row missing provenance with the file's
    for fams in data.values():
        if isinstance(fams, dict):
            for rec in fams.values():
                if isinstance(rec, dict) and "recorded" not in rec:
                    rec["recorded"] = _override_stamp(path)
    return data


def backend_platform() -> str:
    try:
        return "tpu" if jax.default_backend() in ("tpu", "axon") else "cpu"
    except Exception:
        return "cpu"


def ladder_prefers_pallas(op: str | None) -> bool | None:
    """What the measured ladder says for this op family on this backend:
    True/False when a (pallas_us, xla_us) pair is recorded, None when the
    ladder is silent (caller falls back to the platform default)."""
    if not op:
        return None
    rec = _ladder().get(backend_platform(), {}).get(op)
    if not rec:
        return None
    try:
        return float(rec["pallas_us"]) <= float(rec["xla_us"])
    except (KeyError, TypeError, ValueError):
        return None


def use_pallas(op: str | None = None) -> bool:
    """Kernel eligibility for the *unsharded* (single-device) call form.

    Under SPMD the per-op dispatchers instead consult :func:`spmd_mesh` and
    route through the shard_map-wrapped kernel entry points; a bare kernel
    would not partition, so this returns False while a mesh without a
    sharded wrapper is active.

    ``op`` names the caller's ladder family (e.g. ``"ragged_attn"``): the
    auto policy then picks whichever backend the recorded microbench
    ladder measured faster for that op on this platform, instead of a
    blanket per-platform rule.  Env overrides still win.
    """
    if _spmd_active:
        return False
    return _use_pallas_env(op)


@lru_cache(maxsize=None)
def _use_pallas_env(op: str | None = None) -> bool:
    if os.environ.get("IPEX_LLM_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    if os.environ.get("IPEX_LLM_TPU_FORCE_PALLAS", "0") == "1":
        return True  # kernel testing: interpret-mode Pallas off-TPU
    measured = ladder_prefers_pallas(op)
    if measured is not None:
        return measured
    # ladder silent for this op: platform default — only real TPU
    # backends run the Pallas kernels (on CPU they would execute in the
    # interpreter, which the ladder's recorded rows all measure slower
    # than the XLA reference path)
    return backend_platform() == "tpu"


def use_pallas_sharded(op: str | None = None) -> bool:
    """Kernel eligibility for shard_map-wrapped entry points."""
    return _use_pallas_env(op)


def ladder_provenance() -> dict:
    """The /health ``dispatch`` block: where every Pallas-vs-XLA auto
    decision on THIS platform comes from and when it was measured.

    Per op family: the recorded pair, the winner the pair selects, and
    the ``recorded`` bench-round stamp (builtin rows carry the round that
    measured them — BENCH_r05/r06/r12 as of this writing; an
    ``IPEX_LLM_TPU_DISPATCH_LADDER`` override is stamped from the dump's
    own round field or its file mtime).  ``recorded: "unstamped"`` means
    a hand-edited table with no provenance at all — the loudest kind of
    stale."""
    platform = backend_platform()
    table = _ladder().get(platform, {})
    fams = {}
    for fam, rec in sorted(table.items()):
        try:
            prefers = ("pallas" if float(rec["pallas_us"])
                       <= float(rec["xla_us"]) else "xla")
        except (KeyError, TypeError, ValueError):
            prefers = None
        fams[fam] = {
            "pallas_us": rec.get("pallas_us"),
            "xla_us": rec.get("xla_us"),
            "prefers": prefers,
            "recorded": rec.get("recorded", "unstamped"),
        }
    return {
        "platform": platform,
        "source": (os.environ.get("IPEX_LLM_TPU_DISPATCH_LADDER")
                   or "builtin"),
        "default": "pallas" if platform == "tpu" else "xla",
        "families": fams,
    }


def clear_cache() -> None:
    _use_pallas_env.cache_clear()
    _ladder.cache_clear()
