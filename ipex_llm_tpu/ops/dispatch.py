"""Backend dispatch for the op library.

Every hot op has two implementations:
  - a Pallas TPU kernel (the ``xe_linear``/``xe_addons`` equivalent, §2.3), and
  - a pure-jnp XLA reference (the reference's CPU-fallback pattern,
    models/common.py:289-306), which doubles as the test oracle.

Selection is per-process: Pallas on TPU backends, jnp elsewhere, overridable
with IPEX_LLM_TPU_DISABLE_PALLAS=1 (mirrors the reference's env-flag style,
SURVEY.md §5 config system).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax


# When a >1-device mesh drives the model, a bare ``pallas_call`` inside
# ``jit`` does not partition under GSPMD sharding propagation — kernels must
# be wrapped in ``jax.shard_map`` with per-shard block specs.  The generate/
# forward drivers record the active mesh here; the op dispatchers use it to
# emit shard_map-wrapped kernel calls (ops/pallas/*::*_sharded), falling back
# to the jnp/GSPMD path when no sharded wrapper applies.
_spmd_mesh = None
_spmd_active: bool = False


from contextlib import contextmanager


@contextmanager
def spmd(mesh_or_active):
    """Scoped SPMD context (nesting-safe).

    Pass the active ``jax.sharding.Mesh`` so kernel dispatch can emit
    shard_map-wrapped Pallas calls; a bare ``True`` marks SPMD tracing with
    an unknown mesh (kernels then fall back to the jnp path, the pre-r3
    behaviour).  Falsy values are a no-op passthrough.
    """
    global _spmd_mesh, _spmd_active
    prev_mesh, prev_active = _spmd_mesh, _spmd_active
    if mesh_or_active is None or mesh_or_active is False:
        pass
    elif mesh_or_active is True:
        # unknown mesh: kernels must fall back to jnp, so the outer scope's
        # recorded mesh must not leak into this scope
        _spmd_mesh = None
        _spmd_active = True
    else:
        _spmd_mesh = mesh_or_active
        _spmd_active = True
    try:
        yield
    finally:
        _spmd_mesh, _spmd_active = prev_mesh, prev_active


def spmd_mesh():
    """The mesh recorded by the innermost ``spmd(mesh)`` scope (or None)."""
    return _spmd_mesh


# Context-parallel ring attention (ops/ring_attention.py): set by the
# training/prefill caller that guarantees full-sequence causal semantics
# (no left-pad, no sliding window).  None = dense attention.
_ring_mesh = None


def ring_mesh():
    return _ring_mesh


@contextmanager
def ring(mesh):
    """Scoped context-parallel mesh for sdpa dispatch."""
    global _ring_mesh
    prev = _ring_mesh
    _ring_mesh = mesh
    try:
        yield
    finally:
        _ring_mesh = prev


def use_pallas() -> bool:
    """Kernel eligibility for the *unsharded* (single-device) call form.

    Under SPMD the per-op dispatchers instead consult :func:`spmd_mesh` and
    route through the shard_map-wrapped kernel entry points; a bare kernel
    would not partition, so this returns False while a mesh without a
    sharded wrapper is active.
    """
    if _spmd_active:
        return False
    return _use_pallas_env()


@lru_cache(maxsize=None)
def _use_pallas_env() -> bool:
    if os.environ.get("IPEX_LLM_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    if os.environ.get("IPEX_LLM_TPU_FORCE_PALLAS", "0") == "1":
        return True  # kernel testing: interpret-mode Pallas off-TPU
    # Auto policy: only real TPU backends run the Pallas kernels.  On the
    # CPU backend the kernels would execute in the Pallas INTERPRETER,
    # which is strictly slower than the XLA reference path (BENCH_r05
    # microbench: decode_attn 540us interpret vs 268us XLA) — so CPU
    # auto-prefers the XLA path and interpret-mode stays opt-in via
    # IPEX_LLM_TPU_FORCE_PALLAS=1.
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def use_pallas_sharded() -> bool:
    """Kernel eligibility for shard_map-wrapped entry points."""
    return _use_pallas_env()


def clear_cache() -> None:
    _use_pallas_env.cache_clear()
