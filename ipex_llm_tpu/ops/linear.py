"""Quantized linear (dequant-matmul).

TPU-native replacement for ``LowBitLinear.forward`` and its native kernels
``xe_linear.forward_new`` / ``xe_batch.batch_forward`` (reference:
low_bit_linear.py:605-756, §2.3).  Instead of a C++ dispatch per call, the op
is a jittable function over a ``QTensor``; on TPU the packed-int4 path runs a
Pallas kernel that streams packed bytes from HBM and unpacks them in VMEM next
to the MXU (see ops/pallas/qmatmul.py), every other format falls back to an
XLA dequantize→matmul which the compiler fuses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ipex_llm_tpu.ops import dispatch
from ipex_llm_tpu.quantize import core as qcore
from ipex_llm_tpu.quantize.core import QTensor


def qmatmul_reference(x: jnp.ndarray, qt: QTensor, compute_dtype=jnp.bfloat16) -> jnp.ndarray:
    """x [..., in] @ dequant(qt) [in, out] -> [..., out]; XLA fallback/oracle."""
    w = qcore.dequantize(qt, dtype=compute_dtype)
    return jnp.matmul(x.astype(compute_dtype), w, preferred_element_type=jnp.float32).astype(
        x.dtype
    )


_PALLAS_QTYPES = ("sym_int4", "asym_int4", "nf4", "fp4", "sym_int8",
                  "sym_int5", "asym_int5", "fp6", "fp8_e4m3", "fp8_e5m2")


def qmatmul(x: jnp.ndarray, qt: QTensor, compute_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Quantized matmul with backend dispatch.

    The Pallas path currently covers the 4-bit packed formats (sym_int4 /
    asym_int4 / nf4 / fp4) and sym_int8 — the formats the reference routes to
    ``xe_linear``/``xe_batch`` — and the backend choice is data-driven per
    qtype family from the measured microbench ladder
    (``dispatch.use_pallas("qmatmul_<qtype>")``: the recorded decode-shape
    rows show CPU-interpret losing to the XLA block-dequant path, TPU has
    no recorded loss so the compiled kernel stands).  Under an active SPMD
    mesh, TP-sharded weights (``qt.tp_mode`` stamped by parallel/shard.py)
    run the shard_map-wrapped kernel; everything else falls back to the
    XLA dequant path which GSPMD partitions itself.
    """
    fam = f"qmatmul_{qt.qtype}"
    mt = dispatch.manual_tp_state()
    if mt is not None:
        # manual-mesh region (parallel/manual.py): the planes are already
        # per-shard slices and the trace is single-device.  Row-parallel
        # weights are THE cross-chip math — local f32 partial products
        # combined through the quantized-collective family at full
        # accumulator width (casting to x.dtype only AFTER the reduce
        # keeps the exact family bit-stable against the single-chip
        # matmul; the Pallas kernel cannot serve here, it emits at
        # compute_dtype and a narrowed partial would break that
        # guarantee).  Column/replicated weights are pure local compute
        # and fall THROUGH to the ordinary single-device ladder below —
        # the per-shard matmul takes the same measured Pallas-vs-XLA
        # call the single-chip trace takes.
        axis, cq = mt
        if qt.tp_mode == "row":
            w = qcore.dequantize(qt, dtype=compute_dtype)
            part = jnp.matmul(x.astype(compute_dtype), w,
                              preferred_element_type=jnp.float32)
            from ipex_llm_tpu.ops import collectives

            return collectives.all_reduce(part, axis, qtype=cq,
                                          out_dtype=x.dtype)
    else:
        mesh = dispatch.spmd_mesh()
        if (
            mesh is not None
            and qt.tp_mode in ("col", "row")
            and mesh.shape.get("tp", 1) > 1
            and dispatch.use_pallas_sharded(fam)
            and qt.qtype in _PALLAS_QTYPES
        ):
            try:
                from ipex_llm_tpu.ops.pallas import qmatmul as pallas_qmatmul

                return pallas_qmatmul.qmatmul_pallas_sharded(
                    x, qt, mesh, compute_dtype
                )
            except (ImportError, NotImplementedError):
                pass
    if dispatch.use_pallas(fam) and qt.qtype in _PALLAS_QTYPES:
        try:
            from ipex_llm_tpu.ops.pallas import qmatmul as pallas_qmatmul

            return pallas_qmatmul.qmatmul_pallas(x, qt, compute_dtype)
        except (ImportError, NotImplementedError):
            pass  # fall through to the XLA reference path
    return qmatmul_reference(x, qt, compute_dtype)


def linear(x: jnp.ndarray, w, bias: jnp.ndarray | None = None) -> jnp.ndarray:
    """General linear over a QTensor, a plain array, or a LoraWeight.

    Reference counterpart: models/common.py:309 ``linear_forward`` and, for
    the LoRA path, ``LoraLowBitLinear.forward`` (qlora.py:66): frozen base
    matmul plus ``(x·A)·B · α/r`` with gradients flowing only through A/B.
    """
    base = getattr(w, "base", None)
    if base is not None:  # training.qlora.LoraWeight
        y = linear(x, base)
        lora = (x.astype(w.a.dtype) @ w.a) @ w.b * w.scale
        y = y + lora.astype(y.dtype)
    elif isinstance(w, QTensor):
        y = qmatmul(x, w)
    else:
        y = jnp.matmul(
            x.astype(w.dtype), w, preferred_element_type=jnp.float32
        ).astype(x.dtype)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y
