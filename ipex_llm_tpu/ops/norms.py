"""RMSNorm / LayerNorm.

Reference counterparts: ``xe_addons.rms_norm`` / ``xe_addons.layer_norm``
called through models/common.py:184,205.  On TPU these are bandwidth-bound
elementwise+reduce ops that XLA fuses into neighbours, so the jnp form *is*
the fast path; a bespoke Pallas kernel buys nothing here (unlike SYCL where
the reference needed a fused kernel to avoid eager-mode dispatch overhead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6,
             offset: float = 0.0) -> jnp.ndarray:
    """RMSNorm in fp32 accumulation, cast back to x.dtype.

    ``offset=1.0`` covers Gemma-style (1+w) norms without a weight rewrite.
    """
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * (weight.astype(jnp.float32) + offset)).astype(dt)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray | None,
               bias: jnp.ndarray | None, eps: float = 1e-5) -> jnp.ndarray:
    dt = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dt)
