"""Token sampling ops (greedy / temperature / top-k / top-p / penalties).

Reference counterparts: HF's LogitsProcessor stack used by the patched
generate loops, plus ``xe_addons.repetition_penalty_logits_process_inplaced``
(§2.3).  Implemented as pure jnp so the whole sample step stays inside the
jitted decode program — no host round-trip per token, unlike the reference's
Python-driven sampling loop (SURVEY.md §3.2).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 1.0
    top_k: int = 0          # 0 = off
    top_p: float = 1.0      # 1.0 = off
    repetition_penalty: float = 1.0
    do_sample: bool = False


def apply_repetition_penalty(
    logits: jnp.ndarray, prev_tokens: jnp.ndarray, penalty: float
) -> jnp.ndarray:
    """CTRL-style repetition penalty over previously seen tokens.

    logits [B, V]; prev_tokens [B, L] with -1 padding for unused slots.
    """
    if penalty == 1.0:
        return logits
    b, v = logits.shape
    seen = jnp.zeros((b, v), dtype=bool)
    valid = prev_tokens >= 0
    idx = jnp.where(valid, prev_tokens, 0)
    seen = seen.at[jnp.arange(b)[:, None], idx].set(valid)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def _top_k_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def _top_p_mask(logits: jnp.ndarray, p) -> jnp.ndarray:
    """Nucleus mask; ``p`` is a scalar or a per-row [B] vector."""
    if not isinstance(p, (int, float)):
        p = jnp.asarray(p)[..., None]
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the top-1)
    cutoff_mask = cum - probs > p
    # smallest *kept* logit: flood dropped slots with +inf before the min
    # (NEG_INF here would make the cutoff -inf and mask nothing)
    cutoff = jnp.where(cutoff_mask, jnp.inf, sorted_logits).min(axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def sample_rows(logits: jnp.ndarray, temps: jnp.ndarray, top_ps: jnp.ndarray,
                key: jax.Array) -> jnp.ndarray:
    """Vectorized per-row sampling for the serving engine: rows with
    temperature 0 take argmax, others sample from the temperature-scaled,
    per-row-nucleus-masked distribution.  logits [R, V]; temps/top_ps [R]."""
    return sample_rows_with_logprobs(logits, temps, top_ps, key)[0]


def _top_k_mask_rows(logits: jnp.ndarray, ks: jnp.ndarray) -> jnp.ndarray:
    """Per-row top-k mask; ks [R] int32, <=0 disables for that row."""
    v = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    idx = jnp.clip(ks - 1, 0, v - 1)[:, None]
    cutoff = jnp.take_along_axis(sorted_desc, idx, axis=-1)
    masked = jnp.where(logits < cutoff, NEG_INF, logits)
    return jnp.where((ks > 0)[:, None], masked, logits)


def sample_rows_with_logprobs(logits: jnp.ndarray, temps: jnp.ndarray,
                              top_ps: jnp.ndarray, key: jax.Array,
                              seeds: jnp.ndarray | None = None,
                              steps: jnp.ndarray | None = None,
                              top_ks: jnp.ndarray | None = None,
                              active: jnp.ndarray | None = None):
    """sample_rows plus the chosen token's logprob under the MODEL
    distribution (raw log-softmax, the OpenAI ``logprobs`` convention —
    not the temperature/top-p-modified sampling distribution).

    ``seeds`` [R] int32 (-1 = unseeded) with ``steps`` [R] gives rows a
    DETERMINISTIC stream — fold_in(PRNGKey(seed), step) — independent of
    which other requests share the batch; unseeded rows derive per-row
    keys from the engine's stepping key.  ``step`` is the row's OUTPUT
    INDEX, so a first token always draws from fold_in(seed, 0) no matter
    which program samples it — the serving engine's mixed admission step
    folds first-token sampling into the batched chunk program (steps=0)
    and reproduces the sequential per-row first-token stream bit-for-bit.

    ``active`` [R] bool masks dead rows to (token 0, logprob 0) — ONE
    definition of the serving engines' row masking, shared by the plain,
    pipelined, and fused-horizon decode steps so their emitted padding
    stays identical."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-4)[:, None]
    if top_ks is not None:
        scaled = _top_k_mask_rows(scaled, top_ks)
    scaled = _top_p_mask(scaled, top_ps)
    r = logits.shape[0]
    if seeds is None:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    else:
        base = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(r))
        seeded = jax.vmap(
            lambda sd, st: jax.random.fold_in(jax.random.PRNGKey(sd), st)
        )(jnp.maximum(seeds, 0), steps)
        keys = jnp.where((seeds >= 0)[:, None], seeded, base)
        sampled = jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg)
        )(keys, scaled)
    sampled = sampled.astype(jnp.int32)
    chosen = jnp.where(temps > 0, sampled, greedy)
    lp = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), chosen[:, None], axis=-1
    )[:, 0]
    if active is not None:
        chosen = jnp.where(active, chosen, 0)
        lp = jnp.where(active, lp, 0.0)
    return chosen, lp


def _transform_logits(logits: jnp.ndarray, params: SamplingParams) -> jnp.ndarray:
    """The temperature/top-k/top-p transform chain shared by ``sample`` and
    ``transformed_probs`` — ONE definition, because speculative rejection
    sampling is distribution-identical to plain sampling only while the two
    stay byte-for-byte the same."""
    logits = logits.astype(jnp.float32)
    if params.temperature not in (0.0, 1.0):
        logits = logits / params.temperature
    if params.top_k > 0:
        logits = _top_k_mask(logits, params.top_k)
    if params.top_p < 1.0:
        logits = _top_p_mask(logits, params.top_p)
    return logits


def transformed_probs(logits: jnp.ndarray, params: SamplingParams) -> jnp.ndarray:
    """Post-transform (temperature/top-k/top-p) probability rows.

    The distribution ``sample`` draws from, materialized — used by
    speculative rejection-sampling verification, where both the draft's
    proposal q and the target's p must be actual distributions.
    """
    return jax.nn.softmax(_transform_logits(logits, params), axis=-1)


def sample(
    logits: jnp.ndarray,           # [B, V]
    key: jax.Array,
    params: SamplingParams,
    prev_tokens: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Returns next token ids [B] (int32). Jit-safe with static params."""
    logits = logits.astype(jnp.float32)
    if prev_tokens is not None and params.repetition_penalty != 1.0:
        logits = apply_repetition_penalty(logits, prev_tokens, params.repetition_penalty)
    if not params.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _transform_logits(logits, params)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
