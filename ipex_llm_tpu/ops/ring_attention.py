"""Ring attention over a ``cp`` mesh axis (context/sequence parallelism).

The reference has NO sequence parallelism — its long-context levers are
memory-side only (fp8 KV, SnapKV; SURVEY.md §5) — so this op is the
"exceed the reference" capability: sequences shard over ``cp``, each device
holds a [B, S/cp, H, D] chunk of Q/K/V, and K/V blocks rotate around the
ring via ``ppermute`` while a streaming-softmax accumulator builds the
exact attention output.  Communication rides ICI; peak memory per device is
O(S/cp) instead of O(S).

Math: classic online softmax (flash-attention accumulation) — per ring step
``s = q·k_blk``, running max ``m``, normalizer ``l``, and rescaled value
accumulator; causal masking uses global positions so the result is
bit-for-bit the same attention as the dense computation (up to fp
accumulation order).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _ring_body(r, state, axis_name: str, n_dev: int, s_blk: int, scale,
               causal: bool, n_rep: int, window, softcap):
    m, l, acc, k_blk, v_blk, q, my_idx, window_on = state
    # which global block the K/V chunk we currently hold came from
    blk_idx = (my_idx - r) % n_dev
    q_pos = my_idx * s_blk + jnp.arange(q.shape[1])          # [Sq]
    kv_pos = blk_idx * s_blk + jnp.arange(k_blk.shape[1])    # [Sk]

    kr = jnp.repeat(k_blk, n_rep, axis=2) if n_rep > 1 else k_blk
    vr = jnp.repeat(v_blk, n_rep, axis=2) if n_rep > 1 else v_blk
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   kr.astype(jnp.float32)) * scale
    if softcap is not None:  # gemma2-style logit softcapping
        s = jnp.tanh(s / softcap) * softcap
    if causal:
        mask = kv_pos[None, None, None, :] <= q_pos[None, None, :, None]
        if window is not None:
            # sliding window, gated by the traced per-layer flag (gemma
            # alternates global/local layers inside one scanned body)
            in_w = (kv_pos[None, None, None, :]
                    > q_pos[None, None, :, None] - window)
            mask = mask & (in_w | jnp.logical_not(window_on))
        s = jnp.where(mask, s, NEG_INF)

    m_new = jnp.maximum(m, s.max(axis=-1))                   # [B,H,T]
    # guard fully-masked rows (m_new == NEG_INF): exp(0)=1 but l stays 0
    alpha = jnp.exp(jnp.where(m_new <= NEG_INF / 2, 0.0, m - m_new))
    p = jnp.exp(jnp.where(m_new[..., None] <= NEG_INF / 2, NEG_INF,
                          s - m_new[..., None]))
    l = l * alpha + p.sum(axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhts,bshd->bhtd", p, vr.astype(jnp.float32)
    )

    # rotate K/V to the next device in the ring
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return (m_new, l, acc, k_blk, v_blk, q, my_idx, window_on)


def _ring_attention_local(q, k, v, window_on, *, axis_name: str,
                          n_dev: int, scale: float, causal: bool,
                          window, softcap):
    """Runs inside shard_map: q/k/v are the per-device chunks."""
    b, s_blk, hq, d = q.shape
    hkv = k.shape[2]
    n_rep = hq // hkv
    my_idx = jax.lax.axis_index(axis_name)

    m = jnp.full((b, hq, s_blk), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hq, s_blk), jnp.float32)
    acc = jnp.zeros((b, hq, s_blk, d), jnp.float32)

    body = partial(_ring_body, axis_name=axis_name, n_dev=n_dev,
                   s_blk=s_blk, scale=scale, causal=causal, n_rep=n_rep,
                   window=window, softcap=softcap)
    state = (m, l, acc, k, v, q, my_idx, window_on)
    for r in range(n_dev):  # unrolled: n_dev is small and static
        state = body(r, state)
    m, l, acc = state[0], state[1], state[2]
    out = acc / jnp.maximum(l, 1e-20)[..., None]             # [B,H,T,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,T,H,D]


def ring_sdpa(
    q: jnp.ndarray,   # [B, S, Hq, D] (full logical sequence)
    k: jnp.ndarray,   # [B, S, Hkv, D]
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis: str = "cp",
    causal: bool = True,
    scale: float | None = None,
    window: int | None = None,
    window_on=True,
    softcap: float | None = None,
) -> jnp.ndarray:
    """Exact attention with the sequence sharded over ``mesh[axis]``.

    ``window``/``softcap`` extend CP to gemma-style families (VERDICT r3
    weak #8 — previously windowed layers silently skipped ring attention);
    ``window_on`` may be a traced bool (per-layer gate)."""
    from ipex_llm_tpu.parallel.compat import shard_map

    n_dev = mesh.shape[axis]
    if q.shape[1] % n_dev != 0:
        raise ValueError(f"sequence {q.shape[1]} not divisible by {axis}={n_dev}")
    if scale is None:
        scale = q.shape[-1] ** -0.5

    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis, n_dev=n_dev,
                scale=scale, causal=causal, window=window, softcap=softcap),
        mesh=mesh,
        in_specs=(spec, spec, spec, P()),
        out_specs=spec,
    )
    return fn(q, k, v, jnp.asarray(window_on))
