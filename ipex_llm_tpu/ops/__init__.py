"""TPU op library: the xe_linear/xe_batch/xe_addons equivalent (SURVEY.md §2.3).

Each hot op ships a Pallas TPU kernel plus a pure-jnp XLA reference that
doubles as the CPU fallback and test oracle.
"""

# NOTE: the `linear` *function* is deliberately exported as `linear_forward`;
# re-exporting it under its own name would rebind the package attribute that
# points at the `ops.linear` submodule and break `from ipex_llm_tpu.ops
# import linear as linear_ops` module imports (round-1 regression).
from ipex_llm_tpu.ops.linear import qmatmul, qmatmul_reference
from ipex_llm_tpu.ops.linear import linear as linear_forward
from ipex_llm_tpu.ops.norms import layer_norm, rms_norm
from ipex_llm_tpu.ops.rope import RopeScaling, apply_rope, cos_sin
from ipex_llm_tpu.ops.attention import sdpa, sdpa_reference
from ipex_llm_tpu.ops.mlp import gated_act_mul, split_gate_up
from ipex_llm_tpu.ops.sampling import SamplingParams, sample

__all__ = [
    "linear_forward", "qmatmul", "qmatmul_reference",
    "layer_norm", "rms_norm",
    "RopeScaling", "apply_rope", "cos_sin",
    "sdpa", "sdpa_reference",
    "gated_act_mul", "split_gate_up",
    "SamplingParams", "sample",
]
