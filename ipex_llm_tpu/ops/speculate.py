"""On-device speculative-decoding helpers for the fused engine tick.

The serving engine's speculative path used to draft on the HOST: an
n-gram table scan over ``prompt_ids + output_ids`` per row per step
(``serving/engine.py::_propose_ngram``), a per-step draft upload, and a
blocking d2h sync to walk the acceptance chain.  This module is the
device-resident half of moving that loop inside the one-dispatch tick
(the Medusa/EAGLE observation — PAPERS.md arXiv 2401.10774 / 2401.15077:
speculative decoding pays off when draft+verify+accept stay resident on
the accelerator): the proposer below scans each row's device-resident
token history inside the traced program, so a speculative horizon step
needs no host n-gram table, no draft upload, and no per-step sync.

Bit-exactness contract: :func:`propose_ngram_rows` computes, per row,
EXACTLY what ``engine._propose_ngram`` computes on the host (longest
n-gram first, most recent earlier occurrence wins, continuation clipped
at the history end) — locked by
``tests/test_serving_spec.py::test_device_proposer_matches_host``.  The
token streams themselves never depend on the drafts (acceptance only
emits tokens sampled from the true conditionals), but keeping the
proposers identical makes accept-rate telemetry comparable between the
fused tick and the host-walk oracle.
"""

from __future__ import annotations

import jax.numpy as jnp


def propose_ngram_rows(hist: jnp.ndarray, lens: jnp.ndarray, k: int,
                       ngram: int):
    """Prompt-lookup draft candidates for a batch of rows, fully traced.

    ``hist`` [R, S] int32 token history per row (prompt + emitted tokens,
    zero-padded); ``lens`` [R] the valid history length per row (the
    current token sits at ``hist[r, lens[r] - 1]``).  For each row, find
    the most recent earlier occurrence of the trailing n-gram (longest
    ``n <= ngram`` first — the host ``_propose_ngram`` order) and propose
    the ``k`` tokens that followed it.

    Returns ``(drafts [R, k] int32, n_prop [R] int32)``: ``drafts[r, j]``
    is valid for ``j < n_prop[r]`` and zero-filled beyond (the value the
    host path feeds the verify forward at unproposed positions), and
    ``n_prop`` counts the proposed run — ``min(k, continuation length)``,
    0 when no n-gram of any length matches.  A row whose history is too
    short for even a 1-gram match (``lens < 2``) proposes nothing.
    """
    r, s = hist.shape
    idx = jnp.arange(s)
    found = jnp.zeros((r,), bool)
    best_start = jnp.zeros((r,), jnp.int32)   # continuation start per row
    for n in range(ngram, 0, -1):
        # trailing n-gram per row: hist[r, lens[r]-n : lens[r]]
        tpos = lens[:, None] - n + jnp.arange(n)[None, :]
        tail = jnp.take_along_axis(hist, jnp.clip(tpos, 0, s - 1), axis=1)
        # m[r, s0] == (hist[r, s0:s0+n] == tail[r]) via shifted compares;
        # the roll wraparound only touches s0 > S - n, which the validity
        # bound below excludes (s0 < lens - n <= S - n)
        m = jnp.ones((r, s), bool)
        for j in range(n):
            m = m & (jnp.roll(hist, -j, axis=1) == tail[:, j:j + 1])
        # a *previous* occurrence entirely before the tail window, and
        # only for rows whose history admits an n-gram (host loop bound:
        # n <= lens - 1)
        valid = (m & (idx[None, :] < (lens - n)[:, None])
                 & ((lens - 1) >= n)[:, None])
        any_m = valid.any(axis=1)
        start = (jnp.where(valid, idx, -1).max(axis=1) + n).astype(jnp.int32)
        take = any_m & ~found                 # longest n wins, host order
        best_start = jnp.where(take, start, best_start)
        found = found | any_m
    cpos = best_start[:, None] + jnp.arange(k)[None, :]
    cand = jnp.take_along_axis(hist, jnp.clip(cpos, 0, s - 1), axis=1)
    # continuation clipped at the history end (host: nxt = hist[s0+n :
    # s0+n+k], -1-padded; first pad truncates the proposed run)
    n_prop = jnp.where(
        found, jnp.clip(lens - best_start, 0, k), 0).astype(jnp.int32)
    drafts = jnp.where(jnp.arange(k)[None, :] < n_prop[:, None], cand, 0)
    return drafts.astype(jnp.int32), n_prop
