"""Rotary position embeddings.

Reference counterparts: ``xe_addons.rotary_half_inplaced`` /
``rotary_two_inplaced`` (+ ``*_with_cache_inplaced``) called from the
per-model attention forwards (llama.py:154-166, models/common.py:354-367).
TPU-first shape: sin/cos are computed once per step from integer positions and
applied as pure elementwise math that XLA fuses into the surrounding QKV ops —
no in-place mutation, no cache side table.

Two layouts, matching HF conventions:
  - "half"  (rotate_half, llama/mistral/qwen): pairs are (x[i], x[i+d/2])
  - "two"   (interleaved, chatglm/gptj style): pairs are (x[2i], x[2i+1])

Scaling variants (linear / dynamic NTK / llama3 / yarn / longrope) are handled
upstream by ``RopeScaling.inv_freq`` so this module stays a pure applicator.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class RopeScaling:
    """Frequency table builder covering HF rope_scaling configs."""

    head_dim: int
    base: float = 10000.0
    kind: str = "default"  # default | linear | dynamic | llama3 | yarn | longrope
    factor: float = 1.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192
    partial_rotary_factor: float = 1.0
    attention_factor: float | None = None
    short_factor: tuple[float, ...] | None = None
    long_factor: tuple[float, ...] | None = None

    @property
    def rotary_dim(self) -> int:
        rd = int(self.head_dim * self.partial_rotary_factor)
        return rd - (rd % 2)

    def inv_freq(self, seq_len: int | None = None) -> np.ndarray:
        rd = self.rotary_dim
        inv = 1.0 / (self.base ** (np.arange(0, rd, 2, dtype=np.float64) / rd))
        if self.kind == "linear":
            inv = inv / self.factor
        elif self.kind == "dynamic":
            # NTK-aware: stretch base when seq_len exceeds the original window
            sl = max(seq_len or 0, self.original_max_position)
            if sl > self.original_max_position:
                base = self.base * (
                    (self.factor * sl / self.original_max_position) - (self.factor - 1)
                ) ** (rd / (rd - 2))
                inv = 1.0 / (base ** (np.arange(0, rd, 2, dtype=np.float64) / rd))
        elif self.kind == "llama3":
            low_wl = self.original_max_position / self.low_freq_factor
            high_wl = self.original_max_position / self.high_freq_factor
            wl = 2 * np.pi / inv
            smooth = (self.original_max_position / wl - self.low_freq_factor) / (
                self.high_freq_factor - self.low_freq_factor
            )
            scaled = np.where(
                wl < high_wl,
                inv,
                np.where(wl > low_wl, inv / self.factor,
                         (1 - smooth) * inv / self.factor + smooth * inv),
            )
            inv = scaled
        elif self.kind in ("yarn", "longrope"):
            if self.kind == "longrope" and self.short_factor and self.long_factor:
                sl = seq_len or self.original_max_position
                ext = np.array(
                    self.long_factor if sl > self.original_max_position else self.short_factor
                )
                inv = inv / ext
            else:  # yarn interpolation ramp
                lo = max(np.floor(rd * np.log(self.original_max_position /
                         (32 * 2 * np.pi)) / (2 * np.log(self.base))), 0)
                hi = min(np.ceil(rd * np.log(self.original_max_position /
                         (1 * 2 * np.pi)) / (2 * np.log(self.base))), rd - 1)
                ramp = np.clip(
                    (np.arange(rd // 2, dtype=np.float64) - lo) / max(hi - lo, 1e-3), 0, 1
                )
                inv = inv / self.factor * ramp + inv * (1 - ramp)
        return inv.astype(np.float32)

    def mscale(self, seq_len: int | None = None) -> float:
        if self.attention_factor is not None:
            return float(self.attention_factor)
        if self.kind == "yarn" and self.factor > 1:
            return float(0.1 * np.log(self.factor) + 1.0)
        return 1.0


def cos_sin(positions: jnp.ndarray, inv_freq: jnp.ndarray, mscale: float = 1.0):
    """positions [..., T] int -> (cos, sin) each [..., T, rotary_dim/2] fp32."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(angles) * mscale, jnp.sin(angles) * mscale


def cos_sin_mrope(positions: jnp.ndarray, inv_freq: jnp.ndarray,
                  section: tuple[int, ...]):
    """Qwen2-VL multimodal rope (reference qwen2_vl.py M-ROPE patches).

    positions [B, 3, T]: temporal/height/width position channels.  Each
    frequency index is assigned to one channel by ``mrope_section`` (e.g.
    (16, 24, 24) over 64 freqs); text tokens carry equal channels so the
    result reduces to plain rope.
    Returns (cos, sin) each [B, T, rd/2].
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B,3,T,F]
    idx = jnp.concatenate([
        jnp.full((s,), c, jnp.int32) for c, s in enumerate(section)
    ])                                                            # [F]
    sel = jax.nn.one_hot(idx, 3, dtype=jnp.float32)               # [F,3]
    merged = jnp.einsum("bctf,fc->btf", angles, sel)
    return jnp.cos(merged), jnp.sin(merged)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               layout: str = "half") -> jnp.ndarray:
    """Rotate q or k.

    x: [B, T, H, D]; cos/sin: [B, T, D/2] (or broadcastable); returns same
    shape/dtype as x.  For partial-rotary models pass x pre-split.
    """
    dt = x.dtype
    xf = x.astype(jnp.float32)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    if layout == "half":
        d2 = x.shape[-1] // 2
        x1, x2 = xf[..., :d2], xf[..., d2:]
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    elif layout == "two":
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        r1 = x1 * c - x2 * s
        r2 = x2 * c + x1 * s
        out = jnp.stack([r1, r2], axis=-1).reshape(xf.shape)
    else:
        raise ValueError(f"unknown rope layout {layout!r}")
    return out.astype(dt)
