"""Sparse Mixture-of-Experts dispatch.

Reference counterparts: ``xe_linear.moe_forward_vec`` + ``moe_group_topk``
(reference deepseek.py:293-322, models/common.py:342-375) and the FlashMoE
CPU-offload runtime (docs/mddocs/Quickstart/flashmoe_quickstart.md).  The r2
decoder computed EVERY expert on EVERY token (dense-compute MoE) — correct
but E/k× wasted FLOPs and full-expert HBM traffic each step.

TPU-native sparse design, all shapes static (SURVEY.md §7 hard part (b)):

- **gather mode** (decode / tiny batches): for each (token, top-k) pair,
  gather just that expert's packed weight planes from the stacked expert
  QTensor with a dynamic index — XLA lowers to an HBM gather that reads
  only the addressed experts, so decode weight traffic drops from E experts
  to ≤ N·k (4× for Mixtral's E=8,k=2 at batch 1).
- **capacity mode** (prefill / training): sort the (token, expert) pairs by
  expert, scatter into a ``[E, C, H]`` bucket tensor (capacity
  ``C = min(N, ceil(N·k/E · cf))``), run ONE vmapped expert computation
  over the expert axis (a batched matmul GSPMD shards over ``ep`` with no
  sequential scan), and scatter-add the weighted results back.  Tokens
  beyond an expert's capacity are dropped (standard capacity-factor
  semantics; cf defaults to 2.0 ⇒ drops only under >2× imbalance).

The dense all-experts scan remains in models/decoder.py as the oracle and
the fallback for odd configs (IPEX_LLM_TPU_DENSE_MOE=1).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp

from ipex_llm_tpu.ops import linear as linear_ops
from ipex_llm_tpu.ops import mlp as mlp_ops

# pairs at or below this run gather mode (decode-shaped batches)
GATHER_PAIR_LIMIT = 32


def capacity_factor() -> float:
    return float(os.environ.get("IPEX_LLM_TPU_MOE_CF", "2.0"))


def use_sparse() -> bool:
    return os.environ.get("IPEX_LLM_TPU_DENSE_MOE", "0") != "1"


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _take_expert(qt_or_arr, idx):
    """Index the leading expert axis of a stacked weight (QTensor-aware)."""
    return jax.tree_util.tree_map(lambda x: x[idx], qt_or_arr)


def _expert_ffn(x, gate_up, down, act, gated=True, ub=None, db=None):
    """x [..., H] through one expert's FFN (dequant fused by XLA): gated
    SwiGLU (mixtral-style) or fc1 -> act -> fc2 with biases (phixtral,
    reference phixtral.py:phixtral_mlp_forward)."""
    inner = linear_ops.linear(x, gate_up)
    if ub is not None:
        inner = inner + ub.astype(inner.dtype)
    if gated:
        gate, up = mlp_ops.split_gate_up(inner)
        y = mlp_ops.gated_act_mul(gate, up, act)
    else:
        y = mlp_ops.act(inner, act)
    out = linear_ops.linear(y, down)
    if db is not None:
        out = out + db.astype(out.dtype)
    return out


def moe_gather(h, w, idx, gate_up, down, act, gated=True,
               up_bias=None, down_bias=None):
    """Per-pair expert gather: h [B,T,H], w/idx [B,T,k].

    Weight traffic ∝ number of pairs, not E — the decode-path win.
    """
    b, t, hidden = h.shape
    k = idx.shape[-1]
    n = b * t
    hf = h.reshape(n, hidden)
    idx_f = idx.reshape(n * k)
    w_f = w.reshape(n * k)
    tok_f = jnp.repeat(jnp.arange(n), k)

    # None bias leaves vanish from the pytree, so ONE vmap serves both
    ew = {"gu": gate_up, "dn": down, "ub": up_bias, "db": down_bias}
    pair_w = _take_expert(ew, idx_f)           # [P, ...] packed planes
    xi = hf[tok_f]                             # [P, H]

    y = jax.vmap(
        lambda x_, pw: _expert_ffn(x_[None], pw["gu"], pw["dn"], act,
                                   gated, pw.get("ub"), pw.get("db"))[0]
    )(xi, pair_w)                              # [P, H]
    y = y * w_f[:, None].astype(y.dtype)
    out = jnp.zeros((n, hidden), y.dtype).at[tok_f].add(y)
    return out.reshape(b, t, hidden)


def _dequant_stack(qt_or_arr):
    """Stacked expert weight [E, ...] -> dense [E, K, N] bf16."""
    from ipex_llm_tpu.quantize import core as qcore
    from ipex_llm_tpu.quantize.core import QTensor

    if isinstance(qt_or_arr, QTensor):
        return jax.vmap(qcore.dequantize)(qt_or_arr).astype(jnp.bfloat16)
    return qt_or_arr.astype(jnp.bfloat16)


def moe_ragged(h, w, idx, gate_up, down, act, n_experts: int, gated=True,
               up_bias=None, down_bias=None):
    """Exact sorted dispatch via ``lax.ragged_dot`` (MXU group-gemm).

    Tokens sort by expert and run ONE ragged matmul per projection over
    the expert-major dense weight stack — exact results (no capacity
    drops), FLOPs proportional to routed pairs, one pass of expert
    weight traffic (the same traffic dense-all-experts pays, E/k fewer
    FLOPs).  This is the single-mesh prefill path; the capacity-bucketed
    form below remains for ``ep``-sharded meshes where the expert axis
    is partitioned.
    """
    b, t, hidden = h.shape
    k = idx.shape[-1]
    n = b * t
    hf = h.reshape(n, hidden).astype(jnp.bfloat16)
    e_f = idx.reshape(n * k)
    w_f = w.reshape(n * k)
    tok_f = jnp.repeat(jnp.arange(n), k)

    order = jnp.argsort(e_f)
    tok_s = tok_f[order]
    w_s = w_f[order]
    counts = jnp.bincount(e_f, length=n_experts)

    e_s = e_f[order]
    x = hf[tok_s]                                   # [P, H]
    gu = _dequant_stack(gate_up)                    # [E, H, 2I]
    inner = jax.lax.ragged_dot(
        x, gu, counts, preferred_element_type=jnp.float32
    )
    if up_bias is not None:
        inner = inner + up_bias[e_s].astype(inner.dtype)
    if gated:
        gate, up = mlp_ops.split_gate_up(inner)
        act_x = mlp_ops.gated_act_mul(gate, up, act).astype(jnp.bfloat16)
    else:
        act_x = mlp_ops.act(inner, act).astype(jnp.bfloat16)
    dn = _dequant_stack(down)                       # [E, I, H]
    y = jax.lax.ragged_dot(
        act_x, dn, counts, preferred_element_type=jnp.float32
    )
    if down_bias is not None:
        y = y + down_bias[e_s].astype(y.dtype)
    y = y * w_s[:, None].astype(y.dtype)
    out = jnp.zeros((n, hidden), y.dtype).at[tok_s].add(y)
    return out.reshape(b, t, hidden).astype(h.dtype)


def moe_capacity(h, w, idx, gate_up, down, act, n_experts: int,
                 cf: float | None = None, gated=True,
                 up_bias=None, down_bias=None):
    """Capacity-bucketed sort dispatch: h [B,T,H], w/idx [B,T,k]."""
    b, t, hidden = h.shape
    k = idx.shape[-1]
    n = b * t
    cf = capacity_factor() if cf is None else cf
    cap = min(n, _round_up(max(int(n * k / n_experts * cf), 1), 8))

    hf = h.reshape(n, hidden)
    e_f = idx.reshape(n * k)
    w_f = w.reshape(n * k)
    tok_f = jnp.repeat(jnp.arange(n), k)

    order = jnp.argsort(e_f)                   # stable: token order per expert
    e_sorted = e_f[order]
    tok_sorted = tok_f[order]
    w_sorted = w_f[order]
    counts = jnp.bincount(e_f, length=n_experts)
    starts = jnp.cumsum(counts) - counts       # exclusive prefix
    pos_in_e = jnp.arange(n * k) - starts[e_sorted]
    keep = pos_in_e < cap
    # dropped pairs land in a scratch row past the real buckets
    slot = jnp.where(keep, e_sorted * cap + pos_in_e, n_experts * cap)

    x_buckets = jnp.zeros((n_experts * cap + 1, hidden), hf.dtype)
    x_buckets = x_buckets.at[slot].set(hf[tok_sorted])
    x_buckets = x_buckets[:-1].reshape(n_experts, cap, hidden)

    ew = {"gu": gate_up, "dn": down, "ub": up_bias, "db": down_bias}
    y = jax.vmap(
        lambda xe, ew_: _expert_ffn(xe, ew_["gu"], ew_["dn"], act,
                                    gated, ew_.get("ub"), ew_.get("db"))
    )(x_buckets, ew)                           # [E, C, H]

    y_pairs = y.reshape(n_experts * cap, hidden)[
        jnp.clip(slot, 0, n_experts * cap - 1)
    ]
    y_pairs = jnp.where(keep[:, None], y_pairs, 0.0)
    y_pairs = y_pairs * w_sorted[:, None].astype(y_pairs.dtype)
    out = jnp.zeros((n, hidden), y_pairs.dtype).at[tok_sorted].add(y_pairs)
    return out.reshape(b, t, hidden)


def moe_ffn(h, w, idx, gate_up, down, act, n_experts: int, gated=True,
            up_bias=None, down_bias=None):
    """Route by static pair count and mesh: gather (decode), ragged
    group-gemm (exact, single-mesh prefill), capacity buckets (ep)."""
    from ipex_llm_tpu.ops import dispatch

    kw = dict(gated=gated, up_bias=up_bias, down_bias=down_bias)
    n_pairs = h.shape[0] * h.shape[1] * idx.shape[-1]
    if n_pairs <= GATHER_PAIR_LIMIT:
        return moe_gather(h, w, idx, gate_up, down, act, **kw)
    mesh = dispatch.spmd_mesh()
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        return moe_capacity(h, w, idx, gate_up, down, act, n_experts, **kw)
    return moe_ragged(h, w, idx, gate_up, down, act, n_experts, **kw)
