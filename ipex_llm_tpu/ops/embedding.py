"""Embedding variants: low-bit (quantized) embedding row lookup.

Reference counterparts: ``LowBitEmbedding`` (reference embedding.py:179,
backed by ``xe_linear.dequantize_rows``) plus the CPU/disk offload variants
(embedding.py:29-96).  On TPU the memory lever is HBM, not host RAM, and a
host lookup inside the jitted decode loop would cost a device round-trip
per token — so the TPU-native variant quantizes the table in HBM and
dequantizes only the gathered rows in-jit.  ``cpu_embedding`` /
``disk_embedding`` flags map onto this (documented deviation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ipex_llm_tpu.quantize import numerics
from ipex_llm_tpu.quantize.core import QTensor

EMBED_QTYPES = ("sym_int8", "sym_int4", "nf4", "fp4")


def embed_lookup(table, ids: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """rows = table[ids]; table is a dense array or a QTensor laid out
    ``[vocab, hidden]`` (vocab = contraction/block axis).

    The gather touches only ``len(ids)`` rows — the xe_linear
    ``dequantize_rows`` equivalent, fused into the forward by XLA.
    """
    if not isinstance(table, QTensor):
        return jnp.take(table, ids, axis=0).astype(dtype)

    bs = table.block_size
    qtype = table.qtype
    block = ids // bs                     # [...,]
    offset = ids % bs
    scales = jnp.take(table.scales, block, axis=0).astype(jnp.float32)

    if qtype == "sym_int8":
        codes = jnp.take(table.data, ids, axis=0).astype(jnp.int32)
        rows = (codes - 128).astype(jnp.float32) * scales
    else:  # packed 4-bit: block-local halves pairing (core._pack_nibbles)
        half = bs // 2
        in_low = offset < half
        packed_row = jnp.where(
            in_low, block * half + offset, block * half + offset - half
        )
        bytes_ = jnp.take(table.data, packed_row, axis=0).astype(jnp.int32)
        codes = jnp.where(in_low[..., None], bytes_ & 0x0F, bytes_ >> 4)
        if qtype == "sym_int4":
            rows = (codes - 8).astype(jnp.float32) * scales
        else:
            import numpy as np

            tab = jnp.asarray(
                numerics.NF4_TABLE if qtype == "nf4" else numerics.FP4_TABLE,
                jnp.float32,
            )
            rows = jnp.take(tab, codes, axis=0) * scales
    if table.zeros is not None:
        rows = rows + jnp.take(table.zeros, block, axis=0).astype(jnp.float32)
    return rows.astype(dtype)
