"""Scaled dot-product attention (unified SDPA).

Reference counterpart: the single entry point
``models/common.py:219-306 scaled_dot_product_attention`` dispatching to
``xe_addons.sdp / sdp_causal / sdp_non_causal / sdp_fp8*`` (§2.3).  Here one
jnp reference implementation covers causal/non-causal, GQA, sliding window,
and Gemma-style logit softcapping; the Pallas flash kernel
(ops/pallas/flash_attention.py) takes over on TPU for the long-sequence
prefill path.  All masking is static-shape: the KV buffer has a fixed
``S_max`` and validity is derived from integer lengths, which keeps every
shape XLA-static (SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(kv: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] (GQA head broadcast)."""
    if n_rep == 1:
        return kv
    b, s, h, d = kv.shape
    return jnp.broadcast_to(kv[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def sdpa_reference(
    q: jnp.ndarray,          # [B, T, Hq, D]
    k: jnp.ndarray,          # [B, S, Hkv, D]
    v: jnp.ndarray,          # [B, S, Hkv, Dv]
    *,
    scale: float | None = None,
    causal: bool = True,
    q_positions: jnp.ndarray | None = None,  # [B, T] absolute slot positions
    kv_len: jnp.ndarray | None = None,       # [B] valid cache length
    kv_start: jnp.ndarray | None = None,     # [B] first valid slot (left pad)
    window: int | None = None,               # sliding-window size (static)
    window_on: jnp.ndarray | bool = True,    # traced per-layer window enable
    softcap: float | None = None,            # gemma2 logit softcapping
    bias: jnp.ndarray | None = None,         # additive mask/bias [B,1|Hq,T,S]
) -> jnp.ndarray:
    """Returns [B, T, Hq, Dv] in q.dtype; softmax in fp32."""
    b, t, hq, d = q.shape
    s = k.shape[1]
    hkv = k.shape[2]
    n_rep = hq // hkv
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    if scale is None:
        scale = d ** -0.5

    scores = jnp.einsum(
        "bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if softcap is not None:
        scores = jnp.tanh(scores / softcap) * softcap
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)

    kv_pos = jnp.arange(s)[None, None, None, :]  # [1,1,1,S]
    mask = jnp.ones((b, 1, t, s), dtype=bool)
    if kv_len is not None:
        mask &= kv_pos < kv_len[:, None, None, None]
    if kv_start is not None:
        mask &= kv_pos >= kv_start[:, None, None, None]
    if causal:
        if q_positions is None:
            q_positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
        qp = q_positions[:, None, :, None]  # [B,1,T,1]
        mask &= kv_pos <= qp
        if window is not None:
            in_window = kv_pos > qp - window
            mask &= in_window | jnp.logical_not(window_on)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    **kwargs,
) -> jnp.ndarray:
    """Backend-dispatching SDPA; see ``sdpa_reference`` for semantics."""
    from ipex_llm_tpu.ops import dispatch

    rm = dispatch.ring_mesh()
    if (
        rm is not None
        and q.shape[1] == k.shape[1]                  # full self-attention
        and q.shape[1] % rm.shape["cp"] == 0
        and kwargs.get("kv_start") is None            # no left padding
        and kwargs.get("bias") is None
    ):
        from ipex_llm_tpu.ops.ring_attention import ring_sdpa

        return ring_sdpa(
            q, k, v, rm, causal=kwargs.get("causal", True),
            scale=kwargs.get("scale"),
            window=kwargs.get("window"),
            window_on=kwargs.get("window_on", True),
            softcap=kwargs.get("softcap"),
        )

    mesh = dispatch.spmd_mesh()
    if (
        mesh is not None
        and mesh.shape.get("tp", 1) > 1
        and dispatch.use_pallas_sharded()
        and q.shape[1] >= 128
        and kwargs.get("bias") is None
        and kwargs.get("causal", True)
    ):
        try:
            from ipex_llm_tpu.ops.pallas import flash_attention

            kw = dict(kwargs)
            kw.pop("causal", None)
            return flash_attention.flash_sdpa_sharded(
                q, k, v, mesh,
                q_positions=kw.pop("q_positions", None),
                kv_len=kw.pop("kv_len", None),
                kv_start=kw.pop("kv_start", None),
                window_on=kw.pop("window_on", True),
                causal=True, **kw,
            )
        except (ImportError, NotImplementedError):
            pass
    if dispatch.use_pallas() and q.shape[1] >= 128 and kwargs.get("bias") is None:
        try:
            from ipex_llm_tpu.ops.pallas import flash_attention

            return flash_attention.flash_sdpa(q, k, v, **kwargs)
        except (ImportError, NotImplementedError):
            pass
    return sdpa_reference(q, k, v, **kwargs)


def _decode_kernel_mode(dispatch, op: str | None = None) -> str | None:
    """Which decode-kernel variant the active dispatch state allows:
    'single' (no mesh, Pallas on), 'sharded' (tp mesh with shard_map
    wrappers), or None (jnp/gather fallback).  One policy for both the
    paged and dense decode ladders in :func:`cached_sdpa`; ``op`` keys
    the measured-ladder lookup (dispatch.use_pallas)."""
    mesh = dispatch.spmd_mesh()
    if mesh is None:
        return "single" if dispatch.use_pallas(op) else None
    if mesh.shape.get("tp", 1) > 1 and dispatch.use_pallas_sharded(op):
        return "sharded"
    return None


def cached_sdpa(
    q: jnp.ndarray,            # [B, T, Hq, D]
    kl: jnp.ndarray,           # [B, Hkv, S, D] raw cache layer (maybe fp8)
    vl: jnp.ndarray,
    cache,
    *,
    compute_dtype=jnp.bfloat16,
    **kwargs,
) -> jnp.ndarray:
    """SDPA over a cache layer in its *storage* layout and dtype.

    Decode steps (T=1) route to the specialized Pallas kernel
    (ops/pallas/decode_attention.py) which reads the head-major cache
    natively — including fp8 tiles dequantized in-kernel, the
    ``xe_addons.sdp_fp8`` equivalent (reference models/common.py:273-286).
    Every other shape casts/permutes the layer once and uses the generic
    :func:`sdpa` dispatch (XLA cancels the permute against the flash
    kernel's own head-major view).
    """
    from ipex_llm_tpu.ops import dispatch

    chunk_lens = kwargs.pop("chunk_lens", None)
    if hasattr(cache, "tables"):
        # paged pool layer (serving engine; rows right-aligned from slot 0,
        # queries at slots [kv_len - T, kv_len) — the engine's invariant).
        # The layer arrives in STORAGE dtype: an fp8(e5m2) pool streams its
        # tiles into the Pallas kernels, which widen to bf16 in-kernel (the
        # ``xe_addons.sdp_fp8`` equivalent — HBM reads stay half-width),
        # and the gather fallback gathers the fp8 codes (still half the
        # bytes) before ``decode_layer`` casts once next to the op.
        # The ragged tick rides this same path with a RAGGED right-padded
        # chunk: ``chunk_lens`` [B] names each row's real query count (a
        # decode row has 1, a prefill row up to T, an idle row 0), and the
        # pad tail past a row's last valid token is causally hidden — so
        # ONE kernel program (ops/pallas/ragged_paged_attention.py) serves
        # every row shape in the batch without per-row dispatch.
        if (
            kwargs.get("bias") is None
            and kwargs.get("window") is None
            and kwargs.get("softcap") is None
            and kwargs.get("kv_start") is None
            and kwargs.get("kv_len") is not None
            and q.shape[2] % kl.shape[1] == 0
        ):
            # read ONLY the row's own pages through the scalar-prefetched
            # block table — no table-width gather.  The op family key
            # makes the backend choice data-driven from the measured
            # microbench ladder (dispatch._BUILTIN_LADDER / the env
            # override): the same rows microbench records are what decide
            # kernel-vs-XLA here.
            op = ("ragged_attn_fp8" if "float8" in str(kl.dtype)
                  else "ragged_attn")
            mode = _decode_kernel_mode(dispatch, op)
            if mode is not None:
                try:
                    from ipex_llm_tpu.ops.pallas import \
                        ragged_paged_attention

                    if mode == "single":
                        return ragged_paged_attention.ragged_paged_sdpa(
                            q, kl, vl, cache.tables, kwargs.get("kv_len"),
                            chunk_lens, scale=kwargs.get("scale"))
                    # TP serving: per-shard kernel over the kv-head split
                    return ragged_paged_attention.ragged_paged_sdpa_sharded(
                        q, kl, vl, cache.tables, kwargs.get("kv_len"),
                        dispatch.spmd_mesh(), chunk_lens,
                        scale=kwargs.get("scale"))
                except (ImportError, NotImplementedError):
                    pass
        # fallback: gather the rows' pages into the head-major
        # [B, Hkv, S, D] view; tail pages beyond kv_len are garbage and
        # masked exactly like dense-cache slack (per-row chunk lens are
        # already folded into kv_len by the caller, so the reference mask
        # needs no extra input)
        kl = cache.gather_layer(kl)
        vl = cache.gather_layer(vl)

    t = q.shape[1]
    decode_ok = (
        t == 1
        and kwargs.get("bias") is None
        and dispatch.ring_mesh() is None
        and q.shape[2] % kl.shape[1] == 0
    )
    if decode_ok:
        dk = dict(
            scale=kwargs.get("scale"),
            kv_len=kwargs.get("kv_len"),
            kv_start=kwargs.get("kv_start"),
            window=kwargs.get("window"),
            window_on=kwargs.get("window_on", True),
            softcap=kwargs.get("softcap"),
        )
        op = ("decode_attn_fp8" if "float8" in str(kl.dtype)
              else "decode_attn")
        mode = _decode_kernel_mode(dispatch, op)
        if mode is not None:
            try:
                from ipex_llm_tpu.ops.pallas import decode_attention

                if mode == "single":
                    return decode_attention.decode_sdpa(q, kl, vl, **dk)
                return decode_attention.decode_sdpa_sharded(
                    q, kl, vl, dispatch.spmd_mesh(), **dk
                )
            except (ImportError, NotImplementedError):
                pass
    kd = cache.decode_layer(kl, compute_dtype).transpose(0, 2, 1, 3)
    vd = cache.decode_layer(vl, compute_dtype).transpose(0, 2, 1, 3)
    return sdpa(q, kd, vd, **kwargs)


def packed_mha(x_q, x_k, x_v, in_proj, in_proj_b, o, o_b, n_heads: int):
    """torch ``nn.MultiheadAttention`` semantics over a packed [3E, E]
    ``in_proj`` weight (quantized), shared by the Qwen-VL and MiniCPM-V
    towers.  When q/k/v come from the SAME tensor (ViT self-attention) the
    projection runs as ONE GEMM and splits; the cross-attention form pays
    the packed width per distinct input.
    """
    import jax.numpy as jnp

    from ipex_llm_tpu.ops import linear as linear_ops

    b, nq, e = x_q.shape
    if x_q is x_k and x_k is x_v:
        qkv = linear_ops.linear(x_q.astype(jnp.bfloat16), in_proj, in_proj_b)
        q, k, v = qkv[..., :e], qkv[..., e:2 * e], qkv[..., 2 * e:]
    else:
        q = linear_ops.linear(x_q.astype(jnp.bfloat16), in_proj,
                              in_proj_b)[..., :e]
        k = linear_ops.linear(x_k.astype(jnp.bfloat16), in_proj,
                              in_proj_b)[..., e:2 * e]
        v = linear_ops.linear(x_v.astype(jnp.bfloat16), in_proj,
                              in_proj_b)[..., 2 * e:]
    hd = e // n_heads
    attn = sdpa_reference(
        q.reshape(b, nq, n_heads, hd),
        k.reshape(b, k.shape[1], n_heads, hd),
        v.reshape(b, v.shape[1], n_heads, hd),
        causal=False,
    ).reshape(b, nq, e)
    return linear_ops.linear(attn.astype(jnp.bfloat16), o, o_b
                             ).astype(jnp.float32)
