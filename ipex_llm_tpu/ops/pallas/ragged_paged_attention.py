"""Ragged paged attention: ONE kernel for every row shape in an engine tick.

The "Ragged Paged Attention" design (PAPERS.md, arxiv 2604.15464) applied
to this repo's paged pool: a single Pallas program walks a ragged batch of
rows where each row is either a **decode row** (1 query token at slot
``kv_len - 1``), a **prefill-chunk row** (up to C query tokens right-padded
to the chunk width, causally masked against its own history), or an **idle
row** (``chunk_len == 0`` — fully masked, zero output).  It subsumes both
``paged_attention.paged_decode_sdpa`` and ``paged_prefill_sdpa``, so the
serving engine's fused tick program (``serving/engine.py::_ragged_tick_fn``)
carries exactly one attention kernel family regardless of the admission mix.

What it fixes over the split kernels (the BENCH_r05 per-op losses):

- **per-row raggedness is traced, not static**: ``chunk_len`` [R] rides the
  scalar prefetch, so one compiled program serves every (decode, prefill,
  idle) row mix — the causal mask is keyed off ``(kv_len, chunk_len)`` per
  row instead of a uniform static chunk;
- **fewer pool round-trips per page**: the K/V BlockSpec index maps clamp
  the page-grid index to the row's LAST VALID page, so the tail of the
  static ``maxP`` grid re-maps to an already-resident block and Pallas
  elides the DMA entirely (the old kernel streamed every dead tail page
  from HBM just to skip its compute);
- **query-row tiling**: the whole GQA group x chunk tile ``[G*C, D]`` feeds
  ONE MXU dot per page tile, amortizing each K/V page fetch across every
  query row that needs it (the split decode kernel issued [G, D] slivers);
- **no full-width accumulator re-materialization**: the online-softmax
  state (m/l/acc) lives in VMEM scratch across the page walk and the
  output tile is written exactly once, at the last page step — versus the
  XLA fallback materializing fp32 ``[R, H, C, maxP*ps]`` score/prob
  tensors over the row's full table width per layer.

K/V tiles stream in the pool's STORAGE dtype and widen to the compute
dtype in-kernel: an fp8(e5m2) pool (``EngineConfig.kv_storage="fp8"``)
costs half the HBM bytes end to end — the paged, ragged form of the
reference's ``xe_addons.sdp_fp8`` contract (PR 5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ipex_llm_tpu.ops.pallas._compat import (
    COMPILER_PARAMS as _COMPILER_PARAMS,
    NEG_INF,
    interpret as _interpret,
    round_up as _round_up,
)
from ipex_llm_tpu.parallel.compat import shard_map as _shard_map


def _kernel(tables_ref, len_ref, chunk_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, scale, ps, c, compute_dtype):
    """One (row, kv-head, page) grid step of the ragged walk.

    q rows are the ``[G, C]`` group x chunk tile flattened chunk-minor:
    flat row j is the query at absolute slot
    ``kv_len - chunk_len + (j % c)``, so a decode row (``chunk_len == 1``,
    ``c`` may still be > 1 when batched with prefill rows) reduces to the
    classic single query at ``kv_len - 1``, and a prefill row's valid
    queries are causal against their own history.  Pad query rows
    (``j % c >= chunk_len``) land past ``kv_len`` and read only valid
    slots — bounded garbage the caller discards.  ``chunk_len == 0`` rows
    never enter the live branch and emit exact zeros.
    """
    r = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = len_ref[r]
    chunk = chunk_ref[r]
    lo = pi * ps
    tile_live = (lo < kv_len) & (chunk > 0)

    @pl.when(tile_live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)              # [G*C, D]
        # storage-dtype tile (possibly e5m2) widens HERE, inside the
        # kernel, so fp8 pools stream half the HBM bytes
        k = k_ref[0, 0].astype(compute_dtype).astype(jnp.float32)  # [ps, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [G*C, ps]
        g = s.shape[0]
        kpos = lo + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 1)
        qpos = (kv_len - chunk
                + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 0) % c)
        # per-row causal mask keyed off (kv_len, chunk_len): a pad query
        # (qpos >= kv_len) still needs the kv_len bound — unlike the
        # uniform-chunk kernel, its own position no longer subsumes it
        s = jnp.where((kpos <= qpos) & (kpos < kv_len), s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.maximum(m_new, -1e29)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(jnp.maximum(m_prev, -1e29) - m_safe)
        v = v_ref[0, 0].astype(compute_dtype)            # [ps, Dv]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(pi == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "out_dtype", "c"))
def _ragged(q, k_pool, v_pool, tables, kv_len, chunk_len, *, scale,
            out_dtype, c=1):
    """q [R, Hkv, G*C, D]; k/v_pool [P, Hkv, ps, D(v)]; tables [R, maxP];
    kv_len [R] valid slots incl. this chunk; chunk_len [R] valid queries
    (0 = idle row); ``c`` the static padded chunk width the G axis was
    flattened with."""
    r, hkv, gc, d = q.shape
    n_pages, _, ps, dv = v_pool.shape

    gc_pad = _round_up(gc, 8)
    d_pad = _round_up(d, 128)
    dv_pad = _round_up(dv, 128)
    if (gc_pad, d_pad) != (gc, d):
        q = jnp.pad(q, ((0, 0), (0, 0), (0, gc_pad - gc), (0, d_pad - d)))
    if d_pad != d:
        k_pool = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, d_pad - d)))
    if dv_pad != dv:
        v_pool = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, dv_pad - dv)))
    # unallocated (-1) table slots clip to the engine scratch page 0; the
    # index-map clamp below keeps them from ever being streamed for rows
    # whose kv_len ends earlier
    tables = jnp.clip(tables, 0, n_pages - 1).astype(jnp.int32)
    maxp = tables.shape[1]

    def kv_map(ri, hi, pi, tables_ref, len_ref, chunk_ref):
        # clamp the page walk to the row's last valid page: every tail
        # grid step re-maps to the block already resident from the
        # previous step, so Pallas skips its DMA — dead table width costs
        # no pool round-trips (the page axis is the innermost grid dim).
        # Idle slots (chunk_len 0 — batch pads, ensure-failed rows) clamp
        # to page 0 outright: their kv_len is the scratch-routing
        # sentinel (past the table width), which would otherwise walk
        # the whole grid of someone else's table for a row that computes
        # nothing.
        last = jnp.where(chunk_ref[ri] > 0,
                         jnp.maximum((len_ref[ri] - 1) // ps, 0), 0)
        return (tables_ref[ri, jnp.minimum(pi, last)], hi, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(r, hkv, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, gc_pad, d_pad),
                         lambda ri, hi, pi, t, n, cl: (ri, hi, 0, 0)),
            pl.BlockSpec((1, 1, ps, d_pad), kv_map),
            pl.BlockSpec((1, 1, ps, dv_pad), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, gc_pad, dv_pad),
                               lambda ri, hi, pi, t, n, cl: (ri, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gc_pad, 1), jnp.float32),
            pltpu.VMEM((gc_pad, 1), jnp.float32),
            pltpu.VMEM((gc_pad, dv_pad), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, ps=ps, c=c,
                          compute_dtype=jnp.bfloat16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, hkv, gc_pad, dv_pad), out_dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(tables, kv_len.astype(jnp.int32), chunk_len.astype(jnp.int32),
      q, k_pool, v_pool)
    return out[:, :, :gc, :dv]


def ragged_paged_sdpa(
    q: jnp.ndarray,            # [R, C, Hq, D] right-padded per-row chunks
    k_pool: jnp.ndarray,       # [P, Hkv, ps, D] pool layer (storage dtype)
    v_pool: jnp.ndarray,       # [P, Hkv, ps, Dv]
    tables: jnp.ndarray,       # [R, maxP] int32 (-1 = unallocated)
    kv_len: jnp.ndarray,       # [R] valid slots INCLUDING this chunk
    chunk_len: jnp.ndarray | None = None,  # [R] valid queries; None = all C
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """Ragged-batch attention straight off the paged pool.

    Row i's ``chunk_len[i]`` valid queries sit right-aligned at absolute
    slots ``[kv_len[i] - chunk_len[i], kv_len[i])`` — ``C == 1`` with
    ``chunk_len == 1`` is exactly the decode step, ``chunk_len[i] == 0``
    marks an idle row (zero output), and anything between is a ragged
    prefill chunk whose pad-position outputs are garbage the caller
    discards (the engine's ``gather_positions`` contract).  The chunk's
    own K/V must already be scattered into the pool (the decoder's
    update-then-attend order).  Returns [R, C, Hq, Dv] in q.dtype.
    """
    r, c, hq, d = q.shape
    hkv = k_pool.shape[1]
    if hq % hkv:
        raise NotImplementedError("Hq must be a multiple of Hkv")
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    if chunk_len is None:
        chunk_len = jnp.full((r,), c, jnp.int32)
    # [R, C, Hq, D] -> [R, Hkv, G*C, D], chunk axis minor (kernel contract)
    qg = q.transpose(0, 2, 1, 3).reshape(r, hkv, g, c, d).reshape(
        r, hkv, g * c, d)
    out = _ragged(qg, k_pool, v_pool, tables, kv_len, chunk_len,
                  scale=float(scale), out_dtype=q.dtype, c=c)
    dv = v_pool.shape[-1]
    return out.reshape(r, hkv, g, c, dv).transpose(0, 3, 1, 2, 4).reshape(
        r, c, hq, dv)


def ragged_paged_sdpa_sharded(q, k_pool, v_pool, tables, kv_len, mesh,
                              chunk_len=None, *,
                              scale: float | None = None):
    """TP form: q heads sharded over ``tp``, pool kv heads sharded (or
    GQA-repeated up to ``tp`` — repeat-of-replicated feeding a
    head-sharded consumer lowers to a local per-shard slice); tables,
    lengths, and chunk lens replicated.  Attention is head-local, so the
    per-shard kernel needs no collective — the following row-parallel
    o-proj psum combines shards (the paged_decode_sdpa_sharded contract,
    extended to the ragged batch)."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    hq, hkv = q.shape[2], k_pool.shape[1]
    if hq % tp:
        raise NotImplementedError("q heads must divide tp")
    if hkv % tp:
        if tp % hkv or (hq // hkv) % (tp // hkv):
            raise NotImplementedError("unsupported head/tp factorization")
        rep = tp // hkv
        k_pool = jnp.repeat(k_pool, rep, axis=1)
        v_pool = jnp.repeat(v_pool, rep, axis=1)
    if chunk_len is None:
        chunk_len = jnp.full((q.shape[0],), q.shape[1], jnp.int32)

    def run(ql, kl, vl, tb, ln, cl):
        return ragged_paged_sdpa(ql, kl, vl, tb, ln, cl, scale=scale)

    q_spec = P(None, None, "tp", None)
    pool_spec = P(None, "tp", None, None)
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=(q_spec, pool_spec, pool_spec, P(None, None), P(None),
                  P(None)),
        out_specs=q_spec,
        check_vma=False,
    )(q, k_pool, v_pool, tables, kv_len, chunk_len)
