"""Flash (tiled online-softmax) SDPA kernel.

The ``xe_addons.sdp / sdp_causal`` equivalent (reference models/common.py:
219-306, §2.3), built the TPU way: one grid step per (batch·head, Q tile,
KV tile), running softmax statistics (max, denominator) held in VMEM scratch
across the KV-tile sweep, so the [T, S] score matrix never exists in HBM.

Masking semantics match ``ops.attention.sdpa_reference`` exactly (the test
oracle): static-capacity KV buffer with validity from integer ``kv_len`` /
``kv_start`` per row, causal against absolute ``q_positions``, optional
sliding window with a *traced* per-layer enable flag (gemma2 alternation
enters the kernel as data, not Python control flow), and Gemma-style logit
softcapping.

GQA never materializes repeated K/V: the kv-head for each q-head is picked by
the BlockSpec index map, so K/V tiles stream from HBM once per kv-head.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ipex_llm_tpu.ops.pallas._compat import (
    COMPILER_PARAMS as _COMPILER_PARAMS,
    NEG_INF,
    interpret as _interpret,
    round_up as _round_up,
)
from ipex_llm_tpu.parallel.compat import shard_map as _shard_map


def _kernel(qpos_ref, kvlen_ref, kvstart_ref, won_ref, q_ref, k_ref, v_ref,
            o_ref, m_ref, l_ref, acc_ref, *,
            scale, causal, window, softcap, bs_kv):
    si = pl.program_id(2)

    @pl.when(si == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)          # [BT, D]
    k = k_ref[0].astype(jnp.float32)          # [BS, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                  # [BT, BS]
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap

    bt = s.shape[0]
    kpos = si * bs_kv + jax.lax.broadcasted_iota(jnp.int32, (bt, bs_kv), 1)
    mask = (kpos < kvlen_ref[0, 0]) & (kpos >= kvstart_ref[0, 0])
    if causal:
        qpos = qpos_ref[0]                     # [BT, 1]
        mask &= kpos <= qpos
        if window is not None:
            in_window = kpos > qpos - window
            mask &= in_window | (won_ref[0, 0] == 0)
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_ref[:]                          # [BT, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # keep the running max finite so fully-masked tiles contribute exp(-big)=0
    # without producing NaN via exp(NEG_INF - NEG_INF)
    m_safe = jnp.maximum(m_new, -1e29)
    p = jnp.exp(s - m_safe)                    # [BT, BS]
    alpha = jnp.exp(jnp.maximum(m_prev, -1e29) - m_safe)
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(si == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "causal", "window", "softcap", "n_rep"),
)
def _flash(q, k, v, qpos, kv_len, kv_start, won, *,
           scale, causal, window, softcap, n_rep):
    """q [BH, T, D]; k/v [BKV, S, D]; qpos [B, T]; kv_len/kv_start [B];
    won [B] int32 (per-call window enable, broadcast of the traced flag)."""
    bh, t, d = q.shape
    bkv, s, dv = k.shape[0], k.shape[1], v.shape[2]
    b = qpos.shape[0]
    h = bh // b
    hkv = bkv // b

    bt = min(256, _round_up(t, 16))
    bs_kv = min(512, _round_up(s, 128))
    d_pad = _round_up(d, 128)
    dv_pad = _round_up(dv, 128)
    tp, sp = _round_up(t, bt), _round_up(s, bs_kv)
    if (tp, d_pad) != (t, d):
        q = jnp.pad(q, ((0, 0), (0, tp - t), (0, d_pad - d)))
    if (sp, d_pad) != (s, d):
        k = jnp.pad(k, ((0, 0), (0, sp - s), (0, d_pad - d)))
    if (sp, dv_pad) != (s, dv):
        v = jnp.pad(v, ((0, 0), (0, sp - s), (0, dv_pad - dv)))
    if tp != t:
        # padded q rows attend to slot 0 only; sliced off below either way
        qpos = jnp.pad(qpos, ((0, 0), (0, tp - t)))
    qpos = qpos.astype(jnp.int32)[:, :, None]   # [B, T, 1] column layout

    grid = (bh, tp // bt, sp // bs_kv)

    def b_of(bhi):
        return bhi // h

    def kv_of(bhi):
        return (bhi // h) * hkv + (bhi % h) // n_rep

    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, causal=causal, window=window,
            softcap=softcap, bs_kv=bs_kv,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bt, 1), lambda bhi, ti, si: (b_of(bhi), ti, 0)),
            pl.BlockSpec((1, 1), lambda bhi, ti, si: (b_of(bhi), 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda bhi, ti, si: (b_of(bhi), 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda bhi, ti, si: (b_of(bhi), 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bt, d_pad), lambda bhi, ti, si: (bhi, ti, 0)),
            pl.BlockSpec((1, bs_kv, d_pad), lambda bhi, ti, si: (kv_of(bhi), si, 0)),
            pl.BlockSpec((1, bs_kv, dv_pad), lambda bhi, ti, si: (kv_of(bhi), si, 0)),
        ],
        out_specs=pl.BlockSpec((1, bt, dv_pad), lambda bhi, ti, si: (bhi, ti, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tp, dv_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bt, 1), jnp.float32),
            pltpu.VMEM((bt, 1), jnp.float32),
            pltpu.VMEM((bt, dv_pad), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * tp * sp * d_pad,
            bytes_accessed=2 * (bh * tp * d_pad + 2 * bkv * sp * d_pad),
            transcendentals=bh * tp * sp,
        ),
        interpret=_interpret(),
    )(qpos, kv_len.reshape(-1, 1).astype(jnp.int32),
      kv_start.reshape(-1, 1).astype(jnp.int32),
      won.reshape(-1, 1).astype(jnp.int32), q, k, v)
    return out[:, :t, :dv]


def flash_sdpa(
    q: jnp.ndarray,          # [B, T, Hq, D]
    k: jnp.ndarray,          # [B, S, Hkv, D]
    v: jnp.ndarray,          # [B, S, Hkv, Dv]
    *,
    scale: float | None = None,
    causal: bool = True,
    q_positions: jnp.ndarray | None = None,
    kv_len: jnp.ndarray | None = None,
    kv_start: jnp.ndarray | None = None,
    window: int | None = None,
    window_on: jnp.ndarray | bool = True,
    softcap: float | None = None,
    bias: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Same contract as ``ops.attention.sdpa_reference``; returns
    [B, T, Hq, Dv] in q.dtype."""
    if bias is not None:
        raise NotImplementedError("bias not supported by the flash kernel")
    b, t, hq, d = q.shape
    s, hkv, dv = k.shape[1], k.shape[2], v.shape[3]
    n_rep = hq // hkv
    if scale is None:
        scale = d ** -0.5

    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    if kv_len is None:
        kv_len = jnp.full((b,), s, jnp.int32)
    if kv_start is None:
        kv_start = jnp.zeros((b,), jnp.int32)
    won = jnp.broadcast_to(
        jnp.asarray(window_on, jnp.int32).astype(jnp.int32), (b,)
    )

    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, t, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, dv)
    out = _flash(
        qt, kt, vt, q_positions, kv_len, kv_start, won,
        scale=float(scale), causal=causal,
        window=None if window is None else int(window),
        softcap=None if softcap is None else float(softcap),
        n_rep=n_rep,
    )
    return out.reshape(b, hq, t, dv).transpose(0, 2, 1, 3)


def flash_sdpa_sharded(q, k, v, mesh, *, q_positions=None, kv_len=None,
                       kv_start=None, window_on=True, **static_kwargs):
    """Tensor-parallel flash SDPA: heads sharded over ``tp``, kernel runs
    per-shard under ``jax.shard_map`` (attention is head-local, so no
    collective; only ``tp`` is manual, dp/pp/cp stay under GSPMD)."""
    from jax.sharding import PartitionSpec as P

    b, t, hq, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    tp = mesh.shape["tp"]
    if hq % tp or hkv % tp or (hq // tp) % (hkv // tp):
        raise NotImplementedError("head counts must divide tp")
    if q_positions is None:
        q_positions = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None], (b, t)
        )
    if kv_len is None:
        kv_len = jnp.full((b,), s, jnp.int32)
    if kv_start is None:
        kv_start = jnp.zeros((b,), jnp.int32)
    won = jnp.broadcast_to(jnp.asarray(window_on, jnp.int32), (b,))

    def run(ql, kl, vl, qpos, klen, kstart, wl):
        return flash_sdpa(
            ql, kl, vl, q_positions=qpos, kv_len=klen, kv_start=kstart,
            window_on=wl, **static_kwargs,
        )

    hspec = P(None, None, "tp", None)
    rep2, rep1 = P(None, None), P(None)
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=(hspec, hspec, hspec, rep2, rep1, rep1, rep1),
        out_specs=hspec, check_vma=False,
    )(q, k, v, q_positions.astype(jnp.int32), kv_len, kv_start, won)
