"""Shims and tiny helpers shared by every Pallas kernel module.

jax renamed ``TPUCompilerParams`` -> ``CompilerParams`` (0.4.38); accept
both so the kernels lower (and interpret-run) on either side of the
rename.  One definition — a third name in a future jax lands here, not
in five copy-pasted blocks.  Same rule for the backend probe
(``interpret``: a new TPU-like platform string is added once), the tile
rounding helper, and the masking constant.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

NEG_INF = -1e30


def interpret() -> bool:
    """True off-TPU: kernels run in the (slow) Pallas interpreter."""
    return jax.default_backend() not in ("tpu", "axon")


def round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
