"""Fused block-dequant matmul kernel (the ``xe_linear.forward_new`` /
``xe_batch.batch_forward`` equivalent, reference low_bit_linear.py:545,699).

Design (TPU-first, see SURVEY.md §2.3 "TPU mapping"):

- Weights stay packed in HBM (4-bit: two codes per byte, block-local halves
  layout from quantize/core.py::_pack_nibbles; 8-bit: one code per byte).
  Each grid step DMAs one ``[BK(/2), BN]`` tile into VMEM, unpacks it with a
  reshape + concat (no sublane shuffle, thanks to the halves layout), applies
  the per-block scales, and feeds the MXU.  HBM traffic per weight is ~4.5
  bits instead of 16 — the decode-path win the reference gets from its SYCL
  kernels.
- Accumulation runs in fp32 in the revisited output block across the K grid
  dimension (innermost), the standard Pallas matmul pattern.
- The contraction (K) axis is the quantization-block axis, so a K tile always
  covers whole quantization blocks and scales slice as ``[BK/bs, BN]``.

Supported formats: sym_int4 / asym_int4 / sym_int8, the 4-bit codebook
formats nf4 / fp4 (16-entry lookup unrolled as a select chain on the VPU),
the minifloats fp8_e4m3 / fp8_e5m2 / fp6 (exponent/mantissa decoded
arithmetically in-kernel — ``exp2`` on the VPU, no 256-entry table), and
sym/asym_int5 (dual-plane unpack of the _pack_5bit layout: nibble plane +
bit plane).  Anything else falls back to the XLA reference path in
ops/linear.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ipex_llm_tpu.ops.pallas._compat import (
    COMPILER_PARAMS as _COMPILER_PARAMS,
    interpret as _interpret,
    round_up as _round_up,
)
from ipex_llm_tpu.parallel.compat import shard_map as _shard_map

from ipex_llm_tpu.quantize import numerics
from ipex_llm_tpu.quantize.core import QTensor

_NIB4 = ("sym_int4", "asym_int4", "nf4", "fp4")
_BIT5 = ("sym_int5", "asym_int5")
_MINIFLOAT = {  # qtype -> (exp_bits, man_bits, bias)
    "fp8_e4m3": (4, 3, 7),
    "fp8_e5m2": (5, 2, 15),
    "fp6": (3, 2, 3),
}
_SUPPORTED = _NIB4 + _BIT5 + ("sym_int8",) + tuple(_MINIFLOAT)


def _data_row_factor(qtype: str) -> tuple[int, int]:
    """(num, den): logical K rows = data rows * num / den."""
    if qtype in _NIB4:
        return 2, 1
    if qtype in _BIT5:
        return 8, 5
    return 1, 1


def _codebook_select(codes: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    """16-entry codebook lookup as an unrolled select chain (VPU-friendly)."""
    out = jnp.full(codes.shape, float(table[0]), jnp.float32)
    for i in range(1, len(table)):
        out = jnp.where(codes == i, float(table[i]), out)
    return out


def _minifloat_decode(c: jnp.ndarray, exp_bits: int, man_bits: int,
                      bias: int) -> jnp.ndarray:
    """Arithmetic 1+e+m minifloat decode (matches numerics._minifloat_table):
    sign × (1 + m/2^mb) × 2^(e-bias), subnormals m/2^mb × 2^(1-bias)."""
    man_div = float(1 << man_bits)
    sign = 1.0 - 2.0 * ((c >> (exp_bits + man_bits)) & 1).astype(jnp.float32)
    e = ((c >> man_bits) & ((1 << exp_bits) - 1)).astype(jnp.float32)
    man = (c & ((1 << man_bits) - 1)).astype(jnp.float32)
    mag = jnp.where(
        e > 0,
        (1.0 + man / man_div) * jnp.exp2(e - bias),
        man / man_div * (2.0 ** (1 - bias)),
    )
    return sign * mag


def _dequant_tile(codes, scales, zeros, qtype: str, bs: int, bk: int, bn: int,
                  high=None):
    """codes [BK(/2), BN] (+ ``high`` [BK/8, BN] for 5-bit) -> w [BK, BN]
    f32 inside the kernel."""
    nb = bk // bs
    # Mosaic can't lower uint8 bit-ops/casts directly; widen to int32 first
    if qtype in _NIB4 or qtype in _BIT5:
        p = codes.reshape(nb, bs // 2, bn).astype(jnp.int32)
        c = jnp.concatenate([p & 0x0F, p >> 4], axis=1)  # [nb, bs, bn]
        if qtype in _BIT5:  # OR in the fifth-bit plane (core.py::_pack_5bit)
            hb = high.astype(jnp.int32)  # [bk//8, bn]
            hi = jnp.stack([(hb >> j) & 1 for j in range(8)], axis=1)
            c = c | (hi.reshape(nb, bs, bn) << 4)
    else:  # byte-per-code: sym_int8 / fp8 / fp6
        c = codes.reshape(nb, bs, bn).astype(jnp.int32)
    s = scales.reshape(nb, 1, bn)
    if qtype == "sym_int4":
        w = (c.astype(jnp.float32) - 8.0) * s
    elif qtype == "sym_int5":
        w = (c.astype(jnp.float32) - 16.0) * s
    elif qtype == "sym_int8":
        w = (c.astype(jnp.float32) - 128.0) * s
    elif qtype in ("asym_int4", "asym_int5"):
        w = c.astype(jnp.float32) * s + zeros.reshape(nb, 1, bn)
    elif qtype == "nf4":
        w = _codebook_select(c, numerics.NF4_TABLE) * s
    elif qtype in _MINIFLOAT:
        w = _minifloat_decode(c, *_MINIFLOAT[qtype]) * s
    else:  # fp4
        w = _codebook_select(c, numerics.FP4_TABLE) * s
    return w.reshape(bk, bn)


def _make_kernel(qtype, bs, bk, bn, compute_dtype, has_high, has_zeros):
    def kern(*refs):
        x_ref, d_ref = refs[0], refs[1]
        i = 2
        h_ref = None
        if has_high:
            h_ref, i = refs[i], i + 1
        s_ref, i = refs[i], i + 1
        z_ref = refs[i] if has_zeros else None
        o_ref = refs[-1]

        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

        w = _dequant_tile(
            d_ref[:], s_ref[:], None if z_ref is None else z_ref[:],
            qtype, bs, bk, bn,
            high=None if h_ref is None else h_ref[:],
        ).astype(compute_dtype)
        o_ref[:] += jnp.dot(
            x_ref[:].astype(compute_dtype), w,
            preferred_element_type=jnp.float32,
        )

    return kern


@functools.partial(
    jax.jit, static_argnames=("qtype", "bs", "logical_out", "compute_dtype")
)
def _qmatmul_2d(x, data, scales, zeros, *, qtype: str, bs: int,
                logical_out: int, compute_dtype):
    """x [M, K_pad] @ dequant(data) [K_pad, N_pad] -> [M, logical_out]."""
    m, k = x.shape
    n = data.shape[1]
    bit5 = qtype in _BIT5
    num, den = _data_row_factor(qtype)

    bm = min(128, _round_up(m, 16))
    bn = min(512, _round_up(n, 128))
    # K tile: whole quantization blocks, target ~2048 contraction rows
    bk = min(k, _round_up(min(k, 2048), bs))

    # pad every dim so grid blocks tile exactly (zero scale rows/cols are
    # numerically inert: dequant yields w=0 there for all supported formats
    # except asym_int4/5, whose zero-point plane is also zero-padded)
    mp, kp, np_ = _round_up(m, bm), _round_up(k, bk), _round_up(n, bn)
    if mp != m or kp != k:
        x = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    high = None
    if bit5:
        # split the _pack_5bit planes: [K/2, N] nibbles ++ [K/8, N] top bits
        high = data[k // 2:]
        data = data[: k // 2]
        if kp != k or np_ != n:
            data = jnp.pad(data, ((0, (kp - k) // 2), (0, np_ - n)))
            high = jnp.pad(high, ((0, (kp - k) // 8), (0, np_ - n)))
    else:
        drows = kp * den // num
        if data.shape[0] != drows or np_ != n:
            data = jnp.pad(data, ((0, drows - data.shape[0]), (0, np_ - n)))
    nb_p = kp // bs
    scales = jnp.pad(
        scales, ((0, nb_p - scales.shape[0]), (0, np_ - n))
    ).astype(jnp.float32)
    if zeros is not None:
        zeros = jnp.pad(
            zeros, ((0, nb_p - zeros.shape[0]), (0, np_ - n))
        ).astype(jnp.float32)

    grid = (mp // bm, np_ // bn, kp // bk)
    d_rows = bk // 2 if (qtype in _NIB4 or bit5) else bk
    blk = lambda mi, ni, ki: (ki, ni)  # noqa: E731
    in_specs = [
        pl.BlockSpec((bm, bk), lambda mi, ni, ki: (mi, ki)),
        pl.BlockSpec((d_rows, bn), blk),
    ]
    args = [x, data]
    if bit5:
        in_specs.append(pl.BlockSpec((bk // 8, bn), blk))
        args.append(high)
    in_specs.append(pl.BlockSpec((bk // bs, bn), blk))
    args.append(scales)
    if zeros is not None:
        in_specs.append(pl.BlockSpec((bk // bs, bn), blk))
        args.append(zeros)

    kern = _make_kernel(qtype, bs, bk, bn, compute_dtype,
                        has_high=bit5, has_zeros=zeros is not None)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni, ki: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * kp,
            bytes_accessed=(
                mp * kp * 2 + (kp * np_ * den // num) + mp * np_ * 4
            ),
            transcendentals=0,
        ),
        interpret=_interpret(),
    )(*args)
    return out[:m, :logical_out]


def qmatmul_pallas(x: jnp.ndarray, qt: QTensor, compute_dtype=jnp.bfloat16,
                   keep_f32: bool = False):
    """x [..., in] @ dequant(qt) -> [..., out] via the fused Pallas kernel.

    ``keep_f32`` returns the fp32 accumulator untouched (the row-parallel
    shard_map wrapper psums partial products in fp32 before the final cast).
    """
    if qt.qtype not in _SUPPORTED:
        raise NotImplementedError(qt.qtype)
    lead = x.shape[:-1]
    k = x.shape[-1]
    num, den = _data_row_factor(qt.qtype)
    k_pad = qt.data.shape[0] * num // den
    x2 = x.reshape(-1, k)
    if k_pad != k:  # quantization block padding (core.py::_to_blocks)
        x2 = jnp.pad(x2, ((0, 0), (0, k_pad - k)))
    out = _qmatmul_2d(
        x2, qt.data, qt.scales, qt.zeros,
        qtype=qt.qtype, bs=qt.block_size, logical_out=qt.out_features,
        compute_dtype=compute_dtype,
    )
    out = out.reshape(*lead, qt.out_features)
    return out if keep_f32 else out.astype(x.dtype)


def qmatmul_pallas_sharded(x: jnp.ndarray, qt: QTensor, mesh,
                           compute_dtype=jnp.bfloat16):
    """Tensor-parallel fused dequant-matmul: the kernel runs per-shard under
    ``jax.shard_map`` with only the ``tp`` axis manual, so dp/pp/cp stay
    under GSPMD management (partial-auto mode).

    - ``tp_mode='col'`` (qkv/gate_up): weight planes sharded on the out
      axis, x replicated over tp, output tp-sharded on its last axis — no
      collective.
    - ``tp_mode='row'`` (o/down): weight planes sharded on the in axis, x
      tp-sharded on its last axis, fp32 partials combined with ``psum``
      over ICI (the AutoTP ``inference_all_reduce`` equivalent, reference
      low_bit_linear.py:715-722) — but here fused right after the kernel.
    """
    from jax.sharding import PartitionSpec as P

    if qt.qtype not in _SUPPORTED:
        raise NotImplementedError(qt.qtype)
    tp = mesh.shape["tp"]
    lead = (None,) * (x.ndim - 1)
    has_zeros = qt.zeros is not None

    if qt.tp_mode == "col":
        if qt.out_features % tp:
            raise NotImplementedError("out_features not divisible by tp")
        local_shape = (qt.in_features, qt.out_features // tp)
        w_spec = P(None, "tp")
        x_spec = P(*lead, None)
        out_spec = P(*lead, "tp")
    elif qt.tp_mode == "row":
        bs = qt.block_size or 1
        if qt.in_features % (bs * tp) or qt.qtype in _BIT5:
            raise NotImplementedError("in_features not divisible by bs*tp")
        local_shape = (qt.in_features // tp, qt.out_features)
        w_spec = P("tp", None)
        x_spec = P(*lead, "tp")
        out_spec = P(*lead, None)
    else:
        raise NotImplementedError(f"tp_mode={qt.tp_mode}")

    def run(xl, data, scales, zeros=None):
        lqt = QTensor(data, scales, zeros, qt.qtype, local_shape,
                      qt.block_size)
        if qt.tp_mode == "col":
            return qmatmul_pallas(xl, lqt, compute_dtype)
        part = qmatmul_pallas(xl, lqt, compute_dtype, keep_f32=True)
        return jax.lax.psum(part, "tp").astype(xl.dtype)

    in_specs = [x_spec, w_spec, w_spec] + ([w_spec] if has_zeros else [])
    args = [x, qt.data, qt.scales] + ([qt.zeros] if has_zeros else [])
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=tuple(in_specs), out_specs=out_spec, check_vma=False,
    )(*args)
