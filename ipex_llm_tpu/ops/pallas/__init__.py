"""Pallas TPU kernel library.

The ``xe_linear`` / ``xe_batch`` / ``xe_addons`` equivalent (reference §2.3;
call sites low_bit_linear.py:545,699, models/common.py:219-306): the hot ops
where a hand-written kernel beats XLA's default lowering —

- ``qmatmul``: fused block-dequant matmul.  Streams packed sub-byte codes
  from HBM and unpacks them in VMEM next to the MXU, so INT4 decode moves
  ~4x fewer HBM bytes than a bf16 matmul (the whole point of low-bit on a
  bandwidth-bound decode).
- ``flash_attention``: tiled online-softmax SDPA for long-sequence prefill;
  never materializes the [T, S] score matrix in HBM.

Every kernel has a pure-jnp reference twin in ``ipex_llm_tpu.ops`` used as
the CPU fallback and the test oracle; kernels run in interpreter mode off-TPU
so the same code paths are exercised by the CPU test suite.
"""
