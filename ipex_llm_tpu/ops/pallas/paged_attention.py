"""Paged decode attention: block-table KV read directly from the page pool.

Reference counterpart: the role vLLM's PagedAttention kernels play for the
reference's serving stack (SURVEY §2.1 vllm/).  The r3 fallback gathers a
row's pages into a contiguous [R, H, S_max, D] buffer every step — correct,
but it materializes table-width KV per layer.  This kernel instead uses
Pallas **scalar-prefetched block tables**: the grid's page axis indexes the
pool THROUGH the table inside each BlockSpec index_map, so the DMA engine
streams exactly the row's own pages (invalid tail pages clip to the
engine's scratch page 0 and are masked by ``kv_len``).

Same online-softmax structure as ops/pallas/decode_attention.py; rows are
right-aligned from slot 0 (the paged engine's invariant), so there is no
``kv_start``.

K/V tiles stream in the pool's STORAGE dtype: an fp8(e5m2) pool
(``EngineConfig.kv_storage="fp8"``) is read as e5m2 codes and widened to
the compute dtype *inside* the kernel — the paged form of
``xe_addons.sdp_fp8`` (reference models/utils.py:102-192), so fp8 KV
actually halves the decode path's HBM traffic rather than paying a
full-width materialization before attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ipex_llm_tpu.ops.pallas._compat import (
    COMPILER_PARAMS as _COMPILER_PARAMS,
    NEG_INF,
    interpret as _interpret,
    round_up as _round_up,
)
from ipex_llm_tpu.parallel.compat import shard_map as _shard_map


def _kernel(tables_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, scale, ps, chunk, compute_dtype):
    """``chunk=1``: decode (each q row sees slots [0, kv_len)).  ``chunk=C``:
    chunked prefill — q rows are [G, C] flattened with the chunk axis minor,
    row j is the query at absolute slot ``kv_len - C + j % C`` and sees only
    slots up to itself (causal), which also hides the right-pad garbage the
    engine wrote past ``n_valid``."""
    r = pl.program_id(0)
    pi = pl.program_id(2)

    @pl.when(pi == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = len_ref[r]
    lo = pi * ps
    tile_live = lo < kv_len

    @pl.when(tile_live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)             # [G, D]
        k = k_ref[0, 0].astype(compute_dtype).astype(jnp.float32)  # [ps, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [G, ps]
        g = s.shape[0]
        kpos = lo + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 1)
        qpos = (kv_len - chunk
                + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 0) % chunk)
        s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.maximum(m_new, -1e29)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(jnp.maximum(m_prev, -1e29) - m_safe)
        v = v_ref[0, 0].astype(compute_dtype)            # [ps, Dv]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(pi == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "out_dtype", "chunk"))
def _paged(q, k_pool, v_pool, tables, kv_len, *, scale, out_dtype, chunk=1):
    """q [R, Hkv, G, D]; k/v_pool [P, Hkv, ps, D(v)]; tables [R, maxP];
    kv_len [R].  ``chunk`` > 1 marks the G axis as [groups, chunk] flattened
    prefill queries (see _kernel)."""
    r, hkv, g, d = q.shape
    n_pages, _, ps, dv = v_pool.shape

    g_pad = _round_up(g, 8)
    d_pad = _round_up(d, 128)
    dv_pad = _round_up(dv, 128)
    if (g_pad, d_pad) != (g, d):
        q = jnp.pad(q, ((0, 0), (0, 0), (0, g_pad - g), (0, d_pad - d)))
    if d_pad != d:
        k_pool = jnp.pad(k_pool, ((0, 0), (0, 0), (0, 0), (0, d_pad - d)))
    if dv_pad != dv:
        v_pool = jnp.pad(v_pool, ((0, 0), (0, 0), (0, 0), (0, dv_pad - dv)))
    # unallocated (-1) table slots clip to the engine scratch page 0; their
    # positions sit beyond kv_len and are masked in-kernel
    tables = jnp.clip(tables, 0, n_pages - 1).astype(jnp.int32)
    maxp = tables.shape[1]

    def k_map(ri, hi, pi, tables_ref, len_ref):
        return (tables_ref[ri, pi], hi, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r, hkv, maxp),
        in_specs=[
            pl.BlockSpec((1, 1, g_pad, d_pad),
                         lambda ri, hi, pi, t, n: (ri, hi, 0, 0)),
            pl.BlockSpec((1, 1, ps, d_pad), k_map),
            pl.BlockSpec((1, 1, ps, dv_pad), k_map),
        ],
        out_specs=pl.BlockSpec((1, 1, g_pad, dv_pad),
                               lambda ri, hi, pi, t, n: (ri, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, dv_pad), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, ps=ps, chunk=chunk,
                          compute_dtype=jnp.bfloat16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((r, hkv, g_pad, dv_pad), out_dtype),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(tables, kv_len.astype(jnp.int32), q, k_pool, v_pool)
    return out[:, :, :g, :dv]


def paged_decode_sdpa(
    q: jnp.ndarray,            # [R, 1, Hq, D]
    k_pool: jnp.ndarray,       # [P, Hkv, ps, D] pool layer
    v_pool: jnp.ndarray,       # [P, Hkv, ps, Dv]
    tables: jnp.ndarray,       # [R, maxP] int32 (-1 = unallocated)
    kv_len: jnp.ndarray,       # [R]
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """T=1 attention straight off the paged pool; returns [R, 1, Hq, Dv]."""
    r, t, hq, d = q.shape
    assert t == 1, "paged kernel is specialized for single-token steps"
    hkv = k_pool.shape[1]
    if hq % hkv:
        raise NotImplementedError("Hq must be a multiple of Hkv")
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    qg = q[:, 0].reshape(r, hkv, g, d)
    out = _paged(qg, k_pool, v_pool, tables, kv_len,
                 scale=float(scale), out_dtype=q.dtype)
    return out.reshape(r, 1, hq, v_pool.shape[-1])


def paged_prefill_sdpa(
    q: jnp.ndarray,            # [R, C, Hq, D] right-padded prompt chunk
    k_pool: jnp.ndarray,       # [P, Hkv, ps, D] pool layer (chunk written)
    v_pool: jnp.ndarray,       # [P, Hkv, ps, Dv]
    tables: jnp.ndarray,       # [R, maxP] int32 (-1 = unallocated)
    kv_len: jnp.ndarray,       # [R] slots incl. this chunk (base + C)
    *,
    scale: float | None = None,
) -> jnp.ndarray:
    """Chunked-prefill attention straight off the paged pool (VERDICT r3
    weak #3: the gather fallback materialized the row's full-capacity
    [1, H, maxP*ps, D] view per layer per chunk).  The chunk's own K/V must
    already be scattered into the pool (the decoder's update-then-attend
    order); queries are right-aligned at slots [kv_len - C, kv_len) and
    causally masked in-kernel, so right-pad garbage past ``n_valid`` is
    never seen by valid queries.  Returns [R, C, Hq, Dv]."""
    r, c, hq, d = q.shape
    hkv = k_pool.shape[1]
    if hq % hkv:
        raise NotImplementedError("Hq must be a multiple of Hkv")
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    # [R, C, Hq, D] -> [R, Hkv, g*C, D], chunk axis minor (kernel contract)
    qg = q.transpose(0, 2, 1, 3).reshape(r, hkv, g, c, d).reshape(
        r, hkv, g * c, d)
    out = _paged(qg, k_pool, v_pool, tables, kv_len,
                 scale=float(scale), out_dtype=q.dtype, chunk=c)
    dv = v_pool.shape[-1]
    return out.reshape(r, hkv, g, c, dv).transpose(0, 3, 1, 2, 4).reshape(
        r, c, hq, dv)


def paged_prefill_sdpa_sharded(q, k_pool, v_pool, tables, kv_len, mesh, *,
                               scale: float | None = None):
    """TP form of :func:`paged_prefill_sdpa`; head split identical to
    :func:`paged_decode_sdpa_sharded` (incl. the GQA kv-head repeat)."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    hq, hkv = q.shape[2], k_pool.shape[1]
    if hq % tp:
        raise NotImplementedError("q heads must divide tp")
    if hkv % tp:
        if tp % hkv or (hq // hkv) % (tp // hkv):
            raise NotImplementedError("unsupported head/tp factorization")
        rep = tp // hkv
        k_pool = jnp.repeat(k_pool, rep, axis=1)
        v_pool = jnp.repeat(v_pool, rep, axis=1)

    def run(ql, kl, vl, tb, ln):
        return paged_prefill_sdpa(ql, kl, vl, tb, ln, scale=scale)

    q_spec = P(None, None, "tp", None)
    pool_spec = P(None, "tp", None, None)
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=(q_spec, pool_spec, pool_spec, P(None, None), P(None)),
        out_specs=q_spec,
        check_vma=False,
    )(q, k_pool, v_pool, tables, kv_len)


def paged_decode_sdpa_sharded(q, k_pool, v_pool, tables, kv_len, mesh, *,
                              scale: float | None = None):
    """Tensor-parallel paged decode: q heads sharded over ``tp``.

    Two regimes (parallel/shard.py::shard_paged_cache conventions):

    - ``Hkv % tp == 0``: the pool layer [P, Hkv, ps, D] is head-sharded too
      and each shard's kernel reads only its own kv slice;
    - ``tp % Hkv == 0`` (GQA with fewer kv heads than chips — the 70B
      north-star: 8 kv heads on tp=16): kv heads are repeated up to ``tp``
      before the shard_map; XLA turns repeat-of-replicated + head-sharded
      consumer into a local slice, so each shard reads the ONE kv head its
      q-head group attends to.

    Block tables and lengths are replicated.  Attention is head-local so
    the per-shard kernel needs no collective — the following row-parallel
    o-proj psum combines shards, the same contract as decode_sdpa_sharded
    (reference role: vLLM TP paged-attention workers, SURVEY §2.1 vllm/).
    """
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    hq, hkv = q.shape[2], k_pool.shape[1]
    if hq % tp:
        raise NotImplementedError("q heads must divide tp")
    if hkv % tp:
        if tp % hkv or (hq // hkv) % (tp // hkv):
            raise NotImplementedError("unsupported head/tp factorization")
        # repeat kv heads up to tp: the source is replicated, the consumer
        # spec is head-sharded, so XLA lowers this to a LOCAL slice per
        # shard — no materialized [P, tp, ps, D] array, and per-chip HBM
        # traffic stays that shard's single kv head
        rep = tp // hkv
        k_pool = jnp.repeat(k_pool, rep, axis=1)
        v_pool = jnp.repeat(v_pool, rep, axis=1)

    def run(ql, kl, vl, tb, ln):
        return paged_decode_sdpa(ql, kl, vl, tb, ln, scale=scale)

    q_spec = P(None, None, "tp", None)
    pool_spec = P(None, "tp", None, None)
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=(q_spec, pool_spec, pool_spec, P(None, None), P(None)),
        out_specs=q_spec,
        check_vma=False,
    )(q, k_pool, v_pool, tables, kv_len)
