"""Decode-step (T=1) fused attention kernel.

The reference serves decode attention with ``xe_addons.sdp`` and its fp8
variants ``sdp_fp8*`` (models/common.py:273-286); the repo's r2 gap
(VERDICT weak#4) was that T=1 steps ran the jnp reference path: fp32
``[B,H,1,S_max]`` scores over the whole static capacity, plus — on the fp8
cache — a full bf16 materialization of every layer's K/V before attention.

This kernel is built for the decode hot loop:

- K/V stream from HBM **in the cache's native head-major ``[B, Hkv, S, D]``
  layout and storage dtype** — no XLA-level transpose or cast of the cache
  ever materializes, and each grid step's ``[S_block, D]`` tile is a
  contiguous per-head stream (Mosaic's last-two-dims tile requirement).
  fp8(e5m2) tiles are widened to bf16 *inside* the kernel, so fp8 KV
  actually halves HBM traffic (the reason the format exists).
- Grid ``(B, Hkv, S_blocks)``: one q-head group (the GQA group of
  ``Hq/Hkv`` heads) per kv head, flash-style online softmax over KV tiles
  held in VMEM scratch.
- Tiles fully outside ``[kv_start, kv_len)`` skip their compute via
  ``pl.when`` (their DMA still runs — grid shapes are static; capacity
  bucketing in generation.py keeps dead slack ≤ one DECODE_BLOCK).

Masking semantics match ``ops.attention.sdpa_reference`` for a T=1 query at
absolute position ``kv_len - 1``: slots ``[kv_start, kv_len)`` are valid,
sliding window (traced enable flag) and softcap as in the prefill kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ipex_llm_tpu.ops.pallas._compat import (
    COMPILER_PARAMS as _COMPILER_PARAMS,
    NEG_INF,
    interpret as _interpret,
    round_up as _round_up,
)
from ipex_llm_tpu.parallel.compat import shard_map as _shard_map


def _kernel(len_ref, start_ref, won_ref, q_ref, k_ref, v_ref, o_ref,
            m_ref, l_ref, acc_ref, *, scale, window, softcap, bs_kv,
            compute_dtype):
    b = pl.program_id(0)
    si = pl.program_id(2)

    @pl.when(si == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = len_ref[b]
    kv_start = start_ref[b]
    # tile intersects the valid slot range [kv_start, kv_len)?
    lo = si * bs_kv
    tile_live = (lo < kv_len) & (lo + bs_kv > kv_start)

    @pl.when(tile_live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)            # [G, D]
        k = k_ref[0, 0].astype(compute_dtype).astype(jnp.float32)
        s = jax.lax.dot_general(                        # [G, BS]
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap

        g = s.shape[0]
        kpos = lo + jax.lax.broadcasted_iota(jnp.int32, (g, bs_kv), 1)
        mask = (kpos < kv_len) & (kpos >= kv_start)
        if window is not None:
            in_window = kpos > (kv_len - 1) - window
            mask &= in_window | (won_ref[0] == 0)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.maximum(m_new, -1e29)
        p = jnp.exp(s - m_safe)
        alpha = jnp.exp(jnp.maximum(m_prev, -1e29) - m_safe)
        v = v_ref[0, 0].astype(compute_dtype)           # [BS, Dv]
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(compute_dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(si == pl.num_programs(2) - 1)
    def _():
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "window", "softcap", "out_dtype"),
)
def _decode(q, k, v, kv_len, kv_start, won, *, scale, window, softcap,
            out_dtype):
    """q [B, Hkv, G, D]; k [B, Hkv, S, D]; v [B, Hkv, S, Dv] (storage
    dtype, possibly fp8); kv_len/kv_start/won [B] int32."""
    b, hkv, g, d = q.shape
    s, dv = k.shape[2], v.shape[3]

    g_pad = _round_up(g, 8)
    d_pad = _round_up(d, 128)
    dv_pad = _round_up(dv, 128)
    bs_kv = min(512, _round_up(s, 128))
    sp = _round_up(s, bs_kv)
    if (g_pad, d_pad) != (g, d):
        q = jnp.pad(q, ((0, 0), (0, 0), (0, g_pad - g), (0, d_pad - d)))
    if (sp, d_pad) != (s, d):
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sp - s), (0, d_pad - d)))
    if (sp, dv_pad) != (s, dv):
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sp - s), (0, dv_pad - dv)))

    grid = (b, hkv, sp // bs_kv)
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, window=window, softcap=softcap,
            bs_kv=bs_kv, compute_dtype=jnp.bfloat16,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # kv_len [B]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # kv_start [B]
            pl.BlockSpec(memory_space=pltpu.SMEM),   # window enable [B]
            pl.BlockSpec((1, 1, g_pad, d_pad), lambda bi, hi, si: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bs_kv, d_pad), lambda bi, hi, si: (bi, hi, si, 0)),
            pl.BlockSpec((1, 1, bs_kv, dv_pad), lambda bi, hi, si: (bi, hi, si, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g_pad, dv_pad), lambda bi, hi, si: (bi, hi, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g_pad, dv_pad), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, 1), jnp.float32),
            pltpu.VMEM((g_pad, dv_pad), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hkv * g_pad * sp * d_pad,
            bytes_accessed=(
                b * sp * hkv * (d_pad + dv_pad) * k.dtype.itemsize
                + b * hkv * g_pad * d_pad * 2
            ),
            transcendentals=b * hkv * g_pad * sp,
        ),
        interpret=_interpret(),
    )(kv_len.astype(jnp.int32), kv_start.astype(jnp.int32),
      won.astype(jnp.int32), q, k, v)
    return out[:, :, :g, :dv]


def decode_sdpa(
    q: jnp.ndarray,            # [B, 1, Hq, D]
    k_raw: jnp.ndarray,        # [B, Hkv, S, D] cache storage layout/dtype
    v_raw: jnp.ndarray,        # [B, Hkv, S, Dv]
    *,
    scale: float | None = None,
    kv_len: jnp.ndarray | None = None,
    kv_start: jnp.ndarray | None = None,
    window: int | None = None,
    window_on: jnp.ndarray | bool = True,
    softcap: float | None = None,
) -> jnp.ndarray:
    """T=1 attention over the raw (possibly fp8) head-major KV cache.

    Returns [B, 1, Hq, Dv] in q.dtype.  The query is assumed to sit at
    absolute position ``kv_len - 1`` (the decode-loop invariant), which
    subsumes the causal mask.
    """
    b, t, hq, d = q.shape
    assert t == 1, "decode kernel is specialized for single-token steps"
    hkv, s, dv = k_raw.shape[1], k_raw.shape[2], v_raw.shape[3]
    if hq % hkv:
        raise NotImplementedError("Hq must be a multiple of Hkv")
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    if kv_len is None:
        kv_len = jnp.full((b,), s, jnp.int32)
    if kv_start is None:
        kv_start = jnp.zeros((b,), jnp.int32)
    won = jnp.broadcast_to(jnp.asarray(window_on, jnp.int32), (b,))

    # [B, 1, Hq, D] -> [B, Hkv, G, D]: head h of kv-group kvh is q-head
    # kvh*G + h, matching sdpa_reference's _repeat_kv expansion order
    qg = q[:, 0].reshape(b, hkv, g, d)
    out = _decode(
        qg, k_raw, v_raw, kv_len, kv_start, won,
        scale=float(scale),
        window=None if window is None else int(window),
        softcap=None if softcap is None else float(softcap),
        out_dtype=q.dtype,
    )
    return out.reshape(b, 1, hq, dv)


def decode_sdpa_sharded(q, k_raw, v_raw, mesh, **kwargs):
    """Tensor-parallel decode attention: heads are sharded over ``tp``
    (cache_sharding in parallel/shard.py), so the kernel runs per-shard
    under ``jax.shard_map`` with only ``tp`` manual — no collective needed
    (attention is head-local; the following o-proj row-psum combines)."""
    from jax.sharding import PartitionSpec as P

    tp = mesh.shape["tp"]
    hq, hkv = q.shape[2], k_raw.shape[1]
    if hq % tp:
        raise NotImplementedError("q heads must divide tp")
    if hkv % tp:
        if tp % hkv or (hq // hkv) % (tp // hkv):
            raise NotImplementedError("unsupported head/tp factorization")
        # GQA with fewer kv heads than chips (70B north-star: 8 kv heads,
        # tp=16): repeat kv heads up to tp — repeat-of-replicated feeding a
        # head-sharded consumer lowers to a local per-shard slice, so each
        # chip reads only the kv head its q-head group attends to
        rep = tp // hkv
        k_raw = jnp.repeat(k_raw, rep, axis=1)
        v_raw = jnp.repeat(v_raw, rep, axis=1)

    def run(ql, kl, vl):
        return decode_sdpa(ql, kl, vl, **kwargs)

    q_spec = P(None, None, "tp", None)
    kv_spec = P(None, "tp", None, None)
    return _shard_map(
        run, mesh=mesh, axis_names={"tp"},
        in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec,
        check_vma=False,
    )(q, k_raw, v_raw)
