"""Self-speculative + prompt-lookup decoding, fully jitted.

Reference counterparts: ``speculative_generate`` (reference
speculative.py:805-1100 — draft k tokens with the sym_int4 copy of the same
weights, verify in ONE batched target forward, accept the longest matching
prefix, crop the KV cache) and ``PromptLookupCandidateGenerator`` /
``lookup_generate`` (lookup.py:145-274 — n-gram candidates mined from the
sequence so far, no draft model at all).

TPU-native redesign (one XLA program, zero host syncs per round):

- the whole draft→verify→accept loop is a ``lax.while_loop``; every round
  has a static shape (k draft steps, k+1 verify tokens);
- **KV "crop" is free**: cache validity is governed by the ``length`` scalar
  that masks attention (kv.py), so rolling back speculative entries is just
  resetting ``length`` — no copies, unlike the reference's
  ``_crop_past_key_values`` tensor surgery (speculative.py:480);
- the draft cache is healed by an idempotent 2-token catch-up step each
  round: re-writing a KV slot for an already-accepted token produces
  identical values, so the draft cache never needs rollback bookkeeping;
- prompt-lookup runs the same verify loop with the draft forward replaced by
  a vectorized n-gram scan over the generated-so-far ring.

Greedy only (the reference's benchmark path): with greedy verification the
output is guaranteed token-identical to plain target-model decoding.
"""

from __future__ import annotations

import time
from dataclasses import replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu import kv as kv_mod
from ipex_llm_tpu.generation import (
    DECODE_BLOCK,
    GenerateResult,
    GenerationConfig,
    _round_up,
    pad_batch,
)
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.decoder import decoder_forward


def _greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def _forward_at(cfg, params, cache, seq_buf, start, t: int, length):
    """Run ``t`` tokens seq_buf[start:start+t] with cache length set to
    ``length``; returns (logits [1,t,V], cache advanced to length+t)."""
    tokens = jax.lax.dynamic_slice(seq_buf, (0, start), (1, t))
    pos = start + jnp.arange(t)[None, :]
    cache = replace(cache, length=length.astype(jnp.int32))
    logits, cache = decoder_forward(cfg, params, tokens, cache, pos)
    return logits, cache


@partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "k", "max_new", "eos_ids", "ngram"),
)
def _spec_loop(
    cfg: ModelConfig,
    draft_cfg: ModelConfig,
    params: dict,
    draft_params: dict | None,   # None => prompt-lookup mode
    cache,                       # target cache, prefilled through n_p-1
    draft_cache,                 # draft cache (unused in lookup mode)
    seq_buf: jnp.ndarray,        # [1, S] prompt + first token at n_p
    n_prompt: jnp.ndarray,       # scalar: prompt length
    k: int,
    max_new: int,
    eos_ids: tuple[int, ...],
    ngram: int = 2,
):
    """Speculative rounds until max_new tokens (or EOS).  Returns
    (seq_buf, n_generated, n_rounds, n_drafted, n_matched)."""
    eos = jnp.asarray(eos_ids, jnp.int32) if eos_ids else None
    s_max = seq_buf.shape[1]

    def is_eos(t):
        if eos is None:
            return jnp.zeros(jnp.shape(t), bool)
        return (t[..., None] == eos).any(-1)

    def draft_model_candidates(seq, n, draft_cache):
        """Draft k tokens with the draft model (self-speculative path)."""
        # catch-up: 2-token step over [t_{n-2}, t_{n-1}] heals the cache hole
        # left by a fully-accepted previous round (see module docstring)
        logits, draft_cache = _forward_at(
            draft_cfg, draft_params, draft_cache, seq, n - 2, 2, n - 2
        )
        d1 = _greedy(logits[:, -1])

        def step(carry, _):
            tok, dc = carry
            pos = dc.length[None, None]  # [1,1]
            lg, dc = decoder_forward(draft_cfg, draft_params, tok, dc, pos)
            nxt = _greedy(lg[:, -1])[:, None]  # [1,1]
            return (nxt, dc), tok[0]

        (last, draft_cache), drafted = jax.lax.scan(
            step, (d1[:, None], draft_cache), None, length=k - 1
        )
        # drafted: [k-1, 1] consumed tokens d1..d_{k-1}; add final d_k
        drafts = jnp.concatenate([drafted[:, 0], last[0]])  # [k]
        return drafts, draft_cache

    def lookup_candidates(seq, n, draft_cache):
        """Propose k tokens by matching the trailing n-gram in seq[0:n]."""
        ng = ngram
        tail = jax.lax.dynamic_slice(seq, (0, n - ng), (1, ng))[0]  # [ng]
        idx = jnp.arange(s_max)
        # windows[i] == seq[0, i:i+ng]
        m = jnp.ones((s_max,), bool)
        for j in range(ng):
            m &= jnp.roll(seq[0], -j) == tail[j]
        # a *previous* occurrence: window entirely inside [0, n-ng)
        valid = m & (idx + ng <= n - ng)
        any_match = valid.any()
        best = jnp.where(valid, idx, -1).max()
        start = jnp.where(any_match, best + ng, 0)
        cand = jax.lax.dynamic_slice(seq, (0, start), (1, k))[0]
        # no match: propose pad tokens (they will simply fail verification)
        drafts = jnp.where(any_match, cand, -jnp.ones((k,), jnp.int32))
        return drafts, draft_cache

    candidates = lookup_candidates if draft_params is None else draft_model_candidates

    def cond(st):
        return (st["n_new"] < max_new) & ~st["done"]

    def body(st):
        seq, n = st["seq"], st["n"]
        drafts, dcache = candidates(seq, n, st["draft_cache"])

        # verify: ONE target forward over [cur, d1..dk]
        verify_buf = jax.lax.dynamic_update_slice(
            seq, drafts[None, :], (0, n)
        )
        logits, tcache = _forward_at(
            cfg, params, st["cache"], verify_buf, n - 1, k + 1, n - 1
        )
        g = _greedy(logits[0])                      # [k+1] target greedy
        match = drafts == g[:k]                     # [k]
        n_acc = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((1,), bool)])
        ).astype(jnp.int32)                         # leading-match run length

        # accepted tokens this round: d1..d_{n_acc} then bonus g[n_acc]
        acc = jnp.where(jnp.arange(k + 1) < n_acc, g[: k + 1], g[n_acc])
        # stop at the first EOS inside the accepted run
        eos_hit = is_eos(acc) & (jnp.arange(k + 1) <= n_acc)
        any_eos = eos_hit.any()
        first_eos = jnp.argmax(eos_hit).astype(jnp.int32)
        n_take = jnp.where(any_eos, first_eos + 1, n_acc + 1)
        # budget clip
        n_take = jnp.minimum(n_take, max_new - st["n_new"])

        window_old = jax.lax.dynamic_slice(seq, (0, n), (1, k + 1))
        window = jnp.where(jnp.arange(k + 1)[None, :] < n_take, acc[None, :],
                           window_old)
        seq = jax.lax.dynamic_update_slice(seq, window, (0, n))

        n2 = n + n_take
        tcache = replace(tcache, length=(n2 - 1).astype(jnp.int32))
        return {
            "seq": seq, "n": n2, "n_new": st["n_new"] + n_take,
            "cache": tcache, "draft_cache": dcache,
            "done": st["done"] | any_eos,
            "rounds": st["rounds"] + 1,
            "drafted": st["drafted"] + k,
            "matched": st["matched"] + n_acc,
        }

    st = {
        "seq": seq_buf,
        "n": n_prompt + 1,
        "n_new": jnp.asarray(1, jnp.int32),
        "cache": cache,
        "draft_cache": draft_cache,
        "done": is_eos(seq_buf[0, n_prompt]),
        "rounds": jnp.asarray(0, jnp.int32),
        "drafted": jnp.asarray(0, jnp.int32),
        "matched": jnp.asarray(0, jnp.int32),
    }
    st = jax.lax.while_loop(cond, body, st)
    return st["seq"], st["n_new"], st["rounds"], st["drafted"], st["matched"]


def speculative_generate(
    cfg: ModelConfig,
    params: dict,
    input_ids: Any,
    generation_config: GenerationConfig,
    draft_params: dict | None = None,
    draft_cfg: ModelConfig | None = None,
    max_step_draft: int = 6,
    lookup: bool = False,
    ngram_size: int = 2,
    mesh=None,
) -> GenerateResult:
    """Speculative (or prompt-lookup when ``lookup=True``) greedy decoding.

    ``draft_params`` defaults to the target params (still profitable when the
    verify forward amortizes weight reads over k+1 tokens).  Batch size 1,
    greedy only — matching the reference's supported envelope
    (speculative.py:811 asserts bs==1).
    """
    gen = generation_config
    if gen.do_sample:
        raise NotImplementedError("speculative decoding is greedy-only")
    from ipex_llm_tpu.ops import dispatch as _dispatch

    with _dispatch.spmd(mesh if mesh is not None and mesh.size > 1 else None):
        return _speculative_inner(
            cfg, params, input_ids, gen, draft_params, draft_cfg,
            max_step_draft, lookup, ngram_size, mesh,
        )


def _speculative_inner(cfg, params, input_ids, gen, draft_params, draft_cfg,
                       max_step_draft, lookup, ngram_size, mesh):
    tokens, lengths, tpad = pad_batch(input_ids, gen.pad_token_id, bucket=1)
    if tokens.shape[0] != 1:
        raise ValueError("speculative decoding supports batch size 1")
    n_p = int(lengths[0])
    k = max_step_draft

    if lookup:
        draft_params = None
        draft_cfg = cfg
    else:
        draft_params = draft_params if draft_params is not None else params
        draft_cfg = draft_cfg or cfg

    same_weights = draft_params is params
    s_max = _round_up(n_p + gen.max_new_tokens + k + 2, DECODE_BLOCK)
    cache = kv_mod.make_cache(
        "normal", cfg.num_layers, 1, s_max, cfg.num_kv_heads, cfg.head_dim
    )
    if lookup:
        # unused by the lookup path; a 1-slot dummy avoids donating the
        # target cache buffers twice
        draft_cache = kv_mod.make_cache("normal", 1, 1, 1, 1, 1)
    elif not same_weights:
        draft_cache = kv_mod.make_cache(
            "normal", draft_cfg.num_layers, 1, s_max, draft_cfg.num_kv_heads,
            draft_cfg.head_dim,
        )
    if mesh is not None:
        from ipex_llm_tpu.parallel import shard as shard_mod

        cache = shard_mod.shard_cache(cache, mesh)
        if not lookup and not same_weights:
            draft_cache = shard_mod.shard_cache(draft_cache, mesh)

    seq_buf = np.zeros((1, s_max), np.int32)
    seq_buf[0, :n_p] = tokens[0, tpad - n_p:]
    seq_buf = jnp.asarray(seq_buf)

    # prefill both models; sample the first token from the target
    t0 = time.perf_counter()
    pos = jnp.arange(n_p)[None, :]
    logits, cache = decoder_forward(
        cfg, params, seq_buf[:, :n_p], cache, pos, last_token_only=True
    )
    if not lookup and same_weights:
        # self-speculative with byte-identical weights: the draft cache is a
        # copy of the target's prefilled K/V (one prompt pass, not two);
        # every leaf must be a fresh buffer — both caches are donated
        draft_cache = replace(
            cache, k=jnp.copy(cache.k), v=jnp.copy(cache.v),
            length=jnp.copy(cache.length),
        )
    elif not lookup:
        _, draft_cache = decoder_forward(
            draft_cfg, draft_params, seq_buf[:, :n_p], draft_cache, pos,
            last_token_only=True,
        )
    first = _greedy(logits)
    seq_buf = jax.lax.dynamic_update_slice(seq_buf, first[None], (0, n_p))
    jax.block_until_ready(first)
    ttft = time.perf_counter() - t0

    t1 = time.perf_counter()
    seq_buf, n_new, rounds, drafted, matched = _spec_loop(
        cfg, draft_cfg, params,
        None if lookup else draft_params,
        cache, draft_cache, seq_buf, jnp.asarray(n_p, jnp.int32),
        k, gen.max_new_tokens, gen.eos_token_id, ngram=ngram_size,
    )
    seq = np.asarray(seq_buf)
    n_new = int(n_new)
    dt = time.perf_counter() - t1

    res = GenerateResult(
        sequences=seq[:, : n_p + n_new],
        num_prompt_tokens=n_p,
        num_new_tokens=np.asarray([n_new], np.int32),
        first_token_s=ttft,
        rest_token_s=dt / max(n_new - 1, 1),
    )
    # reference-style acceptance telemetry (speculative.py clear_benchmarks)
    res.n_rounds = int(rounds)
    res.n_drafted = int(drafted)
    res.n_matched = int(matched)
    return res
