"""Self-speculative + prompt-lookup decoding, fully jitted.

Reference counterparts: ``speculative_generate`` (reference
speculative.py:805-1100 — draft k tokens with the sym_int4 copy of the same
weights, verify in ONE batched target forward, accept the longest matching
prefix, crop the KV cache) and ``PromptLookupCandidateGenerator`` /
``lookup_generate`` (lookup.py:145-274 — n-gram candidates mined from the
sequence so far, no draft model at all).

TPU-native redesign (one XLA program, zero host syncs per round):

- the whole draft→verify→accept loop is a ``lax.while_loop``; every round
  has a static shape (k draft steps, k+1 verify tokens);
- **KV "crop" is free**: cache validity is governed by the ``length`` scalar
  that masks attention (kv.py), so rolling back speculative entries is just
  resetting ``length`` — no copies, unlike the reference's
  ``_crop_past_key_values`` tensor surgery (speculative.py:480);
- the draft cache is healed by an idempotent 2-token catch-up step each
  round: re-writing a KV slot for an already-accepted token produces
  identical values, so the draft cache never needs rollback bookkeeping;
- prompt-lookup runs the same verify loop with the draft forward replaced by
  a vectorized n-gram scan over the generated-so-far ring.

Verification: greedy (token-identical to plain decoding) or rejection
sampling (distribution-identical to plain target sampling); the draft leg
stops early on low confidence with the reference's auto-tuned
``th_stop_draft`` (speculative.py:811-812) carried in loop state.
"""

from __future__ import annotations

import time
from dataclasses import replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu import kv as kv_mod
from ipex_llm_tpu.generation import (
    DECODE_BLOCK,
    GenerateResult,
    GenerationConfig,
    _round_up,
    pad_batch,
)
from ipex_llm_tpu.hostutil import d2h, h2d
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.decoder import decoder_forward


def _greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)


def _forward_at(cfg, params, cache, seq_buf, start, t: int, length):
    """Run ``t`` tokens seq_buf[start:start+t] with cache length set to
    ``length``; returns (logits [1,t,V], cache advanced to length+t)."""
    tokens = jax.lax.dynamic_slice(seq_buf, (0, start), (1, t))
    pos = start + jnp.arange(t)[None, :]
    cache = replace(cache, length=length.astype(jnp.int32))
    logits, cache = decoder_forward(cfg, params, tokens, cache, pos)
    return logits, cache


@partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "k", "max_new", "eos_ids", "ngram",
                     "sp", "adaptive"),
    # seq_buf is dead after the call (the caller rebinds it to the returned
    # buffer) and matches the output aval — donate it so the [1, S] window
    # aliases instead of copying.  The caches are consumed on-device and
    # never returned, so they have no output aval to alias: donating them
    # would be silently dropped (JL007's heuristic is satisfied by the
    # seq_buf donation; JP101 verifies the alias survives lowering).
    donate_argnums=(6,),
)
def _spec_loop(
    cfg: ModelConfig,
    draft_cfg: ModelConfig,
    params: dict,
    draft_params: dict | None,   # None => prompt-lookup mode
    cache,                       # target cache, prefilled through n_p-1
    draft_cache,                 # draft cache (unused in lookup mode)
    seq_buf: jnp.ndarray,        # [1, S] prompt + first token at n_p
    n_prompt: jnp.ndarray,       # scalar: prompt length
    key: jax.Array,
    th0: jnp.ndarray,            # f32 scalar: initial th_stop_draft
    k: int,
    max_new: int,
    eos_ids: tuple[int, ...],
    ngram: int = 2,
    sp=None,                     # SamplingParams; do_sample selects the
                                 # rejection-sampling verifier
    adaptive: bool = True,
):
    """Speculative rounds until max_new tokens (or EOS).  Returns
    (seq_buf, n_generated, n_rounds, n_drafted, n_matched, th_final).

    Verification modes (reference speculative.py:805-1100):
    - greedy: accept the longest prefix where draft == target argmax —
      token-identical to plain decoding.
    - sampling: per-token rejection sampling — accept x with prob
      min(1, p(x)/q(x)); on reject draw from normalize(max(p-q, 0)); if
      every draft survives, draw the bonus token from p_{k+1}.  The
      output distribution provably equals plain target sampling.

    Adaptive drafting: the draft leg is a ``lax.while_loop`` that stops
    early when the draft's own confidence in its last token falls below a
    threshold carried in loop state — the reference's ``th_stop_draft``
    with its accept-rate auto-tuning (speculative.py:811-812,
    auto_th_stop_draft) — so low-confidence rounds don't burn k draft
    forwards.  All shapes stay static; only trip counts vary.
    """
    eos = h2d(eos_ids, jnp.int32) if eos_ids else None
    s_max = seq_buf.shape[1]
    vocab = cfg.vocab_size
    sampling = sp is not None and sp.do_sample

    def is_eos(t):
        if eos is None:
            return jnp.zeros(jnp.shape(t), bool)
        return (t[..., None] == eos).any(-1)

    def dist(logits):  # [.., V] target/draft distribution (post-transform)
        from ipex_llm_tpu.ops.sampling import transformed_probs

        return transformed_probs(logits, sp)

    def pick(lg, subkey):
        """Draft token + its proposal-prob row from one logits row [1,V]."""
        if sampling:
            qrow = dist(lg)[0]                       # [V]
            tok = jax.random.categorical(subkey, jnp.log(qrow + 1e-30))
            tok = tok.astype(jnp.int32)
            conf = qrow[tok]
            return tok[None], qrow, conf
        probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)[0]
        tok = jnp.argmax(probs).astype(jnp.int32)
        return tok[None], probs, probs[tok]

    def draft_model_candidates(seq, n, draft_cache, th, key):
        """Draft up to k tokens, stopping early below the confidence th."""
        # catch-up: 2-token step over [t_{n-2}, t_{n-1}] heals the cache hole
        # left by a fully-accepted previous round (see module docstring)
        logits, draft_cache = _forward_at(
            draft_cfg, draft_params, draft_cache, seq, n - 2, 2, n - 2
        )
        key, sub = jax.random.split(key)
        d1, q1, conf1 = pick(logits[:, -1], sub)

        drafts0 = jnp.full((k,), -1, jnp.int32).at[0].set(d1[0])
        qbuf0 = jnp.zeros((k, vocab), jnp.float32).at[0].set(q1)

        def dcond(c):
            j, _, _, stop, _, _, _ = c
            return (j < k) & ~stop

        def dbody(c):
            j, tok, dc, _, drafts, qbuf, key = c
            pos = dc.length[None, None]
            lg, dc = decoder_forward(draft_cfg, draft_params, tok[None], dc,
                                     pos)
            key, sub = jax.random.split(key)
            nxt, qrow, conf = pick(lg[:, -1], sub)
            drafts = drafts.at[j].set(nxt[0])
            qbuf = jax.lax.dynamic_update_slice(qbuf, qrow[None], (j, 0))
            stop = adaptive & (conf < th)
            return (j + 1, nxt, dc, stop, drafts, qbuf, key)

        j, _, draft_cache, _, drafts, qbuf, key = jax.lax.while_loop(
            dcond, dbody,
            (jnp.asarray(1, jnp.int32), d1, draft_cache,
             adaptive & (conf1 < th), drafts0, qbuf0, key),
        )
        return drafts, qbuf, j, draft_cache, key

    def lookup_candidates(seq, n, draft_cache, th, key):
        """Propose k tokens by matching the trailing n-gram in seq[0:n]."""
        ng = ngram
        tail = jax.lax.dynamic_slice(seq, (0, n - ng), (1, ng))[0]  # [ng]
        idx = jnp.arange(s_max)
        # windows[i] == seq[0, i:i+ng]
        m = jnp.ones((s_max,), bool)
        for j in range(ng):
            m &= jnp.roll(seq[0], -j) == tail[j]
        # a *previous* occurrence: window entirely inside [0, n-ng)
        valid = m & (idx + ng <= n - ng)
        any_match = valid.any()
        best = jnp.where(valid, idx, -1).max()
        start = jnp.where(any_match, best + ng, 0)
        cand = jax.lax.dynamic_slice(seq, (0, start), (1, k))[0]
        # no match: propose pad tokens (they will simply fail verification)
        drafts = jnp.where(any_match, cand, -jnp.ones((k,), jnp.int32))
        # lookup proposals carry no distribution: verification falls back to
        # prefix-matching against per-position target samples (still exact)
        qbuf = jnp.zeros((k, vocab), jnp.float32)
        return drafts, qbuf, h2d(k, jnp.int32), draft_cache, key

    lookup_mode = draft_params is None
    candidates = lookup_candidates if lookup_mode else draft_model_candidates

    def accept_greedy(drafts, qbuf, logits, k_drafted, key):
        """Longest draft==argmax prefix, bonus from argmax."""
        g = _greedy(logits)                          # [k+1]
        match = (drafts == g[:k]) & (jnp.arange(k) < k_drafted)
        n_acc = jnp.argmin(
            jnp.concatenate([match, jnp.zeros((1,), bool)])
        ).astype(jnp.int32)
        acc = jnp.where(jnp.arange(k + 1) < n_acc, g[: k + 1], g[n_acc])
        return acc, n_acc, key

    def accept_sampling(drafts, qbuf, logits, k_drafted, key):
        """Leviathan-style rejection sampling over the drafted run."""
        p = dist(logits)                             # [k+1, V]
        ar = jnp.arange(k)
        live = (ar < k_drafted) & (drafts >= 0)
        x = jnp.clip(drafts, 0, vocab - 1)
        px = p[ar, x]
        qx = qbuf[ar, x]
        key, ku, kr = jax.random.split(key, 3)
        u = jax.random.uniform(ku, (k,))
        if lookup_mode:
            # no q: sample the target chain and accept the matching prefix
            t_chain = jax.random.categorical(
                kr, jnp.log(p + 1e-30), axis=-1
            ).astype(jnp.int32)                      # [k+1]
            ok = (drafts == t_chain[:k]) & live
            n_acc = jnp.argmin(
                jnp.concatenate([ok, jnp.zeros((1,), bool)])
            ).astype(jnp.int32)
            corr = t_chain[n_acc]
        else:
            ok = (u * qx <= px) & live
            n_acc = jnp.argmin(
                jnp.concatenate([ok, jnp.zeros((1,), bool)])
            ).astype(jnp.int32)
            # correction token: residual max(p-q, 0) at the reject slot, or
            # plain p_{k} when every draft survived
            p_at = p[n_acc]                          # [V]
            q_at = jnp.where(n_acc < k, qbuf[jnp.minimum(n_acc, k - 1)], 0.0)
            res = jnp.maximum(p_at - q_at, 0.0)
            res_sum = res.sum()
            res = jnp.where(res_sum > 0, res / jnp.maximum(res_sum, 1e-20),
                            p_at)
            corr = jax.random.categorical(
                kr, jnp.log(res + 1e-30)
            ).astype(jnp.int32)
        acc = jnp.where(jnp.arange(k + 1) < n_acc, jnp.append(x, 0), corr)
        return acc, n_acc, key

    accept = accept_sampling if sampling else accept_greedy

    def cond(st):
        return (st["n_new"] < max_new) & ~st["done"]

    def body(st):
        seq, n = st["seq"], st["n"]
        drafts, qbuf, k_drafted, dcache, key = candidates(
            seq, n, st["draft_cache"], st["th"], st["key"]
        )

        # verify: ONE target forward over [cur, d1..dk]
        verify_buf = jax.lax.dynamic_update_slice(
            seq, drafts[None, :], (0, n)
        )
        logits, tcache = _forward_at(
            cfg, params, st["cache"], verify_buf, n - 1, k + 1, n - 1
        )
        acc, n_acc, key = accept(drafts, qbuf, logits[0], k_drafted, key)

        # stop at the first EOS inside the accepted run
        eos_hit = is_eos(acc) & (jnp.arange(k + 1) <= n_acc)
        any_eos = eos_hit.any()
        first_eos = jnp.argmax(eos_hit).astype(jnp.int32)
        n_take = jnp.where(any_eos, first_eos + 1, n_acc + 1)
        # budget clip
        n_take = jnp.minimum(n_take, max_new - st["n_new"])

        window_old = jax.lax.dynamic_slice(seq, (0, n), (1, k + 1))
        window = jnp.where(jnp.arange(k + 1)[None, :] < n_take, acc[None, :],
                           window_old)
        seq = jax.lax.dynamic_update_slice(seq, window, (0, n))

        # th_stop_draft auto-tune (reference speculative.py:811-812): full
        # acceptance => draft deeper next round (lower threshold); under
        # half accepted => draft shallower (raise it)
        frac = n_acc.astype(jnp.float32) / jnp.maximum(
            k_drafted.astype(jnp.float32), 1.0
        )
        th = st["th"]
        th = jnp.where(n_acc >= k_drafted, th * 0.9,
                       jnp.where(frac < 0.5, th * 1.2, th))
        th = jnp.clip(th, 0.02, 0.9)

        n2 = n + n_take
        tcache = replace(tcache, length=(n2 - 1).astype(jnp.int32))
        return {
            "seq": seq, "n": n2, "n_new": st["n_new"] + n_take,
            "cache": tcache, "draft_cache": dcache, "key": key, "th": th,
            "done": st["done"] | any_eos,
            "rounds": st["rounds"] + 1,
            "drafted": st["drafted"] + k_drafted,
            "matched": st["matched"] + n_acc,
        }

    st = {
        "seq": seq_buf,
        "n": n_prompt + 1,
        "n_new": jnp.asarray(1, jnp.int32),
        "cache": cache,
        "draft_cache": draft_cache,
        "key": key,
        "th": th0.astype(jnp.float32),
        "done": is_eos(seq_buf[0, n_prompt]),
        "rounds": jnp.asarray(0, jnp.int32),
        "drafted": jnp.asarray(0, jnp.int32),
        "matched": jnp.asarray(0, jnp.int32),
    }
    st = jax.lax.while_loop(cond, body, st)
    return (st["seq"], st["n_new"], st["rounds"], st["drafted"],
            st["matched"], st["th"])


def speculative_generate(
    cfg: ModelConfig,
    params: dict,
    input_ids: Any,
    generation_config: GenerationConfig,
    draft_params: dict | None = None,
    draft_cfg: ModelConfig | None = None,
    max_step_draft: int = 6,
    lookup: bool = False,
    ngram_size: int = 2,
    mesh=None,
    th_stop_draft: float = 0.8,
    auto_th_stop_draft: bool = True,
    seed: int | None = None,
) -> GenerateResult:
    """Speculative (or prompt-lookup when ``lookup=True``) decoding.

    ``draft_params`` defaults to the target params (still profitable when the
    verify forward amortizes weight reads over k+1 tokens).  Batch size 1,
    matching the reference's supported envelope (speculative.py:811 asserts
    bs==1).  Greedy verification reproduces plain decoding token-for-token;
    ``do_sample=True`` runs rejection-sampling verification whose output
    distribution equals plain target sampling.  ``th_stop_draft`` /
    ``auto_th_stop_draft`` mirror the reference kwargs (speculative.py:811).
    """
    gen = generation_config
    if gen.do_sample and gen.repetition_penalty != 1.0:
        raise NotImplementedError(
            "sampled speculative decoding does not support repetition_penalty"
        )
    from ipex_llm_tpu.ops import dispatch as _dispatch

    with _dispatch.spmd(mesh if mesh is not None and mesh.size > 1 else None):
        return _speculative_inner(
            cfg, params, input_ids, gen, draft_params, draft_cfg,
            max_step_draft, lookup, ngram_size, mesh, th_stop_draft,
            auto_th_stop_draft, seed,
        )


def _speculative_inner(cfg, params, input_ids, gen, draft_params, draft_cfg,
                       max_step_draft, lookup, ngram_size, mesh,
                       th_stop_draft, auto_th_stop_draft, seed=None):
    tokens, lengths, tpad = pad_batch(input_ids, gen.pad_token_id, bucket=1)
    if tokens.shape[0] != 1:
        raise ValueError("speculative decoding supports batch size 1")
    n_p = int(lengths[0])
    k = max_step_draft

    if lookup:
        draft_params = None
        draft_cfg = cfg
    else:
        draft_params = draft_params if draft_params is not None else params
        draft_cfg = draft_cfg or cfg

    same_weights = draft_params is params
    s_max = _round_up(n_p + gen.max_new_tokens + k + 2, DECODE_BLOCK)
    cache = kv_mod.make_cache(
        "normal", cfg.num_layers, 1, s_max, cfg.num_kv_heads, cfg.head_dim,
        v_head_dim=cfg.v_dim,
    )
    if lookup:
        # unused by the lookup path; a 1-slot dummy avoids donating the
        # target cache buffers twice
        draft_cache = kv_mod.make_cache("normal", 1, 1, 1, 1, 1)
    elif not same_weights:
        draft_cache = kv_mod.make_cache(
            "normal", draft_cfg.num_layers, 1, s_max, draft_cfg.num_kv_heads,
            draft_cfg.head_dim, v_head_dim=draft_cfg.v_dim,
        )
    if mesh is not None:
        from ipex_llm_tpu.parallel import shard as shard_mod

        cache = shard_mod.shard_cache(cache, mesh)
        if not lookup and not same_weights:
            draft_cache = shard_mod.shard_cache(draft_cache, mesh)

    seq_buf = np.zeros((1, s_max), np.int32)
    seq_buf[0, :n_p] = tokens[0, tpad - n_p:]
    seq_buf = h2d(seq_buf)

    # prefill both models; sample the first token from the target
    t0 = time.perf_counter()
    pos = jnp.arange(n_p)[None, :]
    logits, cache = decoder_forward(
        cfg, params, seq_buf[:, :n_p], cache, pos, last_token_only=True
    )
    if not lookup and same_weights:
        # self-speculative with byte-identical weights: the draft cache is a
        # copy of the target's prefilled K/V (one prompt pass, not two);
        # every leaf must be a fresh buffer — both caches are donated
        draft_cache = replace(
            cache, k=jnp.copy(cache.k), v=jnp.copy(cache.v),
            length=jnp.copy(cache.length),
        )
    elif not lookup:
        _, draft_cache = decoder_forward(
            draft_cfg, draft_params, seq_buf[:, :n_p], draft_cache, pos,
            last_token_only=True,
        )
    # greedy verification ignores sampling params — keep them out of
    # the jit static key so temperature changes don't recompile
    sp = gen.sampling() if gen.do_sample else None
    # ``seed`` overrides gen.seed WITHOUT entering the jit static args, so
    # sweeping seeds (e.g. the distribution test) reuses one compilation
    key = jax.random.PRNGKey(gen.seed if seed is None else seed)
    key, kfirst = jax.random.split(key)
    if gen.do_sample:
        from ipex_llm_tpu.ops.sampling import sample

        first = sample(logits, kfirst, sp)
    else:
        first = _greedy(logits)
    seq_buf = jax.lax.dynamic_update_slice(seq_buf, first[None], (0, n_p))
    jax.block_until_ready(first)  # jaxlint: disable=JL002 -- deliberate: TTFT measurement needs the first token finished before the clock stops
    ttft = time.perf_counter() - t0

    t1 = time.perf_counter()
    seq_buf, n_new, rounds, drafted, matched, th_final = _spec_loop(
        cfg, draft_cfg, params,
        None if lookup else draft_params,
        cache, draft_cache, seq_buf, h2d(n_p, jnp.int32),
        key, h2d(th_stop_draft, jnp.float32),
        k, gen.max_new_tokens, gen.eos_token_id, ngram=ngram_size,
        sp=sp, adaptive=auto_th_stop_draft,
    )
    seq = d2h(seq_buf)  # jaxlint: disable=JL002 -- end-of-generation materialization: the spec loop is done, the result must come home
    n_new = int(n_new)  # jaxlint: disable=JL002 -- rides the end-of-generation sync above
    dt = time.perf_counter() - t1

    res = GenerateResult(
        sequences=seq[:, : n_p + n_new],
        num_prompt_tokens=n_p,
        num_new_tokens=np.asarray([n_new], np.int32),
        first_token_s=ttft,
        rest_token_s=dt / max(n_new - 1, 1),
    )
    # reference-style acceptance telemetry (speculative.py clear_benchmarks)
    res.n_rounds = int(rounds)  # jaxlint: disable=JL002 -- post-loop telemetry materialization, not in the decode loop
    res.n_drafted = int(drafted)  # jaxlint: disable=JL002 -- post-loop telemetry materialization, not in the decode loop
    res.n_matched = int(matched)  # jaxlint: disable=JL002 -- post-loop telemetry materialization, not in the decode loop
    res.th_stop_draft = float(th_final)  # jaxlint: disable=JL002 -- post-loop telemetry materialization, not in the decode loop
    return res
