"""Host-RAM expert offload — the FlashMoE equivalent.

Reference counterpart: the ``flash-moe`` runtime (reference
docs/mddocs/Quickstart/flashmoe_quickstart.md:20-25) which runs
DeepSeek-671B / Qwen3MoE-235B on 1-2 GPUs by keeping experts in host RAM.
BASELINE.md tracks "Mixtral-8x7B + DeepSeek-V2 MoE (expert offload)" as a
functional config: Mixtral-8x7B INT4 is ~23 GB of experts against a 16 GB
v5e chip, so the experts *cannot* all live in HBM.

TPU-native design:

- expert weight planes (the ``moe_gate_up`` / ``moe_down`` stacks) stay in
  host RAM as packed numpy QTensors; everything else (attention, router,
  norms, shared experts, embeddings) lives in HBM as usual;
- an **HBM LRU cache** holds the hottest (layer, expert) entries under a
  byte budget; a miss issues an async ``jax.device_put`` of the packed
  planes (~4.5 bit/weight over PCIe) — dispatch returns immediately, so
  the transfer overlaps the jitted attention of the same layer;
- the forward is a **layer-by-layer Python drive** (not one jitted scan):
  after each layer's router the top-k expert ids sync to the host, which
  fetches exactly those experts.  This is the one host round-trip per
  layer that data-dependent weight residency fundamentally requires — the
  same structural trade the reference's FlashMoE binary makes.

Throughput is PCIe/HBM-budget bound by construction; the point is the
*capability*: models whose experts exceed HBM decode on a single chip.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.hostutil import HostLRU, h2d
from ipex_llm_tpu.models.config import ModelConfig

EXPERT_SLOTS = ("moe_gate_up", "moe_down")


def _to_host(tree):
    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _qt_nbytes(tree) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "size")
    )


class ExpertStore:
    """Host-RAM packed expert store with an HBM LRU cache.

    The byte-budget/eviction bookkeeping is ``hostutil.HostLRU`` — the
    same helper the serving KV page store (serving/pagestore.py) budgets
    its host spill tier with."""

    def __init__(self, host_slots: dict[str, Any], hbm_budget_bytes: int):
        self.host = host_slots            # slot -> stacked [L, E, ...] np QTensor
        self.budget = hbm_budget_bytes
        self._cache = HostLRU(hbm_budget_bytes)

    @property
    def hits(self) -> int:
        return self._cache.hits

    @property
    def misses(self) -> int:
        return self._cache.misses

    def get(self, layer: int, expert: int) -> dict[str, Any]:
        """Device QTensors {slot: qt} for one (layer, expert); LRU-cached."""
        key = (layer, expert)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        entry = {}
        for slot, stacked in self.host.items():
            per = jax.tree_util.tree_map(lambda a: a[layer, expert], stacked)
            # jaxlint: disable=JL001 -- zero-copy is intended here: host expert stacks are written once at split time and never mutated; copying would double peak host RAM per expert fetch
            entry[slot] = jax.device_put(per)   # async dispatch
        size = sum(_qt_nbytes(v) for v in entry.values())
        self._cache.put(key, entry, size)
        return entry

    def prefetch(self, layer: int, experts) -> None:
        for e in experts:
            self.get(layer, int(e))


def split_expert_params(params: dict) -> tuple[dict, dict]:
    """Move the expert stacks to host; return (device_params, host_slots)."""
    layers = dict(params["layers"])
    host = {}
    for slot in EXPERT_SLOTS:
        if slot in layers:
            host[slot] = _to_host(layers.pop(slot))
    out = dict(params)
    out["layers"] = layers
    return out, host


# ---------------------------------------------------------------------------
# jitted layer pieces (driven from Python per layer)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("cfg",))
def _embed(cfg: ModelConfig, params, tokens):
    from ipex_llm_tpu.models.decoder import COMPUTE_DTYPE
    from ipex_llm_tpu.ops.embedding import embed_lookup

    x = embed_lookup(params["embed"], tokens, COMPUTE_DTYPE)
    if cfg.embedding_multiplier != 1.0:
        x = x * h2d(cfg.embedding_multiplier, COMPUTE_DTYPE)
    return x


@partial(jax.jit, static_argnames=("cfg",))
def _layer_attn_router(cfg: ModelConfig, layer, params, x, kl, vl,
                       slot0, q_slots, kv_len, kv_start, cos, sin, sliding,
                       cache):
    """One layer's attention + router; returns the residual state, the
    normalized FFN input, top-k (w, idx) and the updated KV planes.

    ``layer`` is a *traced* index so all L layers share one compiled
    program per (prefill, decode) shape."""
    from ipex_llm_tpu.models import decoder as dec

    lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
    attn_out, kl, vl, _ = dec._attention_block(
        cfg, lp, x, kl, vl, cos, sin, slot0, q_slots, kv_len, kv_start,
        sliding, cache, 0,
    )
    if cfg.residual_multiplier != 1.0:  # minicpm-style depth scaling
        attn_out = attn_out * h2d(cfg.residual_multiplier,
                                          attn_out.dtype)
    x = x + attn_out
    h = dec._norm(x, lp["mlp_norm"], cfg)
    router_logits = jnp.matmul(h.astype(jnp.float32), lp["router"])
    k = cfg.num_experts_per_tok
    if cfg.moe_softmax_before_topk:
        probs = jax.nn.softmax(router_logits, axis=-1)
        w, idx = jax.lax.top_k(probs, k)
        if cfg.moe_norm_topk_prob:
            w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-20)
    else:
        lg, idx = jax.lax.top_k(router_logits, k)
        w = jax.nn.softmax(lg, axis=-1)
    if cfg.moe_router_scale != 1.0:
        w = w * cfg.moe_router_scale
    return x, h, w, idx, kl, vl


@partial(jax.jit, static_argnames=("cfg", "n_exp"))
def _apply_experts(cfg: ModelConfig, n_exp: int, layer, params, x, h,
                   gates, expert_qts):
    """x += Σ_e gates[e] ⊙ expert_e(h) (+ shared expert), experts fetched.

    gates [n_exp, B, T]; expert_qts: tuple of (gate_up, down) QTensor pairs.
    """
    from ipex_llm_tpu.ops import linear as linear_ops
    from ipex_llm_tpu.ops import mlp as mlp_ops
    from ipex_llm_tpu.ops.moe import _expert_ffn

    y = jnp.zeros_like(x)
    for i in range(n_exp):
        gu, dn = expert_qts[i]
        ye = _expert_ffn(h, gu, dn, cfg.act)
        y = y + ye * gates[i][..., None].astype(ye.dtype)

    lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
    if "shared_gate_up" in lp:
        gate, up = mlp_ops.split_gate_up(
            linear_ops.linear(h, lp["shared_gate_up"])
        )
        ys = linear_ops.linear(
            mlp_ops.gated_act_mul(gate, up, cfg.act), lp["shared_down"]
        )
        if "shared_router" in lp:
            g = jax.nn.sigmoid(jnp.matmul(h.astype(jnp.float32),
                                          lp["shared_router"]))
            ys = ys * g.astype(ys.dtype)
        y = y + ys
    if cfg.residual_multiplier != 1.0:  # minicpm-style depth scaling
        y = y * h2d(cfg.residual_multiplier, y.dtype)
    return x + y


@partial(jax.jit, static_argnames=("cfg",))
def _final_logits(cfg: ModelConfig, params, x):
    from ipex_llm_tpu.models import decoder as dec
    from ipex_llm_tpu.ops import linear as linear_ops

    x = dec._norm(x[:, -1:], params["final_norm"], cfg,
                  params.get("final_norm_bias"))
    lm_head = params.get("lm_head")
    if lm_head is None:
        logits = jnp.matmul(
            x.astype(dec.COMPUTE_DTYPE),
            params["embed"].T.astype(dec.COMPUTE_DTYPE),
            preferred_element_type=jnp.float32,
        )
    else:
        logits = linear_ops.linear(x, lm_head, params.get("lm_head_bias"))
    logits = logits.astype(jnp.float32)
    if cfg.logit_scale != 1.0:  # cohere/minicpm logits multiplier
        logits = logits * cfg.logit_scale
    if cfg.logit_softcap is not None:
        logits = jnp.tanh(logits / cfg.logit_softcap) * cfg.logit_softcap
    return logits[:, 0]


class OffloadedMoE:
    """Layer-by-layer MoE runtime with host-resident experts.

    ``hbm_budget_mb`` caps the device-side expert cache; set it below the
    total expert footprint to exercise real streaming (the Mixtral-on-16GB
    regime).
    """

    def __init__(self, cfg: ModelConfig, params: dict,
                 hbm_budget_mb: float = 4096.0):
        if cfg.num_experts == 0:
            raise ValueError("OffloadedMoE requires an MoE config")
        self.cfg = cfg
        self.params, host = split_expert_params(params)
        self.store = ExpertStore(host, int(hbm_budget_mb * 1024 * 1024))

    # -- forward ------------------------------------------------------------

    def _forward(self, tokens: jnp.ndarray, caches, proto, slot0: int):
        """tokens [1, T] through all layers; returns (logits [1,V], caches)."""
        from ipex_llm_tpu.ops import rope as rope_ops

        cfg = self.cfg
        b, t = tokens.shape
        x = _embed(cfg, self.params, tokens)
        slot0_j = h2d(slot0, jnp.int32)
        q_slots = jnp.broadcast_to(
            slot0_j + jnp.arange(t)[None, :], (b, t)
        )
        kv_len = jnp.broadcast_to(slot0_j + t, (b,))
        kv_start = jnp.zeros((b,), jnp.int32)
        cos, sin = (None, None)
        if cfg.rope is not None:
            cos, sin = rope_ops.cos_sin(
                q_slots, self.params["inv_freq"],
                self.params.get("rope_mscale", 1.0),
            )

        for layer in range(cfg.num_layers):
            kl, vl = caches[layer]
            x, h, w, idx, kl, vl = _layer_attn_router(
                cfg, h2d(layer, jnp.int32), self.params, x, kl, vl,
                slot0_j, q_slots, kv_len, kv_start, cos, sin,
                h2d(cfg.layer_is_sliding(layer)), proto,
            )
            caches[layer] = (kl, vl)
            # host sync: which experts does this layer need?
            idx_np = np.asarray(idx)            # [1, T, k]
            w_np = np.asarray(w)
            used = sorted(set(int(e) for e in idx_np.reshape(-1)))
            # bucket the expert count so _apply_experts retraces only per
            # power-of-two bucket, padding with a zero-weight repeat
            n_exp = 1
            while n_exp < len(used):
                n_exp *= 2
            gates = np.zeros((n_exp, b, t), np.float32)
            for i, e in enumerate(used):
                gates[i] = ((idx_np == e) * w_np).sum(-1)
            qts = []
            for i in range(n_exp):
                e = used[i] if i < len(used) else used[0]
                entry = self.store.get(layer, e)
                qts.append((entry["moe_gate_up"], entry["moe_down"]))
            x = _apply_experts(
                cfg, n_exp, h2d(layer, jnp.int32), self.params, x, h,
                h2d(gates), tuple(qts),
            )
        return _final_logits(cfg, self.params, x), caches

    # -- public API ---------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int = 32):
        """Greedy batch-1 decode with streamed experts.

        Returns np.ndarray [1, len(prompt) + new]."""
        from ipex_llm_tpu.kv import KVCache

        cfg = self.cfg
        prompt = np.asarray(prompt_ids, np.int32).reshape(1, -1)
        t0 = prompt.shape[1]
        cap = t0 + max_new_tokens + 8
        full = KVCache.init(1, 1, cap, cfg.num_kv_heads, cfg.head_dim,
                            v_head_dim=cfg.v_dim)
        caches = [(full.k[0], full.v[0]) for _ in range(cfg.num_layers)]
        # dtype/method provider only — tiny, so the per-layer jit doesn't
        # haul a stacked cache around
        from dataclasses import replace as _replace

        proto = _replace(full, k=full.k[:1, :, :, :1], v=full.v[:1, :, :, :1])

        logits, caches = self._forward(h2d(prompt), caches, proto, 0)
        out = [int(np.asarray(jnp.argmax(logits, -1))[0])]
        for step in range(1, max_new_tokens):
            tok = h2d([[out[-1]]], jnp.int32)
            logits, caches = self._forward(tok, caches, proto,
                                           t0 + step - 1)
            out.append(int(np.asarray(jnp.argmax(logits, -1))[0]))
        return np.concatenate([prompt, np.asarray(out)[None]], axis=1)
