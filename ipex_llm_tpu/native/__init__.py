"""Native (C++) runtime components, loaded via ctypes.

Reference counterpart: the prebuilt ggml C library + ctypes bindings
(reference ggml/model/llama/llama_cpp.py:71-109, low_bit_linear.py:106-279).
Here the native quantizer builds from source on first use (g++ is in the
image; no wheel needed) and the pure-jnp codec remains the fallback and the
correctness oracle — the native path must be bit-exact with it.
"""

from ipex_llm_tpu.native.quantizer import (
    available,
    build,
    quantize_sym_native,
)

__all__ = ["available", "build", "quantize_sym_native"]
