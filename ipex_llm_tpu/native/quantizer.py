"""ctypes loader + builder for the C++ block quantizer (csrc/quantize.cpp)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None | bool = None  # None=untried, False=unavailable

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc",
                    "quantize.cpp")
_OUT = os.path.join(os.path.dirname(__file__), "_libquantize.so")


def build(force: bool = False) -> str | None:
    """Compile the shared library (g++ -O3 -march=native -fopenmp)."""
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    if os.path.exists(_OUT) and not force and (
        os.path.getmtime(_OUT) >= os.path.getmtime(src)
    ):
        return _OUT
    cmd = ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
           src, "-o", _OUT]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, FileNotFoundError):
        return None
    return _OUT


def _load() -> ctypes.CDLL | None:
    global _LIB
    with _LOCK:
        if _LIB is None:
            path = build()
            if path is None:
                _LIB = False
            else:
                try:
                    lib = ctypes.CDLL(path)
                    lib.quantize_sym.restype = ctypes.c_int
                    lib.quantize_sym.argtypes = [
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.c_int64, ctypes.c_int64,
                        ctypes.c_int, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_uint8),
                        ctypes.POINTER(ctypes.c_uint16),
                    ]
                    lib.dequantize_sym.restype = ctypes.c_int
                    lib.dequantize_sym.argtypes = [
                        ctypes.POINTER(ctypes.c_uint8),
                        ctypes.POINTER(ctypes.c_uint16),
                        ctypes.c_int64, ctypes.c_int64,
                        ctypes.c_int, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_float),
                    ]
                    lib.quantize_asym.restype = ctypes.c_int
                    lib.quantize_asym.argtypes = [
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.c_int64, ctypes.c_int64,
                        ctypes.c_int, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_uint8),
                        ctypes.POINTER(ctypes.c_uint16),
                        ctypes.POINTER(ctypes.c_uint16),
                    ]
                    lib.quantize_codebook.restype = ctypes.c_int
                    lib.quantize_codebook.argtypes = [
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int,
                        ctypes.POINTER(ctypes.c_uint8),
                        ctypes.POINTER(ctypes.c_uint16),
                    ]
                    _LIB = lib
                except OSError:
                    _LIB = False
        return _LIB or None


def available() -> bool:
    if os.environ.get("IPEX_LLM_TPU_DISABLE_NATIVE", "0") == "1":
        return False
    return _load() is not None


def quantize_sym_native(w: np.ndarray, bits: int, bs: int):
    """Bit-exact native counterpart of core._quant_int_sym for fp32 numpy
    input.  Returns (data uint8, scales float16) or None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    w = np.ascontiguousarray(w, np.float32)
    n_in, n_out = w.shape
    pad = (-n_in) % bs
    if pad:
        w = np.concatenate([w, np.zeros((pad, n_out), np.float32)], axis=0)
        n_in += pad
    n_blocks = n_in // bs
    data_rows = n_in // 2 if bits == 4 else n_in
    data = np.empty((data_rows, n_out), np.uint8)
    scales = np.empty((n_blocks, n_out), np.uint16)
    rc = lib.quantize_sym(
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_in, n_out, bs, bits,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
    )
    if rc != 0:
        return None
    return data, scales.view(np.float16)


def quantize_asym_native(w: np.ndarray, bits: int, bs: int):
    """Bit-exact native counterpart of core._quant_int_asym (q4_1/q5_1
    style).  Returns (data uint8, scales f16, zeros f16) or None."""
    lib = _load()
    if lib is None:
        return None
    w = np.ascontiguousarray(w, np.float32)
    n_in, n_out = w.shape
    pad = (-n_in) % bs
    if pad:
        w = np.concatenate([w, np.zeros((pad, n_out), np.float32)], axis=0)
        n_in += pad
    n_blocks = n_in // bs
    data_rows = n_in // 2 if bits == 4 else n_in
    data = np.empty((data_rows, n_out), np.uint8)
    scales = np.empty((n_blocks, n_out), np.uint16)
    zeros = np.empty((n_blocks, n_out), np.uint16)
    rc = lib.quantize_asym(
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_in, n_out, bs, bits,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        zeros.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
    )
    if rc != 0:
        return None
    return data, scales.view(np.float16), zeros.view(np.float16)


def quantize_codebook_native(w: np.ndarray, table: np.ndarray, bs: int):
    """Bit-exact native counterpart of core._quant_codebook for 16-entry
    codebooks (nf4/fp4).  Returns (data uint8 nibbles, scales f16) or
    None."""
    lib = _load()
    if lib is None or len(table) > 16 or bs > 512:
        return None
    w = np.ascontiguousarray(w, np.float32)
    n_in, n_out = w.shape
    pad = (-n_in) % bs
    if pad:
        w = np.concatenate([w, np.zeros((pad, n_out), np.float32)], axis=0)
        n_in += pad
    n_blocks = n_in // bs
    t = np.ascontiguousarray(table, np.float32)
    data = np.empty((n_in // 2, n_out), np.uint8)
    scales = np.empty((n_blocks, n_out), np.uint16)
    rc = lib.quantize_codebook(
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_in, n_out, bs,
        t.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), len(t),
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scales.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
    )
    if rc != 0:
        return None
    return data, scales.view(np.float16)
