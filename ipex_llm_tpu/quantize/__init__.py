"""Low-bit block quantization: formats, codecs, and the QTensor pytree.

Reference counterparts: ipex_llm/ggml/quantize.py (qtype table),
low_bit_linear.py FP4Params (quantize-on-move tensor) and the ggml C
quantize/dequantize bindings (§2.3).
"""

from ipex_llm_tpu.quantize.qtypes import (
    ggml_tensor_qtype,
    QTypeInfo,
    all_qtypes,
    is_supported,
    resolve,
)
from ipex_llm_tpu.quantize.core import QTensor, dequantize, quantize

__all__ = [
    "ggml_tensor_qtype",
    "QTypeInfo",
    "QTensor",
    "all_qtypes",
    "is_supported",
    "resolve",
    "quantize",
    "dequantize",
]
