"""GGUF k-quant superblock decoders (q2_k/q3_k/q4_k/q5_k/q6_k/q8_k).

The reference imports GGUF k-quant tensors either by dequantizing them or by
re-using the raw blocks in its native kernels (reference:
transformers/gguf/api.py:31 and §2.1 "GGUF import").  Here the raw superblock
bytes are kept verbatim in ``QTensor.data`` (shape ``[out, nb*type_size]``
uint8) and decoded **in pure jnp** — shifts, masks and table-free arithmetic —
so the decode can run fused on TPU inside the dequant-matmul path, not just on
the host at load time.

Implemented from the public GGUF/llama.cpp block-format *specification*
(superblock structs of 256 elements with 6-bit sub-scales); this is an
independent vectorized implementation, validated against a literal scalar
spec decoder in tests/test_kquants.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QK_K = 256

#: bytes per 256-element superblock
TYPE_SIZES = {
    "q2_k": 2 + 2 + 16 + 64,          # d, dmin, scales[16], qs[64] -> 84
    "q3_k": 32 + 64 + 12 + 2,         # hmask[32], qs[64], scales[12], d -> 110
    "q4_k": 2 + 2 + 12 + 128,         # d, dmin, scales[12], qs[128] -> 144
    "q5_k": 2 + 2 + 12 + 32 + 128,    # d, dmin, scales[12], qh[32], qs[128] -> 176
    "q6_k": 128 + 64 + 16 + 2,        # ql[128], qh[64], scales[16] int8, d -> 210
    "q8_k": 4 + 256 + 32,             # d fp32, qs[256] int8, bsums[16] -> 292
}


def _f16(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Two uint8 byte planes (little endian) -> float32 value."""
    u16 = lo.astype(jnp.uint16) | (hi.astype(jnp.uint16) << 8)
    return jax.lax.bitcast_convert_type(u16, jnp.float16).astype(jnp.float32)


def _f32(b0, b1, b2, b3) -> jnp.ndarray:
    u32 = (
        b0.astype(jnp.uint32)
        | (b1.astype(jnp.uint32) << 8)
        | (b2.astype(jnp.uint32) << 16)
        | (b3.astype(jnp.uint32) << 24)
    )
    return jax.lax.bitcast_convert_type(u32, jnp.float32)


def _i8(b: jnp.ndarray) -> jnp.ndarray:
    """uint8 byte plane -> signed int8 value as float32."""
    return jnp.where(b >= 128, b.astype(jnp.int32) - 256, b.astype(jnp.int32)).astype(
        jnp.float32
    )


def _scale_min_k4(scales: jnp.ndarray, j: int):
    """6-bit (scale, min) pair j of 8 from the packed 12-byte q4_k/q5_k field."""
    if j < 4:
        sc = scales[..., j] & 63
        m = scales[..., j + 4] & 63
    else:
        sc = (scales[..., j + 4] & 0x0F) | ((scales[..., j - 4] >> 6) << 4)
        m = (scales[..., j + 4] >> 4) | ((scales[..., j] >> 6) << 4)
    return sc.astype(jnp.float32), m.astype(jnp.float32)


# Each decoder: raw [..., type_size] uint8 -> [..., 256] float32.


def _dequant_q4_k(raw: jnp.ndarray) -> jnp.ndarray:
    d = _f16(raw[..., 0], raw[..., 1])[..., None]
    dmin = _f16(raw[..., 2], raw[..., 3])[..., None]
    scales = raw[..., 4:16]
    qs = raw[..., 16:144]  # [..., 128]
    out = []
    for j in range(8):  # sub-block j of 32 elements
        grp = qs[..., (j // 2) * 32 : (j // 2) * 32 + 32]
        q = (grp & 0x0F) if j % 2 == 0 else (grp >> 4)
        sc, m = _scale_min_k4(scales, j)
        out.append(d * sc[..., None] * q.astype(jnp.float32) - dmin * m[..., None])
    return jnp.concatenate(out, axis=-1)


def _dequant_q5_k(raw: jnp.ndarray) -> jnp.ndarray:
    d = _f16(raw[..., 0], raw[..., 1])[..., None]
    dmin = _f16(raw[..., 2], raw[..., 3])[..., None]
    scales = raw[..., 4:16]
    qh = raw[..., 16:48]   # [..., 32]
    qs = raw[..., 48:176]  # [..., 128]
    out = []
    for j in range(8):
        grp = qs[..., (j // 2) * 32 : (j // 2) * 32 + 32]
        lo = (grp & 0x0F) if j % 2 == 0 else (grp >> 4)
        hbit = (qh >> j) & 1
        q = lo.astype(jnp.float32) + 16.0 * hbit.astype(jnp.float32)
        sc, m = _scale_min_k4(scales, j)
        out.append(d * sc[..., None] * q - dmin * m[..., None])
    return jnp.concatenate(out, axis=-1)


def _dequant_q6_k(raw: jnp.ndarray) -> jnp.ndarray:
    ql = raw[..., 0:128]
    qh = raw[..., 128:192]
    sc = _i8(raw[..., 192:208])  # [..., 16] signed 8-bit sub-scales
    d = _f16(raw[..., 208], raw[..., 209])[..., None]
    halves = []
    for n in range(2):  # two 128-element halves
        lq = ql[..., n * 64 : n * 64 + 64]
        hq = qh[..., n * 32 : n * 32 + 32]
        s = sc[..., n * 8 : n * 8 + 8]
        # four 32-element quarters within the half
        q1 = (lq[..., 0:32] & 0x0F) | (((hq >> 0) & 3) << 4)
        q2 = (lq[..., 32:64] & 0x0F) | (((hq >> 2) & 3) << 4)
        q3 = (lq[..., 0:32] >> 4) | (((hq >> 4) & 3) << 4)
        q4 = (lq[..., 32:64] >> 4) | (((hq >> 6) & 3) << 4)
        quarters = [q1, q2, q3, q4]
        vals = []
        for qi, q in enumerate(quarters):
            qf = q.astype(jnp.float32) - 32.0
            # scale index: each quarter of 32 spans two 16-element scale groups
            s0 = s[..., 2 * qi][..., None]
            s1 = s[..., 2 * qi + 1][..., None]
            vals.append(d * jnp.concatenate([s0 * qf[..., :16], s1 * qf[..., 16:]], axis=-1))
        halves.append(jnp.concatenate(vals, axis=-1))
    return jnp.concatenate(halves, axis=-1)


def _dequant_q2_k(raw: jnp.ndarray) -> jnp.ndarray:
    scales = raw[..., 0:16]
    qs = raw[..., 16:80]
    d = _f16(raw[..., 80], raw[..., 81])[..., None]
    dmin = _f16(raw[..., 82], raw[..., 83])[..., None]
    out = []
    for n in range(2):  # 128-element groups, 32 source bytes each
        grp = qs[..., n * 32 : n * 32 + 32]
        for shift in (0, 2, 4, 6):
            q = (grp >> shift) & 3
            for half in range(2):  # two 16-element sub-blocks
                idx = n * 8 + (shift // 2) * 2 + half
                sc = (scales[..., idx] & 0x0F).astype(jnp.float32)[..., None]
                m = (scales[..., idx] >> 4).astype(jnp.float32)[..., None]
                qq = q[..., half * 16 : half * 16 + 16].astype(jnp.float32)
                out.append(d * sc * qq - dmin * m)
    return jnp.concatenate(out, axis=-1)


def _q3_scales(scales: jnp.ndarray) -> list[jnp.ndarray]:
    """Unpack 16 6-bit signed scales from the 12-byte q3_k field."""
    out = []
    for j in range(16):
        low4 = (scales[..., j] & 0x0F) if j < 8 else (scales[..., j - 8] >> 4)
        high2 = (scales[..., 8 + j % 4] >> (2 * (j // 4))) & 3
        out.append((low4 | (high2 << 4)).astype(jnp.float32) - 32.0)
    return out


def _dequant_q3_k(raw: jnp.ndarray) -> jnp.ndarray:
    hmask = raw[..., 0:32]
    qs = raw[..., 32:96]
    sc = _q3_scales(raw[..., 96:108])
    d = _f16(raw[..., 108], raw[..., 109])[..., None]
    out = []
    for n in range(2):
        grp = qs[..., n * 32 : n * 32 + 32]
        for si, shift in enumerate((0, 2, 4, 6)):
            mbit = n * 4 + si
            q = ((grp >> shift) & 3).astype(jnp.int32)
            h = ((hmask >> mbit) & 1).astype(jnp.int32)
            q = (q - 4 * (1 - h)).astype(jnp.float32)
            for half in range(2):
                idx = n * 8 + si * 2 + half
                out.append(d * sc[idx][..., None] * q[..., half * 16 : half * 16 + 16])
    return jnp.concatenate(out, axis=-1)


def _dequant_q8_k(raw: jnp.ndarray) -> jnp.ndarray:
    d = _f32(raw[..., 0], raw[..., 1], raw[..., 2], raw[..., 3])[..., None]
    return d * _i8(raw[..., 4:260])


_DECODERS = {
    "q2_k": _dequant_q2_k,
    "q3_k": _dequant_q3_k,
    "q4_k": _dequant_q4_k,
    "q5_k": _dequant_q5_k,
    "q6_k": _dequant_q6_k,
    "q8_k": _dequant_q8_k,
}


def dequantize(qt) -> jnp.ndarray:
    """QTensor with k-quant raw bytes -> float32 [in_features, out_features]."""
    if qt.qtype not in _DECODERS:
        raise NotImplementedError(
            f"GGUF qtype {qt.qtype} decode not implemented yet "
            f"(supported: {sorted(_DECODERS)})"
        )
    n_in, n_out = qt.shape
    ts = TYPE_SIZES[qt.qtype]
    nb = n_in // QK_K
    raw = qt.data.reshape(n_out, nb, ts)
    vals = _DECODERS[qt.qtype](raw)  # [out, nb, 256]
    return vals.reshape(n_out, n_in).T
