"""Numeric codebooks and minifloat codecs for the low-bit formats.

All tables are float32 numpy constants; encode/decode are pure ``jnp``
functions so they trace cleanly under ``jit`` on any backend.  These replace
the reference's ggml C quantize/dequantize routines for nf4/nf3/fp4/fp6/fp8
(reference: ggml/quantize.py qtype table and the native libs of §2.3); the
numerics are the standard published definitions (QLoRA NF4, e2m1 FP4,
e3m2 FP6, OCP FP8), not a port of ggml code.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# ---------------------------------------------------------------------------
# Codebooks (normalized to [-1, 1]; used with a per-block absmax scale)
# ---------------------------------------------------------------------------

# NormalFloat-4 from the QLoRA paper (Dettmers et al. 2023), information-
# theoretically optimal 4-bit code for N(0,1) weights.
NF4_TABLE = np.array(
    [
        -1.0, -0.6961928009986877, -0.5250730514526367, -0.39491748809814453,
        -0.28444138169288635, -0.18477343022823334, -0.09105003625154495, 0.0,
        0.07958029955625534, 0.16093020141124725, 0.24611230194568634,
        0.33791524171829224, 0.44070982933044434, 0.5626170039176941,
        0.7229568362236023, 1.0,
    ],
    dtype=np.float32,
)

# NormalFloat-3: same construction as NF4 but with 2^3 levels — quantiles of
# N(0,1) with 0 pinned and the ends pinned at ±1 (our own derivation of the
# QLoRA recipe; the reference's nf3 table lives in its closed native wheel).
NF3_TABLE = np.array(
    [-1.0, -0.5350227355957031, -0.2469314038753510, 0.0,
     0.1833375245332718, 0.3819939494132996, 0.6229856610298157, 1.0],
    dtype=np.float32,
)

# FP4 (e2m1): sign × {0, .5, 1, 1.5, 2, 3, 4, 6} / 6, index = sign<<3 | code.
_FP4_MAGS = np.array([0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0], dtype=np.float32)
FP4_TABLE = np.concatenate([_FP4_MAGS, -_FP4_MAGS]) / 6.0  # normalized to [-1,1]


def _minifloat_table(exp_bits: int, man_bits: int, bias: int) -> np.ndarray:
    """Enumerate all values of a 1+e+m minifloat (with subnormals, no inf/nan)."""
    n = 1 << (1 + exp_bits + man_bits)
    codes = np.arange(n, dtype=np.uint32)
    sign = np.where(codes >> (exp_bits + man_bits) & 1, -1.0, 1.0)
    exp = (codes >> man_bits) & ((1 << exp_bits) - 1)
    man = codes & ((1 << man_bits) - 1)
    normal = exp > 0
    vals = np.where(
        normal,
        sign * (1.0 + man / (1 << man_bits)) * np.exp2(exp.astype(np.float64) - bias),
        sign * (man / (1 << man_bits)) * np.exp2(1.0 - bias),
    )
    return vals.astype(np.float32)


# FP6 (e3m2, bias 3) — the FP6-LLM format; max magnitude 28.
FP6_TABLE = _minifloat_table(3, 2, 3)
FP6_MAX = float(np.max(FP6_TABLE))  # 28.0

# FP8 tables for fallback decode; primary fp8 path uses ml_dtypes casts.
FP8_E4M3_MAX = 448.0
FP8_E5M2_MAX = 57344.0


def codebook_encode(x: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    """Map each element of normalized x to the nearest codebook index (uint8)."""
    t = jnp.asarray(table)
    # [..., 1] vs [levels] — argmin over the last axis
    idx = jnp.argmin(jnp.abs(x[..., None] - t), axis=-1)
    return idx.astype(jnp.uint8)


def codebook_decode(codes: jnp.ndarray, table: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(table)[codes.astype(jnp.int32)]


def _fp8_dtype(variant: str):
    return jnp.float8_e4m3fn if variant == "e4m3" else jnp.float8_e5m2


def fp8_to_codes(x: jnp.ndarray, variant: str) -> jnp.ndarray:
    """Cast to fp8 (RNE via XLA convert) and reinterpret as uint8 codes."""
    return jnp.asarray(x.astype(_fp8_dtype(variant))).view(jnp.uint8)


def fp8_from_codes(codes: jnp.ndarray, variant: str) -> jnp.ndarray:
    return codes.view(_fp8_dtype(variant)).astype(jnp.float32)
