"""Quantization type registry.

Name/id parity with the reference's ``ggml_tensor_qtype`` table
(reference: python/llm/src/ipex_llm/ggml/quantize.py:28-64) so user-facing
``load_in_low_bit=...`` strings are drop-in compatible.  The *storage layouts*
are our own TPU-first design (see ipex_llm_tpu/quantize/core.py): packed
uint8 planes + fp16 block scales laid out along the matmul contraction axis so
a Pallas kernel can unpack a (block, lane) tile with vector shifts and feed the
MXU directly — not ggml's interleaved C blocks.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class QTypeInfo:
    """Static description of one quantization format.

    kind:
      int_sym    — signed ints, per-block absmax scale (q4_0/q5_0/q8_0 family)
      int_asym   — unsigned ints, per-block scale + min (q4_1/q5_1 family)
      codebook   — nearest-entry lookup table with absmax scale (nf4/nf3/fp4)
      minifloat  — small float codes with per-block absmax scale (fp6/fp8)
      native     — plain dtype cast, no blocks (fp16/bf16)
      kquant     — GGUF k-quant superblocks (import/dequant path)
      alias      — resolves to another qtype (rtn variants, fp8 synonyms)
    """

    name: str
    qid: int
    kind: str
    bits: float = 0.0
    block_size: int = 0
    alias_of: str | None = None


# name -> id table mirrors reference ggml/quantize.py:28-60 (names and ids only)
_REGISTRY: dict[str, QTypeInfo] = {}


def _reg(info: QTypeInfo) -> None:
    _REGISTRY[info.name] = info


_reg(QTypeInfo("sym_int4", 2, "int_sym", bits=4, block_size=32))
_reg(QTypeInfo("asym_int4", 3, "int_asym", bits=4, block_size=32))
_reg(QTypeInfo("sym_int5", 6, "int_sym", bits=5, block_size=32))
_reg(QTypeInfo("asym_int5", 7, "int_asym", bits=5, block_size=32))
_reg(QTypeInfo("sym_int8", 8, "int_sym", bits=8, block_size=32))
_reg(QTypeInfo("nf4", 10, "codebook", bits=4, block_size=64))
_reg(QTypeInfo("nf3", 11, "codebook", bits=3, block_size=64))
_reg(QTypeInfo("fp16", 12, "native", bits=16))
_reg(QTypeInfo("fp8_e4m3", 15, "minifloat", bits=8, block_size=128))
_reg(QTypeInfo("fp4", 16, "codebook", bits=4, block_size=64))
_reg(QTypeInfo("mixed_fp4", 17, "alias", alias_of="fp4"))   # MOFQ4: per-layer fp4/sym_int4 pick
_reg(QTypeInfo("mixed_fp8", 18, "alias", alias_of="fp8_e4m3"))
_reg(QTypeInfo("fp8_e5m2", 19, "minifloat", bits=8, block_size=128))
_reg(QTypeInfo("fp8", 19, "alias", alias_of="fp8_e5m2"))
_reg(QTypeInfo("bf16", 20, "native", bits=16))
_reg(QTypeInfo("q2_k", 23, "kquant", bits=2.5625, block_size=256))
_reg(QTypeInfo("q6_k", 26, "kquant", bits=6.5625, block_size=256))
_reg(QTypeInfo("q4_k", 27, "kquant", bits=4.5, block_size=256))
_reg(QTypeInfo("q5_k", 28, "kquant", bits=5.5, block_size=256))
_reg(QTypeInfo("fp6", 29, "minifloat", bits=6, block_size=64))
_reg(QTypeInfo("fp6_k", 30, "alias", alias_of="fp6"))
_reg(QTypeInfo("sym_int4_rtn", 31, "alias", alias_of="sym_int4"))
_reg(QTypeInfo("sym_int8_rtn", 32, "alias", alias_of="sym_int8"))
_reg(QTypeInfo("asym_int4_rtn", 33, "alias", alias_of="asym_int4"))
_reg(QTypeInfo("woq_int4", 34, "alias", alias_of="sym_int4"))
_reg(QTypeInfo("torch_fp8_e5m2", 35, "alias", alias_of="fp8_e5m2"))
_reg(QTypeInfo("torch_fp8", 35, "alias", alias_of="fp8_e5m2"))
_reg(QTypeInfo("torch_fp8_e4m3", 36, "alias", alias_of="fp8_e4m3"))
# q3_k / q8_k have no reference qtype id but are needed for GGUF import
_reg(QTypeInfo("q3_k", 103, "kquant", bits=3.4375, block_size=256))
_reg(QTypeInfo("q8_k", 108, "kquant", bits=8.5, block_size=256))

# i-quant class (reference GGUF-IQ2 example: quantize-at-load to ~2 bpw
# with an imatrix).  llama.cpp's iq2/iq1 E8-lattice grids are non-derivable
# data tables, so these names get TPU-NATIVE codecs at the same bit budgets
# (quantize/core.py::_quant_iq2/_quant_iq1: complete {1,3}^8 magnitude
# codebook + sign plane at ~2.19 bpw; packed trits at ~1.81 bpw) — the
# quantize-and-run capability is full parity, while IMPORT of externally
# produced iq2/iq1 GGUF files stays a loud error (GGUF_TYPE_TO_QTYPE has no
# entry for those file ids).
_reg(QTypeInfo("gguf_iq2_xxs", 21, "iquant", bits=2.1875, block_size=256))
_reg(QTypeInfo("gguf_iq2_xs", 22, "alias", alias_of="gguf_iq2_xxs"))
_reg(QTypeInfo("gguf_iq1_s", 24, "iquant", bits=1.8125, block_size=256))
_reg(QTypeInfo("gguf_iq1_m", 25, "alias", alias_of="gguf_iq1_s"))

UNSUPPORTED_QTYPE_IDS: dict[str, int] = {}

#: name -> numeric id, the reference-compatible table
ggml_tensor_qtype: dict[str, int] = {
    **{n: i.qid for n, i in _REGISTRY.items()},
    **UNSUPPORTED_QTYPE_IDS,
}

# gguf file-level tensor type ids (ggml GGMLQuantizationType) -> our qtype name;
# used by the GGUF importer (reference counterpart: transformers/gguf/api.py)
GGUF_TYPE_TO_QTYPE: dict[int, str] = {
    0: "fp32",
    1: "fp16",
    2: "sym_int4",    # Q4_0
    3: "asym_int4",   # Q4_1
    6: "sym_int5",    # Q5_0
    7: "asym_int5",   # Q5_1
    8: "sym_int8",    # Q8_0
    10: "q2_k",
    11: "q3_k",
    12: "q4_k",
    13: "q5_k",
    14: "q6_k",
    15: "q8_k",
    30: "bf16",
}


def resolve(qtype: str) -> QTypeInfo:
    """Resolve a user-facing qtype name (following aliases) to its info."""
    if qtype not in _REGISTRY:
        raise ValueError(
            f"Unknown load_in_low_bit qtype {qtype!r}. "
            f"Supported: {sorted(_REGISTRY)}"
        )
    info = _REGISTRY[qtype]
    seen = {qtype}
    while info.kind == "alias":
        assert info.alias_of is not None and info.alias_of not in seen
        seen.add(info.alias_of)
        info = _REGISTRY[info.alias_of]
    return info


def is_supported(qtype: str) -> bool:
    return qtype in _REGISTRY


def all_qtypes() -> list[str]:
    return sorted(_REGISTRY)
