"""Block-quantized tensor core.

TPU-first replacement for the reference's ``FP4Params`` tensor subclass and the
ggml quantize/dequantize C routines (reference: low_bit_linear.py:332-491,
ggml/model/llama/llama_cpp.py:71-109).  Differences by design:

- A ``QTensor`` is a registered JAX pytree (packed code planes + fp16 scales
  as leaves; qtype/shape static) so it flows through ``jit``/``pjit``/
  ``jax.sharding`` like any array — no custom device-move hooks, no
  cpu↔device layout conversion step (ggml_q_format_convet_cpu2xpu has no
  TPU equivalent because the layout is already kernel-native).
- Quantization happens along the matmul **contraction axis** (axis 0 of the
  logical ``[in_features, out_features]`` weight).  Scales have shape
  ``[n_blocks, out]``; packed int4 nibble pairs sit along the contraction
  axis.  A Pallas tile ``[block, 128 lanes]`` therefore unpacks with two
  vector shifts and multiplies straight into the MXU.
- All codecs are pure jnp and jittable; the same code runs on CPU for tests
  and TPU for real loads.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.quantize import numerics, qtypes

SCALE_DTYPE = jnp.float16


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """A block-quantized 2-D weight ``[in_features, out_features]``.

    data:   packed codes; layout depends on qtype (see codecs below)
    scales: per-(block, out) scale, fp16
    zeros:  per-(block, out) zero/min for asym formats, else None
    qtype:  resolved qtype name (static)
    shape:  logical (in_features, out_features) (static)
    block_size: contraction-axis block size (static)
    tp_mode: tensor-parallel style stamped by parallel/shard.py —
        'col' (out axis sharded over tp), 'row' (in axis sharded, psum
        combine) or None.  Static so the Pallas dispatch can pick the
        matching shard_map wrapper at trace time.
    """

    data: jnp.ndarray
    scales: jnp.ndarray | None
    zeros: jnp.ndarray | None
    qtype: str
    shape: tuple[int, int]
    block_size: int
    tp_mode: str | None = None

    def tree_flatten(self):
        return (self.data, self.scales, self.zeros), (
            self.qtype, self.shape, self.block_size, self.tp_mode,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scales, zeros = children
        qtype, shape, block_size = aux[:3]
        tp_mode = aux[3] if len(aux) > 3 else None
        return cls(data, scales, zeros, qtype, shape, block_size, tp_mode)

    @property
    def in_features(self) -> int:
        return self.shape[0]

    @property
    def out_features(self) -> int:
        return self.shape[1]

    @property
    def nbytes(self) -> int:
        n = self.data.size * self.data.dtype.itemsize
        if self.scales is not None:
            n += self.scales.size * self.scales.dtype.itemsize
        if self.zeros is not None:
            n += self.zeros.size * self.zeros.dtype.itemsize
        return n

    def __repr__(self) -> str:  # keep pytree prints short
        return f"QTensor({self.qtype}, {self.shape}, bs={self.block_size})"


# ---------------------------------------------------------------------------
# packing helpers (contraction axis = axis 0 of each [bs, out] block)
# ---------------------------------------------------------------------------


def _pack_nibbles(codes: jnp.ndarray, bs: int) -> jnp.ndarray:
    """[in, out] uint8 codes in [0,16) -> [in//2, out] packed bytes.

    Block-local halves layout: within each ``bs``-row quantization block, row
    ``j`` (low nibble) pairs with row ``j + bs/2`` (high nibble).  Unpacking a
    block is then a contiguous [lo; hi] concat along the sublane axis — no
    row interleave — which the Pallas dequant-matmul kernel
    (ops/pallas/qmatmul.py) relies on for cheap in-VMEM unpack.
    """
    nb = codes.shape[0] // bs
    c = codes.reshape(nb, bs, codes.shape[1])
    lo, hi = c[:, : bs // 2], c[:, bs // 2 :]
    return (lo | (hi << 4)).astype(jnp.uint8).reshape(-1, codes.shape[1])


def _unpack_nibbles(packed: jnp.ndarray, bs: int) -> jnp.ndarray:
    """[in//2, out] bytes -> [in, out] uint8 codes (block-local halves)."""
    nb = packed.shape[0] // (bs // 2)
    p = packed.reshape(nb, bs // 2, packed.shape[1])
    codes = jnp.concatenate([p & 0x0F, p >> 4], axis=1)
    return codes.reshape(-1, packed.shape[1])


def _pack_5bit(codes: jnp.ndarray, bs: int) -> jnp.ndarray:
    """[in, out] codes in [0,32) -> [in//2 + in//8, out] packed bytes.

    Layout (the ggml Q5_0 idea in the kernel-friendly plane form): the low
    nibbles pack exactly like int4 (block-local halves), followed by the
    fifth bits packed 8-per-byte along the contraction axis — 5 bits/weight
    of real storage instead of the byte-per-code the r2 VERDICT flagged
    (weak #9).
    """
    n_in, n_out = codes.shape
    low = _pack_nibbles((codes & 0x0F).astype(jnp.uint8), bs)
    hb = (codes >> 4).astype(jnp.uint8).reshape(n_in // 8, 8, n_out)
    high = jnp.zeros((n_in // 8, n_out), jnp.uint8)
    for j in range(8):
        high = high | (hb[:, j] << j)
    return jnp.concatenate([low, high], axis=0)


def _unpack_5bit(packed: jnp.ndarray, bs: int) -> jnp.ndarray:
    """[in//2 + in//8, out] -> [in, out] uint8 codes in [0,32)."""
    n_in = packed.shape[0] * 8 // 5
    low = _unpack_nibbles(packed[: n_in // 2], bs)
    hb = packed[n_in // 2 :]                      # [in//8, out]
    hi = jnp.stack([(hb >> j) & 1 for j in range(8)], axis=1)
    return (low | (hi.reshape(n_in, -1) << 4)).astype(jnp.uint8)


def _pack_codes(codes: jnp.ndarray, bs: int, bits: int) -> jnp.ndarray:
    if bits == 4:
        return _pack_nibbles(codes, bs)
    if bits == 5:
        return _pack_5bit(codes, bs)
    return codes


def _unpack_codes(data: jnp.ndarray, bs: int, bits: int) -> jnp.ndarray:
    if bits == 4:
        return _unpack_nibbles(data, bs)
    if bits == 5:
        return _unpack_5bit(data, bs)
    return data


def _to_blocks(w: jnp.ndarray, bs: int) -> jnp.ndarray:
    """[in, out] -> [n_blocks, bs, out], zero-padding a trailing partial block.

    The reference's C quantizer requires whole blocks; models with
    in_features not divisible by the block size (e.g. fp8's 128) get a
    zero tail here, trimmed again by :func:`dequantize` (the VERDICT r1
    "fp8 remainder" fix).
    """
    n_in, n_out = w.shape
    pad = (-n_in) % bs
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, n_out), w.dtype)], axis=0)
    return w.reshape(-1, bs, n_out)


def _from_blocks(b: jnp.ndarray) -> jnp.ndarray:
    return b.reshape(b.shape[0] * b.shape[1], b.shape[2])


# ---------------------------------------------------------------------------
# codecs: each returns (data, scales, zeros) / reconstructs float
# ---------------------------------------------------------------------------


def _quant_int_sym(w, bs: int, bits: int):
    """llama.cpp-style symmetric round-to-nearest: d = signed_absmax / -2^(b-1),
    codes biased into [0, 2^b)."""
    blocks = _to_blocks(w, bs)
    qmax = 1 << (bits - 1)  # 8 / 16 / 128
    # pick the signed value with max magnitude so the sign of d matches it
    amax_idx = jnp.argmax(jnp.abs(blocks), axis=1, keepdims=True)
    signed_max = jnp.take_along_axis(blocks, amax_idx, axis=1)  # [nb, 1, out]
    d = signed_max / -qmax
    inv_d = jnp.where(d == 0, 0.0, 1.0 / d)
    q = jnp.clip(jnp.round(blocks * inv_d) + qmax, 0, 2 * qmax - 1)
    codes = _from_blocks(q.astype(jnp.uint8))
    scales = d[:, 0, :].astype(SCALE_DTYPE)
    return _pack_codes(codes, bs, bits), scales, None


def _dequant_int_sym(qt: QTensor, bits: int):
    qmax = 1 << (bits - 1)
    codes = _unpack_codes(qt.data, qt.block_size, bits)
    blocks = _to_blocks(codes.astype(jnp.float32) - qmax, qt.block_size)
    return _from_blocks(blocks * qt.scales[:, None, :].astype(jnp.float32))


def _quant_int_sym_opt(w, bs: int, bits: int, weights=None, n_cand: int = 21,
                       span: float = 0.25):
    """Scale-search symmetric quantization (llama.cpp ``make_qx_quants``
    style): per block, try ``n_cand`` scale multipliers around the absmax
    scale and keep the one minimizing (optionally importance-weighted)
    squared reconstruction error.  This is the error-compensated requant
    used by LoRA merging and the ``imatrix``-weighted path
    (``ggml_quantize_tensor_with_weights``, SURVEY §2.3): ``weights`` is a
    per-input-channel importance vector ``[in_features]``.
    """
    blocks = _to_blocks(w, bs)                       # [nb, bs, out]
    qmax = 1 << (bits - 1)
    amax_idx = jnp.argmax(jnp.abs(blocks), axis=1, keepdims=True)
    signed_max = jnp.take_along_axis(blocks, amax_idx, axis=1)
    d0 = signed_max / -qmax                          # [nb, 1, out]
    if weights is None:
        # x² importance (llama.cpp make_qx_quants rmse_type=1): penalizes
        # clipping the block's outliers, which dominate model quality
        wgt = blocks * blocks
    else:
        wv = jnp.asarray(weights, jnp.float32).reshape(-1)
        pad = (-wv.shape[0]) % bs
        if pad:
            wv = jnp.concatenate([wv, jnp.zeros((pad,), jnp.float32)])
        wgt = wv.reshape(-1, bs, 1)                  # [nb, bs, 1]

    def err_for(d):
        inv_d = jnp.where(d == 0, 0.0, 1.0 / d)
        q = jnp.clip(jnp.round(blocks * inv_d) + qmax, 0, 2 * qmax - 1)
        recon = (q - qmax) * d
        return (((blocks - recon) ** 2) * wgt).sum(axis=1), q  # [nb, out]

    def body(carry, mult):
        best_err, best_d = carry
        d = d0 * mult
        err, _ = err_for(d)
        better = err < best_err
        return (
            jnp.where(better, err, best_err),
            jnp.where(better, d[:, 0, :], best_d),
        ), None

    mults = jnp.linspace(1.0 - span, 1.0 + span, n_cand)
    err0, _ = err_for(d0)
    (best_err, best_d), _ = jax.lax.scan(body, (err0, d0[:, 0, :]), mults)
    d = best_d[:, None, :]
    inv_d = jnp.where(d == 0, 0.0, 1.0 / d)
    q = jnp.clip(jnp.round(blocks * inv_d) + qmax, 0, 2 * qmax - 1)
    codes = _from_blocks(q.astype(jnp.uint8))
    scales = best_d.astype(SCALE_DTYPE)
    return _pack_codes(codes, bs, bits), scales, None


def _quant_int_asym(w, bs: int, bits: int):
    """q4_1/q5_1 style: d = (max-min)/(2^b-1), m = min; x ≈ q*d + m."""
    blocks = _to_blocks(w, bs)
    mn = jnp.min(blocks, axis=1, keepdims=True)
    mx = jnp.max(blocks, axis=1, keepdims=True)
    levels = (1 << bits) - 1
    d = (mx - mn) / levels
    inv_d = jnp.where(d == 0, 0.0, 1.0 / d)
    q = jnp.clip(jnp.round((blocks - mn) * inv_d), 0, levels)
    codes = _from_blocks(q.astype(jnp.uint8))
    scales = d[:, 0, :].astype(SCALE_DTYPE)
    zeros = mn[:, 0, :].astype(SCALE_DTYPE)
    return _pack_codes(codes, bs, bits), scales, zeros


def _dequant_int_asym(qt: QTensor, bits: int):
    codes = _unpack_codes(qt.data, qt.block_size, bits)
    blocks = _to_blocks(codes.astype(jnp.float32), qt.block_size)
    return _from_blocks(
        blocks * qt.scales[:, None, :].astype(jnp.float32)
        + qt.zeros[:, None, :].astype(jnp.float32)
    )


def _codebook_table(qtype: str) -> np.ndarray:
    return {
        "nf4": numerics.NF4_TABLE,
        "nf3": numerics.NF3_TABLE,
        "fp4": numerics.FP4_TABLE,
    }[qtype]


def _quant_codebook(w, bs: int, qtype: str, bits: int):
    blocks = _to_blocks(w, bs)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    d = jnp.where(amax == 0, 1.0, amax)
    normalized = blocks / d
    codes = numerics.codebook_encode(normalized, _codebook_table(qtype))
    codes = _from_blocks(codes)
    scales = d[:, 0, :].astype(SCALE_DTYPE)
    data = _pack_nibbles(codes, bs) if bits == 4 else codes
    return data, scales, None


def _quant_codebook_opt(w, bs: int, qtype: str, bits: int, weights=None,
                        n_cand: int = 21, span: float = 0.25):
    """Scale-search codebook quantization (the nf4/fp4 peer of
    ``_quant_int_sym_opt``): per block, pick the scale minimizing
    importance-weighted squared reconstruction error."""
    table = jnp.asarray(_codebook_table(qtype), jnp.float32)
    blocks = _to_blocks(w, bs)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    d0 = jnp.where(amax == 0, 1.0, amax)                 # [nb, 1, out]
    if weights is None:
        wgt = blocks * blocks
    else:
        wv = jnp.asarray(weights, jnp.float32).reshape(-1)
        pad = (-wv.shape[0]) % bs
        if pad:
            wv = jnp.concatenate([wv, jnp.zeros((pad,), jnp.float32)])
        wgt = wv.reshape(-1, bs, 1)

    def recon_err(d):
        codes = numerics.codebook_encode(
            jnp.clip(blocks / d, -1.0, 1.0), table
        )
        recon = numerics.codebook_decode(codes, table) * d
        return (((blocks - recon) ** 2) * wgt).sum(axis=1)

    def body(carry, mult):
        best_err, best_d = carry
        d = d0 * mult
        err = recon_err(d)
        better = err < best_err
        return (
            jnp.where(better, err, best_err),
            jnp.where(better, d[:, 0, :], best_d),
        ), None

    mults = jnp.linspace(1.0 - span, 1.0 + span, n_cand)
    (best_err, best_d), _ = jax.lax.scan(
        body, (recon_err(d0), d0[:, 0, :]), mults
    )
    d = best_d[:, None, :]
    codes = numerics.codebook_encode(jnp.clip(blocks / d, -1.0, 1.0), table)
    codes = _from_blocks(codes)
    scales = best_d.astype(SCALE_DTYPE)
    return _pack_codes(codes, bs, bits), scales, None


def _dequant_codebook(qt: QTensor, qtype: str, bits: int):
    codes = _unpack_nibbles(qt.data, qt.block_size) if bits == 4 else qt.data
    vals = numerics.codebook_decode(codes, _codebook_table(qtype))
    blocks = _to_blocks(vals, qt.block_size)
    return _from_blocks(blocks * qt.scales[:, None, :].astype(jnp.float32))


# ---------------------------------------------------------------------------
# i-quant class: TPU-native ~2 / ~1.8 bpw codecs (reference GGUF-IQ2 story).
# llama.cpp's iq2/iq1 use hand-selected E8-lattice grid tables (pure data,
# not derivable here); these codecs hit the same bit budgets with COMPLETE
# derivable codebooks instead: magnitudes {1,3} per element + sign plane
# (= the full {1,3}^8 codebook llama.cpp subsets) for iq2, packed trits
# {-1,0,1} for iq1.  Two-level scales like the k-quants: per-32 4-bit
# subscale under a per-256 fp16 super scale.  Layouts are our own; external
# iq-GGUF files still refuse to import (qtypes.GGUF_TYPE_TO_QTYPE).
# ---------------------------------------------------------------------------

_IQ_BLOCK = 256
_IQ_GROUP = 32


def _iq_prepare(w, weights):
    blocks = _to_blocks(w, _IQ_BLOCK)                 # [nb, 256, out]
    nb, _, n_out = blocks.shape
    g = blocks.reshape(nb, _IQ_BLOCK // _IQ_GROUP, _IQ_GROUP, n_out)
    if weights is None:
        wg = jnp.ones_like(g)
    else:
        ww = jnp.asarray(weights, jnp.float32).reshape(-1)
        pad = (-ww.shape[0]) % _IQ_BLOCK
        ww = jnp.concatenate([ww, jnp.zeros((pad,), jnp.float32)])
        wg = jnp.broadcast_to(
            ww.reshape(nb, _IQ_BLOCK // _IQ_GROUP, _IQ_GROUP, 1), g.shape
        )
        wg = jnp.maximum(wg, 1e-8)
    return g, wg, nb, n_out


def _iq_two_level_scales(s):
    """Per-group scale s [nb, G, 1, out] -> (fp16 d [nb, out], nibble codes
    [nb, G, 1, out], reconstructed s_q): s ≈ d * (nib + 1) / 16."""
    d = jnp.maximum(jnp.max(s, axis=1, keepdims=True), 1e-12)
    nib = jnp.clip(jnp.round(s * 16.0 / d) - 1.0, 0.0, 15.0)
    s_q = d * (nib + 1.0) / 16.0
    scales = d[:, 0, 0, :].astype(SCALE_DTYPE)
    return scales, nib, s_q


def _pack_bits8(b, nb, n_out):
    """[nb, G, 32, out] 0/1 -> [nb, G*4, out] bytes (bit j = element j of 8,
    elements consecutive along the in axis)."""
    g = b.shape[1]
    bb = b.reshape(nb, g, 4, 8, n_out).astype(jnp.uint8)
    out = jnp.zeros((nb, g, 4, n_out), jnp.uint8)
    for j in range(8):
        out = out | (bb[:, :, :, j] << j)
    return out.reshape(nb, g * 4, n_out)


def _unpack_bits8(p, nb, g, n_out):
    bb = p.reshape(nb, g, 4, n_out).astype(jnp.int32)
    cols = [(bb >> j) & 1 for j in range(8)]
    return jnp.stack(cols, axis=3).reshape(nb, g, 32, n_out)


def _pack_nib8(nib, nb, n_out):
    """[nb, 8, 1, out] codes in [0,16) -> [nb, 4, out] bytes."""
    n = nib[:, :, 0, :].astype(jnp.uint8)               # [nb, 8, out]
    return (n[:, 0::2] | (n[:, 1::2] << 4)).reshape(nb, 4, n_out)


def _unpack_nib8(p, nb, n_out):
    b = p.reshape(nb, 4, n_out).astype(jnp.int32)
    lo, hi = b & 0x0F, b >> 4
    return jnp.stack([lo, hi], axis=2).reshape(nb, 8, 1, n_out)


def _quant_iq2(w, weights=None):
    """~2.19 bpw: per element |w| in {1,3}·s_g with a sign bit; per-group
    subscale refined by weighted least squares (the make_qx_quants idea)."""
    g, wg, nb, n_out = _iq_prepare(w, weights)
    a = jnp.abs(g)
    s = jnp.maximum(jnp.max(a, axis=2, keepdims=True) / 3.0, 1e-12)
    for _ in range(2):
        m = jnp.where(a >= 2.0 * s, 3.0, 1.0)
        num = jnp.sum(wg * a * m, axis=2, keepdims=True)
        den = jnp.sum(wg * m * m, axis=2, keepdims=True)
        s = jnp.where(den > 0, num / jnp.maximum(den, 1e-12), s)
        s = jnp.maximum(s, 1e-12)
    scales, nib, s_q = _iq_two_level_scales(s)
    m = jnp.where(a >= 2.0 * s_q, 1, 0)                  # magnitude bit: 3 vs 1
    sign = (g < 0).astype(jnp.uint8)
    data = jnp.concatenate([
        _pack_bits8(m, nb, n_out),                       # 32 bytes / block
        _pack_bits8(sign, nb, n_out),                    # 32 bytes
        _pack_nib8(nib, nb, n_out),                      # 4 bytes
    ], axis=1).reshape(nb * 68, n_out)
    return data, scales, None


def _dequant_iq2(qt: QTensor):
    n_out = qt.data.shape[1]
    nb = qt.data.shape[0] // 68
    raw = qt.data.reshape(nb, 68, n_out)
    mag = _unpack_bits8(raw[:, :32], nb, 8, n_out)       # [nb, 8, 32, out]
    sign = _unpack_bits8(raw[:, 32:64], nb, 8, n_out)
    nib = _unpack_nib8(raw[:, 64:68], nb, n_out)
    d = qt.scales.astype(jnp.float32).reshape(nb, 1, 1, n_out)
    s_q = d * (nib.astype(jnp.float32) + 1.0) / 16.0
    vals = (1.0 + 2.0 * mag) * jnp.where(sign == 1, -1.0, 1.0) * s_q
    return vals.reshape(nb * _IQ_BLOCK, n_out)


def _pack_trits(t, nb, n_out):
    """[nb, 260, out] codes in {0,1,2} -> [nb, 52, out] base-3 bytes."""
    tt = t.reshape(nb, 52, 5, n_out).astype(jnp.uint8)
    out = jnp.zeros((nb, 52, n_out), jnp.uint8)
    p = 1
    for j in range(5):
        out = out + tt[:, :, j] * p
        p *= 3
    return out


def _unpack_trits(p, nb, n_out):
    b = p.astype(jnp.int32)
    digs = []
    for _ in range(5):
        digs.append(b % 3)
        b = b // 3
    return jnp.stack(digs, axis=2).reshape(nb, 260, n_out)


def _quant_iq1(w, weights=None):
    """~1.81 bpw: per element in {-1, 0, +1}·s_g, trits packed 5-per-byte."""
    g, wg, nb, n_out = _iq_prepare(w, weights)
    a = jnp.abs(g)
    s = jnp.maximum(jnp.max(a, axis=2, keepdims=True), 1e-12)
    for _ in range(2):
        m = (a >= 0.5 * s).astype(jnp.float32)
        num = jnp.sum(wg * a * m, axis=2, keepdims=True)
        den = jnp.sum(wg * m, axis=2, keepdims=True)
        s = jnp.where(den > 0, num / jnp.maximum(den, 1e-12), s)
        s = jnp.maximum(s, 1e-12)
    scales, nib, s_q = _iq_two_level_scales(s)
    t = jnp.where(a >= 0.5 * s_q, jnp.sign(g), 0.0)      # {-1, 0, 1}
    codes = (t + 1.0).reshape(nb, _IQ_BLOCK, n_out)
    codes = jnp.concatenate(
        [codes, jnp.ones((nb, 4, n_out), codes.dtype)], axis=1
    )  # pad 256 -> 260 (code 1 = zero value)
    data = jnp.concatenate([
        _pack_trits(codes, nb, n_out),                   # 52 bytes / block
        _pack_nib8(nib, nb, n_out),                      # 4 bytes
    ], axis=1).reshape(nb * 56, n_out)
    return data, scales, None


def _dequant_iq1(qt: QTensor):
    n_out = qt.data.shape[1]
    nb = qt.data.shape[0] // 56
    raw = qt.data.reshape(nb, 56, n_out)
    t = _unpack_trits(raw[:, :52], nb, n_out)[:, :_IQ_BLOCK] - 1  # {-1,0,1}
    nib = _unpack_nib8(raw[:, 52:56], nb, n_out)
    d = qt.scales.astype(jnp.float32).reshape(nb, 1, 1, n_out)
    s_q = d * (nib.astype(jnp.float32) + 1.0) / 16.0
    vals = t.reshape(nb, 8, 32, n_out).astype(jnp.float32) * s_q
    return vals.reshape(nb * _IQ_BLOCK, n_out)


def _quant_fp6(w, bs: int):
    blocks = _to_blocks(w, bs)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    d = jnp.where(amax == 0, 1.0, amax / numerics.FP6_MAX)
    codes = numerics.codebook_encode(
        jnp.clip(blocks / d, -numerics.FP6_MAX, numerics.FP6_MAX)
        / numerics.FP6_MAX,
        numerics.FP6_TABLE / numerics.FP6_MAX,
    )
    scales = d[:, 0, :].astype(SCALE_DTYPE)
    return _from_blocks(codes), scales, None


def _dequant_fp6(qt: QTensor):
    vals = numerics.codebook_decode(qt.data, numerics.FP6_TABLE)
    blocks = _to_blocks(vals, qt.block_size)
    return _from_blocks(blocks * qt.scales[:, None, :].astype(jnp.float32))


def _quant_fp8(w, bs: int, variant: str):
    blocks = _to_blocks(w, bs)
    fmax = numerics.FP8_E4M3_MAX if variant == "e4m3" else numerics.FP8_E5M2_MAX
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    d = jnp.where(amax == 0, 1.0, amax / fmax)
    codes = numerics.fp8_to_codes(blocks / d, variant)
    scales = d[:, 0, :].astype(SCALE_DTYPE)
    return _from_blocks(codes), scales, None


def _dequant_fp8(qt: QTensor, variant: str):
    vals = numerics.fp8_from_codes(qt.data, variant)
    blocks = _to_blocks(vals, qt.block_size)
    return _from_blocks(blocks * qt.scales[:, None, :].astype(jnp.float32))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _as_jnp_f32(w: Any) -> jnp.ndarray:
    if hasattr(w, "detach"):  # torch tensor without importing torch
        w = w.detach().cpu().float().numpy()
    return jnp.asarray(np.asarray(w), dtype=jnp.float32)


@partial(jax.jit, static_argnames=("qtype", "block_size", "optimize"))
def _quantize_jit(w: jnp.ndarray, qtype: str, block_size: int,
                  optimize: bool = False, imatrix=None):
    info = qtypes.resolve(qtype)
    if info.kind == "int_sym":
        if optimize or imatrix is not None:
            return _quant_int_sym_opt(w, block_size, int(info.bits),
                                      weights=imatrix)
        return _quant_int_sym(w, block_size, int(info.bits))
    if info.kind == "int_asym":
        return _quant_int_asym(w, block_size, int(info.bits))
    if info.kind == "codebook":
        if optimize or imatrix is not None:
            return _quant_codebook_opt(w, block_size, info.name,
                                       int(info.bits), weights=imatrix)
        return _quant_codebook(w, block_size, info.name, int(info.bits))
    if info.kind == "minifloat":
        if info.name == "fp6":
            return _quant_fp6(w, block_size)
        return _quant_fp8(w, block_size, info.name.split("_")[-1])
    if info.kind == "iquant":
        if info.name == "gguf_iq2_xxs":
            return _quant_iq2(w, weights=imatrix)
        return _quant_iq1(w, weights=imatrix)
    raise ValueError(f"cannot block-quantize kind={info.kind} ({qtype})")


def quantize(w: Any, qtype: str, block_size: int | None = None, *,
             optimize: bool = False, imatrix: Any = None) -> QTensor:
    """Quantize a 2-D ``[in_features, out_features]`` weight.

    Reference counterpart: ``FP4Params.quantize`` → ``ggml_convert_qtype``
    (low_bit_linear.py:370,106); here a pure-jnp jitted codec.

    ``optimize=True`` runs the per-block scale search (more faithful, ~20×
    the codec cost — used for LoRA merges).  ``imatrix`` is a per-input-
    channel importance vector enabling weighted quantization (the
    reference's ``ggml_quantize_tensor_with_weights``); it implies the
    scale-search path.
    """
    import numpy as _np

    info = qtypes.resolve(qtype)
    if (
        isinstance(w, _np.ndarray)
        and info.kind in ("int_sym", "int_asym", "codebook")
        and int(info.bits) in (4, 8)
        and not optimize
        and imatrix is None
    ):
        # C++ quantizer (the ggml CPU quantizer equivalent, native/): same
        # math for sym/asym int and the 16-entry codebooks, a fraction of
        # the load-time cost; falls through when the library is unavailable
        from ipex_llm_tpu.native import quantizer as _nq

        if _nq.available():
            shape = tuple(w.shape)
            bs = block_size or info.block_size
            wf = _np.asarray(w, _np.float32)
            if info.kind == "int_sym":
                out = _nq.quantize_sym_native(wf, int(info.bits), bs)
                if out is not None:
                    data, scales = out
                    return QTensor(jnp.asarray(data), jnp.asarray(scales),
                                   None, info.name, shape, bs)
            elif info.kind == "int_asym":
                out = _nq.quantize_asym_native(wf, int(info.bits), bs)
                if out is not None:
                    data, scales, zeros = out
                    return QTensor(jnp.asarray(data), jnp.asarray(scales),
                                   jnp.asarray(zeros), info.name, shape, bs)
            elif int(info.bits) == 4:  # codebook: nf4 / fp4
                out = _nq.quantize_codebook_native(
                    wf, _codebook_table(info.name), bs)
                if out is not None:
                    data, scales = out
                    return QTensor(jnp.asarray(data), jnp.asarray(scales),
                                   None, info.name, shape, bs)

    w = _as_jnp_f32(w)
    if w.ndim != 2:
        raise ValueError(f"expected 2-D weight, got shape {w.shape}")
    if imatrix is not None:
        im_np = np.asarray(imatrix, np.float32).reshape(-1)
        if im_np.shape[0] != w.shape[0]:
            raise ValueError(
                f"imatrix length {im_np.shape[0]} != in_features {w.shape[0]}"
                " (importance is per input channel, reference"
                " ggml_quantize_tensor_with_weights)"
            )
        imatrix = im_np
    if (optimize or imatrix is not None) and info.kind not in (
        "int_sym", "codebook", "iquant"
    ):
        import warnings

        warnings.warn(
            f"optimize/imatrix quantization is not implemented for "
            f"kind={info.kind!r} ({qtype}); using the standard codec",
            stacklevel=2,
        )
        optimize, imatrix = False, None
    if info.kind == "native":
        dt = jnp.float16 if info.name == "fp16" else jnp.bfloat16
        return QTensor(w.astype(dt), None, None, info.name, tuple(w.shape), 0)
    bs = block_size or info.block_size
    im = None if imatrix is None else jnp.asarray(imatrix, jnp.float32)
    data, scales, zeros = _quantize_jit(w, info.name, bs, optimize=optimize,
                                        imatrix=im)
    return QTensor(data, scales, zeros, info.name, tuple(w.shape), bs)


@partial(jax.jit, static_argnames=("dtype",))
def dequantize(qt: QTensor, dtype=jnp.float32) -> jnp.ndarray:
    """Reconstruct the float weight ``[in_features, out_features]``."""
    info = qtypes.resolve(qt.qtype)
    if info.kind == "native":
        return qt.data.astype(dtype)
    if info.kind == "int_sym":
        out = _dequant_int_sym(qt, int(info.bits))
    elif info.kind == "int_asym":
        out = _dequant_int_asym(qt, int(info.bits))
    elif info.kind == "codebook":
        out = _dequant_codebook(qt, info.name, int(info.bits))
    elif info.kind == "minifloat":
        out = _dequant_fp6(qt) if info.name == "fp6" else _dequant_fp8(
            qt, info.name.split("_")[-1]
        )
    elif info.kind == "iquant":
        out = (_dequant_iq2(qt) if info.name == "gguf_iq2_xxs"
               else _dequant_iq1(qt))
    elif info.kind == "kquant":
        from ipex_llm_tpu.quantize import kquants

        out = kquants.dequantize(qt)
    else:
        raise ValueError(f"cannot dequantize {qt.qtype}")
    return out[: qt.in_features].astype(dtype)
