"""llama.cpp importance-matrix (imatrix) file support.

Reference counterpart: ``load_imatrix_data`` (reference
transformers/utils.py:186-240, itself adapted from llama.cpp's quantize
tool) and the ``imatrix=`` kwarg on ``from_pretrained`` (reference
model.py:111,333).  The binary layout is llama.cpp's public format:

    int32 n_entries
    per entry: int32 name_len, name bytes (e.g. "blk.14.attn_output.weight"),
               int32 ncall, int32 nval, float32 values[nval]

Entries are re-keyed "{layer}_{slot}" ("14_o", "0_q", "3_down", and
"{layer}_{slot}_{expert}" for MoE) to stay checkpoint-name agnostic; the
values are per-input-channel importance (mean squared activations), which
``quantize/core.quantize(..., imatrix=...)`` uses for weighted scale
search."""

from __future__ import annotations

import numpy as np

#: gguf tensor stem -> slot key used by the loader
_STEM_TO_SLOT = {
    "attn_q": "q", "attn_k": "k", "attn_v": "v", "attn_output": "o",
    "attn_qkv": "qkv",
    "ffn_gate": "gate", "ffn_up": "up", "ffn_down": "down",
}


def load_imatrix(path: str) -> dict[str, np.ndarray]:
    """Parse a llama.cpp imatrix file -> {"{layer}_{slot}": [in_features]}."""
    data: dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        n_entries = int.from_bytes(f.read(4), "little")
        if n_entries < 1:
            raise ValueError(f"no entries in imatrix file {path!r}")
        for _ in range(n_entries):
            name_len = int.from_bytes(f.read(4), "little")
            name = f.read(name_len).decode("utf-8")
            ncall = int.from_bytes(f.read(4), "little")
            nval = int.from_bytes(f.read(4), "little")
            if nval < 1:
                raise ValueError(f"bad entry {name!r} in {path!r}")
            vals = np.frombuffer(f.read(4 * nval), dtype=np.float32).copy()
            if ncall > 0:
                vals = vals / ncall
            key = _rekey(name)
            if key is not None:
                data[key] = vals
    return data


def _rekey(name: str) -> str | None:
    parts = name.split(".")
    if parts[0] != "blk" or len(parts) < 4:
        return None          # output.weight / token_embd etc: unused
    layer = parts[1]
    stem = parts[2]
    slot = _STEM_TO_SLOT.get(stem)
    if slot is None:
        return None
    if len(parts) == 5:      # mixtral per-expert: blk.0.ffn_down.3.weight
        return f"{layer}_{slot}_{parts[3]}"
    return f"{layer}_{slot}"


def slot_importance(data: dict[str, np.ndarray] | None, layer: int,
                    slot: str, expert: int | None = None
                    ) -> np.ndarray | None:
    """Importance vector for one (layer, slot[, expert]) with
    merged-projection fallbacks: the fused qkv matmul reads the attention
    input (same activations llama.cpp records for attn_q), and the fused
    gate_up matmul reads the MLP input (recorded for ffn_gate/ffn_up).
    ``expert`` selects mixtral-style per-expert entries
    ("blk.N.ffn_down.E.weight"), falling back to the shared entry."""
    if data is None:
        return None
    cands = {
        "qkv": [f"{layer}_qkv", f"{layer}_q", f"{layer}_k", f"{layer}_v"],
        "gate_up": [f"{layer}_gate", f"{layer}_up"],
    }.get(slot, [f"{layer}_{slot}"])
    if expert is not None:
        cands = [f"{c}_{expert}" for c in cands] + cands
    for c in cands:
        if c in data:
            return data[c]
    return None
