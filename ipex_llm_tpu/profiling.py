"""Tracing / profiling hooks.

Reference counterpart: the BenchmarkWrapper timing instrumentation
(reference utils/benchmark_util_*.py:353 — first-token vs rest latency) and
the NPU builder's profile flag.  TPU-native: ``jax.profiler`` traces (for
xprof/tensorboard) plus a lightweight step-timer that the generate loop and
serving engine already feed (first_cost / rest_cost_mean attributes).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field


@contextlib.contextmanager
def trace(log_dir: str | None = None):
    """Capture a jax.profiler trace (view with tensorboard/xprof).

    Enabled explicitly or via IPEX_LLM_TPU_PROFILE=<dir>.
    """
    import jax

    log_dir = log_dir or os.environ.get("IPEX_LLM_TPU_PROFILE")
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


_capture_lock = threading.Lock()


def capture(log_dir: str, seconds: float) -> dict:
    """Operational jax.profiler capture: trace THIS process for
    ``seconds`` into ``log_dir`` (view with tensorboard/xprof/Perfetto).

    The blocking body behind the serving tier's ``/debug/profile``
    endpoint (run it in an executor): reuses :func:`trace`, serializes
    concurrent captures (jax.profiler allows one at a time — a second
    caller gets a clean error instead of a runtime crash), and returns
    the artifact location.
    """
    seconds = max(0.1, min(float(seconds), 120.0))
    if not _capture_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    try:
        t0 = time.perf_counter()
        with trace(log_dir):
            time.sleep(seconds)
        return {"log_dir": log_dir,
                "seconds": round(time.perf_counter() - t0, 3)}
    finally:
        _capture_lock.release()


@dataclass
class StepTimer:
    """TTFT + per-token latency accumulator (BenchmarkWrapper metrics)."""

    first_token_s: float | None = None
    token_times: list = field(default_factory=list)
    _t0: float | None = None

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def tick(self):
        now = time.perf_counter()
        if self.first_token_s is None:
            self.first_token_s = now - self._t0
        else:
            self.token_times.append(now - self._t0)
        self._t0 = now

    @property
    def rest_cost_mean(self) -> float:
        return sum(self.token_times) / max(len(self.token_times), 1)

    def summary(self) -> dict:
        return {
            "first_token_s": self.first_token_s,
            "rest_token_s": self.rest_cost_mean,
            "decode_tok_s": (1.0 / self.rest_cost_mean
                             if self.token_times else 0.0),
        }
