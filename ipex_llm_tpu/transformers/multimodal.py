"""Qwen2-VL multimodal causal LM (vision-language drop-in).

Reference counterpart: transformers/models/qwen2_vl.py — the reference
patches HF's Qwen2VLForConditionalGeneration (merged qkv, SDPA, M-ROPE
kept intact).  Here the HF checkpoint is a weight source: the vision tower
(models/vision.py) produces image embeddings that replace the
``image_token_id`` slots, and the shared decoder runs with
``input_embeds`` + 3-channel M-ROPE positions.

Naming tolerates both checkpoint layouts: legacy ``visual.* / model.*`` and
the 4.52+ nested ``model.visual.* / model.language_model.*``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ipex_llm_tpu.hostutil import h2d
from ipex_llm_tpu.models.build import build_params
from ipex_llm_tpu.models.config import ModelConfig
from ipex_llm_tpu.models.families import WeightScheme, _base_cfg
from ipex_llm_tpu.models.loader import CheckpointReader, read_config
from ipex_llm_tpu.models.vision import (
    VisionConfig,
    build_vision_params,
    vision_forward,
    vision_rotary,
)


def _qwen2_vl_text_config(hf: dict) -> ModelConfig:
    text = dict(hf.get("text_config") or hf)
    text.setdefault("model_type", "qwen2_vl")
    rs = text.get("rope_scaling") or hf.get("rope_scaling") or {}
    section = rs.get("mrope_section")
    d = _base_cfg(
        text,
        attention_bias=True,
        attention_out_bias=False,
        mrope_section=tuple(section) if section else None,
    )
    # mrope's rope table is plain default frequencies; the section logic
    # lives in ops/rope.cos_sin_mrope
    d["rope"] = d["rope"].__class__(
        head_dim=d["head_dim"], base=text.get("rope_theta", 10000.0)
    )
    return ModelConfig(**d)


class _AliasReader:
    """Try canonical then nested (model.language_model.) weight names."""

    def __init__(self, reader):
        self.reader = reader

    def _resolve(self, name: str) -> str:
        if self.reader.has(name):
            return name
        cands = []
        if name.startswith("model."):
            suffix = name[len("model."):]
            cands += ["model.language_model." + suffix,   # 4.52+ nested
                      "language_model.model." + suffix,   # legacy submodel
                      "llm.model." + suffix]              # minicpm-v
        if name == "lm_head.weight":
            cands += ["model.lm_head.weight", "language_model.lm_head.weight",
                      "llm.lm_head.weight"]
        for alt in cands:
            if self.reader.has(alt):
                return alt
        return name

    def get(self, name: str):
        return self.reader.get(self._resolve(name))

    def has(self, name: str) -> bool:
        return self.reader.has(self._resolve(name))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _mm_prefill(cfg, params, cache, tokens, pos, embeds):
    from ipex_llm_tpu.models.decoder import decoder_forward

    return decoder_forward(cfg, params, tokens, cache, pos,
                           input_embeds=embeds, last_token_only=True)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(2,))
def _mm_decode(cfg, params, cache, tok, pos):
    from ipex_llm_tpu.models.decoder import decoder_forward

    return decoder_forward(cfg, params, tok, cache, pos)


class TPUModelForVision2Seq:
    """Qwen2-VL-style conditional generation (image + text -> text)."""

    def __init__(self, cfg: ModelConfig, vcfg: VisionConfig, params: dict,
                 vparams: dict, hf_config: dict, qtype: str):
        self.config = cfg
        self.vision_config = vcfg
        self.params = params
        self.vision_params = vparams
        self.hf_config = hf_config
        self.qtype = qtype
        self.image_token_id = hf_config.get("image_token_id", 151655)
        self.vision_start_token_id = hf_config.get("vision_start_token_id",
                                                   151652)
        self.spatial_merge = vcfg.spatial_merge_size

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        if hf_config.get("model_type") not in ("qwen2_vl",):
            raise ValueError(
                f"AutoModelForVision2Seq supports qwen2_vl checkpoints; got "
                f"{hf_config.get('model_type')!r}"
            )
        cfg = _qwen2_vl_text_config(hf_config)
        vcfg = VisionConfig.from_hf(hf_config["vision_config"],
                                    text_hidden=cfg.hidden_size)
        reader = _AliasReader(CheckpointReader(path))
        params = build_params(cfg, WeightScheme(), reader.get, reader.has,
                              qtype=qtype)
        vparams = build_vision_params(vcfg, reader.reader.get,
                                      reader.reader.has, qtype)
        return cls(cfg, vcfg, params, vparams, hf_config, qtype)

    def save_low_bit(self, path: str) -> None:
        from ipex_llm_tpu.models import serialize

        serialize.save_low_bit(
            path, {"text": self.params, "vision": self.vision_params},
            self.hf_config, self.qtype,
        )

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize

        tree, hf, qtype = serialize.load_low_bit(path)
        cfg = _qwen2_vl_text_config(hf)
        vcfg = VisionConfig.from_hf(hf["vision_config"],
                                    text_hidden=cfg.hidden_size)
        return cls(cfg, vcfg, tree["text"], tree["vision"], hf, qtype)

    # -- M-ROPE position ids (reference: Qwen2VL get_rope_index) ------------

    def get_rope_index(self, input_ids: np.ndarray,
                       image_grid_thw: list[tuple[int, int, int]]):
        """input_ids [T] -> positions [3, T] + rope_delta (next text pos -
        sequence length).  Single-row form; batching left-pads upstream."""
        toks = np.asarray(input_ids)
        t_len = len(toks)
        pos = np.zeros((3, t_len), np.int32)
        img_iter = iter(image_grid_thw)
        st = 0          # next position value
        i = 0
        m = self.spatial_merge
        while i < t_len:
            if toks[i] == self.image_token_id:
                t, h, w = next(img_iter)
                gh, gw = h // m, w // m
                n = t * gh * gw
                t_idx = np.repeat(np.arange(t), gh * gw)
                h_idx = np.tile(np.repeat(np.arange(gh), gw), t)
                w_idx = np.tile(np.arange(gw), t * gh)
                pos[0, i : i + n] = st + t_idx
                pos[1, i : i + n] = st + h_idx
                pos[2, i : i + n] = st + w_idx
                st = pos[:, i : i + n].max() + 1
                i += n
            else:
                pos[:, i] = st
                st += 1
                i += 1
        return pos, int(st - t_len)

    # -- forward / generate ---------------------------------------------------

    def _embed_multimodal(self, input_ids: np.ndarray,
                          pixel_values, image_grid_thw):
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(input_ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            img_embeds = []
            off = 0
            px = h2d(pixel_values, jnp.float32)
            for thw in image_grid_thw:
                n = int(np.prod(thw))
                freqs = h2d(vision_rotary(self.vision_config,
                                                  tuple(thw)))
                img_embeds.append(vision_forward(
                    self.vision_config, self.vision_params,
                    px[off : off + n], freqs,
                ))
                off += n
            img = jnp.concatenate(img_embeds).astype(x.dtype)
            mask = np.asarray(input_ids) == self.image_token_id
            (idx,) = np.nonzero(mask)
            assert len(idx) == img.shape[0], (
                f"{len(idx)} image tokens vs {img.shape[0]} image embeds"
            )
            x = x.at[0, h2d(idx)].set(img)
        return x

    def forward_logits(self, input_ids, pixel_values=None,
                       image_grid_thw=()):
        """Full-sequence logits [1, T, V] (parity/eval path)."""
        from ipex_llm_tpu import kv as kv_mod
        from ipex_llm_tpu.models.decoder import decoder_forward

        ids = np.asarray(input_ids, np.int32).reshape(-1)
        x = self._embed_multimodal(ids, pixel_values, image_grid_thw)
        pos, _ = self.get_rope_index(ids, list(image_grid_thw))
        cache = kv_mod.make_cache(
            "normal", self.config.num_layers, 1, len(ids),
            self.config.num_kv_heads, self.config.head_dim,
            v_head_dim=self.config.v_dim,
        )
        logits, _ = decoder_forward(
            self.config, self.params, h2d(ids[None]), cache,
            h2d(pos[None]), input_embeds=x,
        )
        return logits

    def generate(self, input_ids, pixel_values=None, image_grid_thw=(),
                 max_new_tokens: int = 32, **kwargs):
        """Greedy image+text generation (batch 1)."""
        from ipex_llm_tpu import kv as kv_mod

        ids = np.asarray(input_ids, np.int32).reshape(-1)
        n_p = len(ids)
        x = self._embed_multimodal(ids, pixel_values, image_grid_thw)
        pos, delta = self.get_rope_index(ids, list(image_grid_thw))
        # text continuation: all three channels advance together from the
        # multimodal position max (rope_delta), not the slot index
        return _greedy_generate(
            self, ids, x, h2d(pos[None]),
            lambda step: jnp.full((1, 3, 1), n_p + step + delta, jnp.int32),
            max_new_tokens,
        )


def _eos_set(hf_config: dict) -> set:
    """EOS ids from the top-level config or (composite multimodal configs)
    the nested text_config."""
    eos = hf_config.get("eos_token_id")
    if eos is None:
        eos = (hf_config.get("text_config") or {}).get("eos_token_id")
    if eos is None:
        return set()
    return set(eos) if isinstance(eos, (list, tuple)) else {eos}


def _greedy_generate(model, ids, embeds, prefill_pos, step_pos,
                     max_new_tokens: int):
    """Shared image+text greedy loop (qwen2-vl / internvl): jitted prefill
    with spliced embeddings, then jitted single-token steps whose rope
    positions come from ``step_pos(step)``."""
    from ipex_llm_tpu import kv as kv_mod

    n_p = len(ids)
    cache = kv_mod.make_cache(
        "normal", model.config.num_layers, 1, n_p + max_new_tokens,
        model.config.num_kv_heads, model.config.head_dim,
        v_head_dim=model.config.v_dim,
    )
    logits, cache = _mm_prefill(
        model.config, model.params, cache, h2d(ids[None]),
        prefill_pos, embeds,
    )
    out = list(ids)
    eos = _eos_set(model.hf_config)
    tok = int(jnp.argmax(logits[0]))
    for step in range(max_new_tokens):
        out.append(tok)
        if tok in eos:
            break
        logits, cache = _mm_decode(
            model.config, model.params, cache,
            h2d([[tok]], jnp.int32), step_pos(step),
        )
        tok = int(jnp.argmax(logits[0, -1]))
    return np.asarray(out, np.int32)[None]


class TPUInternVLForConditionalGeneration:
    """InternVL: InternViT tower + pixel-shuffle projector + qwen2 text.

    Reference counterpart: transformers/models/internvl.py patches.  The
    text side reuses the shared decoder through the SAME jitted
    prefill/decode steps as qwen2-vl (plain rope — no M-ROPE)."""

    def __init__(self, cfg: ModelConfig, vcfg, params: dict, vparams: dict,
                 hf_config: dict, qtype: str):
        self.config = cfg
        self.vision_config = vcfg
        self.params = params
        self.vision_params = vparams
        self.hf_config = hf_config
        self.qtype = qtype
        self.image_token_id = hf_config.get("image_token_id", 151667)

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_internvl import (
            InternVLVisionConfig,
            build_internvl_vision_params,
        )

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        text = dict(hf_config["text_config"])
        fam = get_family(text.get("model_type", "qwen2"))
        cfg = fam.to_config(text)
        vcfg = InternVLVisionConfig.from_hf(
            hf_config["vision_config"],
            downsample=hf_config.get("downsample_ratio", 0.5),
            projector_act=hf_config.get("projector_hidden_act", "gelu"),
        )
        reader = _AliasReader(CheckpointReader(path))
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_internvl_vision_params(
            vcfg, reader.reader.get, reader.reader.has, qtype
        )
        return cls(cfg, vcfg, params, vparams, hf_config, qtype)

    def _embed_multimodal(self, ids: np.ndarray, pixel_values):
        from ipex_llm_tpu.models.vision_internvl import internvl_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            px = h2d(pixel_values, jnp.float32)
            img = internvl_vision_forward(
                self.vision_config, self.vision_params, px
            ).reshape(-1, x.shape[-1]).astype(x.dtype)
            (idx,) = np.nonzero(np.asarray(ids) == self.image_token_id)
            assert len(idx) == img.shape[0], (
                f"{len(idx)} image tokens vs {img.shape[0]} image embeds"
            )
            x = x.at[0, h2d(idx)].set(img)
        return x

    def forward_logits(self, input_ids, pixel_values=None, image_bound=None,
                       **kwargs):
        from ipex_llm_tpu import kv as kv_mod
        from ipex_llm_tpu.models.decoder import decoder_forward

        mm = {} if image_bound is None else {"image_bound": image_bound}
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        x = self._embed_multimodal(ids, pixel_values, **mm)
        cache = kv_mod.make_cache(
            "normal", self.config.num_layers, 1, len(ids),
            self.config.num_kv_heads, self.config.head_dim,
            v_head_dim=self.config.v_dim,
        )
        pos = jnp.arange(len(ids))[None, :]
        logits, _ = decoder_forward(
            self.config, self.params, h2d(ids[None]), cache, pos,
            input_embeds=x,
        )
        return logits

    def generate(self, input_ids, pixel_values=None, max_new_tokens: int = 32,
                 image_bound=None, **kwargs):
        mm = {} if image_bound is None else {"image_bound": image_bound}
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        n_p = len(ids)
        x = self._embed_multimodal(ids, pixel_values, **mm)
        return _greedy_generate(
            self, ids, x, jnp.arange(n_p)[None, :],
            lambda step: h2d([[n_p + step]], jnp.int32),
            max_new_tokens,
        )

    def save_low_bit(self, path: str) -> None:
        from ipex_llm_tpu.models import serialize

        serialize.save_low_bit(
            path, {"text": self.params, "vision": self.vision_params},
            self.hf_config, self.qtype,
        )

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_internvl import InternVLVisionConfig

        tree, hf, qtype = serialize.load_low_bit(path)
        text = dict(hf["text_config"])
        cfg = get_family(text.get("model_type", "qwen2")).to_config(text)
        vcfg = InternVLVisionConfig.from_hf(
            hf["vision_config"],
            downsample=hf.get("downsample_ratio", 0.5),
            projector_act=hf.get("projector_hidden_act", "gelu"),
        )
        return cls(cfg, vcfg, tree["text"], tree["vision"], hf, qtype)


class TPULlavaForConditionalGeneration(TPUInternVLForConditionalGeneration):
    """LLaVA: CLIP tower (penultimate features) + MLP projector + llama-family
    text, all through the shared decoder's embed-replacement path.

    Reference counterpart: the CLIP-tower+projector pattern of the
    reference's multimodal patches (minicpmv.py / qwen_vl.py); HF's mainline
    ``LlavaForConditionalGeneration`` is the weight source and oracle.
    Inherits forward/generate/save from the InternVL glue — only the vision
    tower and config wiring differ."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_clip import (
            ClipVisionConfig,
            build_clip_vision_params,
        )

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        text = dict(hf_config["text_config"])
        fam = get_family(text.get("model_type", "llama"))
        cfg = fam.to_config(text)
        vcfg = ClipVisionConfig.from_hf(
            hf_config["vision_config"],
            feature_layer=hf_config.get("vision_feature_layer", -2),
            select_strategy=hf_config.get("vision_feature_select_strategy",
                                          "default"),
            projector_act=hf_config.get("projector_hidden_act", "gelu"),
        )
        reader = _AliasReader(CheckpointReader(path))
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_clip_vision_params(
            vcfg, reader.reader.get, reader.reader.has, qtype
        )
        m = cls(cfg, vcfg, params, vparams, hf_config, qtype)
        m.image_token_id = hf_config.get("image_token_index", 32000)
        return m

    def _embed_multimodal(self, ids: np.ndarray, pixel_values):
        from ipex_llm_tpu.models.vision_clip import clip_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            px = h2d(pixel_values, jnp.float32)
            img = clip_vision_forward(
                self.vision_config, self.vision_params, px
            ).reshape(-1, x.shape[-1]).astype(x.dtype)
            (idx,) = np.nonzero(np.asarray(ids) == self.image_token_id)
            assert len(idx) == img.shape[0], (
                f"{len(idx)} image tokens vs {img.shape[0]} image embeds"
            )
            x = x.at[0, h2d(idx)].set(img)
        return x

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_clip import ClipVisionConfig

        tree, hf, qtype = serialize.load_low_bit(path)
        text = dict(hf["text_config"])
        cfg = get_family(text.get("model_type", "llama")).to_config(text)
        vcfg = ClipVisionConfig.from_hf(
            hf["vision_config"],
            feature_layer=hf.get("vision_feature_layer", -2),
            select_strategy=hf.get("vision_feature_select_strategy",
                                   "default"),
            projector_act=hf.get("projector_hidden_act", "gelu"),
        )
        m = cls(cfg, vcfg, tree["text"], tree["vision"], hf, qtype)
        m.image_token_id = hf.get("image_token_index", 32000)
        return m


def _janus_vision_cfg(hf_config: dict):
    from ipex_llm_tpu.models.vision_clip import ClipVisionConfig

    v = hf_config["vision_config"]
    return ClipVisionConfig(
        hidden_size=v["hidden_size"],
        num_layers=v["num_hidden_layers"],
        num_heads=v["num_attention_heads"],
        intermediate_size=v.get("intermediate_size") or int(
            v.get("mlp_ratio", 4.0) * v["hidden_size"]),
        patch_size=v.get("patch_size", 16),
        image_size=v.get("image_size", 384),
        norm_eps=v.get("layer_norm_eps", 1e-6),
        act=v.get("hidden_act", "gelu"),
        feature_layer=v["num_hidden_layers"],   # full tower
        select_strategy="full",                  # no CLS to drop
        projector_act=v.get("hidden_act", "gelu"),
        variant="janus",
        aligner_depth=v.get("depth", 2),
    )


class TPUJanusForConditionalGeneration(TPULlavaForConditionalGeneration):
    """Janus (image understanding path): SigLIP-style tower + aligner MLP +
    llama text via embed replacement.

    Reference counterpart: transformers/models/janus.py (vision SDPA patch).
    The image-GENERATION path (VQ-VAE token head) is not implemented — this
    covers the multimodal-understanding direction the reference accelerates."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_clip import build_clip_vision_params

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        text = dict(hf_config["text_config"])
        fam = get_family(text.get("model_type", "llama"))
        cfg = fam.to_config(text)
        vcfg = _janus_vision_cfg(hf_config)
        reader = _AliasReader(CheckpointReader(path))
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_clip_vision_params(
            vcfg, reader.reader.get, reader.reader.has, qtype
        )
        m = cls(cfg, vcfg, params, vparams, hf_config, qtype)
        m.image_token_id = hf_config.get("image_token_id", 100581)
        return m

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family

        tree, hf, qtype = serialize.load_low_bit(path)
        text = dict(hf["text_config"])
        cfg = get_family(text.get("model_type", "llama")).to_config(text)
        m = cls(cfg, _janus_vision_cfg(hf), tree["text"], tree["vision"],
                hf, qtype)
        m.image_token_id = hf.get("image_token_id", 100581)
        return m


class TPUQwenVLForConditionalGeneration(TPUInternVLForConditionalGeneration):
    """Qwen-VL (v1): OpenCLIP-style ViT + cross-attn resampler feeding 256
    image tokens per image into the qwen(v1) text model.

    Reference counterpart: transformers/models/qwen_vl.py (vision
    transformer + resampler + model forward that splices image embeds
    between the ``image_start_id`` / ``image_start_id+1`` markers)."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_qwenvl import (
            QwenVLVisionConfig,
            build_qwenvl_vision_params,
        )

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        fam = get_family("qwen")
        cfg = fam.to_config(hf_config)
        vcfg = QwenVLVisionConfig.from_hf(hf_config["visual"])
        reader = CheckpointReader(path)
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_qwenvl_vision_params(vcfg, reader.get, reader.has,
                                             qtype)
        m = cls(cfg, vcfg, params, vparams, hf_config, qtype)
        m.image_start_id = hf_config["visual"].get("image_start_id", 151857)
        return m

    def _embed_multimodal(self, ids: np.ndarray, pixel_values):
        from ipex_llm_tpu.models.vision_qwenvl import qwenvl_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            px = h2d(pixel_values, jnp.float32)
            if px.ndim == 3:
                px = px[None]
            img = qwenvl_vision_forward(self.vision_config,
                                        self.vision_params, px)
            # splice each image's n_queries tokens between its start/end
            # markers (reference qwen_vl.py model forward: bos_pos /
            # eos_pos pairs)
            ids_np = np.asarray(ids)
            (starts,) = np.nonzero(ids_np == self.image_start_id)
            (ends,) = np.nonzero(ids_np == self.image_start_id + 1)
            nq = self.vision_config.n_queries
            assert len(starts) == len(ends) == img.shape[0], (
                f"{len(starts)} image markers vs {img.shape[0]} images")
            for j, (s, e) in enumerate(zip(starts, ends)):
                assert e - s - 1 == nq, (
                    f"{e - s - 1} slots between markers != {nq} queries")
                x = x.at[0, s + 1 : e].set(img[j].astype(x.dtype))
        return x

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_qwenvl import QwenVLVisionConfig

        tree, hf, qtype = serialize.load_low_bit(path)
        cfg = get_family("qwen").to_config(hf)
        vcfg = QwenVLVisionConfig.from_hf(hf["visual"])
        m = cls(cfg, vcfg, tree["text"], tree["vision"], hf, qtype)
        m.image_start_id = hf["visual"].get("image_start_id", 151857)
        return m


def _minicpmv_text_family(hf: dict) -> str:
    """MiniCPM-V carries its LLM arch implicitly: v2.6+ is qwen2, v2.5 is
    llama (MiniCPM-Llama3-V), earlier is minicpm."""
    v = float(hf.get("version", 2.6))
    if v >= 2.6:
        return "qwen2"
    if v >= 2.5:
        return "llama"
    return "minicpm"


def _minicpmv_vision_cfg(hf: dict):
    from ipex_llm_tpu.models.vision_clip import ClipVisionConfig

    v = hf["vision_config"]
    return ClipVisionConfig(
        hidden_size=v["hidden_size"],
        num_layers=v["num_hidden_layers"],
        num_heads=v["num_attention_heads"],
        intermediate_size=v["intermediate_size"],
        patch_size=v.get("patch_size", 14),
        image_size=v.get("image_size", 448),
        norm_eps=v.get("layer_norm_eps", 1e-6),
        act=v.get("hidden_act", "gelu_pytorch_tanh"),
        feature_layer=v["num_hidden_layers"],
        select_strategy="full",
        variant="siglip",
    )


class TPUMiniCPMVForConditionalGeneration(TPUInternVLForConditionalGeneration):
    """MiniCPM-V: SigLIP tower (vpm.) + perceiver resampler + llm. text.

    Reference counterpart: transformers/models/minicpmv.py.  Image features
    enter at ``image_bound`` (start, end) spans — the remote model's own
    forward contract — each span exactly ``query_num`` tokens wide."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.minicpmv import build_resampler_params
        from ipex_llm_tpu.models.vision_clip import build_clip_vision_params

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        fam = get_family(_minicpmv_text_family(hf_config))
        cfg = fam.to_config(hf_config)
        vcfg = _minicpmv_vision_cfg(hf_config)
        reader = _AliasReader(CheckpointReader(path))
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_clip_vision_params(
            vcfg, reader.reader.get, reader.reader.has, qtype)
        vparams["resampler"] = build_resampler_params(
            reader.reader.get, reader.reader.has, qtype)
        m = cls(cfg, vcfg, params, vparams, hf_config, qtype)
        m.query_num = hf_config.get("query_num", 64)
        return m

    def _embed_multimodal(self, ids: np.ndarray, pixel_values,
                          image_bound=None):
        from ipex_llm_tpu.models.minicpmv import resampler_forward
        from ipex_llm_tpu.models.vision_clip import clip_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            px = h2d(pixel_values, jnp.float32)
            if px.ndim == 3:
                px = px[None]
            feats = clip_vision_forward(self.vision_config,
                                        self.vision_params, px)
            g = px.shape[-2] // self.vision_config.patch_size
            gw = px.shape[-1] // self.vision_config.patch_size
            e = self.vision_params["resampler"]["query"].shape[1]
            img = resampler_forward(self.vision_params["resampler"], feats,
                                    max(1, e // 128), (g, gw))
            bounds = list(image_bound or [])
            assert len(bounds) == img.shape[0], (
                f"{len(bounds)} image_bound spans vs {img.shape[0]} images")
            for j, (s, en) in enumerate(bounds):
                assert en - s == self.query_num, (
                    f"span [{s},{en}) != query_num {self.query_num}")
                x = x.at[0, s:en].set(img[j].astype(x.dtype))
        return x

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family

        tree, hf, qtype = serialize.load_low_bit(path)
        fam = get_family(_minicpmv_text_family(hf))
        cfg = fam.to_config(hf)
        m = cls(cfg, _minicpmv_vision_cfg(hf), tree["text"], tree["vision"],
                hf, qtype)
        m.query_num = hf.get("query_num", 64)
        return m


class TPUGemma3ForConditionalGeneration(TPUInternVLForConditionalGeneration):
    """Gemma3 VLM: SigLIP tower + avg-pool/RMSNorm/matmul projector +
    gemma3_text, via embed replacement at ``image_token_index``.

    HF splices raw projector outputs into ALREADY-SCALED text embeddings;
    the shared decoder applies the gemma embedding multiplier to the whole
    input_embeds tensor, so image features are pre-divided by it here."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.build import quantize_weight
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_clip import (
            ClipVisionConfig,
            build_clip_vision_params,
        )

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        text = dict(hf_config["text_config"])
        text.setdefault("model_type", "gemma3_text")
        fam = get_family("gemma3_text")
        cfg = fam.to_config(text)
        v = hf_config["vision_config"]
        reader = _AliasReader(CheckpointReader(path))
        prefix = "model.vision_tower.vision_model."
        if not reader.reader.has(prefix + "embeddings.patch_embedding.weight"):
            prefix = "vision_tower.vision_model."
        vcfg = ClipVisionConfig(
            hidden_size=v["hidden_size"],
            num_layers=v["num_hidden_layers"],
            num_heads=v["num_attention_heads"],
            intermediate_size=v["intermediate_size"],
            patch_size=v.get("patch_size", 14),
            image_size=v.get("image_size", 896),
            norm_eps=v.get("layer_norm_eps", 1e-6),
            act=v.get("hidden_act", "gelu_pytorch_tanh"),
            feature_layer=v["num_hidden_layers"],
            select_strategy="full",
            variant="siglip",
            prefix=prefix,
        )
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_clip_vision_params(
            vcfg, reader.reader.get, reader.reader.has, qtype)
        mp = prefix.replace("vision_tower.vision_model.",
                            "multi_modal_projector.")
        vparams["proj_norm"] = h2d(
            reader.reader.get(mp + "mm_soft_emb_norm.weight"), jnp.float32)
        vparams["proj_w"] = quantize_weight(
            np.ascontiguousarray(
                reader.reader.get(mp + "mm_input_projection_weight").T),
            qtype)
        m = cls(cfg, vcfg, params, vparams, hf_config, qtype)
        m.image_token_id = hf_config.get("image_token_index", 262144)
        m.mm_tokens_per_image = hf_config.get("mm_tokens_per_image", 256)
        return m

    def _project(self, feats):
        """avg-pool the patch grid to mm_tokens_per_image, RMS-norm (gemma
        1+w), then matmul into the text width (Gemma3MultiModalProjector)."""
        from ipex_llm_tpu.ops.norms import rms_norm

        b, n, d = feats.shape
        g = int(np.sqrt(n))
        side = int(np.sqrt(self.mm_tokens_per_image))
        k = g // side
        pooled = feats.reshape(b, side, k, side, k, d).mean(axis=(2, 4))
        pooled = pooled.reshape(b, side * side, d)
        normed = rms_norm(pooled, self.vision_params["proj_norm"],
                          self.config.norm_eps, offset=1.0)
        from ipex_llm_tpu.ops import linear as linear_ops

        return linear_ops.linear(normed.astype(jnp.bfloat16),
                                 self.vision_params["proj_w"]
                                 ).astype(jnp.float32)

    def _embed_multimodal(self, ids: np.ndarray, pixel_values):
        from ipex_llm_tpu.models.vision_clip import clip_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        toks = h2d(np.asarray(ids, np.int32)[None])
        x = embed_lookup(self.params["embed"], toks, jnp.bfloat16)
        if pixel_values is not None:
            px = h2d(pixel_values, jnp.float32)
            if px.ndim == 3:
                px = px[None]
            feats = clip_vision_forward(self.vision_config,
                                        self.vision_params, px)
            img = self._project(feats).reshape(-1, x.shape[-1])
            # decoder scales input_embeds by the gemma multiplier; HF
            # splices image features unscaled — pre-divide to compensate
            img = img / h2d(self.config.embedding_multiplier,
                                    img.dtype)
            (idx,) = np.nonzero(np.asarray(ids) == self.image_token_id)
            assert len(idx) == img.shape[0], (
                f"{len(idx)} image tokens vs {img.shape[0]} image embeds")
            x = x.at[0, h2d(idx)].set(img.astype(x.dtype))
        return x

    @classmethod
    def load_low_bit(cls, path: str):
        raise NotImplementedError(
            "gemma3 load_low_bit: re-quantize with from_pretrained")

    def save_low_bit(self, path: str) -> None:
        raise NotImplementedError(
            "gemma3 save_low_bit not implemented; reload from the HF "
            "checkpoint instead")


class TPUQwen2_5OmniThinker:
    """Qwen2.5-Omni thinker: audio tower (models/audio_omni.py) + qwen2
    M-ROPE text decoder — the speech+text understanding path (reference
    models/qwen2_5_omni.py thinker/audio patches).  Audio features replace
    the prompt's audio placeholder tokens one-for-one (the HF
    masked_scatter contract); rope positions follow the HF
    position_ids=None path (sequential, equal t/h/w channels).  The talker
    / token2wav speech-GENERATION stack is out of scope."""

    def __init__(self, cfg: ModelConfig, acfg, params: dict, aparams: dict,
                 hf_config: dict, qtype: str):
        self.config = cfg
        self.audio_config = acfg
        self.params = params
        self.audio_params = aparams
        self.hf_config = hf_config
        self.qtype = qtype
        self.audio_token_id = hf_config.get(
            "audio_token_index", hf_config.get("audio_token_id"))

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.audio_omni import (OmniAudioConfig,
                                                    build_omni_audio_params)

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        cfg = _qwen2_vl_text_config(hf_config)
        acfg = OmniAudioConfig.from_hf(hf_config["audio_config"])
        reader = CheckpointReader(path)
        params = build_params(cfg, WeightScheme(), reader.get, reader.has,
                              qtype=qtype)
        aparams = build_omni_audio_params(acfg, reader.get, reader.has,
                                          qtype)
        return cls(cfg, acfg, params, aparams, hf_config, qtype)

    def _embed_multimodal(self, ids: np.ndarray, input_features=None,
                          feature_attention_mask=None):
        from ipex_llm_tpu.models.audio_omni import omni_audio_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        ids = np.asarray(ids, np.int32).reshape(-1)
        x = embed_lookup(self.params["embed"], h2d(ids[None]),
                         jnp.bfloat16)
        if input_features is None:
            return x
        mel = h2d(input_features, jnp.float32)
        if mel.ndim == 3:
            mel = mel[0]
        n_valid = (int(np.asarray(feature_attention_mask).sum())
                   if feature_attention_mask is not None else mel.shape[1])
        audio = omni_audio_forward(self.audio_config, self.audio_params,
                                   mel, n_valid)
        (idx,) = np.nonzero(ids == self.audio_token_id)
        assert len(idx) == audio.shape[0], (
            f"{len(idx)} audio tokens vs {audio.shape[0]} audio frames")
        return x.at[0, h2d(idx)].set(audio.astype(x.dtype))

    def forward_logits(self, input_ids, input_features=None,
                       feature_attention_mask=None, **kwargs):
        from ipex_llm_tpu import kv as kv_mod
        from ipex_llm_tpu.models.decoder import decoder_forward

        ids = np.asarray(input_ids, np.int32).reshape(-1)
        x = self._embed_multimodal(ids, input_features,
                                   feature_attention_mask)
        cache = kv_mod.make_cache(
            "normal", self.config.num_layers, 1, len(ids),
            self.config.num_kv_heads, self.config.head_dim,
            v_head_dim=self.config.v_dim,
        )
        pos = jnp.arange(len(ids))[None, :]
        logits, _ = decoder_forward(
            self.config, self.params, h2d(ids[None]), cache, pos,
            input_embeds=x,
        )
        return logits

    def generate(self, input_ids, input_features=None,
                 feature_attention_mask=None, max_new_tokens: int = 32,
                 **kwargs):
        ids = np.asarray(input_ids, np.int32).reshape(-1)
        n_p = len(ids)
        x = self._embed_multimodal(ids, input_features,
                                   feature_attention_mask)
        return _greedy_generate(
            self, ids, x, jnp.arange(n_p)[None, :],
            lambda step: h2d([[n_p + step]], jnp.int32),
            max_new_tokens,
        )

    def save_low_bit(self, path: str) -> None:
        from ipex_llm_tpu.models import serialize

        serialize.save_low_bit(
            path, {"text": self.params, "audio": self.audio_params},
            self.hf_config, self.qtype,
        )

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.audio_omni import OmniAudioConfig

        tree, hf, qtype = serialize.load_low_bit(path)
        cfg = _qwen2_vl_text_config(hf)
        acfg = OmniAudioConfig.from_hf(hf["audio_config"])
        return cls(cfg, acfg, tree["text"], tree["audio"], hf, qtype)


class TPUChatGLM4VForConditionalGeneration:
    """GLM-4V: EVA2-CLIP tower + conv-downsample GLU projector + chatglm
    text (reference transformers/models/chatglm4v.py).  The prompt carries
    ``[boi, placeholder, eoi]``; the projector output (which includes the
    learned boi/eoi embeddings) replaces those three slots, and rope
    positions repeat boi+1 across the patch span (chatglm4v.py:76-89)."""

    def __init__(self, cfg, vcfg, params: dict, vparams: dict,
                 hf_config: dict, qtype: str):
        self.config = cfg
        self.vision_config = vcfg
        self.params = params
        self.vision_params = vparams
        self.hf_config = hf_config
        self.qtype = qtype
        self.boi_token_id = hf_config.get("boi_token_id")
        self.eoi_token_id = hf_config.get("eoi_token_id")

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_eva import (
            EVAVisionConfig,
            build_eva_vision_params,
        )

        qtype = kwargs.pop("load_in_low_bit", None) or (
            "sym_int4" if kwargs.pop("load_in_4bit", False) else "bf16"
        )
        hf_config = read_config(path)
        fam = get_family(hf_config.get("model_type", "chatglm"))
        cfg = fam.to_config(hf_config)
        vcfg = EVAVisionConfig.from_hf(hf_config["vision_config"])
        reader = CheckpointReader(path)
        params = build_params(cfg, fam.scheme, reader.get, reader.has,
                              qtype=qtype, qkv_transform=fam.qkv_transform)
        vparams = build_eva_vision_params(vcfg, reader.get, reader.has, qtype)
        return cls(cfg, vcfg, params, vparams, hf_config, qtype)

    def _splice(self, ids: np.ndarray, pixel_values):
        """Returns (embeds [1, N, H], rope positions [1, N], n_tokens)."""
        from ipex_llm_tpu.models.vision_eva import eva_vision_forward
        from ipex_llm_tpu.ops.embedding import embed_lookup

        ids = np.asarray(ids, np.int32).reshape(-1)
        L = len(ids)
        x = embed_lookup(self.params["embed"], h2d(ids[None]),
                         jnp.bfloat16)
        pos = np.arange(L, dtype=np.int32)
        if pixel_values is None:
            return x, h2d(pos[None]), L
        px = h2d(pixel_values, jnp.float32)
        if px.ndim == 3:
            px = px[None]
        img = eva_vision_forward(self.vision_config, self.vision_params, px)
        boi = int(np.nonzero(ids == self.boi_token_id)[0][0])
        eoi = int(np.nonzero(ids == self.eoi_token_id)[0][0])
        assert eoi - boi == 2, f"boi/eoi span must be 3 tokens, got {ids}"
        img = img.astype(x.dtype)
        x = jnp.concatenate([x[:, :boi], img, x[:, eoi + 1:]], axis=1)
        n_img = img.shape[1]
        new_pos = np.concatenate([
            pos[: boi + 1],
            np.full((n_img - 2,), pos[boi + 1], np.int32),
            pos[eoi:],
        ])
        assert len(new_pos) == x.shape[1], (len(new_pos), x.shape)
        return x, h2d(new_pos[None]), L

    def forward_logits(self, input_ids, pixel_values=None, **kwargs):
        from ipex_llm_tpu import kv as kv_mod
        from ipex_llm_tpu.models.decoder import decoder_forward

        x, pos, _ = self._splice(input_ids, pixel_values)
        n = x.shape[1]
        cache = kv_mod.make_cache(
            "normal", self.config.num_layers, 1, n,
            self.config.num_kv_heads, self.config.head_dim,
            v_head_dim=self.config.v_dim,
        )
        dummy = jnp.zeros((1, n), jnp.int32)
        logits, _ = decoder_forward(self.config, self.params, dummy, cache,
                                    pos, input_embeds=x)
        return logits

    def generate(self, input_ids, pixel_values=None, max_new_tokens: int = 32,
                 **kwargs):
        from ipex_llm_tpu import kv as kv_mod

        ids = np.asarray(input_ids, np.int32).reshape(-1)
        x, pos, L = self._splice(ids, pixel_values)
        n = x.shape[1]
        cache = kv_mod.make_cache(
            "normal", self.config.num_layers, 1, n + max_new_tokens,
            self.config.num_kv_heads, self.config.head_dim,
            v_head_dim=self.config.v_dim,
        )
        dummy = jnp.zeros((1, n), jnp.int32)
        logits, cache = _mm_prefill(self.config, self.params, cache, dummy,
                                    pos, x)
        out = list(ids)
        eos = _eos_set(self.hf_config)
        tok = int(jnp.argmax(logits[0]))
        for step in range(max_new_tokens):
            out.append(tok)
            if tok in eos:
                break
            logits, cache = _mm_decode(
                self.config, self.params, cache,
                h2d([[tok]], jnp.int32),
                h2d([[L + step]], jnp.int32),
            )
            tok = int(jnp.argmax(logits[0, -1]))
        return np.asarray(out, np.int32)[None]

    def save_low_bit(self, path: str) -> None:
        from ipex_llm_tpu.models import serialize

        serialize.save_low_bit(
            path, {"text": self.params, "vision": self.vision_params},
            self.hf_config, self.qtype,
        )

    @classmethod
    def load_low_bit(cls, path: str):
        from ipex_llm_tpu.models import serialize
        from ipex_llm_tpu.models.families import get_family
        from ipex_llm_tpu.models.vision_eva import EVAVisionConfig

        tree, hf, qtype = serialize.load_low_bit(path)
        cfg = get_family(hf.get("model_type", "chatglm")).to_config(hf)
        vcfg = EVAVisionConfig.from_hf(hf["vision_config"])
        return cls(cfg, vcfg, tree["text"], tree["vision"], hf, qtype)


class AutoModelForVision2Seq:
    """Vision-language loader dispatching by model_type (qwen2_vl,
    internvl, llava, mllama, janus, qwen-vl v1, minicpmv, gemma3,
    chatglm4v)."""

    @classmethod
    def from_pretrained(cls, path: str, **kwargs):
        hf = read_config(str(path))
        mt = hf.get("model_type")
        if mt == "qwen2_vl":
            return TPUModelForVision2Seq.from_pretrained(str(path), **kwargs)
        if mt == "internvl":
            return TPUInternVLForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "llava":
            return TPULlavaForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "mllama":
            from ipex_llm_tpu.models.mllama import (
                TPUMllamaForConditionalGeneration,
            )

            return TPUMllamaForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "janus":
            return TPUJanusForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "qwen" and "visual" in hf:
            return TPUQwenVLForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "minicpmv":
            return TPUMiniCPMVForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt == "gemma3":
            return TPUGemma3ForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt in ("chatglm", "glm4v") and "vision_config" in hf:
            return TPUChatGLM4VForConditionalGeneration.from_pretrained(
                str(path), **kwargs
            )
        if mt in ("qwen2_5_omni", "qwen2_5_omni_thinker"):
            return TPUQwen2_5OmniThinker.from_pretrained(str(path), **kwargs)
        raise ValueError(
            f"AutoModelForVision2Seq supports qwen2_vl/internvl/llava/"
            f"mllama/janus/qwen(-vl v1)/minicpmv/gemma3/chatglm4v; got {mt!r}"
        )

    @classmethod
    def load_low_bit(cls, path: str):
        import json
        import os

        # dispatch from config.json alone — never deserialize the weight
        # tree twice
        with open(os.path.join(str(path), "config.json")) as f:
            mt = json.load(f).get("model_type")
        if mt == "qwen2_vl":
            return TPUModelForVision2Seq.load_low_bit(str(path))
        if mt == "internvl":
            return TPUInternVLForConditionalGeneration.load_low_bit(str(path))
        if mt == "llava":
            return TPULlavaForConditionalGeneration.load_low_bit(str(path))
        if mt == "janus":
            return TPUJanusForConditionalGeneration.load_low_bit(str(path))
        if mt == "qwen":
            return TPUQwenVLForConditionalGeneration.load_low_bit(str(path))
        if mt == "minicpmv":
            return TPUMiniCPMVForConditionalGeneration.load_low_bit(str(path))
        if mt == "mllama":
            from ipex_llm_tpu.models.mllama import (
                TPUMllamaForConditionalGeneration,
            )

            return TPUMllamaForConditionalGeneration.load_low_bit(str(path))
        if mt in ("chatglm", "glm4v"):
            return TPUChatGLM4VForConditionalGeneration.load_low_bit(
                str(path))
        if mt in ("qwen2_5_omni", "qwen2_5_omni_thinker"):
            return TPUQwen2_5OmniThinker.load_low_bit(str(path))
        raise ValueError(
            f"load_low_bit supports qwen2_vl/internvl/llava/mllama/janus/"
            f"qwen(-vl v1)/minicpmv/chatglm4v; got {mt!r}"
        )
